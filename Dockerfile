# cake-tpu runtime image (ref: the reference ships a CUDA multi-stage build;
# JAX wheels bundle the accelerator runtime so a single stage suffices —
# install the TPU extra on TPU VMs, the CPU wheel elsewhere).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY cake_tpu ./cake_tpu
COPY csrc ./csrc

# TPU VMs: pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir jax flax optax msgpack zstandard pyyaml \
        aiohttp tokenizers safetensors huggingface_hub pillow numpy \
    && pip install --no-cache-dir -e . --no-deps --no-build-isolation \
    && make -C csrc

EXPOSE 8000 10128 18337/udp
ENTRYPOINT ["cake-tpu"]
CMD ["--help"]
