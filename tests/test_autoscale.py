"""Closed-loop fleet autoscaler (ISSUE 17 acceptance pins).

Units drive the PURE controller (fleet/autoscale.py decide()) with a
fake clock — no sleeps, no processes: scale-out on fast-burn breach,
scale-in only after a clean slow window has dwelled a full cooldown,
hysteresis on a synthetic oscillating trace (no flapping), the HARD
RULE that outlier/stale flags never change WHETHER the fleet scales
(only WHICH replica drains), min/max bounds, one-action-per-cooldown,
warm-up holds, and batch backlog explicitly NOT being a trigger.

The lifecycle manager (fleet/lifecycle.py) is driven through its
injectable spawner/prober seams with stub processes: spawn -> admit ->
registry join, graceful retire -> cordon -> reap, sweep on kill -9,
spawn timeout, and the spawn-ETA estimate behind the router's
cold-start Retry-After.
"""
import asyncio
import json

import pytest

from cake_tpu.fleet import MembershipPolicy, ReplicaRegistry
from cake_tpu.fleet.autoscale import (DECISION_KINDS, HOLD, SCALE_IN,
                                      SCALE_OUT, Autoscaler,
                                      ControllerState, DecisionLog,
                                      ScalePolicy, decide, select_victim)
from cake_tpu.fleet.lifecycle import (DEFAULT_SPAWN_ETA_S, ManagedReplica,
                                      ReplicaLifecycle)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _policy(**kw):
    base = dict(burn_fast=2.0, headroom_min=100.0, headroom_high=500.0,
                cooldown_s=10.0, min_replicas=1, max_replicas=4,
                warmup_s=5.0, enabled=True)
    base.update(kw)
    return ScalePolicy(**base)


def _mpolicy(**kw):
    base = dict(eject_fails=3, err_window=16, err_rate=0.5,
                degraded_ttft_ms=0.0, eject_s=0.05, replica_inflight=0)
    base.update(kw)
    return MembershipPolicy(**base)


def _rep(name, state="healthy", warm=1000.0, managed=True, outlier=False,
         cordoned=False, headroom=0.0, mass=0.0):
    return {"name": name, "state": state, "warm_age_s": warm,
            "managed": managed, "outlier": outlier, "cordoned": cordoned,
            "headroom_tokens_per_s": headroom, "affinity_mass": mass,
            "inflight": 0, "stale": False}


def _view(*reps, pending=0):
    return {"replicas": list(reps), "pending_spawns": pending}


def _rollup(fast=0.0, slow=0.0, headroom=1000.0, qos=None):
    return {"burn_rate": {"fast": fast, "slow": slow},
            "headroom_tokens_per_s": headroom,
            "qos_backlog": qos or {}}


# ---------------------------------------------------------------------------
# decide(): scale-out
# ---------------------------------------------------------------------------


def test_scale_out_on_fast_burn_breach():
    st = ControllerState()
    d = decide(_rollup(fast=2.5), _view(_rep("a"), _rep("b")),
               _policy(), st, t=100.0)
    assert d.action == SCALE_OUT and d.reason == "burn_fast"
    assert st.last_action_t == 100.0
    assert d.detail["burn_fast"] == 2.5


def test_scale_out_on_low_headroom():
    d = decide(_rollup(headroom=50.0), _view(_rep("a"), _rep("b")),
               _policy(headroom_min=100.0), ControllerState(), t=0.0)
    assert d.action == SCALE_OUT and d.reason == "headroom_low"


def test_headroom_trigger_off_when_zero():
    d = decide(_rollup(headroom=0.0), _view(_rep("a"), _rep("b")),
               _policy(headroom_min=0.0), ControllerState(), t=0.0)
    assert d.action == HOLD and d.reason == "steady"


def test_scale_out_capped_at_max():
    reps = [_rep(f"r{i}") for i in range(4)]
    d = decide(_rollup(fast=9.0), _view(*reps), _policy(max_replicas=4),
               ControllerState(), t=0.0)
    assert d.action == HOLD and d.reason == "at_max"
    # pending spawns count against the bound too
    d = decide(_rollup(fast=9.0), _view(*reps[:3], pending=1),
               _policy(max_replicas=4), ControllerState(), t=0.0)
    assert d.action == HOLD and d.reason == "at_max"


def test_one_action_per_cooldown():
    st = ControllerState()
    pol = _policy(cooldown_s=10.0, max_replicas=8)
    v = _view(_rep("a"), _rep("b"))
    assert decide(_rollup(fast=5.0), v, pol, st, t=0.0).action == SCALE_OUT
    d = decide(_rollup(fast=5.0), v, pol, st, t=5.0)
    assert d.action == HOLD and d.reason == "cooldown"
    assert decide(_rollup(fast=5.0), v, pol, st, t=10.0).action == SCALE_OUT


def test_warming_replica_and_pending_spawn_hold_out_triggers():
    pol = _policy(warmup_s=30.0)
    # a freshly admitted replica is still materializing capacity: judging
    # the trigger again now would double-spend on the same pressure
    d = decide(_rollup(fast=5.0), _view(_rep("a"), _rep("new", warm=3.0)),
               pol, ControllerState(), t=100.0)
    assert d.action == HOLD and d.reason == "warmup"
    d = decide(_rollup(fast=5.0), _view(_rep("a"), pending=1),
               pol, ControllerState(), t=100.0)
    assert d.action == HOLD and d.reason == "warmup"


def test_below_min_bypasses_cooldown_and_warmup():
    st = ControllerState()
    pol = _policy(min_replicas=2, cooldown_s=60.0, warmup_s=30.0)
    # an action just fired and a survivor is mid-warm-up: the floor is
    # not discretionary — kill -9 replacement cannot wait either hold out
    st.last_action_t = 99.0
    d = decide(_rollup(), _view(_rep("a", warm=1.0)), pol, st, t=100.0)
    assert d.action == SCALE_OUT and d.reason == "below_min"
    # ... but pending spawns count toward the floor (no double-replace)
    d = decide(_rollup(), _view(_rep("a", warm=1.0), pending=1), pol, st,
               t=101.0)
    assert d.action != SCALE_OUT


def test_disabled_policy_always_holds():
    d = decide(_rollup(fast=9.0), _view(), _policy(enabled=False),
               ControllerState(), t=0.0)
    assert d.action == HOLD and d.reason == "disabled"


# ---------------------------------------------------------------------------
# decide(): scale-in dwell + hysteresis
# ---------------------------------------------------------------------------


def test_scale_in_requires_continuous_dwell():
    st = ControllerState()
    pol = _policy(cooldown_s=10.0, min_replicas=1)
    v = _view(_rep("a", headroom=300.0, mass=50.0),
              _rep("b", headroom=200.0, mass=10.0))
    high = _rollup(headroom=800.0)
    # high-water starts the dwell clock; nothing fires before a full
    # cooldown has elapsed CONTINUOUSLY
    assert decide(high, v, pol, st, t=100.0).reason == "steady"
    assert decide(high, v, pol, st, t=105.0).reason == "steady"
    d = decide(high, v, pol, st, t=110.0)
    assert d.action == SCALE_IN and d.reason == "headroom_high"
    assert d.victim == "b"          # least affinity mass
    assert st.high_since is None    # dwell re-arms after the action


def test_scale_in_dwell_resets_on_burn_or_dip():
    st = ControllerState()
    pol = _policy(cooldown_s=10.0)
    v = _view(_rep("a", headroom=300.0), _rep("b", headroom=200.0))
    assert decide(_rollup(headroom=800.0), v, pol, st, t=0.0).action == HOLD
    # headroom dips below the high-water mid-dwell: clock resets
    decide(_rollup(headroom=400.0), v, pol, st, t=5.0)
    assert st.high_since is None
    decide(_rollup(headroom=800.0), v, pol, st, t=6.0)
    assert decide(_rollup(headroom=800.0), v, pol, st,
                  t=15.0).action == HOLD          # only 9s of dwell
    # a dirty slow window mid-dwell resets it too
    decide(_rollup(headroom=800.0, slow=1.5), v, pol, st, t=16.0)
    assert st.high_since is None


def test_scale_in_holds_at_min_and_without_victim():
    st = ControllerState()
    pol = _policy(cooldown_s=0.0, min_replicas=2)
    v = _view(_rep("a", headroom=400.0), _rep("b", headroom=400.0))
    d = decide(_rollup(headroom=900.0), v, pol, st, t=0.0)
    assert d.action == HOLD and d.reason == "at_min"
    # above min but nothing managed: the router never retires a process
    # it did not spawn
    v = _view(_rep("a", managed=False), _rep("b", managed=False),
              _rep("c", managed=False))
    st = ControllerState()
    d = decide(_rollup(headroom=900.0), v, _policy(cooldown_s=0.0), st,
               t=0.0)
    assert d.action == HOLD and d.reason == "no_victim"


def test_scale_in_hysteresis_guard():
    # removing the victim would drop headroom below the scale-out floor:
    # the loop must hold, or it would flap out <-> in forever
    st = ControllerState()
    pol = _policy(cooldown_s=0.0, headroom_min=300.0, headroom_high=500.0)
    v = _view(_rep("a", headroom=100.0, mass=50.0),
              _rep("b", headroom=450.0, mass=1.0))   # victim: least mass
    d = decide(_rollup(headroom=550.0), v, pol, st, t=0.0)
    assert d.action == HOLD and d.reason == "hysteresis"
    assert d.detail["predicted_headroom_tokens_per_s"] == 100.0


def test_oscillating_trace_does_not_flap():
    """Synthetic oscillating load: burn alternates dirty/clean every
    cycle and headroom swings around the high-water mark. The loop may
    scale out at most once per cooldown and must never scale in (the
    dwell clock resets on every dirty cycle)."""
    st = ControllerState()
    pol = _policy(cooldown_s=10.0, max_replicas=16, warmup_s=0.0)
    v = _view(*[_rep(f"r{i}", headroom=100.0) for i in range(6)])
    actions = []
    for i in range(60):
        t = float(i)
        fast = 3.0 if i % 2 == 0 else 0.2
        headroom = 900.0 if i % 2 else 120.0
        d = decide(_rollup(fast=fast, headroom=headroom), v, pol, st, t)
        if d.action != HOLD:
            actions.append((t, d.action))
    assert all(a == SCALE_OUT for _, a in actions)
    times = [t for t, _ in actions]
    assert all(b - a >= pol.cooldown_s for a, b in zip(times, times[1:]))
    assert len(actions) <= 6            # 60s / 10s cooldown


# ---------------------------------------------------------------------------
# decide(): outliers advisory, batch not a trigger
# ---------------------------------------------------------------------------


def test_outlier_flags_never_change_direction_only_victim():
    pol = _policy(cooldown_s=0.0)
    plain = [_rep("a", headroom=300.0, mass=5.0),
             _rep("b", headroom=300.0, mass=50.0)]
    flagged = [dict(r, outlier=(r["name"] == "b")) for r in plain]
    for rollup in (_rollup(fast=5.0),              # scale-out pressure
                   _rollup(headroom=900.0),        # scale-in comfort
                   _rollup(headroom=300.0)):       # steady
        d0 = decide(rollup, _view(*plain), pol, ControllerState(), t=100.0)
        d1 = decide(rollup, _view(*flagged), pol, ControllerState(),
                    t=100.0)
        # HARD RULE: same rollup with and without flags -> same action
        assert (d0.action, d0.reason) == (d1.action, d1.reason)
    # ... but when a scale-in fires, the flag picks the victim: "b" is
    # outlier-flagged and outranks "a" despite 10x the affinity mass
    d1 = decide(_rollup(headroom=900.0), _view(*flagged), pol,
                ControllerState(), t=100.0)
    assert d1.action == SCALE_IN and d1.victim == "b"
    d0 = decide(_rollup(headroom=900.0), _view(*plain), pol,
                ControllerState(), t=100.0)
    assert d0.victim == "a"             # unflagged: least mass wins


def test_batch_backlog_is_visible_but_not_a_trigger():
    # a mountain of batch backlog with clean burn and adequate headroom
    # holds: batch absorbs by design (interactive pressure pages through
    # the burn rate, which IS a trigger)
    d = decide(_rollup(headroom=300.0, qos={"batch": 50000.0}),
               _view(_rep("a"), _rep("b")), _policy(), ControllerState(),
               t=0.0)
    assert d.action == HOLD and d.reason == "steady"
    assert d.detail["qos_backlog"] == {"batch": 50000.0}
    # interactive TTFT burn with the same batch mountain DOES trigger
    d = decide(_rollup(fast=3.0, qos={"batch": 50000.0}),
               _view(_rep("a"), _rep("b")), _policy(), ControllerState(),
               t=0.0)
    assert d.action == SCALE_OUT and d.reason == "burn_fast"


def test_select_victim_ordering_and_eligibility():
    reps = [_rep("big", mass=100.0), _rep("small", mass=1.0),
            _rep("bad", mass=999.0, outlier=True),
            _rep("foreign", mass=0.0, managed=False),
            _rep("leaving", mass=0.0, cordoned=True),
            _rep("downed", mass=0.0, state="ejected")]
    v = select_victim(reps)
    assert v["name"] == "bad"           # outlier first, mass ignored
    v = select_victim([r for r in reps if r["name"] != "bad"])
    assert v["name"] == "small"         # then least affinity mass
    assert select_victim([_rep("x", managed=False)]) is None


# ---------------------------------------------------------------------------
# decisions ring
# ---------------------------------------------------------------------------


def test_decision_log_rejects_unknown_kinds_and_rings():
    clk = FakeClock()
    log = DecisionLog(cap=8, clock=clk)
    with pytest.raises(ValueError):
        log.record("resize")            # not in the closed catalog
    for i in range(12):
        log.record("hold", t=float(i), reason=f"h{i}")
    evs = log.events(t=20.0)
    assert len(evs) == 8                # ring capped
    assert evs[-1]["reason"] == "h11" and evs[-1]["age_s"] == 9.0
    assert "t" not in evs[-1]           # rendered as age, never raw t
    log.record("scale_out", t=15.0, reason="burn_fast")
    assert log.last("scale_out")["reason"] == "burn_fast"
    assert log.last("scale_in") is None
    assert set(DECISION_KINDS) >= {"scale_out", "scale_in", "hold",
                                   "spawned", "admitted", "spawn_failed",
                                   "retire", "reaped", "died"}


class _StubLifecycle:
    def __init__(self):
        self.spawns, self.retires = [], []

    def pending_count(self):
        return 0

    def is_managed(self, name):
        return True

    def managed_names(self):
        return []

    def spawn(self, reason=""):
        self.spawns.append(reason)

    def retire(self, name, reason=""):
        self.retires.append((name, reason))
        return True

    def snapshot(self):
        return {"managed": [], "pending_spawns": 0,
                "spawn_eta_s": None, "spawn_cmd_set": False}


def test_autoscaler_step_dedups_holds_and_executes():
    clk = FakeClock(100.0)
    reg = ReplicaRegistry(_mpolicy())
    reg.add("a", "http://h:1")
    reg.add("b", "http://h:2")
    lc = _StubLifecycle()
    log = DecisionLog(cap=64, clock=clk)
    a = Autoscaler(reg, lc, policy=_policy(warmup_s=0.0, cooldown_s=10.0),
                   log=log, clock=clk)
    steady = _rollup(headroom=200.0)
    for _ in range(5):                  # identical holds: ONE ring event
        a.step(steady)
        clk.t += 1.0
    assert [e["kind"] for e in log.events()] == ["hold"]
    a.step(_rollup(fast=5.0))           # breach -> scale_out + spawn
    assert lc.spawns == ["burn_fast"]
    kinds = [e["kind"] for e in log.events()]
    assert kinds == ["hold", "scale_out"]
    clk.t += 20.0                       # past cooldown
    a.step(steady)                      # back to hold: recorded again
    assert [e["kind"] for e in log.events()] == ["hold", "scale_out",
                                                 "hold"]
    s = a.summary()
    assert s["min"] == 1 and s["max"] == 4 and s["enabled"]
    assert s["last"]["kind"] == "hold"
    snap = a.snapshot()
    assert snap["policy"]["cooldown_s"] == 10.0
    assert len(snap["decisions"]) == 3 and "lifecycle" in snap


# ---------------------------------------------------------------------------
# lifecycle manager (stub processes, fake prober)
# ---------------------------------------------------------------------------


class FakeProc:
    """Popen-like stub: poll/terminate/kill/wait against a settable
    returncode; pid points at nothing (os.getpgid fails -> the kill
    path falls back to .kill())."""

    def __init__(self, pid=4_190_000):
        self.pid = pid
        self.returncode = None
        self.terminated = False
        self.killed = False

    def poll(self):
        return self.returncode

    def terminate(self):
        self.terminated = True
        self.returncode = -15

    def kill(self):
        self.killed = True
        self.returncode = -9

    def wait(self, timeout=None):
        return self.returncode


def _lifecycle(clk, reg, events, *, prober, spawner=None, **kw):
    return ReplicaLifecycle(
        reg, spawn_cmd="serve --name {name} --port {port}",
        spawn_timeout_s=30.0, drain_timeout_s=1.0,
        record=lambda kind, **f: events.append((kind, f)),
        clock=clk, spawner=spawner or (lambda cmd: FakeProc()),
        prober=prober, **kw)


def test_lifecycle_spawn_admit_and_eta():
    async def run():
        clk = FakeClock(100.0)
        reg = ReplicaRegistry(_mpolicy())
        events = []
        seen = []

        async def prober(url):
            seen.append(url)
            clk.t += 4.0                # spawn takes 4s on the fake clock
            return True

        lc = _lifecycle(clk, reg, events, prober=prober)
        name = lc.spawn(reason="burn_fast")
        assert name == "scale-1" and lc.pending_count() == 1
        # cold-start ETA before any completed spawn: the default
        assert lc.pending_spawn_eta() == int(DEFAULT_SPAWN_ETA_S)
        await asyncio.sleep(0)          # let the admission task run
        await asyncio.sleep(0)
        assert lc.pending_count() == 0 and lc.is_managed("scale-1")
        assert reg.names() == ["scale-1"]       # admitted AFTER healthy
        assert [k for k, _ in events] == ["spawned", "admitted"]
        assert events[1][1]["spawn_s"] == 4.0
        assert seen and seen[0].startswith("http://127.0.0.1:")
        # next spawn's ETA comes from the completed spawn's duration
        lc.spawn(reason="headroom_low")
        assert lc.pending_spawn_eta() == 4
        await lc.close()
    asyncio.run(run())


def test_lifecycle_spawn_timeout_kills_and_drops():
    async def run():
        clk = FakeClock(0.0)
        reg = ReplicaRegistry(_mpolicy())
        events = []
        proc = FakeProc()

        async def prober(url):
            clk.t += 31.0               # blow the spawn deadline
            return False

        lc = _lifecycle(clk, reg, events, prober=prober,
                        spawner=lambda cmd: proc)
        lc.spawn(reason="burn_fast")
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        assert [k for k, _ in events] == ["spawned", "spawn_failed"]
        assert proc.killed and not lc.managed_names()
        assert reg.names() == []        # never admitted
    asyncio.run(run())


def test_lifecycle_retire_cordons_drains_reaps():
    async def run():
        clk = FakeClock(0.0)
        reg = ReplicaRegistry(_mpolicy())
        events = []
        proc = FakeProc()

        async def prober(url):
            return True

        lc = _lifecycle(clk, reg, events, prober=prober,
                        spawner=lambda cmd: proc)
        lc.spawn()
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        rep = reg.get("scale-1")
        assert rep is not None and rep.routable()
        assert lc.retire("scale-1", reason="headroom_high")
        # cordon lands IMMEDIATELY: no new routing while the drain runs
        assert not rep.routable() and rep.try_acquire() is None
        assert rep.snapshot()["state"] == "draining"
        assert not lc.retire("scale-1")         # idempotent
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        assert proc.terminated                  # SIGTERM, not SIGKILL
        assert [k for k, _ in events] == ["spawned", "admitted", "retire",
                                          "reaped"]
        assert events[3][1]["forced"] is False
        assert reg.names() == [] and not lc.managed_names()
        assert lc.retire("ghost") is False      # unmanaged name guarded
    asyncio.run(run())


def test_lifecycle_sweep_reaps_unexpected_death():
    async def run():
        clk = FakeClock(0.0)
        reg = ReplicaRegistry(_mpolicy())
        events = []
        proc = FakeProc()

        async def prober(url):
            return True

        lc = _lifecycle(clk, reg, events, prober=prober,
                        spawner=lambda cmd: proc)
        lc.spawn()
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        assert reg.names() == ["scale-1"]
        assert lc.sweep() == []                 # alive: nothing to reap
        proc.returncode = -9                    # kill -9 from outside
        assert lc.sweep() == ["scale-1"]
        assert events[-1][0] == "died"
        assert events[-1][1]["exit_code"] == -9
        # removed from routing: gauges retract, below-min sees the hole
        assert reg.names() == [] and not lc.managed_names()
    asyncio.run(run())


def test_lifecycle_without_template_declines_to_spawn():
    reg = ReplicaRegistry(_mpolicy())
    events = []
    lc = ReplicaLifecycle(reg, spawn_cmd=None,
                          record=lambda k, **f: events.append(k))
    assert lc.spawn(reason="burn_fast") is None
    assert events == [] and not lc.managed_names()
    assert lc.snapshot()["spawn_cmd_set"] is False


# ---------------------------------------------------------------------------
# router integration: cold-start Retry-After
# ---------------------------------------------------------------------------


def test_no_replica_503_carries_spawn_eta_retry_after():
    from cake_tpu.fleet.router import FleetRouter
    reg = ReplicaRegistry(_mpolicy())
    router = FleetRouter(reg, autoscale=True)
    assert router.autoscaler is not None and router.lifecycle is not None
    assert router.autoscaler.policy.enabled      # flag wins over env knob
    # an in-flight scale-out: the honest wait is the spawn ETA, not the
    # backlog formula
    clk = FakeClock(100.0)
    router.lifecycle._clock = clk
    router.lifecycle._managed["scale-1"] = ManagedReplica(
        "scale-1", 18080, FakeProc(), spawned_at=97.0)
    resp = router._no_replica()
    assert resp.status == 503
    eta = int(resp.headers["Retry-After"])
    assert eta == int(DEFAULT_SPAWN_ETA_S - 3.0)  # 3s already elapsed
    assert json.loads(resp.body)["scale_out_pending"] is True
    # no pending spawn: back to the backlog-proportional hint
    router.lifecycle._managed.clear()
    resp = router._no_replica()
    assert "scale_out_pending" not in json.loads(resp.body)
    assert int(resp.headers["Retry-After"]) >= 1
