"""Crash-only serve engine (ISSUE 8): supervised recovery from injected
step failures. The acceptance pins: a mid-generation step crash with
concurrent requests costs exactly one rebuild and every request finishes
bit-identical (greedy) to an uninjected run; a poison request 500s alone
while the pool survives; rebuild-budget exhaustion degrades honestly
(typed 503 + /health engine block + restore-loop revival); the watchdog
flags a stalled step. Wall-clock-sensitive cases are marked `slow` to
protect the tier-1 870s budget."""
import time

import jax.numpy as jnp
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import (EngineDown, PoisonedRequest,
                            RequestDeadlineExceeded, ServeEngine)
from cake_tpu.serve import faults
from cake_tpu.serve.supervisor import classify, fingerprint

GREEDY = SamplingConfig(temperature=0.0)
CTX = 256

P_A = [3, 17, 42, 99, 7]
P_B = [8, 8, 1, 30]
P_C = [100, 2, 5, 9, 11, 40]
POISON_TOK = 77
P_POISON = [8, POISON_TOK, 1, 30]


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    """Fault plans are process-global: never leak one into the next test."""
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# units: no model required
# ---------------------------------------------------------------------------


def test_fault_plan_parsing():
    inj = faults.parse_plan("raise_on_step=6;times=2;kind=oom")
    assert (inj.raise_on_step, inj.times, inj.kind) == (6, 2, "oom")
    inj = faults.parse_plan("poison_token=77;poison_after_ops=4")
    assert (inj.poison_token, inj.poison_after_ops) == (77, 4)
    inj = faults.parse_plan("stall_on_step=3;stall_step_ms=250")
    assert (inj.stall_on_step, inj.stall_step_ms) == (3, 250.0)
    with pytest.raises(ValueError):
        faults.parse_plan("raise_on_step")          # no value
    with pytest.raises(ValueError):
        faults.parse_plan("unknown_key=1")
    with pytest.raises(ValueError):
        faults.parse_plan("kind=sharks")
    with pytest.raises(ValueError):
        faults.parse_plan("raise_on_step=1,raise_on_step=2")  # one clause


def test_fault_plan_step_semantics():
    """raise_on_step counts scheduler DISPATCHES (1-based) and times=K
    kills K consecutive ones — the counter survives the rebuilds it
    provokes, which is what makes multi-crash drills deterministic."""

    class _R:
        prompt_ids = [1, 2]
        id = "r"

    inj = faults.parse_plan("raise_on_step=2;times=2")
    inj.on_decode([_R()])                           # op 1: clean
    for _ in range(2):                              # ops 2, 3: crash
        with pytest.raises(faults.InjectedFault):
            inj.on_decode([_R()])
    inj.on_decode([_R()])                           # op 4: clean again
    assert inj.ops == 4


def test_classify_kinds():
    assert classify(faults.InjectedFault("x", fault_kind="oom")) == "oom"
    assert classify(faults.InjectedFault("x", fault_kind="device")) \
        == "device"
    assert classify(MemoryError("small")) == "oom"
    assert classify(RuntimeError("RESOURCE_EXHAUSTED: hbm")) == "oom"
    assert classify(ValueError("bad shape")) == "internal"

    class XlaRuntimeError(RuntimeError):
        pass

    assert classify(XlaRuntimeError("device halted")) == "device"


def test_typed_errors_and_fingerprint():
    e = EngineDown("down", retry_after_s=7)
    assert isinstance(e, RuntimeError) and e.retry_after_s == 7
    d = RequestDeadlineExceeded(12.5, 10.0)
    assert "deadline" in str(d) and d.age_s == 12.5
    assert fingerprint([1, 2, 3]) == fingerprint([1, 2, 3])
    assert fingerprint([1, 2, 3]) != fingerprint([1, 2, 4])


# ---------------------------------------------------------------------------
# engine-level recovery (tiny CPU model)
# ---------------------------------------------------------------------------

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = TextModel(tiny_config("llama"), dtype=jnp.float32,
                           max_cache_len=CTX)
    return _MODEL


@pytest.fixture(scope="module")
def model():
    return _model()


def _ref(model, prompt, n, sampling=GREEDY):
    toks, _ = model.generate(list(prompt), max_new_tokens=n,
                             sampling=sampling)
    return toks


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_step_crash_one_rebuild_bit_identical(model):
    """THE acceptance pin: an injected mid-generation step crash with 3
    concurrent requests costs exactly one rebuild-by-replay and every
    request's greedy output equals the uninjected sequential run
    token-for-token."""
    plans = ((P_A, 12), (P_B, 10), (P_C, 9))
    refs = [_ref(model, p, n) for p, n in plans]
    faults.install("raise_on_step=6;kind=device")
    eng = ServeEngine(model, slots=4, max_queue=8, ctx_len=CTX)
    try:
        rs = [eng.submit(p, max_new_tokens=n, sampling=GREEDY)
              for p, n in plans]
        assert all(r.wait(180) for r in rs)
        for r, ref in zip(rs, refs):
            assert "error" not in r.result, r.result.get("error")
            assert r.result["tokens"] == ref
        assert eng.supervisor.rebuild_count == 1
        assert eng.health()["rebuilds"] == 1
        assert eng.health()["last_failure"]["kind"] == "device"
        # the pool is fully live afterwards: a fresh request still works
        r = eng.submit(P_A, max_new_tokens=6, sampling=GREEDY)
        assert r.wait(120) and r.result["tokens"] == refs[0][:6]
    finally:
        faults.clear()
        eng.close()


def test_poison_request_fails_alone_pool_survives(model):
    """Poison isolation: a request whose tokens crash every dispatch that
    touches them is attributed via the rebuild's solo replay (suspects
    last), fails with a typed PoisonedRequest, and is quarantined — the
    other requests complete bit-identically after at most 2 rebuilds."""
    ref_a = _ref(model, P_A, 12)
    ref_c = _ref(model, P_C, 9)
    # arms after 4 decode dispatches, so the poison request admits
    # cleanly and corrupts the pool MID-generation
    faults.install(f"poison_token={POISON_TOK};poison_after_ops=4")
    eng = ServeEngine(model, slots=4, max_queue=8, ctx_len=CTX)
    try:
        r_a = eng.submit(P_A, max_new_tokens=12, sampling=GREEDY)
        r_p = eng.submit(P_POISON, max_new_tokens=12, sampling=GREEDY)
        r_c = eng.submit(P_C, max_new_tokens=9, sampling=GREEDY)
        assert all(r.wait(180) for r in (r_a, r_p, r_c))
        assert isinstance(r_p.result.get("error"), PoisonedRequest)
        assert r_a.result["tokens"] == ref_a
        assert r_c.result["tokens"] == ref_c
        assert eng.supervisor.rebuild_count <= 2
        assert eng.health()["quarantined"] == 1
        # the fingerprint is quarantined: a retry of the same prompt is
        # refused up front instead of crash-looping the pool again
        with pytest.raises(PoisonedRequest):
            eng.submit(P_POISON, max_new_tokens=4, sampling=GREEDY)
        # ...but other traffic still flows
        r = eng.submit(P_C, max_new_tokens=5, sampling=GREEDY)
        assert r.wait(120) and r.result["tokens"] == ref_c[:5]
    finally:
        faults.clear()
        eng.close()


@pytest.mark.slow
def test_budget_exhaustion_down_then_restore(model):
    """Crash-loop breaker: past CAKE_ENGINE_REBUILDS the engine goes
    honestly DOWN — live requests released with the typed EngineDown,
    submits refused with a Retry-After hint, /health carries the engine
    failure block — and the restore loop revives it once a trial step
    succeeds (the injected fault plan is exhausted by then)."""
    ref = _ref(model, P_A, 12)
    faults.install("raise_on_step=3;times=2;kind=device")
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                      rebuild_budget=1, restore_interval_s=0.05)
    try:
        r = eng.submit(P_A, max_new_tokens=24, sampling=GREEDY)
        assert r.wait(180)
        assert isinstance(r.result.get("error"), EngineDown)
        assert eng.supervisor.is_down()
        info = eng.supervisor.down_info()
        assert "down_for_s" in info and "last_failure" in info
        with pytest.raises(EngineDown) as ei:
            eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
        assert ei.value.retry_after_s >= 1
        # revival: the restore loop's trial step succeeds (plan spent)
        deadline = time.monotonic() + 60
        while eng.supervisor.is_down() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not eng.supervisor.is_down(), "restore loop never revived"
        r2 = eng.submit(P_A, max_new_tokens=12, sampling=GREEDY)
        assert r2.wait(120)
        assert r2.result["tokens"] == ref
    finally:
        faults.clear()
        eng.close()


@pytest.mark.slow
def test_watchdog_flags_stalled_step(model):
    """Wedge watchdog: a dispatch stalled past CAKE_STEP_WATCHDOG_S flags
    the engine wedged (visible in health while stuck, counted in
    cake_serve_engine_wedges_total) WITHOUT killing it — when the stall
    releases, the request completes bit-identically and the flag clears
    (the gray-failure contract). The engine is warmed first so the only
    long dispatch is the injected stall, not a first-bucket compile."""
    from cake_tpu.obs import SERVE_ENGINE_WEDGES
    ref = _ref(model, P_A, 15)
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                      step_watchdog_s=0.25)
    try:
        warm = eng.submit(P_A, max_new_tokens=15, sampling=GREEDY)
        assert warm.wait(120) and warm.result["tokens"] == ref
        w0 = SERVE_ENGINE_WEDGES.value()
        faults.install("stall_on_step=3;stall_step_ms=1500")
        r = eng.submit(P_A, max_new_tokens=15, sampling=GREEDY)
        saw_wedge = False
        deadline = time.monotonic() + 60
        while not r.done.is_set() and time.monotonic() < deadline:
            saw_wedge = saw_wedge or eng.health()["wedged"]
            time.sleep(0.01)
        assert saw_wedge, "watchdog never flagged the stalled dispatch"
        assert SERVE_ENGINE_WEDGES.value() > w0
        assert r.wait(60)
        assert r.result["tokens"] == ref        # slow, not wrong
        assert not eng.health()["wedged"]       # flag cleared on return
        assert eng.supervisor.rebuild_count == 0
    finally:
        faults.clear()
        eng.close()


@pytest.mark.slow
def test_request_deadline_cancels_admitted_slot(model):
    """CAKE_REQUEST_DEADLINE_S: an ADMITTED request whose total age
    passes the deadline is cancelled with the typed 504 error and
    counted — the queue-deadline sweep alone never covers decoding."""
    from cake_tpu.obs import SERVE_REQUEST_TIMEOUTS
    c0 = SERVE_REQUEST_TIMEOUTS.value()
    # warm the (B=4 pool, nb=1) decode executable on a deadline-free
    # engine first: an in-iteration XLA compile (~10s cold on this box)
    # would otherwise eat the whole deadline and cancel BOTH requests
    warm_eng = ServeEngine(model, slots=4, max_queue=4, ctx_len=CTX)
    try:
        w = warm_eng.submit(P_B, max_new_tokens=3, sampling=GREEDY)
        assert w.wait(180)
    finally:
        warm_eng.close()
    # delay_ms paces decode deterministically: 220 tokens can never beat
    # a 1.5s deadline at 50ms/iteration, while the 3-token follow-up
    # finishes in a couple hundred ms regardless of machine load
    faults.install("delay_ms=50")
    eng = ServeEngine(model, slots=4, max_queue=4, ctx_len=CTX,
                      request_deadline_s=1.5)
    try:
        r = eng.submit(P_A, max_new_tokens=220, sampling=GREEDY)
        assert r.wait(120)
        err = r.result.get("error")
        assert isinstance(err, RequestDeadlineExceeded), err
        assert len(r.tokens) < 220              # budget was NOT decoded out
        assert SERVE_REQUEST_TIMEOUTS.value() > c0
        # the slot is reusable immediately
        r2 = eng.submit(P_B, max_new_tokens=3, sampling=GREEDY)
        assert r2.wait(120)
        assert "error" not in r2.result, r2.result.get("error")
        assert r2.result["tokens"] == _ref(model, P_B, 3)
    finally:
        faults.clear()
        eng.close()


def test_abort_prefill_wipe_failure_chains_not_masks(model, monkeypatch):
    """Satellite: a wipe failure during prefill crash handling must not
    substitute the original error — the step failure stays primary with
    the wipe failure chained as __cause__, and the supervisor still
    recovers the engine."""
    orig = RuntimeError("original prefill boom")

    def bad_prefill(layers, slot, ids, pos0):
        raise orig

    real_release = model.slot_release
    calls = {"n": 0}

    def bad_release(layers, slot):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("wipe boom")
        return real_release(layers, slot)

    monkeypatch.setattr(model, "prefill_chunk", bad_prefill)
    monkeypatch.setattr(model, "slot_release", bad_release)
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX)
    try:
        r = eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
        assert r.wait(120)
        err = r.result.get("error")
        assert err is orig                      # first exception wins
        # the request is released BEFORE the supervisor runs (its waiter
        # must never block on recovery), so poll for the rebuild
        deadline = time.monotonic() + 30
        while eng.supervisor.rebuild_count < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        # the supervisor rebuilt past the poisoned pool state
        assert eng.supervisor.rebuild_count >= 1
        lf = eng.health()["last_failure"]
        assert "original prefill boom" in lf["error"]
        monkeypatch.undo()
        r2 = eng.submit(P_B, max_new_tokens=3, sampling=GREEDY)
        assert r2.wait(120)
        assert r2.result["tokens"] == _ref(model, P_B, 3)
    finally:
        eng.close()


def test_dead_engine_submit_is_typed(model):
    """Satellite: submit on a dead/closed engine raises the typed
    EngineDown (503 + Retry-After at the API), not a bare RuntimeError."""
    eng = ServeEngine(model, slots=1, max_queue=2, ctx_len=CTX)
    eng.close()
    with pytest.raises(EngineDown) as ei:
        eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
    assert ei.value.retry_after_s >= 1


# ---------------------------------------------------------------------------
# API mapping
# ---------------------------------------------------------------------------


def test_typed_error_response_mapping():
    """Every typed engine failure answers its documented status on BOTH
    chat paths (the SSE path refuses via the same helper before
    committing to a 200)."""
    from cake_tpu.api.text import _typed_error_response
    from cake_tpu.serve import QueueDeadlineExceeded

    r = _typed_error_response(EngineDown("down", retry_after_s=9))
    assert r.status == 503 and r.headers["Retry-After"] == "9"
    r = _typed_error_response(QueueDeadlineExceeded(3.0))
    assert r.status == 503 and "Retry-After" in r.headers
    assert _typed_error_response(
        RequestDeadlineExceeded(5.0, 4.0)).status == 504
    assert _typed_error_response(PoisonedRequest("poisoned")).status == 500
    assert _typed_error_response(ValueError("nope")) is None


def test_api_down_engine_503_json_and_sse(model):
    """A down engine answers 503 + Retry-After on the JSON path AND the
    streaming path — the stream must refuse BEFORE committing to a 200
    SSE response (same bug class PR 4 fixed for cluster degradation)."""
    import asyncio
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import ApiState, create_app

    class TinyTok:
        def encode(self, text):
            return [3 + (sum(w.encode()) % 200)
                    for w in text.split()][:24] or [3]

        def decode(self, ids):
            return "".join(f"<{i}>" for i in ids)

    eng = ServeEngine(model, slots=1, max_queue=2, ctx_len=CTX)
    eng.close()                                 # dead => typed EngineDown
    st = ApiState(model=model, tokenizer=TinyTok(), model_id="tiny")
    st.engine = eng

    async def scenario():
        app = create_app(st)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            for stream in (False, True):
                resp = await client.post("/v1/chat/completions", json={
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4, "stream": stream})
                assert resp.status == 503, await resp.text()
                assert "Retry-After" in resp.headers
                assert resp.content_type == "application/json"  # no SSE
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(scenario())
