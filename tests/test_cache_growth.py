"""Cache-length bucketing: decode must attend only over the allocated
bucket, growing it bucket-by-bucket with bit-identical results to a
full-length cache (the single-chip perf lever from round-1 review; the
reference instead trims the cache to actual length per step,
ref: models/common/cache.rs:163-210).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.models.common.cache import (grow_cache, grow_layer_kv,
                                          init_cache, init_layer_cache,
                                          update_kv_cache)
from cake_tpu.ops.sampling import SamplingConfig


def _greedy_ref(model, prompt, n_new):
    """Reference decode over a FULL-length cache, one token at a time."""
    cache = model.new_cache()        # full max_cache_len buffers
    logits, cache = model.prefill(cache, prompt)
    toks = [int(np.argmax(np.asarray(logits[0])))]
    while len(toks) < n_new:
        logits, cache = model.decode_logits(cache, toks[-1])
        toks.append(int(np.argmax(np.asarray(logits[0]))))
    return toks


@pytest.mark.parametrize(
    "fam",
    [  # tier-1 keeps one family; the rest ride tier-2 under the 870s cap
        "llama",
        pytest.param("gemma3", marks=pytest.mark.slow),
        pytest.param("qwen3_5", marks=pytest.mark.slow),
    ],
)
def test_generate_growth_parity(fam):
    """Greedy generate (bucketed, growing cache) == full-cache decode."""
    cfg = tiny_config(fam, eos_token_id=255)   # improbable EOS under argmax
    model = TextModel(cfg, dtype=jnp.float32, max_cache_len=64)
    prompt = list(np.random.default_rng(3).integers(0, 200, size=5))
    # chunk=8: initial bucket 32, grows to 64 mid-generation
    out, _ = model.generate(prompt, max_new_tokens=24,
                            sampling=SamplingConfig(temperature=0.0), chunk=8)
    ref = _greedy_ref(model, prompt, len(out))
    assert out == ref


def test_generate_growth_swa():
    """SWA ring smaller than the bucket: growth leaves the ring alone."""
    cfg = tiny_config("mistral", sliding_window=8, eos_token_id=255)
    model = TextModel(cfg, dtype=jnp.float32, max_cache_len=64)
    prompt = [1, 2, 3, 4, 5]
    out, _ = model.generate(prompt, max_new_tokens=24,
                            sampling=SamplingConfig(temperature=0.0), chunk=8)
    ref = _greedy_ref(model, prompt, len(out))
    assert out == ref


def test_grow_layer_kv_ring_remap():
    """Growing a wrapped ring re-homes entries at pos % new_size."""
    cfg = tiny_config("mistral", sliding_window=48)
    spec = cfg.layer_spec(0)
    rng = np.random.default_rng(0)
    k_all = jnp.asarray(rng.standard_normal((1, 40, 2, 16)), jnp.float32)

    # write positions 0..39 into a 32-slot ring (wraps), then grow to 48
    small = init_layer_cache(cfg, spec, 1, 32, jnp.float32)
    for p in range(40):
        small = update_kv_cache(small, k_all[:, p:p + 1], k_all[:, p:p + 1],
                                jnp.asarray(p, jnp.int32))
    grown = grow_layer_kv(small, 48)

    # reference: same writes straight into a 48-slot ring
    big = init_layer_cache(cfg, spec, 1, 48, jnp.float32)
    for p in range(40):
        big = update_kv_cache(big, k_all[:, p:p + 1], k_all[:, p:p + 1],
                              jnp.asarray(p, jnp.int32))

    # a 32-slot ring only retains the last 32 positions; those must land in
    # their % 48 slots, all other grown slots must be empty
    pos_g, pos_b = np.asarray(grown["pos"])[0], np.asarray(big["pos"])[0]
    for p in range(8, 40):                       # survivors of the 32-ring
        assert pos_g[p % 48] == p
        np.testing.assert_array_equal(np.asarray(grown["k"])[0, p % 48],
                                      np.asarray(big["k"])[0, p % 48])
    assert (pos_g >= 0).sum() == 32
    assert grown["k"].shape[1] == 48


def test_grow_cache_full_and_linear_layers():
    cfg = tiny_config("qwen3_5")                 # 3 linear : 1 full hybrid
    cache = init_cache(cfg, 1, 32, jnp.float32)
    grown = grow_cache(cfg, cache, 64)
    for i in range(cfg.num_hidden_layers):
        lc = grown["layers"][i]
        if cfg.layer_spec(i).kind == "linear":
            assert "state" in lc and lc["conv"].shape == \
                cache["layers"][i]["conv"].shape
        else:
            assert lc["k"].shape[1] == 64
    # growth is idempotent at the same size
    again = grow_cache(cfg, grown, 64)
    assert again["layers"][-1]["k"].shape[1] == 64
