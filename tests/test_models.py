"""Model machinery tests: every dense family's block loads and runs with a
tiny synthetic config (mirrors ref tests/unit_tests/test_blocks.rs), plus
the core KV-cache invariant: incremental decode logits == full-prefill
logits at every position.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, init_params, tiny_config
from cake_tpu.models.common.cache import init_cache, update_kv_cache
from cake_tpu.ops.sampling import SamplingConfig

DENSE_FAMILIES = ("llama", "qwen2", "qwen3", "phi4", "mistral", "gemma3",
                  "falcon3", "olmo2", "exaone4", "qwen3_moe")


def make_model(fam, **over):
    cfg = tiny_config(fam, **over)
    return TextModel(cfg, dtype=jnp.float32, max_cache_len=64)


@pytest.mark.parametrize("fam", DENSE_FAMILIES)
def test_prefill_decode_parity(fam):
    """Prefill(t0..tn) must equal prefill(t0..tk) + decode(tk+1..tn)
    — exercises cache scatter, masking, rope offsets, every norm style."""
    model = make_model(fam)
    toks = list(np.random.default_rng(0).integers(0, 255, size=9))

    logits_full, _ = model.prefill(model.new_cache(), toks)

    cache = model.new_cache()
    _, cache = model.prefill(cache, toks[:5])
    logits_inc = None
    for t in toks[5:]:
        logits_inc, cache = model.decode_logits(cache, int(t))
    np.testing.assert_allclose(np.asarray(logits_inc), np.asarray(logits_full),
                               atol=2e-3, rtol=1e-3)


def test_padding_invariance():
    """Bucketed prefill: logits must not depend on pad amount."""
    model = make_model("llama")
    toks = [1, 2, 3, 4, 5]
    l1, _ = model.prefill(model.new_cache(), toks)          # bucket 32
    # same tokens hand-padded to a LARGER bucket via the raw compiled entry
    padded = np.zeros((1, 64), np.int32)
    padded[0, :5] = toks
    l2, _ = model._prefill(model.params, jnp.asarray(padded), model.new_cache(),
                           jnp.asarray(0, jnp.int32), jnp.asarray(5, jnp.int32),
                           flash_mode="fresh")
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=2e-3,
                               rtol=1e-3)
    # chunked prefill across two calls must also agree
    cache = model.new_cache()
    _, cache = model.prefill(cache, toks[:2])
    l3, _ = model.prefill(cache, toks[2:], pos0=2)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l3), atol=2e-3,
                               rtol=1e-3)


def test_prefill_past_cache_end_raises():
    model = make_model("llama")  # max_cache_len 64
    cache = model.new_cache()
    _, cache = model.prefill(cache, list(range(1, 33)))
    with pytest.raises(ValueError, match="prefill past cache end"):
        model.prefill(cache, list(range(1, 33)), pos0=40)


def test_tied_head_worker_partition_has_embed():
    cfg = tiny_config("gemma3")  # tied embeddings
    p = init_params(cfg, jax.random.PRNGKey(0), jnp.float32,
                    layer_range=(2, 4))
    assert "embed_tokens" in p and "norm" in p  # head needs the tied table


def test_sliding_window_ring():
    """SWA ring cache: old positions must be evicted and invisible."""
    cfg = tiny_config("mistral", sliding_window=8)
    model = TextModel(cfg, dtype=jnp.float32, max_cache_len=64)
    toks = list(np.random.default_rng(1).integers(0, 255, size=20))
    # incremental decode across >window tokens: ring wraps several times
    cache = model.new_cache()
    _, cache = model.prefill(cache, toks[:4])
    for t in toks[4:]:
        logits, cache = model.decode_logits(cache, int(t))
    # reference computation: full prefill (mask enforces the same window)
    logits_full, _ = model.prefill(model.new_cache(), toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits_full),
                               atol=2e-3, rtol=1e-3)
    # ring buffer is physically window-sized
    assert cache["layers"][0]["k"].shape[1] == 8


def test_generate_streams_and_stops():
    model = make_model("llama")
    seen = []
    toks, stats = model.generate([1, 2, 3], max_new_tokens=12,
                                 sampling=SamplingConfig(temperature=0.0),
                                 on_token=seen.append, chunk=4)
    assert 1 <= len(toks) <= 12
    assert [t.id for t in seen] == toks
    assert stats["decode_tokens"] == len(toks) - 1
    # greedy must be deterministic
    toks2, _ = model.generate([1, 2, 3], max_new_tokens=12,
                              sampling=SamplingConfig(temperature=0.0), chunk=4)
    assert toks == toks2


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_decode_until_matches_chunked():
    """The single-device-call while_loop decode (non-streaming path) must
    emit exactly the chunked streaming path's tokens, greedy and sampled,
    including EOS stops and cache-bucket growth."""
    import jax
    for arch in ("llama", "qwen3_moe"):
        model = make_model(arch)
        for scfg in (SamplingConfig(temperature=0.0),
                     SamplingConfig(temperature=0.9, top_k=16,
                                    repeat_penalty=1.05)):
            rng = jax.random.PRNGKey(11)
            seen = []
            toks_stream, _ = model.generate(
                [1, 2, 3], max_new_tokens=40, sampling=scfg,
                on_token=seen.append, chunk=4, rng=rng)
            toks_until, stats = model.generate(
                [1, 2, 3], max_new_tokens=40, sampling=scfg, rng=rng)
            assert toks_until == toks_stream
            assert stats["decode_tokens"] == len(toks_until) - 1
    # max_new_tokens=1 short-circuits before the device call
    toks1, _ = model.generate([1, 2], max_new_tokens=1)
    assert len(toks1) == 1
    # bucket-growth segmentation: tiny segments force several device calls
    model = make_model("llama")
    try:
        model.UNTIL_SEGMENT = 4
        rng = jax.random.PRNGKey(3)
        seen = []
        toks_stream, _ = model.generate([1, 2, 3], max_new_tokens=30,
                                        on_token=seen.append, chunk=4, rng=rng)
        toks_until, _ = model.generate([1, 2, 3], max_new_tokens=30, rng=rng)
        assert toks_until == toks_stream
    finally:
        del model.UNTIL_SEGMENT


def test_streaming_blocking_budget_parity_at_cache_end():
    """Near max_cache_len the streaming path must emit exactly the blocking
    path's tokens: full chunks while they fit, then the sub-chunk remainder
    flushed through the while_loop program (a chunk-sized slack clamp here
    used to make the two chat endpoints disagree)."""
    model = make_model("llama")   # max_cache_len=64
    prompt = list(range(1, 41))   # 40 tokens, room for 23 more + first
    for chunk in (16, 8):
        rng = jax.random.PRNGKey(2)
        stream, _ = model.generate(prompt, max_new_tokens=30,
                                   on_token=lambda t: None, chunk=chunk,
                                   rng=rng)
        block, _ = model.generate(prompt, max_new_tokens=30, rng=rng)
        assert stream == block
        assert len(block) == 24   # 1 + (64 - 40 - 1), cache-capped


def test_streaming_pipeline_depth_equivalence():
    """STREAM_DEPTH dispatch-ahead must not change emitted tokens vs
    depth-1 (chunks chain off the device carry either way)."""
    model = make_model("qwen3")
    rng = jax.random.PRNGKey(5)
    base, _ = model.generate([4, 5], max_new_tokens=20,
                             on_token=lambda t: None, chunk=4, rng=rng)
    try:
        model.STREAM_DEPTH = 1
        one, _ = model.generate([4, 5], max_new_tokens=20,
                                on_token=lambda t: None, chunk=4, rng=rng)
    finally:
        del model.STREAM_DEPTH
    assert base == one


def test_generate_eos_stops():
    model = make_model("llama")
    # token 2 is EOS in tiny_config; force it via a cooked lm_head bias:
    # instead just check that if EOS appears the stream ends with it
    toks, _ = model.generate([1], max_new_tokens=50,
                             sampling=SamplingConfig(temperature=1.0))
    if any(model.cfg.is_eos(t) for t in toks):
        assert model.cfg.is_eos(toks[-1])


def test_moe_runs_and_routes():
    model = make_model("qwen3_moe")
    logits, _ = model.prefill(model.new_cache(), [1, 2, 3, 4])
    assert logits.shape == (1, model.cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_worker_layer_range_params():
    """Partial param init: a worker holding layers 1..3 has no embed/head."""
    cfg = tiny_config("llama")
    p = init_params(cfg, jax.random.PRNGKey(0), jnp.float32, layer_range=(1, 3))
    assert "embed_tokens" not in p and "lm_head" not in p and "norm" not in p
    assert len(p["layers"]) == 2
    # cache for the same range
    c = init_cache(cfg, 1, 32, jnp.float32, layer_range=(1, 3))
    assert len(c["layers"]) == 2


def test_update_kv_cache_wrap_and_drop():
    lc = {
        "k": jnp.zeros((1, 4, 1, 2)), "v": jnp.zeros((1, 4, 1, 2)),
        "pos": jnp.full((1, 4), -1, jnp.int32),
    }
    k_new = jnp.arange(12, dtype=jnp.float32).reshape(1, 6, 1, 2)
    out = update_kv_cache(lc, k_new, k_new, jnp.asarray(0), valid_len=jnp.asarray(6))
    # 6 entries into ring of 4: positions 2..5 survive in slots 2,3,0,1
    assert out["pos"][0].tolist() == [4, 5, 2, 3]
    # valid_len drops tail: only first 2 of 6 written
    out2 = update_kv_cache(lc, k_new, k_new, jnp.asarray(0), valid_len=jnp.asarray(2))
    assert out2["pos"][0].tolist() == [0, 1, -1, -1]
