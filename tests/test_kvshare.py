"""Fleet-shared KV tier (ISSUE 20): wire-blob bit-identity, peer
directory codec + registry retraction, cross-replica prefix export/import
with greedy parity, and live stream blob migration splice parity.

Engine tests reuse test_paged's pool shape (12 blocks x 8 tokens, chunk
16, ctx 128) so the paged executables compile once per model and are
shared across modules — the tier-1 suite is timeout-capped."""
import time

import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.fleet.kvshare import (KVBlobMismatch, MAGIC, VERSION,
                                    decode_blob, encode_blob,
                                    encode_directory, parse_directory)
from cake_tpu.fleet.registry import MembershipPolicy, Replica
from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import ServeEngine

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16
BT = 8
BLOCKS = 12


# ---------------------------------------------------------------------------
# wire format: pure codec, no model
# ---------------------------------------------------------------------------


def _sample_payload():
    header = {"kind": "prefix", "units": 2, "flag": True}
    arrays = {
        "tokens": np.arange(32, dtype=np.int32),
        "layers/0/k": np.linspace(-1, 1, 96).astype(np.float32)
                        .reshape(4, 8, 3),
        "layers/0/pos": np.arange(32, dtype=np.int32).reshape(4, 8),
        "snap/0/0": np.ones((2, 5), np.float64) * 0.25,
    }
    return header, arrays


def test_blob_roundtrip_bit_identity():
    header, arrays = _sample_payload()
    data = encode_blob(header, arrays)
    assert data.startswith(MAGIC) and data[len(MAGIC)] == VERSION
    h2, a2 = decode_blob(data)
    for k in ("kind", "units", "flag"):
        assert h2[k] == header[k]
    assert set(a2) == set(arrays)
    for k, a in arrays.items():
        assert a2[k].dtype == a.dtype and a2[k].shape == a.shape
        assert a2[k].tobytes() == a.tobytes()       # bit identity


def test_blob_rejects_every_corruption_mode():
    header, arrays = _sample_payload()
    data = bytearray(encode_blob(header, arrays))
    with pytest.raises(KVBlobMismatch):
        decode_blob(bytes(data[:40]))               # truncated
    bad = bytes(data[:-1]) + bytes([data[-1] ^ 0x40])
    with pytest.raises(KVBlobMismatch):
        decode_blob(bad)                            # payload bit flip
    bad = b"X" + bytes(data[1:])
    with pytest.raises(KVBlobMismatch):
        decode_blob(bad)                            # magic
    bad = bytes(data[:len(MAGIC)]) + bytes([VERSION + 1]) \
        + bytes(data[len(MAGIC) + 1:])
    with pytest.raises(KVBlobMismatch):
        decode_blob(bad)                            # version skew


# ---------------------------------------------------------------------------
# peer directory: codec + registry mirror/retraction
# ---------------------------------------------------------------------------


def test_directory_codec_roundtrip_and_malformed():
    hdr = encode_directory([("http://a:1", ["aa", "bb"]),
                            ("http://b:2", ("cc",)),
                            ("http://c:3", []),     # nothing to advertise
                            ("", ["dd"])])          # no url
    peers = parse_directory(hdr)
    assert [(u, sorted(ks)) for u, ks in peers] == \
        [("http://a:1", ["aa", "bb"]), ("http://b:2", ["cc"])]
    assert "dd" not in {k for _, ks in peers for k in ks}
    assert encode_directory([]) is None
    assert encode_directory([("http://c:3", [])]) is None
    assert parse_directory("not json") == []
    assert parse_directory('{"p": "nope"}') == []


def test_registry_mirrors_and_retracts_inventory():
    rep = Replica("r0", "http://h:1", MembershipPolicy())
    assert rep.kv_inventory() == ()
    body = {"engine": {"alive": True, "slots": 2,
                       "kvshare": {"chains": ["aa", "bb", 7]}}}
    rep.observe_health(200, body)
    assert rep.kv_inventory() == ("aa", "bb")       # non-str dropped
    # stale probe: inventory retracted with the probe state — a peer
    # directory must never point a fetch at an unknown cache
    rep.observe_health(None, None)
    assert rep.kv_inventory() == ()
    rep.observe_health(200, body)
    assert rep.kv_inventory() == ("aa", "bb")
    rep.observe_health(200, {"engine": {"alive": False}})   # sick verdict
    assert rep.kv_inventory() == ()


# ---------------------------------------------------------------------------
# cross-replica prefix export/import + stream migration (tiny CPU llama)
# ---------------------------------------------------------------------------


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = TextModel(tiny_config("llama"), dtype=jnp.float32,
                           max_cache_len=CTX)
    return _MODEL


@pytest.fixture(scope="module")
def model():
    return _model()


def _engine(model, **kw):
    from cake_tpu.fleet.kvshare import KVShareReplica
    kw.setdefault("slots", 2)
    kw.setdefault("max_queue", 8)
    kw.setdefault("ctx_len", CTX)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("kv_blocks", BLOCKS)
    kw.setdefault("kv_block_tokens", BT)
    kw.setdefault("prefix_cache_mb", 8)
    eng = ServeEngine(model, **kw)
    eng.kv_share = KVShareReplica(eng)
    return eng


@pytest.fixture()
def engines(model):
    a, b = _engine(model), _engine(model)
    yield a, b
    a.close()
    b.close()


def _ref(model, prompt, n):
    toks, _ = model.generate(list(prompt), max_new_tokens=n,
                             sampling=GREEDY)
    return toks


SYS = [3 + (i * 7) % 200 for i in range(40)]        # 2 full share units


def test_prefix_blob_cross_replica_greedy_parity(model, engines):
    """Warm replica A, export its SYS chain, install into cold replica B:
    B's next admission splices the fetched blocks (prefix_hit_tokens) and
    the greedy body is bit-identical to the sequential reference — a
    fetched chain is indistinguishable from a locally-computed one."""
    eng_a, eng_b = engines
    ks_a, ks_b = eng_a.kv_share, eng_b.kv_share
    pa, pb = SYS + [9, 11], SYS + [77, 31]
    ra = eng_a.submit(pa, max_new_tokens=6, sampling=GREEDY)
    assert ra.wait(180)
    assert ra.result["tokens"] == _ref(model, pa, 6)
    # inventory mirror follows the cache version on the scheduler thread
    eng_a._wake.set()
    deadline = time.monotonic() + 10
    while not ks_a.health_view()["chains"] \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    chains = ks_a.health_view()["chains"]
    assert len(chains) == 2                 # 2 SYS units, newest first
    blob = ks_a.submit_job("export_prefix", chains[0], 30)
    assert blob is not None
    header, _ = decode_blob(blob)
    assert header["units"] == 2 and header["has_snap"] is False
    # unknown chain: honest None (the route answers 404)
    assert ks_a.submit_job("export_prefix", "ab" * 16, 30) is None
    res = ks_b.submit_job("import_prefix", blob, 30)
    assert res == {"installed_units": 2, "tokens": 32}
    # re-import dedupes instead of re-pinning
    res2 = ks_b.submit_job("import_prefix", blob, 30)
    assert res2["tokens"] == 32
    assert eng_b.prefix_cache.pinned == 2 * eng_b.prefix_cache.bpu
    rb = eng_b.submit(pb, max_new_tokens=6, sampling=GREEDY)
    assert rb.wait(180)
    assert rb.stats["prefix_hit_tokens"] == 32, \
        "imported chain did not splice"
    assert rb.result["tokens"] == _ref(model, pb, 6)
    eng_b.paged.alloc.check()               # allocator invariants hold


def test_prefix_import_rejects_foreign_pool(model, engines):
    """A blob whose pool signature does not match the importing replica
    raises the typed KVBlobMismatch (the route's 422) and leaves the
    cache untouched."""
    eng_a, eng_b = engines
    ra = eng_a.submit(SYS + [5], max_new_tokens=4, sampling=GREEDY)
    assert ra.wait(180)
    eng_a._wake.set()
    deadline = time.monotonic() + 10
    while not eng_a.kv_share.health_view()["chains"] \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    chain = eng_a.kv_share.health_view()["chains"][0]
    blob = eng_a.kv_share.submit_job("export_prefix", chain, 30)
    header, arrays = decode_blob(blob)
    header["pool"] = {"layers": "somewhere-else"}
    forged = encode_blob(header, arrays)
    with pytest.raises(KVBlobMismatch):
        eng_b.kv_share.submit_job("import_prefix", forged, 30)
    assert len(eng_b.prefix_cache._blocks) == 0
    with pytest.raises(KVBlobMismatch):
        eng_b.kv_share.submit_job("import_prefix", b"garbage", 30)


def test_stream_blob_migration_splice_parity(model, engines):
    """Park a live decode on A mid-stream (the fetch IS the migration
    signal), ship the blob to B, adopt: the continued stream finishes
    with exactly the sequential reference's tokens — the generated
    record, KV bytes, and decode carries all rode the blob."""
    from cake_tpu.fleet.kvshare import StreamMigrated
    eng_a, eng_b = engines
    prompt = [3, 17, 42, 99, 7]
    n = 12
    req = eng_a.submit(prompt, max_new_tokens=n, sampling=GREEDY)
    deadline = time.monotonic() + 60
    while len(req.tokens) < 4 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(req.tokens) >= 4, "stream never started decoding"
    blob = eng_a.kv_share.export_stream(req.id, 30)
    assert blob is not None
    # the source request failed TYPED: the SSE handler severs the socket
    # so the router sees a broken leg, never a clean finish
    assert req.wait(30)
    assert isinstance(req.result.get("error"), StreamMigrated)
    # parked blobs re-export from host memory (drain teardown path)
    assert eng_a.kv_share.export_stream(req.id, 30) == blob
    staged = eng_b.kv_share.store_inbound(req.id, blob)
    assert staged["rid"] == req.id and staged["gen_tokens"] >= 4
    req2 = eng_b.kv_share.submit_job(
        "adopt", {"rid": req.id, "sampling": GREEDY}, 30)
    assert req2 is not None
    assert req2.wait(180)
    assert "error" not in req2.result, req2.result.get("error")
    assert req2.result["tokens"] == _ref(model, prompt, n), \
        "migrated stream diverged from the uninterrupted reference"
    assert req2.stats.get("kv_migrated") is True
    # adopting twice is a miss (inbound is consumed), not a crash
    assert eng_b.kv_share.submit_job(
        "adopt", {"rid": req.id, "sampling": GREEDY}, 30) is None
    eng_b.paged.alloc.check()


# ---------------------------------------------------------------------------
# GDN (qwen3_5): row-snapshot layout through the same wire format
# ---------------------------------------------------------------------------


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_prefix_blob_gdn_rows_roundtrip():
    """The second KV layout: GDN's per-slot linear rows ride the blob as
    per-unit boundary snapshots, and the imported chain's splice restores
    them — greedy parity cold vs fetched."""
    gdn = TextModel(tiny_config("qwen3_5"), dtype=jnp.float32,
                    max_cache_len=CTX)
    eng_a, eng_b = _engine(gdn), _engine(gdn)
    try:
        pa, pb = SYS + [9, 11], SYS + [77, 31]
        ra = eng_a.submit(pa, max_new_tokens=6, sampling=GREEDY)
        assert ra.wait(600)
        assert ra.result["tokens"] == _ref(gdn, pa, 6)
        eng_a._wake.set()
        deadline = time.monotonic() + 10
        while not eng_a.kv_share.health_view()["chains"] \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        chain = eng_a.kv_share.health_view()["chains"][0]
        blob = eng_a.kv_share.submit_job("export_prefix", chain, 60)
        header, _ = decode_blob(blob)
        assert header["has_snap"] is True
        res = eng_b.kv_share.submit_job("import_prefix", blob, 60)
        assert res["tokens"] == 32
        rb = eng_b.submit(pb, max_new_tokens=6, sampling=GREEDY)
        assert rb.wait(600)
        assert rb.stats["prefix_hit_tokens"] == 32
        assert rb.result["tokens"] == _ref(gdn, pb, 6)
    finally:
        eng_a.close()
        eng_b.close()
