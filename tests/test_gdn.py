"""Gated DeltaNet (Qwen3.5) tests: reference-math parity via a scalar numpy
implementation, prefill/decode state consistency, hybrid-block integration,
checkpoint round-trip."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, init_params, tiny_config
from cake_tpu.models.qwen3_5 import gdn_forward, init_gdn_params
from cake_tpu.models.common.cache import init_cache


def np_gdn_reference(cfg, p, x):
    """Scalar numpy implementation following linear_attention.rs exactly."""
    la = cfg.linear_attn
    key_dim = la.num_key_heads * la.key_head_dim
    value_dim = la.num_value_heads * la.value_head_dim
    conv_dim = 2 * key_dim + value_dim
    hv, dk, dv = la.num_value_heads, la.key_head_dim, la.value_head_dim
    b, s, _ = x.shape

    proj = x @ np.asarray(p["in_proj"]["weight"], np.float32).T
    mixed, a, bg, z = np.split(
        proj, [conv_dim, conv_dim + hv, conv_dim + 2 * hv], axis=-1)

    # causal depthwise conv + silu
    w = np.asarray(p["conv1d"]["weight"], np.float32)[:, 0, :]  # [C, K]
    kcs = w.shape[1]
    xt = mixed.transpose(0, 2, 1)
    padded = np.concatenate([np.zeros((b, conv_dim, kcs - 1), np.float32), xt], 2)
    y = np.zeros_like(xt)
    for t in range(s):
        y[:, :, t] = np.sum(padded[:, :, t:t + kcs] * w[None], axis=-1)
    y = y / (1 + np.exp(-y))                     # silu
    y = y.transpose(0, 2, 1)

    q = y[..., :key_dim].reshape(b, s, la.num_key_heads, dk)
    k = y[..., key_dim:2 * key_dim].reshape(b, s, la.num_key_heads, dk)
    v = y[..., 2 * key_dim:].reshape(b, s, hv, dv)
    rep = hv // la.num_key_heads
    q = np.repeat(q, rep, axis=2)
    k = np.repeat(k, rep, axis=2)

    def l2n(t):
        return t / np.sqrt(np.sum(t * t, -1, keepdims=True) + 1e-6)
    q = l2n(q) / np.sqrt(dk)
    k = l2n(k)

    a_log = np.asarray(p["A_log"], np.float32)
    dt_bias = np.asarray(p["dt_bias"], np.float32)
    g = -np.exp(a_log) * np.log1p(np.exp(a + dt_bias))
    beta = 1 / (1 + np.exp(-bg))

    S = np.zeros((b, hv, dk, dv), np.float32)
    outs = np.zeros((b, s, hv, dv), np.float32)
    for t in range(s):
        S = S * np.exp(g[:, t])[..., None, None]
        for bi in range(b):
            for h in range(hv):
                r = S[bi, h].T @ k[bi, t, h]
                delta = beta[bi, t, h] * (v[bi, t, h] - r)
                S[bi, h] = S[bi, h] + np.outer(k[bi, t, h], delta)
                outs[bi, t, h] = S[bi, h].T @ q[bi, t, h]

    wn = np.asarray(p["norm"]["weight"], np.float32)
    var = np.mean(outs ** 2, -1, keepdims=True)
    o = outs / np.sqrt(var + cfg.rms_norm_eps) * wn
    zf = z.reshape(b, s, hv, dv)
    o = o * (zf / (1 + np.exp(-zf)))
    return (o.reshape(b, s, value_dim)
            @ np.asarray(p["out_proj"]["weight"], np.float32).T), S


@pytest.fixture
def gdn_setup(rng):
    cfg = tiny_config("qwen3_5")
    p = init_gdn_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    # non-trivial gates
    p["A_log"] = jnp.asarray(rng.standard_normal(
        cfg.linear_attn.num_value_heads) * 0.5, jnp.float32)
    p["dt_bias"] = jnp.asarray(rng.standard_normal(
        cfg.linear_attn.num_value_heads) * 0.5, jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 6, cfg.hidden_size)) * 0.5,
                    jnp.float32)
    return cfg, p, x


def test_gdn_matches_scalar_reference(gdn_setup):
    cfg, p, x = gdn_setup
    want, want_state = np_gdn_reference(cfg, p, np.asarray(x))
    lc = {
        "conv": jnp.zeros((2, p["conv1d"]["weight"].shape[0],
                           cfg.linear_attn.conv_kernel_dim - 1), jnp.float32),
        "state": jnp.zeros((2, cfg.linear_attn.num_value_heads,
                            cfg.linear_attn.key_head_dim,
                            cfg.linear_attn.value_head_dim), jnp.float32),
    }
    got, new_cache = gdn_forward(cfg, p, x, lc, jnp.asarray(0))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(new_cache["state"]), want_state,
                               atol=2e-4, rtol=1e-3)


def test_gdn_prefill_then_decode_consistency(gdn_setup):
    """Processing [t0..t5] at once must equal [t0..t3] then t4, t5 one at a
    time through the carried conv+recurrent state."""
    cfg, p, x = gdn_setup
    la = cfg.linear_attn
    conv_dim = 2 * la.num_key_heads * la.key_head_dim \
        + la.num_value_heads * la.value_head_dim
    def fresh():
        return {"conv": jnp.zeros((2, conv_dim, la.conv_kernel_dim - 1),
                                  jnp.float32),
                "state": jnp.zeros((2, la.num_value_heads, la.key_head_dim,
                                    la.value_head_dim), jnp.float32)}

    full, _ = gdn_forward(cfg, p, x, fresh(), jnp.asarray(0))
    lc = fresh()
    _, lc = gdn_forward(cfg, p, x[:, :4], lc, jnp.asarray(0))
    o4, lc = gdn_forward(cfg, p, x[:, 4:5], lc, jnp.asarray(4))
    o5, lc = gdn_forward(cfg, p, x[:, 5:6], lc, jnp.asarray(5))
    np.testing.assert_allclose(np.asarray(o4), np.asarray(full[:, 4:5]),
                               atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(o5), np.asarray(full[:, 5:6]),
                               atol=2e-4, rtol=1e-3)


def test_gdn_padding_does_not_advance_state(gdn_setup):
    """Padded prefill (valid_len < S) must leave conv+recurrent state as if
    only the valid tokens were processed."""
    cfg, p, x = gdn_setup
    la = cfg.linear_attn
    conv_dim = 2 * la.num_key_heads * la.key_head_dim \
        + la.num_value_heads * la.value_head_dim
    def fresh():
        return {"conv": jnp.zeros((2, conv_dim, la.conv_kernel_dim - 1),
                                  jnp.float32),
                "state": jnp.zeros((2, la.num_value_heads, la.key_head_dim,
                                    la.value_head_dim), jnp.float32)}
    _, lc_exact = gdn_forward(cfg, p, x[:, :3], fresh(), jnp.asarray(0))
    _, lc_padded = gdn_forward(cfg, p, x, fresh(), jnp.asarray(0),
                               valid_len=jnp.asarray(3))
    np.testing.assert_allclose(np.asarray(lc_padded["state"]),
                               np.asarray(lc_exact["state"]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lc_padded["conv"]),
                               np.asarray(lc_exact["conv"]), atol=1e-5)


@pytest.mark.parametrize("fam", ["qwen3_5", "qwen3_5_moe"])
def test_hybrid_model_prefill_decode_parity(fam):
    """Full hybrid model (3 linear : 1 full pattern) through TextModel."""
    cfg = tiny_config(fam)
    model = TextModel(cfg, dtype=jnp.float32, max_cache_len=64)
    toks = list(np.random.default_rng(0).integers(0, 255, size=9))
    logits_full, _ = model.prefill(model.new_cache(), toks)
    cache = model.new_cache()
    _, cache = model.prefill(cache, toks[:5])
    logits_inc = None
    for t in toks[5:]:
        logits_inc, cache = model.decode_logits(cache, int(t))
    np.testing.assert_allclose(np.asarray(logits_inc), np.asarray(logits_full),
                               atol=3e-3, rtol=1e-3)
    # hybrid cache structure: linear layers carry conv+state, full layers KV
    assert "conv" in cache["layers"][0] and "k" in cache["layers"][3]


def test_gdn_generate_runs():
    cfg = tiny_config("qwen3_5")
    model = TextModel(cfg, dtype=jnp.float32, max_cache_len=64)
    from cake_tpu.ops.sampling import SamplingConfig
    toks, stats = model.generate([1, 2, 3], max_new_tokens=8,
                                 sampling=SamplingConfig(temperature=0.0),
                                 chunk=4)
    toks2, _ = model.generate([1, 2, 3], max_new_tokens=8,
                              sampling=SamplingConfig(temperature=0.0), chunk=4)
    assert toks == toks2 and len(toks) >= 1


def test_gdn_checkpoint_roundtrip(tmp_path):
    import json

    from cake_tpu.utils import (load_model_params, params_to_hf_tensors,
                                save_safetensors)
    cfg = tiny_config("qwen3_5")
    params = init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    (tmp_path / "config.json").write_text(json.dumps({"architectures": ["X"]}))
    loaded = load_model_params(cfg, str(tmp_path), jnp.float32)
    la0 = loaded["layers"][0]["linear_attn"]
    np.testing.assert_allclose(
        np.asarray(la0["in_proj"]["weight"]),
        np.asarray(params["layers"][0]["linear_attn"]["in_proj"]["weight"]))
    np.testing.assert_allclose(
        np.asarray(la0["A_log"]),
        np.asarray(params["layers"][0]["linear_attn"]["A_log"]))
