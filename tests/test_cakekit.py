"""Native C++ core tests: builds csrc/libcakekit.so and cross-checks crc32 /
pread / framing against the Python implementations."""
import os
import struct
import zlib

import numpy as np
import pytest

from cake_tpu.utils import cakekit


@pytest.fixture(scope="module")
def native():
    if not cakekit.available():
        pytest.skip("no C++ toolchain to build cakekit")
    return cakekit


def test_native_builds(native):
    assert native.available()


def test_crc32_matches_zlib(native, rng):
    for n in (0, 1, 7, 8, 9, 1000, 65537):
        data = rng.integers(0, 256, n, dtype=np.uint32).astype(np.uint8).tobytes()
        assert native.crc32(data) == (zlib.crc32(data) & 0xFFFFFFFF)
    # seeded / incremental
    a, b = b"hello ", b"world"
    assert native.crc32(b, native.crc32(a)) == (zlib.crc32(a + b) & 0xFFFFFFFF)


def test_pread(native, tmp_path, rng):
    data = rng.integers(0, 256, 10000, dtype=np.uint32).astype(np.uint8).tobytes()
    p = tmp_path / "blob.bin"
    p.write_bytes(data)
    assert native.pread(str(p), 0, 100) == data[:100]
    assert native.pread(str(p), 5000, 123) == data[5000:5123]
    # read past EOF returns the available bytes
    assert native.pread(str(p), 9990, 100) == data[9990:]
    with pytest.raises(OSError):
        native.pread(str(tmp_path / "missing"), 0, 10)


def test_preadv(native, tmp_path, rng):
    data = rng.integers(0, 256, 4096, dtype=np.uint32).astype(np.uint8).tobytes()
    p = tmp_path / "blob.bin"
    p.write_bytes(data)
    chunks = native.preadv(str(p), [(0, 10), (100, 50), (4000, 96)])
    assert chunks[0] == data[:10]
    assert chunks[1] == data[100:150]
    assert chunks[2] == data[4000:4096]


def test_frame_parse(native):
    from cake_tpu.cluster.proto import MAGIC, MAX_FRAME
    hdr = struct.pack("<II", MAGIC, 4096)
    assert native.frame_parse(hdr, MAGIC, MAX_FRAME) == 4096
    assert native.frame_parse(struct.pack("<II", 0xBAD, 10), MAGIC,
                              MAX_FRAME) == -1
    assert native.frame_parse(struct.pack("<II", MAGIC, MAX_FRAME + 1),
                              MAGIC, MAX_FRAME) == -2


def test_tensor_storage_uses_native(native, tmp_path, rng):
    """TensorStorage routes reads through cakekit when built."""
    from cake_tpu.utils.safetensors_io import TensorStorage, save_safetensors
    w = rng.standard_normal((32, 16)).astype(np.float32)
    save_safetensors(str(tmp_path / "m.safetensors"), {"w": w})
    # force re-probe of the module-level handle
    import importlib

    import cake_tpu.utils.safetensors_io as stio
    importlib.reload(stio)
    st = stio.TensorStorage.from_model_dir(str(tmp_path))
    np.testing.assert_array_equal(st.read("w"), w)
    assert stio._CAKEKIT is not None
