"""Product-path tensor parallelism: the SAME code a user runs
(`cake-tpu run/serve --tp N`, `cake-tpu worker --tp N`) must shard over the
virtual 8-device CPU mesh and match single-device logits exactly.

This is the wiring the reference keeps live in its product path as the
intra-worker multi-GPU layer split (ref: cake-core/src/cake/sharding/
worker.rs:126-229) — here it's GSPMD tp over a jax Mesh, reached through
runtime.build_text_model / WorkerServer, not a hand-built test harness.
"""
import asyncio
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import SamplingConfig, TextModel, init_params, tiny_config
from cake_tpu.parallel import serving_mesh
from cake_tpu.utils.export import params_to_hf_tensors
from cake_tpu.utils.safetensors_io import save_safetensors

from test_cluster import _start_worker_thread


@pytest.fixture
def tp_model_dir(tmp_path):
    """Synthetic checkpoint with kv heads divisible by tp=4."""
    cfg = tiny_config("qwen3", num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    mdir = tmp_path / "model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    d = dict(architectures=["Qwen3ForCausalLM"], vocab_size=256,
             hidden_size=64, intermediate_size=128, num_hidden_layers=4,
             num_attention_heads=4, num_key_value_heads=4, rms_norm_eps=1e-5,
             rope_theta=10000.0, max_position_embeddings=128, eos_token_id=2)
    (mdir / "config.json").write_text(json.dumps(d))
    return cfg, params, str(mdir)


def test_serving_mesh_parsing():
    assert serving_mesh(None) is None
    assert serving_mesh(1) is None
    assert serving_mesh("1") is None
    assert serving_mesh(4).shape == {"tp": 4}
    assert serving_mesh("4").shape == {"tp": 4}   # CLI strings pass through
    assert serving_mesh("auto").shape == {"tp": len(jax.devices())}
    with pytest.raises(ValueError):
        serving_mesh(len(jax.devices()) + 1)


def test_tp_divisibility_fails_fast(tp_model_dir, tmp_path):
    """--tp 8 on a 4-kv-head model must fail from the config alone (before
    any weight bytes load)."""
    from cake_tpu.runtime import build_text_model
    _, _, mdir = tp_model_dir
    with pytest.raises(ValueError, match="tp=8"):
        build_text_model(mdir, dtype="f32", download=False, tp=8)


def test_build_text_model_tp_matches_single(tp_model_dir):
    """runtime.build_text_model --tp 4: the actual serve/run construction
    path, greedy generation must match the single-device model exactly."""
    from cake_tpu.runtime import build_text_model

    cfg, params, mdir = tp_model_dir
    gen1, _, _, _ = build_text_model(mdir, dtype="f32", max_cache_len=64,
                                     download=False)
    gen4, _, _, _ = build_text_model(mdir, dtype="f32", max_cache_len=64,
                                     download=False, tp=4)
    assert gen4.mesh is not None and gen4.mesh.shape == {"tp": 4}
    # weights really are distributed over 4 devices
    w = gen4.params["layers"][0]["self_attn"]["q_proj"]["weight"]
    assert len(w.sharding.device_set) == 4

    greedy = SamplingConfig(temperature=0.0)
    want, _ = gen1.generate([1, 2, 3, 4, 5], max_new_tokens=10,
                            sampling=greedy)
    got, _ = gen4.generate([1, 2, 3, 4, 5], max_new_tokens=10,
                           sampling=greedy)
    assert got == want

    # streaming path too (chunked decode programs under the mesh)
    toks = []
    got_s, _ = gen4.generate([1, 2, 3, 4, 5], max_new_tokens=10,
                             sampling=greedy, on_token=toks.append, chunk=4)
    assert got_s == want


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_tp_cache_growth_under_mesh(tp_model_dir):
    """KV bucket growth (the _grow_to path) keeps shardings and numerics."""
    cfg, params, mdir = tp_model_dir
    mesh = serving_mesh(4)
    model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=128,
                      mesh=mesh)
    ref = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=128)
    greedy = SamplingConfig(temperature=0.0)
    # long enough generation to force at least one growth step
    want, _ = ref.generate([1, 2, 3], max_new_tokens=90, sampling=greedy)
    got, _ = model.generate([1, 2, 3], max_new_tokens=90, sampling=greedy)
    assert got == want


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_worker_tp_through_product_path(tp_model_dir):
    """A worker started with tp=4 (the `cake-tpu worker --tp 4` path) serves
    its layer range sharded; distributed greedy matches fully-local."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup

    cfg, params, mdir = tp_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "tpkey", mdir + "-wcache", ready,
                                     tp=4)
    assert ready.wait(10)
    port = holder["port"]
    try:
        assert holder["server"].mesh is not None
        setup = master_setup(
            mdir, "tpkey", cfg,
            workers=[{"name": "w0", "host": "127.0.0.1", "port": port,
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"w0": (1, 3)}, dtype_str="f32", max_cache_len=64)
        # worker's stage params are sharded over its mesh
        wstage = holder["server"].state.stage
        w = wstage.params["layers"][1]["self_attn"]["q_proj"]["weight"]
        assert len(w.sharding.device_set) == 4

        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64)
        greedy = SamplingConfig(temperature=0.0)
        got, _ = dist.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                               sampling=greedy)
        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                                 sampling=greedy)
        assert got == want
        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_master_local_stages_tp(tp_model_dir):
    """master_setup(mesh=...) shards the master's own local stages — the
    runtime path `cake-tpu run --cluster-key K --tp 4` takes when the master
    keeps layers."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup

    cfg, params, mdir = tp_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "tpk2", mdir + "-wc2", ready)
    assert ready.wait(10)
    port = holder["port"]
    mesh = serving_mesh(4)
    try:
        setup = master_setup(
            mdir, "tpk2", cfg,
            workers=[{"name": "w0", "host": "127.0.0.1", "port": port,
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"w0": (1, 3)}, dtype_str="f32", max_cache_len=64,
            mesh=mesh)
        local_stages = [s for s in setup.stages if s.kind == "local"]
        assert local_stages
        for s in local_stages:
            w = s.runner.params["layers"][0]["self_attn"]["q_proj"]["weight"]
            assert len(w.sharding.device_set) == 4

        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64,
                                    mesh=mesh)
        greedy = SamplingConfig(temperature=0.0)
        got, _ = dist.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                               sampling=greedy)
        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                                 sampling=greedy)
        assert got == want
        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)
