"""Image + audio pipeline tests on tiny configs: schedulers vs references,
MMDiT shape/semantics, VAE decode, full generate_image/generate_speech."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.audio import (LuxTTS, VibeVoiceTTS, tiny_luxtts_config,
                                   tiny_tts_config)
from cake_tpu.models.image import (FluxImageModel, tiny_flux_config)
from cake_tpu.models.image.mmdit import (init_mmdit_params, make_img_ids,
                                         make_txt_ids, mmdit_forward,
                                         timestep_embedding)
from cake_tpu.models.image.vae import (latents_to_patches, patches_to_latents)
from cake_tpu.ops.diffusion import (DpmSolverPP, cfg_combine,
                                    flow_matching_euler_step,
                                    flow_matching_schedule)
from cake_tpu.utils.wav import decode_wav, encode_wav


# ------------------------------------------------------------- schedulers

def test_flow_matching_schedule():
    ts = flow_matching_schedule(10)
    assert ts[0] == 1.0 and ts[-1] == 0.0 and len(ts) == 11
    assert np.all(np.diff(ts) < 0)
    shifted = flow_matching_schedule(10, shift_mu=1.15)
    assert shifted[0] > 0.99 and shifted[-1] == 0.0   # shift keeps endpoints
    # mid steps pushed toward 1 (more steps at high noise)
    assert shifted[5] > ts[5]


def test_euler_step_integrates_linear_flow():
    """With the exact constant velocity v = x1 - x0, Euler recovers x0."""
    rng = np.random.default_rng(0)
    x1 = jnp.asarray(rng.standard_normal((2, 8)))    # noise at t=1
    x0 = jnp.asarray(rng.standard_normal((2, 8)))    # data at t=0
    v = x1 - x0                                       # d x_t / dt for lerp path
    ts = flow_matching_schedule(5)
    x = x1
    for i in range(5):
        x = flow_matching_euler_step(x, v, ts[i], ts[i + 1])
    np.testing.assert_allclose(np.asarray(x), np.asarray(x0), atol=1e-5)


def test_dpm_solver_denoises_toward_x0():
    """v-prediction with the TRUE v at each step must recover x0 closely."""
    rng = np.random.default_rng(1)
    x0 = jnp.asarray(rng.standard_normal((4,)), jnp.float32)
    sch = DpmSolverPP.from_betas()
    ts = sch.timesteps(10)
    a0 = float(sch.alphas_cumprod[ts[0]])
    eps = jnp.asarray(rng.standard_normal(4), jnp.float32)
    x = (a0 ** 0.5) * x0 + ((1 - a0) ** 0.5) * eps
    for j, t in enumerate(ts):
        a = float(sch.alphas_cumprod[int(t)])
        alpha_t, sigma_t = a ** 0.5, (1 - a) ** 0.5
        # true eps for current x given x0: eps_t = (x - alpha*x0)/sigma
        eps_t = (x - alpha_t * x0) / max(sigma_t, 1e-8)
        # v-parameterization: v = alpha_t * eps - sigma_t * x0
        v_true = alpha_t * eps_t - sigma_t * x0
        t_next = int(ts[j + 1]) if j + 1 < len(ts) else 0
        x = sch.step(v_true, int(t), t_next, x)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x0), atol=0.05)


def test_cfg_combine():
    u, c = jnp.asarray([1.0]), jnp.asarray([2.0])
    assert float(cfg_combine(u, c, 1.0)[0]) == 2.0
    assert float(cfg_combine(u, c, 0.0)[0]) == 1.0
    assert float(cfg_combine(u, c, 2.0)[0]) == 3.0


# ------------------------------------------------------------------ mmdit

def test_patchify_roundtrip(rng):
    z = jnp.asarray(rng.standard_normal((2, 4, 8, 12)), jnp.float32)
    p = latents_to_patches(z)
    assert p.shape == (2, 4 * 6, 16)
    back = patches_to_latents(p, 8, 12)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(z))


def test_timestep_embedding_distinct():
    e = timestep_embedding(jnp.asarray([0.0, 0.5, 1.0]), 64)
    assert e.shape == (3, 64)
    assert not np.allclose(e[0], e[1])


def test_mmdit_forward_shapes_and_conditioning(rng):
    cfg = tiny_flux_config().mmdit
    params = init_mmdit_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    img = jnp.asarray(rng.standard_normal((1, 24, cfg.in_channels)), jnp.float32)
    txt = jnp.asarray(rng.standard_normal((1, 8, cfg.txt_dim)), jnp.float32)
    vec = jnp.asarray(rng.standard_normal((1, cfg.vec_dim)), jnp.float32)
    img_ids = make_img_ids(4, 6)
    txt_ids = make_txt_ids(8)
    t = jnp.asarray([0.5], jnp.float32)
    g = jnp.asarray([3.5], jnp.float32)
    v1 = mmdit_forward(cfg, params, img, img_ids, txt, txt_ids, t, vec, g)
    assert v1.shape == img.shape
    assert bool(jnp.all(jnp.isfinite(v1)))
    # conditioning matters: different text -> different velocity
    # (NB: scaling txt is ~invisible — FLUX LayerNorms are affine-free and
    # scale-invariant — so perturb direction, not magnitude)
    txt_b = jnp.asarray(rng.standard_normal((1, 8, cfg.txt_dim)), jnp.float32)
    v2 = mmdit_forward(cfg, params, img, img_ids, txt_b, txt_ids,
                       t, vec, g)
    assert not np.allclose(np.asarray(v1), np.asarray(v2), atol=1e-4)
    # timestep matters
    v3 = mmdit_forward(cfg, params, img, img_ids, txt, txt_ids,
                       jnp.asarray([0.9], jnp.float32), vec, g)
    assert not np.allclose(np.asarray(v1), np.asarray(v3), atol=1e-4)


# --------------------------------------------------------------- pipelines

def test_flux_generate_image():
    model = FluxImageModel(tiny_flux_config(), dtype=jnp.float32)
    steps_seen = []
    img = model.generate_image("a tiny cake", width=64, height=64, steps=3,
                               seed=1, on_step=lambda i, n: steps_seen.append(i))
    assert img.size == (64, 64)
    assert steps_seen == [1, 2, 3]
    # determinism
    img2 = model.generate_image("a tiny cake", width=64, height=64, steps=3,
                                seed=1)
    np.testing.assert_array_equal(np.asarray(img), np.asarray(img2))
    # different prompt -> different image (text conditioning reaches output)
    img3 = model.generate_image("a dragon", width=64, height=64, steps=3,
                                seed=1)
    assert not np.array_equal(np.asarray(img), np.asarray(img3))


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_vibevoice_generate_speech():
    tts = VibeVoiceTTS(tiny_tts_config(), dtype=jnp.float32, max_frames=6)
    frames = []
    audio = tts.generate_speech("hello there", max_frames=4,
                                on_frame=frames.append)
    hop = 16  # 4*4 upsample
    assert len(audio.samples) == len(frames) * hop
    assert np.all(np.abs(audio.samples) <= 1.0)
    wav = audio.wav_bytes()
    assert wav[:4] == b"RIFF"
    samples, rate = decode_wav(wav)
    assert rate == tts.cfg.sample_rate
    np.testing.assert_allclose(samples, audio.samples, atol=1e-3)
    assert len(audio.pcm_bytes()) == 2 * len(audio.samples)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_vibevoice_voice_prompt_changes_output():
    tts = VibeVoiceTTS(tiny_tts_config(), dtype=jnp.float32, max_frames=4)
    a = tts.generate_speech("hi", max_frames=3)
    voice = encode_wav(np.sin(np.linspace(0, 100, 4000)).astype(np.float32))
    b = tts.generate_speech("hi", voice_wav=voice, max_frames=3)
    assert not np.allclose(a.samples, b.samples)


def test_luxtts_generate_speech():
    tts = LuxTTS(tiny_luxtts_config(), dtype=jnp.float32)
    audio = tts.generate_speech("hello world")
    assert len(audio.samples) > 0
    assert np.all(np.abs(audio.samples) <= 1.0)
    # deterministic per (text, seed)
    audio2 = tts.generate_speech("hello world")
    np.testing.assert_array_equal(audio.samples, audio2.samples)


def test_wav_roundtrip(rng):
    s = np.clip(rng.standard_normal(1000) * 0.3, -1, 1).astype(np.float32)
    wav = encode_wav(s, 16000)
    back, rate = decode_wav(wav)
    assert rate == 16000
    np.testing.assert_allclose(back, s, atol=1e-4)


# ------------------------------------------------------------------- sd

@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_sd_unet_shapes_and_conditioning(rng):
    from cake_tpu.models.image.sd import (init_unet_params, tiny_sd_config,
                                          unet_forward)
    cfg = tiny_sd_config().unet
    p = init_unet_params(cfg, jax.random.PRNGKey(0))
    x = jnp.asarray(rng.standard_normal((1, 4, 16, 16)), jnp.float32)
    ctx = jnp.asarray(rng.standard_normal((1, 8, cfg.context_dim)), jnp.float32)
    t = jnp.asarray([0.5], jnp.float32)
    e1 = unet_forward(cfg, p, x, t, ctx)
    assert e1.shape == x.shape and bool(jnp.all(jnp.isfinite(e1)))
    ctx2 = jnp.asarray(rng.standard_normal((1, 8, cfg.context_dim)), jnp.float32)
    e2 = unet_forward(cfg, p, x, t, ctx2)
    assert not np.allclose(np.asarray(e1), np.asarray(e2), atol=1e-5)
    e3 = unet_forward(cfg, p, x, jnp.asarray([0.9], jnp.float32), ctx)
    assert not np.allclose(np.asarray(e1), np.asarray(e3), atol=1e-5)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_sd_generate_and_img2img():
    from cake_tpu.models.image.sd import SDImageModel, tiny_sd_config
    model = SDImageModel(tiny_sd_config())
    img = model.generate_image("a fox", width=32, height=32, steps=3, seed=4)
    assert img.size == (32, 32)
    img_b = model.generate_image("a fox", width=32, height=32, steps=3, seed=4)
    np.testing.assert_array_equal(np.asarray(img), np.asarray(img_b))
    # negative prompt changes the output (CFG path)
    img_n = model.generate_image("a fox", width=32, height=32, steps=3, seed=4,
                                 negative_prompt="blurry")
    assert not np.array_equal(np.asarray(img), np.asarray(img_n))
    # img2img from a given latent differs from txt2img
    z0 = np.random.default_rng(0).standard_normal((1, 4, 16, 16)).astype("f")
    img_i = model.generate_image("a fox", width=32, height=32, steps=4, seed=4,
                                 init_image=z0, strength=0.5)
    assert not np.array_equal(np.asarray(img), np.asarray(img_i))


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_sd_intermediate_images_and_trace(tmp_path):
    """intermediate_every decodes in-progress images through on_image
    (ref: sd.rs:526-529 intermediary_images) and trace_dir writes a JAX
    profiler trace (the --sd-tracing analog)."""
    from cake_tpu.models.image.sd import SDImageModel, tiny_sd_config
    model = SDImageModel(tiny_sd_config())
    seen = []
    img = model.generate_image("a fox", width=32, height=32, steps=4, seed=1,
                               intermediate_every=2,
                               on_image=lambda step, pil: seen.append(
                                   (step, pil.size)),
                               trace_dir=str(tmp_path / "trace"))
    assert seen == [(2, (32, 32))]       # step 4 is the final image
    assert img.size == (32, 32)
    trace_files = list((tmp_path / "trace").rglob("*"))
    assert trace_files, "profiler trace directory is empty"
    # final image identical to a run without intermediates
    img_plain = model.generate_image("a fox", width=32, height=32, steps=4,
                                     seed=1)
    np.testing.assert_array_equal(np.asarray(img), np.asarray(img_plain))


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_vibevoice_clone_prefill_bucketed():
    """Voice-clone conditioning pads the reference to 8-frame buckets so
    the jitted LM prefill compiles per bucket, not per clip length — and
    two different clip lengths inside one bucket produce caches advanced
    by their true frame counts."""
    import jax.numpy as jnp

    from cake_tpu.models.audio.vibevoice import (VibeVoiceTTS,
                                                 tiny_tts_config)
    from cake_tpu.utils.wav import encode_wav

    cfg = tiny_tts_config()
    m = VibeVoiceTTS(cfg, dtype=jnp.float32, max_frames=4)
    sr = cfg.sample_rate
    rng = np.random.default_rng(0)
    for n_hops in (3, 5):    # both inside the same 8-hop encoder bucket
        wav = encode_wav(rng.standard_normal(cfg.hop * n_hops)
                         .astype(np.float32) * 0.1, sr)
        audio = m.generate_speech("hi there", voice_wav=wav, seed=0,
                                  max_frames=2)
        assert np.isfinite(audio.samples).all()


def test_resample_antialias_removes_above_band():
    """48kHz reference with a 20kHz tone: after the low-pass + decimate to
    24kHz, the aliased image (4kHz) must be strongly attenuated vs naive
    linear decimation."""
    import jax.numpy as jnp

    from cake_tpu.models.audio.vibevoice import VibeVoiceTTS, tiny_tts_config
    from cake_tpu.utils.wav import encode_wav

    cfg = tiny_tts_config()
    m = VibeVoiceTTS(cfg, dtype=jnp.float32, max_frames=2)
    sr_in = 48000
    t = np.arange(sr_in) / sr_in
    tone = np.sin(2 * np.pi * 20000 * t).astype(np.float32)

    captured = {}
    orig = m.encode_voice_reference

    def spy(samples):
        captured["samples"] = np.asarray(samples)
        return orig(samples)

    m.encode_voice_reference = spy
    m._voice_embeds(encode_wav(tone, sr_in))
    res = captured["samples"]
    # alias image of 20kHz at 24kHz output = 4kHz; measure its energy
    spec = np.abs(np.fft.rfft(res))
    freqs = np.fft.rfftfreq(len(res), 1 / cfg.sample_rate)
    band = spec[(freqs > 3500) & (freqs < 4500)].max()
    assert band < 0.05 * len(res) / 2, band


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_pipelines_run_in_bf16():
    """serve default dtype: the whole image path must not promote to f32
    (regression: np-scalar coefficients promoted bf16 latents)."""
    from cake_tpu.models.image import FluxImageModel, tiny_flux_config
    from cake_tpu.models.image.sd import SDImageModel, tiny_sd_config
    img = FluxImageModel(tiny_flux_config(), dtype=jnp.bfloat16).generate_image(
        "x", width=32, height=32, steps=2)
    assert img.size == (32, 32)
    img2 = SDImageModel(tiny_sd_config(), dtype=jnp.bfloat16).generate_image(
        "x", width=32, height=32, steps=2)
    assert img2.size == (32, 32)
    # the actual promotion guard: scheduler steps must PRESERVE bf16
    from cake_tpu.ops.diffusion import (DpmSolverPP,
                                        flow_matching_euler_step,
                                        flow_matching_schedule)
    x = jnp.ones((2, 4), jnp.bfloat16)
    sch = DpmSolverPP.from_betas(prediction_type="epsilon")
    ts = sch.timesteps(4)
    out = sch.step(jnp.zeros_like(x), int(ts[0]), int(ts[1]), x)
    assert out.dtype == jnp.bfloat16
    fm = flow_matching_schedule(4)
    out2 = flow_matching_euler_step(x, jnp.zeros_like(x),
                                    float(fm[0]), float(fm[1]))
    assert out2.dtype == jnp.bfloat16


def test_vae_encoder_img2img_from_pixels():
    """vae_encode: [H,W,3] pixels -> scheduler-space latent at H/8 with
    finite values, and the full img2img pipeline runs from it (the CLI
    --init-image path); posterior sampling differs from the mode."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from cake_tpu.models.image.sd import SDImageModel, tiny_sd_config

    m = SDImageModel(tiny_sd_config(), dtype=jnp.float32)
    px = np.random.default_rng(0).integers(0, 256, (64, 64, 3),
                                           dtype=np.uint8)
    z0 = m.encode_image(px)
    lc = m.cfg.vae.latent_channels
    f = 2 ** (len(m.cfg.vae.channel_mults) - 1)   # /8 on real SD (4 levels)
    assert z0.shape == (1, lc, 64 // f, 64 // f)
    assert np.isfinite(np.asarray(z0)).all()

    zs = m.encode_image(px, rng=jax.random.PRNGKey(1))
    assert not np.allclose(np.asarray(zs), np.asarray(z0))

    img = m.generate_image("x", width=64, height=64, steps=2,
                           init_image=z0, strength=0.5, seed=3)
    assert img.size == (64, 64)
