"""Pallas flash attention vs the XLA reference path (interpret mode on the
CPU mesh — the kernel's compiled path needs real TPU hardware)."""
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.ops.attention import causal_sdpa, make_attention_mask, \
    multi_head_attention
from cake_tpu.ops.flash import flash_attention


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_flash_matches_xla_causal(rng, hq, hkv):
    b, s, d = 2, 256, 32
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    want = causal_sdpa(q, k, v)
    got = flash_attention(q, k, v, interpret=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


def test_flash_non_causal(rng):
    b, s, h, d = 1, 128, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, k, v, causal=False, interpret=True,
                          block_q=64, block_k=64)
    want = multi_head_attention(q, k, v, mask=None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


def test_flash_serving_prefill_parity(rng, monkeypatch):
    """The real serving path: fresh-cache prefill through TextModel must
    dispatch the kernel and match the mask path, including the cache it
    leaves behind for decode."""
    import cake_tpu.ops.flash as fl
    from cake_tpu.models import TextModel, tiny_config

    calls = []
    orig = fl.flash_attention

    def spy(*a, **k):
        calls.append(1)
        k["interpret"] = True               # CPU test: interpret the kernel
        return orig(*a, **k)

    monkeypatch.setattr(fl, "flash_enabled", lambda: True)
    monkeypatch.setattr(fl, "FLASH_MIN_SEQ", 64)
    monkeypatch.setattr(fl, "flash_attention", spy)

    cfg = tiny_config("qwen3", max_position_embeddings=256)
    toks = list(np.random.default_rng(0).integers(0, 255, 100))  # bucket 128
    m = TextModel(cfg, dtype=jnp.float32, max_cache_len=160)
    l_flash, cache = m.prefill(m.new_cache(), toks)
    assert len(calls) == cfg.num_hidden_layers

    monkeypatch.setattr(fl, "flash_enabled", lambda: False)
    m2 = TextModel(cfg, dtype=jnp.float32, max_cache_len=160)
    l_mask, cache2 = m2.prefill(m2.new_cache(), toks)
    np.testing.assert_allclose(np.asarray(l_flash), np.asarray(l_mask),
                               atol=1e-5)
    d1, _ = m.decode_logits(cache, 7)
    d2, _ = m2.decode_logits(cache2, 7)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1e-5)


def test_flash_valid_len_masks_padding(rng):
    """Keys past valid_len must be invisible, like the position-mask path."""
    b, s, h, d = 1, 128, 2, 16
    vl = 70
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, k, v, valid_len=vl, interpret=True,
                          block_q=64, block_k=64)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    kpos = jnp.where(jnp.arange(s) < vl, jnp.arange(s), -1)[None]
    mask = make_attention_mask(pos, jnp.broadcast_to(kpos, (b, s)))
    want = multi_head_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got)[:, :vl], np.asarray(want)[:, :vl],
                               atol=2e-4, rtol=1e-3)
