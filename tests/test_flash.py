"""Pallas flash attention vs the XLA reference path (interpret mode on the
CPU mesh — the kernel's compiled path needs real TPU hardware)."""
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.ops.attention import causal_sdpa, make_attention_mask, \
    multi_head_attention
from cake_tpu.ops.flash import flash_attention


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_flash_matches_xla_causal(rng, hq, hkv):
    b, s, d = 2, 256, 32
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    want = causal_sdpa(q, k, v)
    got = flash_attention(q, k, v, interpret=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


def test_flash_non_causal(rng):
    b, s, h, d = 1, 128, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, k, v, causal=False, interpret=True,
                          block_q=64, block_k=64)
    want = multi_head_attention(q, k, v, mask=None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


def test_flash_serving_prefill_parity(rng, monkeypatch):
    """The real serving path: fresh-cache prefill through TextModel must
    dispatch the kernel and match the mask path, including the cache it
    leaves behind for decode."""
    import cake_tpu.ops.flash as fl
    from cake_tpu.models import TextModel, tiny_config

    calls = []
    orig = fl.flash_attention

    def spy(*a, **k):
        calls.append(1)
        k["interpret"] = True               # CPU test: interpret the kernel
        return orig(*a, **k)

    monkeypatch.setattr(fl, "flash_enabled", lambda: True)
    monkeypatch.setattr(fl, "FLASH_MIN_SEQ", 64)
    monkeypatch.setattr(fl, "flash_attention", spy)

    cfg = tiny_config("qwen3", max_position_embeddings=256)
    toks = list(np.random.default_rng(0).integers(0, 255, 100))  # bucket 128
    m = TextModel(cfg, dtype=jnp.float32, max_cache_len=160)
    l_flash, cache = m.prefill(m.new_cache(), toks)
    assert len(calls) == cfg.num_hidden_layers

    monkeypatch.setattr(fl, "flash_enabled", lambda: False)
    m2 = TextModel(cfg, dtype=jnp.float32, max_cache_len=160)
    l_mask, cache2 = m2.prefill(m2.new_cache(), toks)
    np.testing.assert_allclose(np.asarray(l_flash), np.asarray(l_mask),
                               atol=1e-5)
    d1, _ = m.decode_logits(cache, 7)
    d2, _ = m2.decode_logits(cache2, 7)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1e-5)


def test_flash_valid_len_masks_padding(rng):
    """Keys past valid_len must be invisible, like the position-mask path."""
    b, s, h, d = 1, 128, 2, 16
    vl = 70
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, k, v, valid_len=vl, interpret=True,
                          block_q=64, block_k=64)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    kpos = jnp.where(jnp.arange(s) < vl, jnp.arange(s), -1)[None]
    mask = make_attention_mask(pos, jnp.broadcast_to(kpos, (b, s)))
    want = multi_head_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got)[:, :vl], np.asarray(want)[:, :vl],
                               atol=2e-4, rtol=1e-3)


def test_flash_sliding_window(rng):
    """SWA masking inside the kernel must equal the position-mask path."""
    b, s, h, d, w = 1, 256, 2, 16, 48
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, k, v, window=w, interpret=True,
                          block_q=64, block_k=64)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    mask = make_attention_mask(pos, pos, window=w)
    want = multi_head_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


def test_flash_non_multiple_lengths(rng):
    """The wrapper pads odd lengths to the block size internally."""
    b, s, h, d = 1, 100, 2, 16          # 100 % 64 != 0
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, k, v, interpret=True, block_q=64, block_k=64)
    want = causal_sdpa(q, k, v)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


def test_flash_append_q_offset(rng):
    """Continued prefill: queries at pos0..pos0+s over a prefix-filled
    buffer must equal full attention over the valid prefix+chunk."""
    b, h, d = 1, 2, 16
    cap, pos0, s = 256, 70, 64
    kv = jnp.asarray(rng.standard_normal((b, cap, h, d)), jnp.float32)
    vv = jnp.asarray(rng.standard_normal((b, cap, h, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    got = flash_attention(q, kv, vv, valid_len=s, q_offset=pos0,
                          interpret=True, block_q=64, block_k=64)
    q_pos = jnp.broadcast_to(pos0 + jnp.arange(s, dtype=jnp.int32)[None],
                             (b, s))
    k_idx = jnp.arange(cap, dtype=jnp.int32)
    k_pos = jnp.where(k_idx < pos0 + s, k_idx, -1)[None]
    mask = make_attention_mask(q_pos, jnp.broadcast_to(k_pos, (b, cap)))
    want = multi_head_attention(q, kv, vv, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_flash_chunked_prefill_serving(rng, monkeypatch):
    """Serving path: chunked prefill (append mode) and SWA fresh prefill
    both dispatch the kernel and match the mask path end to end."""
    import cake_tpu.ops.flash as fl
    from cake_tpu.models import TextModel, tiny_config

    calls = []
    orig = fl.flash_attention

    def spy(*a, **k):
        calls.append(k.get("q_offset") is not None)
        k["interpret"] = True
        return orig(*a, **k)

    monkeypatch.setattr(fl, "flash_enabled", lambda: True)
    monkeypatch.setattr(fl, "FLASH_MIN_SEQ", 64)
    monkeypatch.setattr(fl, "flash_attention", spy)

    toks = list(np.random.default_rng(1).integers(0, 255, 150))
    cfg = tiny_config("qwen3", max_position_embeddings=512)
    m = TextModel(cfg, dtype=jnp.float32, max_cache_len=256)
    cache = m.new_cache()
    _, cache = m.prefill(cache, toks[:80])          # fresh, bucket 128
    n_fresh = len(calls)
    l1, cache = m.prefill(cache, toks[80:], pos0=80)  # append, bucket 128
    assert n_fresh == cfg.num_hidden_layers
    assert any(calls[n_fresh:]), "append mode never dispatched flash"

    monkeypatch.setattr(fl, "flash_enabled", lambda: False)
    m2 = TextModel(cfg, dtype=jnp.float32, max_cache_len=256)
    c2 = m2.new_cache()
    _, c2 = m2.prefill(c2, toks[:80])
    l2, c2 = m2.prefill(c2, toks[80:], pos0=80)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)

    # SWA model: fresh prefill now flashes through the window mask
    calls.clear()
    monkeypatch.setattr(fl, "flash_enabled", lambda: True)
    cfgw = tiny_config("mistral", sliding_window=48,
                       max_position_embeddings=512)
    mw = TextModel(cfgw, dtype=jnp.float32, max_cache_len=256)
    lw, _ = mw.prefill(mw.new_cache(), toks)        # bucket 256
    assert len(calls) == cfgw.num_hidden_layers
    monkeypatch.setattr(fl, "flash_enabled", lambda: False)
    mw2 = TextModel(cfgw, dtype=jnp.float32, max_cache_len=256)
    lw2, _ = mw2.prefill(mw2.new_cache(), toks)
    np.testing.assert_allclose(np.asarray(lw), np.asarray(lw2), atol=1e-5)


def test_flash_distributed_stage_dispatch(rng, monkeypatch):
    """The worker/master stage path (LocalStage.forward_hidden) dispatches
    flash for prefill chunks and matches the mask path."""
    import jax

    import cake_tpu.ops.flash as fl
    from cake_tpu.models import tiny_config
    from cake_tpu.models.common.cache import init_cache
    from cake_tpu.models.common.layers import init_params
    from cake_tpu.models.common.text_model import LocalStage

    calls = []
    orig = fl.flash_attention

    def spy(*a, **k):
        calls.append(1)
        k["interpret"] = True
        return orig(*a, **k)

    monkeypatch.setattr(fl, "flash_enabled", lambda: True)
    monkeypatch.setattr(fl, "FLASH_MIN_SEQ", 64)
    monkeypatch.setattr(fl, "flash_attention", spy)

    cfg = tiny_config("qwen3", max_position_embeddings=256)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32,
                         layer_range=(0, 2))
    sub = {"layers": params["layers"], "rope": params["rope"]}
    stage = LocalStage(cfg, sub, 0, 2)
    x = jnp.asarray(rng.standard_normal((1, 128, cfg.hidden_size)),
                    jnp.float32)
    c1 = init_cache(cfg, 1, 256, jnp.float32, (0, 2))
    y1, _ = stage.forward_hidden(x, c1, jnp.asarray(0, jnp.int32),
                                 jnp.asarray(100, jnp.int32),
                                 flash_mode="fresh")
    assert len(calls) == 2          # one per layer in the range

    c2 = init_cache(cfg, 1, 256, jnp.float32, (0, 2))
    y2, _ = stage.forward_hidden(x, c2, jnp.asarray(0, jnp.int32),
                                 jnp.asarray(100, jnp.int32))   # einsum path
    np.testing.assert_allclose(np.asarray(y1)[:, :100],
                               np.asarray(y2)[:, :100], atol=1e-5)
