"""LuxTTS release-checkpoint loading: synthesize the reference layout
(model.safetensors + vocos.safetensors + config.json + tokens.txt with
the REAL tensor names — ref: luxtts/model.rs weight layout doc) and load
through the public path.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.audio import (detect_luxtts_checkpoint, load_luxtts,
                                   tiny_luxtts_config)
from cake_tpu.models.audio.luxtts import init_luxtts_params
from cake_tpu.models.audio.luxtts_loader import luxtts_mapping, vocos_mapping
from cake_tpu.utils.mapping import flatten_tree
from cake_tpu.utils.safetensors_io import save_safetensors


def synth_luxtts_dir(tmp_path):
    cfg = tiny_luxtts_config()
    params = init_luxtts_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    vocos = params.pop("vocos")
    flat = flatten_tree(params)
    tensors = {name: np.asarray(flat[path], np.float32)
               for path, name in luxtts_mapping(cfg).items()}
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    flat_v = flatten_tree(vocos)
    vtensors = {name: np.asarray(flat_v[path], np.float32)
                for path, name in vocos_mapping(cfg).items()}
    save_safetensors(str(tmp_path / "vocos.safetensors"), vtensors)
    with open(tmp_path / "config.json", "w") as f:
        json.dump({
            "model": {
                "vocab_size": cfg.vocab_size, "feat_dim": cfg.feat_dim,
                "text_encoder_dim": cfg.text_encoder_dim,
                "text_encoder_num_layers": cfg.text_encoder_num_layers,
                "text_encoder_feedforward_dim":
                    cfg.text_encoder_feedforward_dim,
                "text_encoder_num_heads": cfg.text_encoder_num_heads,
                "text_encoder_cnn_module_kernel":
                    cfg.text_encoder_cnn_module_kernel,
                "fm_decoder_dim": cfg.fm_decoder_dim,
                "fm_decoder_feedforward_dim": cfg.fm_decoder_feedforward_dim,
                "fm_decoder_num_heads": cfg.fm_decoder_num_heads,
                "fm_decoder_num_layers": list(cfg.fm_decoder_num_layers),
                "fm_decoder_downsampling_factor":
                    list(cfg.fm_decoder_downsampling_factor),
                "fm_decoder_cnn_module_kernel":
                    list(cfg.fm_decoder_cnn_module_kernel),
                "query_head_dim": cfg.query_head_dim,
                "value_head_dim": cfg.value_head_dim,
                "pos_dim": cfg.pos_dim, "pos_head_dim": cfg.pos_head_dim,
                "time_embed_dim": cfg.time_embed_dim,
            },
            "feature": {"n_fft": cfg.n_fft, "hop_length": cfg.hop_length,
                        "n_mels": cfg.n_mels,
                        "sample_rate": cfg.sample_rate},
        }, f)
    with open(tmp_path / "tokens.txt", "w") as f:
        for i, ch in enumerate("abcdefghijklmnopqrstuvwxyz '"):
            f.write(f"{ch} {i}\n")
    return cfg


EXPECTED_NAMES = [
    "embed.weight",
    "text_encoder.in_proj.weight",
    "text_encoder.layers.0.norm.log_scale",
    "text_encoder.layers.0.self_attn_weights.in_proj.weight",
    "text_encoder.layers.0.self_attn_weights.linear_pos.weight",
    "text_encoder.layers.0.feed_forward2.in_proj.weight",
    "text_encoder.layers.0.nonlin_attention.in_proj.bias",
    "text_encoder.layers.0.conv_module1.depthwise_conv.weight",
    "text_encoder.layers.0.bypass.bypass_scale",
    "fm_decoder.in_proj.weight",
    "fm_decoder.time_embed.0.weight",
    "fm_decoder.time_embed.2.bias",
    "fm_decoder.stack_time_emb.0.1.weight",
    "fm_decoder.downsample.1.bias",
    "fm_decoder.out_combiner.1.bypass_scale",
    "fm_decoder.layers.1.self_attn2.out_proj.weight",
    "fm_decoder.out_proj.bias",
]
EXPECTED_VOCOS = [
    "backbone.embed.weight",
    "backbone.norm.weight",
    "backbone.convnext.0.dwconv.weight",
    "backbone.convnext.1.gamma",
    "backbone.convnext.0.pwconv1.weight",
    "backbone.final_layer_norm.bias",
    "head.out.weight",
    "head.istft.window",
]


def test_names_and_detection(tmp_path):
    synth_luxtts_dir(tmp_path)
    from cake_tpu.utils.safetensors_io import index_file
    names = set(index_file(str(tmp_path / "model.safetensors")))
    missing = [n for n in EXPECTED_NAMES if n not in names]
    assert not missing, f"missing names: {missing}"
    vnames = set(index_file(str(tmp_path / "vocos.safetensors")))
    missing = [n for n in EXPECTED_VOCOS if n not in vnames]
    assert not missing, f"missing vocos names: {missing}"
    assert detect_luxtts_checkpoint(str(tmp_path))


def test_load_and_generate(tmp_path):
    cfg = synth_luxtts_dir(tmp_path)
    tts = load_luxtts(str(tmp_path), dtype=jnp.float32)
    audio = tts.generate_speech("hello world", steps=2, max_frames=8)
    assert audio.sample_rate == cfg.sample_rate * 2     # 24k -> 48k
    assert len(audio.samples) > 0
    assert np.isfinite(audio.samples).all()
    # tokens.txt drove the phonemizer (letters only, in-vocab)
    ids = tts.phonemizer.tokenize("hello world")
    assert all(0 <= i < 28 for i in ids)


def test_runtime_detection(tmp_path):
    synth_luxtts_dir(tmp_path)
    from cake_tpu.runtime import build_audio_model
    tts = build_audio_model(str(tmp_path), dtype="f32")
    assert type(tts).__name__ == "LuxTTS"


def test_voice_conditioning_changes_output(tmp_path):
    synth_luxtts_dir(tmp_path)
    tts = load_luxtts(str(tmp_path), dtype=jnp.float32)
    from cake_tpu.utils.wav import encode_wav
    rng = np.random.default_rng(0)
    wav = encode_wav(rng.standard_normal(4000).astype(np.float32) * 0.1,
                     24000)
    a = tts.generate_speech("hi there", steps=2, max_frames=6)
    b = tts.generate_speech("hi there", voice_wav=wav, steps=2, max_frames=6)
    assert not np.allclose(a.samples, b.samples)
