"""End-to-end observability through the aiohttp API: a short generation on
a real tiny model must leave non-zero TTFT / decode-latency histograms on
GET /metrics (valid Prometheus text exposition) and per-token phase events
in the span recorder's Chrome-trace export — the acceptance path for the
obs subsystem. /health is asserted alongside (worker liveness shape)."""
import json
import re

import jax.numpy as jnp
import pytest

from cake_tpu import obs
from cake_tpu.api import ApiState, create_app
from tests.test_api import MockTokenizer, with_client

PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (-?[0-9.e+-]+|NaN|[+-]Inf)$')


def _metric_value(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    raise AssertionError(f"{name} not found in exposition")


def _assert_valid_exposition(text: str):
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert PROM_LINE.match(line), f"bad exposition line: {line!r}"


@pytest.fixture(scope="module")
def tiny_cluster_state():
    """DistributedTextModel over a single LOCAL stage (no sockets): runs
    the real per-token decode loop — embed / layers / lm_head / sample as
    distinct phases — on a tiny random-weight CPU model."""
    from cake_tpu.cluster.master import DistributedTextModel, Stage
    from cake_tpu.models import TextModel, tiny_config
    from cake_tpu.models.common.text_model import LocalStage

    cfg = tiny_config("qwen3")
    tm = TextModel(cfg, dtype=jnp.float32, max_cache_len=64)
    stage = Stage("local", 0, cfg.num_hidden_layers,
                  LocalStage(cfg, tm.params, 0, cfg.num_hidden_layers))
    dist = DistributedTextModel(cfg, tm.params, [stage],
                                tokenizer=MockTokenizer(),
                                dtype=jnp.float32, max_cache_len=64)
    return ApiState(model=dist, tokenizer=MockTokenizer(),
                    model_id="tiny-dist")


def test_metrics_health_and_trace_after_generation(tiny_cluster_state):
    obs.RECORDER.enable()
    obs.RECORDER.clear()
    ttft_before = obs.TTFT_SECONDS.count()
    decode_before = obs.DECODE_TOKEN_SECONDS.count()
    out = {}

    async def scenario(client):
        resp = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi there"}],
            "max_tokens": 6, "temperature": 0.0})
        assert resp.status == 200
        body = await resp.json()
        assert body["id"].startswith("chatcmpl-")
        assert body["usage"]["completion_tokens"] >= 2
        out["cid"] = body["id"]

        m = await client.get("/metrics")
        assert m.status == 200
        assert m.headers["Content-Type"].startswith("text/plain")
        out["metrics"] = await m.text()

        h = await client.get("/health")
        assert h.status == 200
        out["health"] = await h.json()

    with_client(tiny_cluster_state, scenario)

    # -- /metrics: valid exposition, non-zero TTFT + decode histograms ------
    text = out["metrics"]
    _assert_valid_exposition(text)
    assert _metric_value(text, "cake_ttft_seconds_count") >= ttft_before + 1
    assert _metric_value(text, "cake_decode_token_seconds_count") \
        >= decode_before + 1
    assert _metric_value(text, "cake_ttft_seconds_sum") > 0
    assert 'cake_generated_tokens_total{path="cluster"}' in text
    assert 'cake_generations_total{kind="text",status="ok"}' in text
    # the middleware counted this very scrape's sibling requests
    assert 'endpoint="/v1/chat/completions",status="200"' in text

    # -- /health ------------------------------------------------------------
    health = out["health"]
    assert health["status"] == "ok"
    assert health["workers"] == []          # local-only stage chain
    assert any(m.startswith("tiny-dist") for m in health["models"])

    # -- span recorder: Chrome-trace JSON with per-token phase events -------
    trace = json.loads(json.dumps(obs.RECORDER.to_chrome_trace()))
    events = trace["traceEvents"]
    names = [e["name"] for e in events]
    assert "prefill" in names
    decode_tokens = [e for e in events if e["name"] == "decode_token"]
    assert len(decode_tokens) >= 2          # one span per decoded token
    for phase in ("embed", "layers", "lm_head", "sample"):
        assert names.count(phase) >= len(decode_tokens), phase
    # events append in completion order, so per thread the END timestamps
    # are monotonic (a parent's start precedes its earlier-appended
    # children — fine for Perfetto, which nests by ts+dur)
    ends: dict = {}
    for e in events:
        if e["ph"] != "X":
            continue
        assert e["dur"] >= 0
        assert e["ts"] + e["dur"] >= ends.get(e["tid"], 0)
        ends[e["tid"]] = e["ts"] + e["dur"]
    # spans recorded inside the generation carry the completion id
    gen_events = [e for e in events
                  if e.get("args", {}).get("request_id")]
    assert gen_events and all(
        e["args"]["request_id"] == out["cid"] for e in gen_events)


def test_trace_endpoint():
    state = ApiState(model=None)

    async def scenario(client):
        obs.RECORDER.disable()
        r = await client.get("/api/v1/trace")
        assert r.status == 409              # recorder off -> explicit error
        obs.RECORDER.enable()
        obs.RECORDER.clear()
        with obs.RECORDER.span("x"):
            pass
        r = await client.get("/api/v1/trace?clear=1")
        assert r.status == 200
        body = await r.json()
        assert any(e["name"] == "x" for e in body["traceEvents"])
        assert len(obs.RECORDER) == 0       # ?clear=1 drained the buffer

    with_client(state, scenario)


def test_health_without_model():
    state = ApiState(model=None)

    async def scenario(client):
        h = await client.get("/health")
        assert h.status == 200
        body = await h.json()
        assert body["status"] == "ok"
        assert body["workers"] == [] and body["models"] == []

    with_client(state, scenario)


def test_metrics_endpoint_label_bounded():
    """Unmatched paths must not mint unbounded endpoint labels."""
    state = ApiState(model=None)

    async def scenario(client):
        for path in ("/nope/a", "/nope/b", "/nope/c"):
            r = await client.get(path)
            assert r.status == 404
        m = await client.get("/metrics")
        text = await m.text()
        assert 'endpoint="unmatched",status="404"' in text
        assert "/nope/a" not in text

    with_client(state, scenario)


def test_flight_endpoint_on_demand():
    """GET /api/v1/flight serves the scheduler-iteration ring read-only
    (?n=K truncates to the newest K); 409 without an engine — the ring
    must be inspectable without waiting for a wedge/DOWN dump."""
    from cake_tpu.serve.flight import FlightRecorder

    state = ApiState(model=None)

    async def scenario(client):
        r = await client.get("/api/v1/flight")
        assert r.status == 409              # no engine -> explicit error

        class FakeEngine:
            flight = FlightRecorder(capacity=8)
        for i in range(12):                 # overflow the ring
            FakeEngine.flight.record(iteration=i, occupancy=0.5)
        state.engine = FakeEngine()
        try:
            r = await client.get("/api/v1/flight")
            assert r.status == 200
            body = await r.json()
            assert body["capacity"] == 8 and body["count"] == 8
            assert [it["iteration"] for it in body["iterations"]] == \
                list(range(4, 12))          # oldest evicted, order kept
            r = await client.get("/api/v1/flight?n=3")
            body = await r.json()
            assert [it["iteration"] for it in body["iterations"]] == \
                [9, 10, 11]
            r = await client.get("/api/v1/flight?n=bogus")
            assert (await r.json())["count"] == 8   # tolerated
        finally:
            state.engine = None

    with_client(state, scenario)


def test_worker_health_reports_last_ok_age():
    from cake_tpu.api.obs_routes import STALE_WORKER_S, worker_health
    from cake_tpu.cluster.client import RemoteStage
    from cake_tpu.cluster.master import Stage

    rs = RemoteStage("127.0.0.1", 0, "k", name="w0")
    rs.total_ops = 1
    rs.last_attempt = obs.now() - 2.0
    rs.last_ok = obs.now() - 2.0

    class M:
        stages = [Stage("remote", 0, 4, rs)]

    (w,) = worker_health(M())
    assert w["name"] == "w0" and w["layers"] == [0, 4] and w["ops"] == 1
    assert 1.5 <= w["last_ok_age_s"] <= 10.0
    assert w["failing"] is False

    # long-idle channel stays healthy (idleness is not failure) ...
    rs.last_attempt = rs.last_ok = obs.now() - 10 * STALE_WORKER_S
    (w,) = worker_health(M())
    assert w["failing"] is False
    # ... but attempts without successes for > threshold flag it
    rs.last_attempt = obs.now()
    (w,) = worker_health(M())
    assert w["failing"] is True
    # wedged mid-forward: one attempt newer than the last success, frozen
    # for > threshold with no further attempts arriving
    rs.last_ok = obs.now() - 2 * STALE_WORKER_S
    rs.last_attempt = rs.last_ok + 0.05
    (w,) = worker_health(M())
    assert w["failing"] is True
    # tried and never succeeded: failing immediately
    rs.last_ok = None
    (w,) = worker_health(M())
    assert w["failing"] is True
