"""FLUX.2-klein: transformer forward, schedule/ids vs the reference
formulas, the Qwen3 encoder's capture+padding semantics, and end-to-end
loading of a synthetic diffusers-layout checkpoint through the public
runtime path (ref: flux2_model.rs, flux2_vae.rs, text_encoder.rs, flux.rs).
"""
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import init_params, tiny_config
from cake_tpu.models.common.layers import embed_tokens, forward_layers
from cake_tpu.models.image import (Flux2ImageModel, Flux2TextEncoder,
                                   detect_flux2_checkpoint, flux2_forward,
                                   flux2_schedule, flux2_transformer_mapping,
                                   flux2_vae_mapping, init_flux2_params,
                                   load_flux2_image_model, tiny_flux2_config)
from cake_tpu.models.image.flux2 import (default_output_layers, empirical_mu,
                                         make_img_ids4, make_txt_ids4)
from cake_tpu.models.image.vae import init_vae_decoder_params
from cake_tpu.utils.export import params_to_hf_tensors
from cake_tpu.utils.mapping import flatten_tree
from cake_tpu.utils.safetensors_io import save_safetensors


def test_empirical_mu_matches_reference_formula():
    # ref flux.rs:216-230 — both branches
    for seq, steps in ((4096, 20), (64, 4), (8192, 50)):
        a1, b1 = 8.73809524e-05, 1.89833333
        a2, b2 = 0.00016927, 0.45666666
        if seq > 4300:
            want = a2 * seq + b2
        else:
            m200, m10 = a2 * seq + b2, a1 * seq + b1
            a = (m200 - m10) / 190.0
            b = m200 - 200.0 * a
            want = a * steps + b
        assert empirical_mu(seq, steps) == pytest.approx(want)


def test_schedule_matches_reference_formula():
    mu = empirical_mu(4096, 20)
    ts = flux2_schedule(20, mu)
    assert len(ts) == 21
    assert ts[0] == pytest.approx(math.exp(mu) / (math.exp(mu) + 0.0), abs=1e-9)
    assert ts[-1] == 0.0
    # spot-check an interior value against the scalar formula
    t = 1.0 - 7 / 19.0
    e = math.exp(mu)
    assert ts[7] == pytest.approx(e / (e + (1.0 / t - 1.0)), rel=1e-9)
    # non-increasing; linspace already ends at 0 and the reference appends
    # a terminal 0 on top (flux.rs:254-255), so the tail is [0, 0]
    assert np.all(np.diff(ts) <= 0) and ts[-2] == 0.0


def test_ids_layout():
    img = np.asarray(make_img_ids4(2, 3))
    assert img.shape == (1, 6, 4)
    assert (img[0, :, 0] == 0).all() and (img[0, :, 3] == 0).all()
    assert img[0, 4].tolist() == [0, 1, 1, 0]    # row-major (y,x)=(1,1)
    txt = np.asarray(make_txt_ids4(5))
    assert txt.shape == (1, 5, 4)
    assert txt[0, :, 3].tolist() == [0, 1, 2, 3, 4]
    assert (txt[0, :, :3] == 0).all()


def test_default_output_layers():
    assert default_output_layers(36) == (8, 17, 26)   # klein-4B
    assert default_output_layers(4) == (0, 1, 2)


def test_flux2_forward_shapes():
    cfg = tiny_flux2_config().transformer
    params = init_flux2_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    img = jax.random.normal(jax.random.PRNGKey(1), (1, 6, cfg.in_channels))
    txt = jax.random.normal(jax.random.PRNGKey(2), (1, 5, cfg.context_in_dim))
    v = flux2_forward(cfg, params, img, make_img_ids4(2, 3), txt,
                      make_txt_ids4(5), jnp.asarray([0.5]))
    assert v.shape == (1, 6, cfg.in_channels)
    arr = np.asarray(v)
    assert np.isfinite(arr).all() and arr.std() > 0


@pytest.fixture
def enc_setup():
    cfg = tiny_config("qwen3", hidden_size=32, intermediate_size=64,
                      num_attention_heads=4, num_key_value_heads=2)
    params = init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    return cfg, params


def test_encoder_captures_match_manual(enc_setup):
    cfg, params = enc_setup
    enc = Flux2TextEncoder(cfg, params, max_len=8, output_layers=(0, 1, 2),
                           dtype=jnp.float32)
    ids = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    got = enc._encode(params, ids, jnp.asarray(8, jnp.int32))
    # manual: full stateless forward capturing after each block
    x = embed_tokens(cfg, params, ids)
    outs = []
    for i in range(3):
        x, _ = forward_layers(cfg, params, x, None, jnp.asarray(0, jnp.int32),
                              layer_range=(i, i + 1),
                              valid_len=jnp.asarray(8, jnp.int32))
        outs.append(x)
    want = jnp.concatenate(outs, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    assert got.shape == (1, 8, 3 * cfg.hidden_size)


def test_encoder_padding_mask(enc_setup):
    """Real-token hidden states must be invariant to pad-slot content —
    the causal+padding mask of text_encoder.rs:161-190."""
    cfg, params = enc_setup
    enc = Flux2TextEncoder(cfg, params, max_len=8, output_layers=(0, 1, 2),
                           dtype=jnp.float32)
    a = jnp.asarray([[1, 2, 3, 9, 9, 9, 9, 9]], jnp.int32)
    b = jnp.asarray([[1, 2, 3, 7, 7, 7, 7, 7]], jnp.int32)
    va = np.asarray(enc._encode(params, a, jnp.asarray(3, jnp.int32)))
    vb = np.asarray(enc._encode(params, b, jnp.asarray(3, jnp.int32)))
    np.testing.assert_allclose(va[:, :3], vb[:, :3], atol=1e-6)
    assert not np.allclose(va[:, 3:], vb[:, 3:])   # pads do differ


# ---------------------------------------------------------------------------
# Synthetic diffusers-layout checkpoint
# ---------------------------------------------------------------------------

# literal spot-checks so a systematic mapping bug cannot hide behind
# synthesize-with-the-same-map
EXPECTED_NAMES = [
    "x_embedder.weight",
    "time_guidance_embed.timestep_embedder.linear_1.weight",
    "double_stream_modulation_img.linear.weight",
    "single_stream_modulation.linear.weight",
    "transformer_blocks.0.attn.to_q.weight",
    "transformer_blocks.0.attn.add_k_proj.weight",
    "transformer_blocks.0.attn.norm_added_q.weight",
    "transformer_blocks.1.ff_context.linear_in.weight",
    "single_transformer_blocks.0.attn.to_qkv_mlp_proj.weight",
    "single_transformer_blocks.1.attn.to_out.weight",
    "norm_out.linear.weight",
    "proj_out.weight",
]
EXPECTED_VAE_NAMES = [
    "post_quant_conv.weight",
    "decoder.conv_in.weight",
    "decoder.mid_block.resnets.0.norm1.weight",
    "decoder.mid_block.attentions.0.to_q.weight",
    "decoder.mid_block.attentions.0.group_norm.weight",
    "decoder.up_blocks.0.resnets.0.conv1.weight",
    "decoder.up_blocks.0.upsamplers.0.conv.weight",
    "decoder.up_blocks.1.resnets.0.conv_shortcut.weight",
    "decoder.conv_norm_out.weight",
    "decoder.conv_out.weight",
]


def _qwen_tokenizer_json(path):
    vocab = {f"w{i}": i for i in range(200)}
    vocab["<unk>"] = 200
    vocab["<|endoftext|>"] = 201
    tok = {"version": "1.0", "truncation": None, "padding": None,
           "added_tokens": [], "normalizer": None,
           "pre_tokenizer": {"type": "Whitespace"}, "post_processor": None,
           "decoder": None,
           "model": {"type": "WordLevel", "vocab": vocab,
                     "unk_token": "<unk>"}}
    with open(path, "w") as f:
        json.dump(tok, f)


@pytest.fixture
def flux2_dir(tmp_path):
    pipe = tiny_flux2_config()
    root = tmp_path / "flux2"
    for sub in ("transformer", "vae", "text_encoder", "tokenizer"):
        (root / sub).mkdir(parents=True)

    tmap = flux2_transformer_mapping(pipe.transformer)
    tparams = init_flux2_params(pipe.transformer, jax.random.PRNGKey(0),
                                jnp.float32)
    flat = flatten_tree(tparams)
    save_safetensors(str(root / "transformer" / "model.safetensors"),
                     {name: np.asarray(flat[path], np.float32)
                      for path, name in tmap.items()})

    vmap, vtrans = flux2_vae_mapping(pipe.vae)
    vparams = init_vae_decoder_params(pipe.vae, jax.random.PRNGKey(1),
                                      jnp.float32)
    lc = pipe.vae.latent_channels
    vparams["post_quant_conv"] = {
        "weight": np.eye(lc, dtype=np.float32).reshape(lc, lc, 1, 1),
        "bias": np.zeros((lc,), np.float32)}
    vflat = flatten_tree(vparams)
    vtensors = {}
    for path, name in vmap.items():
        arr = np.asarray(vflat[path], np.float32)
        if path in vtrans:          # inverse of the linear->conv reshape
            arr = arr.reshape(arr.shape[0], arr.shape[1])
        vtensors[name] = arr
    ic = pipe.transformer.in_channels
    vtensors["bn.running_mean"] = np.full((ic,), 0.1, np.float32)
    vtensors["bn.running_var"] = np.full((ic,), 0.9, np.float32)
    save_safetensors(str(root / "vae" / "model.safetensors"), vtensors)

    enc_cfg = tiny_config("qwen3", hidden_size=32, intermediate_size=64,
                          num_attention_heads=4, num_key_value_heads=2)
    enc_params = init_params(enc_cfg, jax.random.PRNGKey(2), jnp.float32)
    save_safetensors(str(root / "text_encoder" / "model.safetensors"),
                     params_to_hf_tensors(enc_cfg, enc_params))
    (root / "text_encoder" / "config.json").write_text(json.dumps(dict(
        architectures=["Qwen3ForCausalLM"], vocab_size=256, hidden_size=32,
        intermediate_size=64, num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=2, rms_norm_eps=1e-5, rope_theta=10000.0,
        max_position_embeddings=128, eos_token_id=2)))
    _qwen_tokenizer_json(root / "tokenizer" / "tokenizer.json")
    (root / "model_index.json").write_text(json.dumps(
        {"_class_name": "Flux2Pipeline"}))
    # tiny axes don't follow the head_dim//4 rule (sum must == head_dim)
    (root / "flux_config.json").write_text(json.dumps(
        {"flux2": {"axes_dims": list(pipe.transformer.axes_dims)}}))
    return str(root), pipe


def test_detect_flux2(flux2_dir, tmp_path):
    root, _ = flux2_dir
    ckpt = detect_flux2_checkpoint(root)
    assert ckpt is not None
    assert os.path.isdir(ckpt.text_encoder_dir)
    assert detect_flux2_checkpoint(str(tmp_path)) is None


def test_synth_names_literal(flux2_dir):
    """The synthesized checkpoint must contain the published diffusers
    names verbatim."""
    root, _ = flux2_dir
    from cake_tpu.utils.safetensors_io import index_file
    tnames = set(index_file(os.path.join(root, "transformer",
                                         "model.safetensors")).keys())
    for n in EXPECTED_NAMES:
        assert n in tnames, n
    vnames = set(index_file(os.path.join(root, "vae",
                                         "model.safetensors")).keys())
    for n in EXPECTED_VAE_NAMES:
        assert n in vnames, n


def test_load_and_generate_end_to_end(flux2_dir):
    root, pipe = flux2_dir
    model = load_flux2_image_model(root, dtype=jnp.float32, max_txt_len=8)
    assert isinstance(model, Flux2ImageModel)
    # loaded weights equal the synthesized originals
    want = init_flux2_params(pipe.transformer, jax.random.PRNGKey(0),
                             jnp.float32)
    got = model.params["transformer"]
    np.testing.assert_allclose(
        np.asarray(got["double"][0]["img_attn"]["q"]["weight"]),
        np.asarray(want["double"][0]["img_attn"]["q"]["weight"]), atol=1e-6)
    # bn stats picked up
    assert model.bn_mean[0] == pytest.approx(0.1)
    img = model.generate_image("a tiny test prompt", width=32, height=32,
                               steps=2, seed=3)
    assert img.size == (32, 32)
    assert np.asarray(img).std() > 0


def test_runtime_dispatch_flux2(flux2_dir):
    from cake_tpu.runtime import build_image_model
    root, _ = flux2_dir
    model = build_image_model(root, dtype="f32")
    assert isinstance(model, Flux2ImageModel)


def test_runtime_demo_flux2():
    from cake_tpu.runtime import build_image_model
    model = build_image_model("demo:flux2", dtype="f32")
    img = model.generate_image("demo", width=16, height=16, steps=1)
    assert img.size == (16, 16)
