"""Ragged (sort-based segment-GEMM) MoE dispatch vs the dense combine path.

The two paths share router + expert weights and must agree numerically;
the ragged path must also issue FLOPs proportional to k/E, which is pinned
by counting dot FLOPs in the compiled HLO (ref: qwen3_moe/moe.rs top-k
dispatch; VERDICT r3 item 3)."""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cake_tpu.ops.moe import (RAGGED_MIN_TOKENS, _moe_ragged,
                              _ragged_available, moe_ffn, router_topk)

# on jax builds without lax.ragged_dot_general the dense combine serves
# every shape (ops/moe._ragged_enabled gates it); the tests that pin the
# ragged machinery itself have nothing to measure there
needs_ragged = pytest.mark.skipif(
    not _ragged_available(),
    reason="installed jax lacks lax.ragged_dot_general")


def _bank(rng, e, i, h):
    return (jnp.asarray(rng.normal(0, 0.3, (e, h)), jnp.float32),
            jnp.asarray(rng.normal(0, 0.3, (e, i, h)), jnp.float32),
            jnp.asarray(rng.normal(0, 0.3, (e, i, h)), jnp.float32),
            jnp.asarray(rng.normal(0, 0.3, (e, h, i)), jnp.float32))


@pytest.mark.parametrize("act", ["silu", "gelu"])
@pytest.mark.parametrize("gate_act", ["softmax", "sigmoid"])
def test_ragged_matches_dense(act, gate_act, rng):
    e, i, h, t, k = 8, 16, 32, 48, 2
    router, gp, up, dp = _bank(rng, e, i, h)
    x = jnp.asarray(rng.normal(0, 1, (t, h)), jnp.float32)
    assert t >= RAGGED_MIN_TOKENS     # moe_ffn takes the ragged path
    got = moe_ffn(x, router, gp, up, dp, k, True, gate_act, act)

    logits = jnp.einsum("th,eh->te", x, router,
                        preferred_element_type=jnp.float32)
    weights, idx = router_topk(logits, k, True, gate_act)
    w = np.asarray(weights)
    ref = np.zeros((t, h), np.float32)
    for tok in range(t):
        for j in range(k):
            ex = int(idx[tok, j])
            g = np.asarray(gp[ex]) @ np.asarray(x[tok])
            u = np.asarray(up[ex]) @ np.asarray(x[tok])
            if act == "silu":
                a = g / (1 + np.exp(-g)) * u
            else:
                a = 0.5 * g * (1 + np.tanh(np.sqrt(2 / np.pi)
                                           * (g + 0.044715 * g ** 3))) * u
            ref[tok] += w[tok, j] * (np.asarray(dp[ex]) @ a)
    assert np.max(np.abs(np.asarray(got) - ref)) < 2e-4


@needs_ragged
def test_decode_still_dense_and_consistent(rng):
    """T below the threshold uses the dense combine; same numerics."""
    e, i, h, k = 8, 16, 32, 2
    router, gp, up, dp = _bank(rng, e, i, h)
    x = jnp.asarray(rng.normal(0, 1, (4, h)), jnp.float32)
    dense = moe_ffn(x, router, gp, up, dp, k, True)
    logits = jnp.einsum("th,eh->te", x, router,
                        preferred_element_type=jnp.float32)
    weights, idx = router_topk(logits, k, True, "softmax")
    ragged = _moe_ragged(x, weights, idx, gp, up, dp, "silu")
    assert np.max(np.abs(np.asarray(dense) - np.asarray(ragged))) < 2e-4


@needs_ragged
def test_dispatch_structure_by_token_count(rng):
    """Prefill-sized T emits ragged_dot_general (TPU segment-GEMM whose
    FLOPs are (k/E) * dense — the CPU backend densifies it in lowering, so
    the k/E claim is measured on hardware by benches/bench_micro.py, and
    here we pin the *dispatch structure* at the jaxpr level); decode-sized
    T stays on the dense combine with no gather/sort machinery."""
    e, i, h, k = 16, 8, 32, 2
    router, gp, up, dp = _bank(rng, e, i, h)

    def f(x):
        return moe_ffn(x, router, gp, up, dp, k, True)

    big = jnp.zeros((RAGGED_MIN_TOKENS, h), jnp.float32)
    small = jnp.zeros((4, h), jnp.float32)
    assert "ragged_dot_general" in str(jax.make_jaxpr(f)(big))
    jx_small = str(jax.make_jaxpr(f)(small))
    assert "ragged_dot_general" not in jx_small
    assert " sort[" not in jx_small      # no dispatch overhead at decode
