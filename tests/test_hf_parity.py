"""External numerics ground truth: every text family cross-checked against
the installed `transformers` implementation (CPU, f32, tiny random configs).

The golden fixtures (tests/golden) pin our own history; these tests pin the
*semantics* to an independent implementation — HF is what the real release
checkpoints were trained with, so divergence here means wrong-from-day-one
numerics, not a harmless style choice (BASELINE.json north star: identical
logits atol 1e-3; reference analog: cake-core/tests/unit_tests/
test_backend_ops.rs cross-checking ops against candle).

Weights flow OUR pytree -> utils/export.params_to_hf_tensors -> HF
state_dict, so the mapping layer is under test too (it is the inverse of
utils/loaders.py, which round-trip tests already pin against it).
"""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.models.common.config import ModelConfig, tiny_config
from cake_tpu.models.common.layers import (forward_train, init_params,
                                           make_rope)
from cake_tpu.models.common.text_model import TextModel
from cake_tpu.utils.export import params_to_hf_tensors

PROMPT = [11, 23, 5, 190, 77, 3, 149, 66, 20, 101, 7, 55]
ATOL = 1e-3


def randomize(cfg: ModelConfig, params: dict, seed: int) -> dict:
    """Replace every weight leaf with non-trivial random values so identity
    weights (norms at 1, zero biases) can't hide mapping or scaling bugs."""
    rng = np.random.default_rng(seed)
    rope = params.pop("rope")

    def rand(leaf):
        arr = rng.normal(0.0, 0.05, np.shape(leaf)).astype(np.float32)
        return jnp.asarray(arr)

    out = jax.tree.map(rand, params)
    out["rope"] = rope
    return out


def our_logits(cfg: ModelConfig, params: dict, prompt=PROMPT) -> np.ndarray:
    """[S, V] f32 logits from the stateless forward."""
    tokens = jnp.asarray([prompt], jnp.int32)
    return np.asarray(forward_train(cfg, params, tokens)[0], np.float32)


def our_cached_last_logits(cfg: ModelConfig, params: dict,
                           prompt=PROMPT) -> np.ndarray:
    """Last-token logits through the product prefill+decode cache path."""
    model = TextModel(cfg, params=params, dtype=jnp.float32, max_cache_len=64)
    cache = model.new_cache()
    _, cache = model.prefill(cache, prompt[:-1])
    logits, _ = model.decode_logits(cache, prompt[-1])
    return np.asarray(logits[0], np.float32)


def load_hf(model_cls, hf_config, tensors: dict[str, np.ndarray],
            allow_missing: tuple[str, ...] = ()):
    hf_config._attn_implementation = "eager"
    torch.manual_seed(0)
    model = model_cls(hf_config)
    sd = {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in tensors.items()}
    missing, unexpected = model.load_state_dict(sd, strict=False)
    assert not unexpected, f"tensors HF does not expect: {unexpected}"
    bad = [k for k in missing
           if not any(k.startswith(p) or k.endswith(p) for p in allow_missing)]
    assert not bad, f"HF tensors our export did not provide: {bad}"
    if getattr(hf_config, "tie_word_embeddings", False):
        model.tie_weights()
    model.eval()
    return model


def hf_logits(model, prompt=PROMPT) -> np.ndarray:
    with torch.no_grad():
        out = model(input_ids=torch.tensor([prompt]), use_cache=False)
    return out.logits[0].float().numpy()


def assert_close(ours: np.ndarray, theirs: np.ndarray, what: str):
    err = np.max(np.abs(ours - theirs))
    assert err < ATOL, f"{what}: max |Δlogit| = {err:.2e} >= {ATOL}"


def check_family(cfg: ModelConfig, model_cls, hf_config, seed: int = 0,
                 fuse_phi: bool = False,
                 allow_missing: tuple[str, ...] = (),
                 extra_tensors=None, prompt=PROMPT):
    params = randomize(cfg, init_params(cfg, jax.random.PRNGKey(0),
                                        jnp.float32), seed)
    params["rope"] = make_rope(cfg)
    tensors = params_to_hf_tensors(cfg, params, fuse_phi=fuse_phi)
    if extra_tensors:
        tensors = extra_tensors(params, tensors)
    model = load_hf(model_cls, hf_config, tensors, allow_missing)
    ref = hf_logits(model, prompt)
    assert_close(our_logits(cfg, params, prompt), ref, "stateless forward")
    assert_close(our_cached_last_logits(cfg, params, prompt), ref[-1],
                 "cached prefill+decode last logit")


# ---------------------------------------------------------------------------
# dense llama-likes
# ---------------------------------------------------------------------------

_TINY_HF = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=2, rms_norm_eps=1e-5, rope_theta=10000.0,
                max_position_embeddings=128, eos_token_id=2,
                tie_word_embeddings=False)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_llama():
    from transformers import LlamaConfig, LlamaForCausalLM
    check_family(tiny_config("llama"), LlamaForCausalLM,
                 LlamaConfig(attention_bias=False, **_TINY_HF))


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_llama3_rope_scaling():
    scaling = dict(rope_type="llama3", factor=8.0, high_freq_factor=4.0,
                   low_freq_factor=1.0, original_max_position_embeddings=32)
    from transformers import LlamaConfig, LlamaForCausalLM
    check_family(tiny_config("llama", rope_scaling=scaling),
                 LlamaForCausalLM,
                 LlamaConfig(rope_scaling=dict(scaling), **_TINY_HF))


def test_falcon3():
    # Falcon3 ships Llama-architecture checkpoints (ref: models/falcon3);
    # HF ground truth is therefore LlamaForCausalLM.
    from transformers import LlamaConfig, LlamaForCausalLM
    check_family(tiny_config("falcon3"), LlamaForCausalLM,
                 LlamaConfig(**_TINY_HF))


def test_qwen2():
    from transformers import Qwen2Config, Qwen2ForCausalLM
    check_family(tiny_config("qwen2"), Qwen2ForCausalLM,
                 Qwen2Config(**_TINY_HF))


def test_qwen3():
    from transformers import Qwen3Config, Qwen3ForCausalLM
    check_family(tiny_config("qwen3"), Qwen3ForCausalLM,
                 Qwen3Config(head_dim=16, **_TINY_HF))


def test_mistral_sliding_window():
    from transformers import MistralConfig, MistralForCausalLM
    check_family(tiny_config("mistral", sliding_window=4),
                 MistralForCausalLM,
                 MistralConfig(sliding_window=4, **_TINY_HF))


def test_phi4():
    from transformers import Phi3Config, Phi3ForCausalLM
    check_family(tiny_config("phi4", partial_rotary_factor=0.5),
                 Phi3ForCausalLM,
                 Phi3Config(partial_rotary_factor=0.5, pad_token_id=0,
                            **_TINY_HF),
                 fuse_phi=True)


def test_olmo2():
    from transformers import Olmo2Config, Olmo2ForCausalLM
    check_family(tiny_config("olmo2"), Olmo2ForCausalLM,
                 Olmo2Config(**_TINY_HF))


def test_exaone4():
    from transformers import Exaone4Config, Exaone4ForCausalLM
    check_family(tiny_config("exaone4", sliding_window=4),
                 Exaone4ForCausalLM,
                 Exaone4Config(sliding_window=4, sliding_window_pattern=4,
                               **_TINY_HF))


def test_gemma3():
    from transformers import Gemma3ForCausalLM, Gemma3TextConfig
    d = dict(_TINY_HF)
    d.update(rope_theta=1_000_000.0, tie_word_embeddings=True)
    cfg = tiny_config("gemma3", rope_theta=1_000_000.0,
                      query_pre_attn_scalar=32, sliding_window=4,
                      sliding_window_pattern=2, rope_local_base_freq=10000.0,
                      rope_scaling={"rope_type": "linear", "factor": 8.0})
    hf = Gemma3TextConfig(head_dim=16, sliding_window=4,
                          sliding_window_pattern=2, query_pre_attn_scalar=32,
                          rope_local_base_freq=10000.0,
                          rope_scaling={"rope_type": "linear", "factor": 8.0},
                          **d)
    check_family(cfg, Gemma3ForCausalLM, hf, allow_missing=("lm_head.weight",))


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------


def _qwen3_next_tensors(cfg):
    """Rewrite our exported GDN projections into HF Qwen3Next's interleaved
    in_proj_qkvz/in_proj_ba layout (inverse of the loader path)."""
    from cake_tpu.models.qwen3_5 import hf_qkvz_ba_from_flat

    def convert(params, tensors):
        out = {}
        for k, v in tensors.items():
            if k.endswith(".linear_attn.in_proj.weight"):
                qkvz, ba = hf_qkvz_ba_from_flat(cfg, v)
                base = k[:-len(".in_proj.weight")]
                out[base + ".in_proj_qkvz.weight"] = qkvz
                out[base + ".in_proj_ba.weight"] = ba
            else:
                out[k] = v
        return out
    return convert


def _qwen3_next_hf(**over):
    from transformers import Qwen3NextConfig
    layer_types = ["linear_attention" if (i + 1) % 4 else "full_attention"
                   for i in range(4)]
    d = dict(_TINY_HF)
    d.update(head_dim=16, partial_rotary_factor=0.25,
             linear_conv_kernel_dim=4, linear_num_key_heads=2,
             linear_key_head_dim=16, linear_num_value_heads=4,
             linear_value_head_dim=16, layer_types=layer_types,
             num_experts=0, mlp_only_layers=list(range(4)))
    d.update(over)
    return Qwen3NextConfig(**d)


def test_qwen3_5():
    """Gated-DeltaNet hybrid vs HF Qwen3Next (the released GDN family)."""
    import dataclasses

    from transformers import Qwen3NextForCausalLM
    cfg = tiny_config("qwen3_5", linear_num_key_heads=2)
    cfg = dataclasses.replace(cfg, model_prefix="model")
    check_family(cfg, Qwen3NextForCausalLM, _qwen3_next_hf(),
                 extra_tensors=_qwen3_next_tensors(cfg))


def test_qwen3_5_moe():
    import dataclasses

    from transformers import Qwen3NextForCausalLM
    cfg = tiny_config("qwen3_5_moe", linear_num_key_heads=2,
                      shared_expert_intermediate_size=48)
    cfg = dataclasses.replace(cfg, model_prefix="model")
    hf = _qwen3_next_hf(num_experts=8, num_experts_per_tok=2,
                        moe_intermediate_size=32, norm_topk_prob=True,
                        shared_expert_intermediate_size=48,
                        mlp_only_layers=[])
    check_family(cfg, Qwen3NextForCausalLM, hf,
                 extra_tensors=_qwen3_next_tensors(cfg))


def test_qwen3_moe():
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM
    check_family(tiny_config("qwen3_moe"), Qwen3MoeForCausalLM,
                 Qwen3MoeConfig(head_dim=16, num_experts=8,
                                num_experts_per_tok=2,
                                moe_intermediate_size=32, norm_topk_prob=True,
                                decoder_sparse_step=1, mlp_only_layers=[],
                                **_TINY_HF))


# ---------------------------------------------------------------------------
# diffusion text encoders (FLUX.1 / SD / SDXL conditioning)
# ---------------------------------------------------------------------------


def _leaf(params, path: str):
    cur = params
    for part in path.split("."):
        cur = cur[int(part)] if part.isdigit() else cur[part]
    return np.asarray(cur, np.float32)


def _hf_tensors_from_mapping(params, mapping: dict) -> dict:
    return {hf_name: _leaf(params, path) for path, hf_name in mapping.items()}


def _rand_pytree(params, seed):
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda l: jnp.asarray(rng.normal(0, 0.05, np.shape(l)),
                              jnp.float32), params)


@pytest.mark.parametrize("act,projection", [("quick_gelu", None),
                                            ("gelu", 24)])
def test_clip_text_encoder(act, projection):
    from transformers import CLIPTextConfig as HFCLIPConfig
    from transformers import CLIPTextModel, CLIPTextModelWithProjection

    from cake_tpu.models.text_encoders.clip import (clip_mapping,
                                                    clip_text_forward,
                                                    init_clip_params,
                                                    tiny_clip_config)
    import dataclasses
    cfg = dataclasses.replace(tiny_clip_config(), hidden_act=act,
                              projection_dim=projection)
    params = _rand_pytree(init_clip_params(cfg, jax.random.PRNGKey(0)), 3)
    tensors = _hf_tensors_from_mapping(params, clip_mapping(cfg))
    hf_cfg = HFCLIPConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_layers, num_attention_heads=cfg.num_heads,
        intermediate_size=cfg.intermediate_size,
        max_position_embeddings=cfg.max_positions, hidden_act=act,
        eos_token_id=cfg.eot_token_id, bos_token_id=0,
        projection_dim=projection or 512)
    cls = CLIPTextModelWithProjection if projection else CLIPTextModel
    model = load_hf(cls, hf_cfg, tensors,
                    allow_missing=("position_ids",))
    ids = [[5, 17, 2, 44, 80, cfg.eot_token_id, 0, 0]]
    with torch.no_grad():
        out = model(input_ids=torch.tensor(ids), output_hidden_states=True)
    hidden, pooled, penult = clip_text_forward(
        cfg, params, jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        hf_hidden = (out.last_hidden_state if projection is None
                     else model.text_model(torch.tensor(ids)).last_hidden_state)
    assert_close(np.asarray(hidden), hf_hidden.numpy(), "clip hidden")
    assert_close(np.asarray(penult), out.hidden_states[-2].numpy(),
                 "clip penultimate")
    hf_pooled = (out.pooler_output if projection is None
                 else out.text_embeds)
    assert_close(np.asarray(pooled), hf_pooled.detach().numpy(),
                 "clip pooled")


def test_t5_encoder():
    from transformers import T5Config as HFT5Config
    from transformers import T5EncoderModel

    from cake_tpu.models.text_encoders.t5 import (init_t5_params, t5_encode,
                                                  t5_mapping, tiny_t5_config)
    cfg = tiny_t5_config()
    params = _rand_pytree(init_t5_params(cfg, jax.random.PRNGKey(0)), 4)
    tensors = _hf_tensors_from_mapping(params, t5_mapping(cfg))
    hf_cfg = HFT5Config(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        num_layers=cfg.num_layers, num_heads=cfg.num_heads, d_kv=cfg.d_kv,
        d_ff=cfg.d_ff, relative_attention_num_buckets=cfg.relative_buckets,
        relative_attention_max_distance=cfg.relative_max_distance,
        layer_norm_epsilon=cfg.layer_norm_eps, feed_forward_proj="gated-gelu",
        is_encoder_decoder=False, use_cache=False, tie_word_embeddings=False)
    model = load_hf(T5EncoderModel, hf_cfg, tensors,
                    allow_missing=("encoder.embed_tokens.weight",))
    ids = [[5, 17, 2, 44, 80, 9, 1, 0]]
    with torch.no_grad():
        ref = model(input_ids=torch.tensor(ids)).last_hidden_state.numpy()
    ours = np.asarray(t5_encode(cfg, params, jnp.asarray(ids, jnp.int32)),
                      np.float32)
    assert_close(ours, ref, "t5 encoder hidden")
