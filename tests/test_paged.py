"""Paged KV subsystem (ISSUE 9): allocator invariants, paged-vs-contiguous
bit parity (llama + qwen3_5/GDN), refcount-bump prefix hits (no KV copy),
steady-state recompile pin across block-table updates, and pool-exhaustion
preemption (swap AND recompute) with bit-identical continuation.

Every engine in this module uses the SAME pool shape (12 blocks x 8
tokens, chunk 16, ctx 128) so the paged executables compile once per
model and are reused across engines — the tier-1 suite is timeout-capped
and a fresh pool shape costs ~10s of XLA compile on this box."""
import random
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import KVPoolExhausted, ServeEngine
from cake_tpu.serve.paged import BlockAllocator, pow2_block_tokens

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16
BT = 8
BLOCKS = 12         # 96 tokens of pool — deliberately < slots * ctx


# ---------------------------------------------------------------------------
# allocator: pure host, no jax
# ---------------------------------------------------------------------------


def test_pow2_block_tokens_alignment():
    assert pow2_block_tokens(16, 64) == 16
    assert pow2_block_tokens(24, 64) == 16    # round down, never up
    assert pow2_block_tokens(7, 64) == 8      # floor 8
    assert pow2_block_tokens(256, 32) == 32   # never exceeds the chunk


def test_allocator_basic_refcount_and_double_free():
    a = BlockAllocator(4, 8, slots=2, max_blocks=4)
    p0, p1 = a.alloc(), a.alloc()
    a.map(0, 0, p0)
    a.map(0, 1, p1)
    assert a.used_count == 2 and a.free_count == 2
    # share p0 with slot 1 (the prefix-hit shape)
    a.ref(p0)
    a.map(1, 0, p0)
    assert a.shared_count == 1
    a.check()
    # releasing slot 1 keeps p0 alive under slot 0
    assert a.unmap_slot(1) == []
    assert a.refcount(p0) == 1 and a.shared_count == 0
    assert sorted(a.unmap_slot(0)) == sorted([p0, p1])
    assert a.free_count == 4
    with pytest.raises(ValueError):
        a.deref(p0)                           # double free
    a.check()


def test_allocator_cow_fork_moves_ref():
    a = BlockAllocator(4, 8, slots=2, max_blocks=4)
    shared = a.alloc()
    a.map(0, 0, shared)
    a.ref(shared)
    a.map(1, 0, shared)
    copies = []
    pid = a.ensure_writable(1, 0, lambda s, d: copies.append((s, d)))
    assert pid != shared and copies == [(shared, pid)]
    assert a.tables[1][0] == pid and a.tables[0][0] == shared
    assert a.refcount(shared) == 1 and a.refcount(pid) == 1
    assert a.cow_forks == 1
    a.check()
    # exclusive block: no fork, no copy
    assert a.ensure_writable(0, 0, lambda s, d: copies.append("no")) \
        == shared
    assert len(copies) == 1


def test_allocator_property_random_ops():
    """Randomized alloc/map/share/release churn keeps every invariant
    (refcounts == mappings + pins, no double ownership, free xor used)."""
    rng = random.Random(9)
    a = BlockAllocator(8, 8, slots=3, max_blocks=6)
    pins: list[int] = []
    for _ in range(400):
        op = rng.random()
        if op < 0.35:
            slot = rng.randrange(3)
            idx = rng.randrange(6)
            if a.tables[slot][idx] == a.NULL:
                a.ensure(slot, idx)
        elif op < 0.55:
            # share an existing mapped block into a free entry elsewhere
            owners = [(s, p) for s in range(3) for p in a.tables[s]
                      if p != a.NULL]
            if owners:
                _, pid = rng.choice(owners)
                dst = rng.randrange(3)
                empties = [i for i, p in enumerate(a.tables[dst])
                           if p == a.NULL]
                if empties and pid not in a.tables[dst]:
                    a.ref(pid)
                    a.map(dst, rng.choice(empties), pid)
        elif op < 0.7:
            used = [p for p in range(8) if a.refcount(p) >= 1]
            if used:
                pid = rng.choice(used)
                a.ref(pid, cache_pin=True)
                pins.append(pid)
        elif op < 0.85:
            if pins:
                a.deref(pins.pop(), cache_pin=True)
        else:
            a.unmap_slot(rng.randrange(3))
        a.check()
    for pid in pins:
        a.deref(pid, cache_pin=True)
    for s in range(3):
        a.unmap_slot(s)
    a.check()
    assert a.free_count == 8


def test_paged_gather_masks_stale_tenant():
    """A freed block is never wiped on the device: the gather masks
    entries from a previous tenant's block range (pos // bt != table
    index) AND entries at/past the slot's write frontier — the
    same-index recycling case that would otherwise present a stale key
    at a position the [cache ; chunk] prefill concat is about to write
    (the double-key corruption the frontier guard exists for)."""
    from cake_tpu.models.common.cache import paged_gather_layer
    pl = {"k": jnp.zeros((3, 4, 1, 2)), "v": jnp.zeros((3, 4, 1, 2)),
          "pos": jnp.full((3, 4), -1, jnp.int32)}
    # block 1 holds positions 4..7 (a previous tenant's block index 1)
    pl["pos"] = pl["pos"].at[1].set(jnp.arange(4, 8))
    # new tenant maps it at table index 0 (logical positions 0..3)
    table = jnp.asarray([1, 3, 3], jnp.int32)       # 3 == NULL
    out = paged_gather_layer(pl, table, jnp.int32(12))
    assert int(jnp.max(out["pos"])) == -1           # stale pos invisible
    # same block at its OWN index, frontier past it: passes through
    table = jnp.asarray([3, 1, 3], jnp.int32)
    out = paged_gather_layer(pl, table, jnp.int32(8))
    np.testing.assert_array_equal(np.asarray(out["pos"][4:8]),
                                  np.arange(4, 8))
    # same-index recycling: frontier BELOW the stale entries masks them
    # (the row's contract is "holds exactly positions 0..frontier-1")
    out = paged_gather_layer(pl, table, jnp.int32(6))
    np.testing.assert_array_equal(np.asarray(out["pos"][4:8]),
                                  [4, 5, -1, -1])


# ---------------------------------------------------------------------------
# e2e: tiny CPU llama through the paged engine
# ---------------------------------------------------------------------------


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = TextModel(tiny_config("llama"), dtype=jnp.float32,
                           max_cache_len=CTX)
    return _MODEL


@pytest.fixture(scope="module")
def model():
    return _model()


def _engine(model, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_queue", 8)
    kw.setdefault("ctx_len", CTX)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("kv_blocks", BLOCKS)
    kw.setdefault("kv_block_tokens", BT)
    kw.setdefault("prefix_cache_mb", 0)
    return ServeEngine(model, **kw)


@pytest.fixture(scope="module")
def engine(model):
    eng = _engine(model, prefix_cache_mb=8)
    yield eng
    eng.close()


def _ref(model, prompt, n, sampling=GREEDY):
    toks, _ = model.generate(list(prompt), max_new_tokens=n,
                             sampling=sampling)
    return toks


P_A = [3, 17, 42, 99, 7]
P_B = [100, 2, 5, 9, 11, 40]
SYS = [3 + (i * 7) % 200 for i in range(40)]        # 2 full share units


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_paged_engine_greedy_matches_contiguous(model, engine):
    """Concurrent greedy requests through the paged pool reproduce the
    contiguous sequential path bit-for-bit — the gathered block view has
    the contiguous row's exact layout, so same bytes, same math."""
    reqs = [engine.submit(p, max_new_tokens=n, sampling=GREEDY)
            for p, n in ((P_A, 12), (P_B, 9))]
    for r, (p, n) in zip(reqs, ((P_A, 12), (P_B, 9))):
        assert r.wait(180)
        assert "error" not in r.result, r.result.get("error")
        assert r.result["tokens"] == _ref(model, p, n)


def test_paged_engine_repeat_penalty_parity(model, engine):
    scfg = SamplingConfig(temperature=0.0, repeat_penalty=1.3)
    r = engine.submit(P_A, max_new_tokens=10, sampling=scfg)
    assert r.wait(180)
    assert r.result["tokens"] == _ref(model, P_A, 10, scfg)


def test_paged_prefix_hit_is_refcount_bump(model, engine):
    """A prefix hit maps the CACHED physical blocks into the new slot's
    table — zero KV bytes copied. Pinned observably: the hit request
    reports skipped tokens, its table prefix IS the cache entry's block
    ids (identity, not equal bytes), and the shared gauge goes >= 1
    while both the cache and the slot hold the blocks."""
    from cake_tpu.obs import SERVE_KV_BLOCKS_SHARED
    pa = SYS + [9, 11]
    pb = SYS + [77, 31]
    ra = engine.submit(pa, max_new_tokens=6, sampling=GREEDY)
    assert ra.wait(180)
    assert ra.result["tokens"] == _ref(model, pa, 6)
    assert ra.stats["prefix_hit_tokens"] == 0
    # warm cache now pins the two SYS units
    rb = engine.submit(pb, max_new_tokens=40, sampling=GREEDY)
    deadline = time.monotonic() + 60
    while not rb.tokens and time.monotonic() < deadline:
        time.sleep(0.005)
    assert rb.tokens, "hit request never started decoding"
    # while rb is live its slot shares the cache's blocks by refcount
    alloc = engine.paged.alloc
    assert alloc.shared_count >= 2, "prefix blocks not shared"
    assert SERVE_KV_BLOCKS_SHARED.value() >= 2
    entry = next(iter(engine.prefix_cache._blocks.values()))
    slot_pids = alloc.tables[rb.slot][:len(entry.pids)]
    assert slot_pids == entry.pids, "hit did not map the cached blocks"
    rb.cancel()
    assert rb.wait(60)
    assert rb.stats["prefix_hit_tokens"] == 32      # 2 units x 16 tokens
    # and the spliced continuation is still bit-identical
    rc = engine.submit(pb, max_new_tokens=6, sampling=GREEDY)
    assert rc.wait(180)
    assert rc.result["tokens"] == _ref(model, pb, 6)


def test_paged_decode_steady_state_no_recompiles(model, engine):
    """Block-table updates (decode crossing block boundaries allocates
    fresh blocks mid-generation) must compile NOTHING new: the table is
    a traced argument, nb is the only static one."""
    from cake_tpu.analysis.sanitizers import assert_no_recompiles
    warm = engine.submit(P_A, max_new_tokens=20, sampling=GREEDY)
    assert warm.wait(180)
    with assert_no_recompiles(model._decode_slots_paged,
                              label="paged decode steady state"):
        # 5-token prompt + 20 tokens crosses block boundaries at 8, 16
        # and 24 — three live table remaps under the guard
        r = engine.submit(P_A, max_new_tokens=20, sampling=GREEDY)
        assert r.wait(180)
    assert r.result["tokens"] == warm.result["tokens"]


@pytest.mark.parametrize("mode", ["swap", "recompute"])
def test_paged_exhaustion_preempts_then_bit_identical(model, mode):
    """Two streams whose KV outgrows the 96-token pool force preemption;
    the victim resumes when blocks free and BOTH outputs stay bit-
    identical to the sequential path (swap restores exact bytes;
    recompute replays — the rebuild parity rule)."""
    from cake_tpu.obs import SERVE_PREEMPTIONS
    before = SERVE_PREEMPTIONS.value(mode=mode)
    ref_a = _ref(model, P_A, 60)
    ref_b = _ref(model, P_B, 60)
    eng = _engine(model, preempt_mode=mode)
    try:
        ra = eng.submit(P_A, max_new_tokens=60, sampling=GREEDY)
        rb = eng.submit(P_B, max_new_tokens=60, sampling=GREEDY)
        assert ra.wait(600) and rb.wait(600)
        assert "error" not in ra.result, ra.result.get("error")
        assert "error" not in rb.result, rb.result.get("error")
        assert ra.result["tokens"] == ref_a
        assert rb.result["tokens"] == ref_b
        assert SERVE_PREEMPTIONS.value(mode=mode) > before, \
            "pool never exhausted — preemption untested"
        h = eng.health()["kv_pool"]
        assert h["preempted_slots"] == 0            # everyone resumed
        if mode == "swap":
            assert h["swaps"] >= 1
    finally:
        eng.close()


def test_paged_pool_too_small_rejects_and_fails_typed(model):
    """Structural limits answer typed errors, not wedges: a prompt that
    can never fit is refused at submit; a generation that outgrows the
    pool with nothing left to reclaim fails with KVPoolExhausted and the
    engine keeps serving."""
    eng = _engine(model)
    try:
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit(list(range(3, 103)), max_new_tokens=4,
                       sampling=GREEDY)
        # single stream, 96-token pool, budget pushes past it: typed fail
        r = eng.submit(P_A, max_new_tokens=110, sampling=GREEDY)
        assert r.wait(600)
        assert isinstance(r.result.get("error"), KVPoolExhausted)
        assert len(r.tokens) > 80                   # got most of the way
        # engine survives and serves the next request
        r2 = eng.submit(P_B, max_new_tokens=6, sampling=GREEDY)
        assert r2.wait(180)
        assert r2.result["tokens"] == _ref(model, P_B, 6)
    finally:
        eng.close()


def test_paged_resume_gate_reclaims_cache_pins(model):
    """A parked request's resume gate must count prefix-cache pins as
    reclaimable capacity (ensure_free): the allocation path evicts
    lazily inside _alloc_one, but a PARKED preempted request never
    allocates — without the gate-side eviction, blocks held only by the
    cache would starve its resume forever."""
    from cake_tpu.serve.paged import PagedKV
    pk = PagedKV.build(model, 2, CTX, 6, BT, CHUNK)
    pids = [pk.alloc.alloc() for _ in range(4)]
    for p in pids:
        pk.alloc.ref(p, cache_pin=True)     # the cache's pin...
        pk.alloc.deref(p)                   # ...outlives the slot ref
    pk.evictor = lambda: (pids and pk.alloc.deref(pids.pop(),
                                                  cache_pin=True)) or 0
    assert pk.alloc.free_count == 2
    assert pk.ensure_free(5)                # reclaims 3 pinned blocks
    assert pk.alloc.free_count >= 5
    assert not pk.ensure_free(7)            # a 6-block pool never can
    pk.alloc.check()


# ---------------------------------------------------------------------------
# GDN (qwen3_5): linear-state boundary snapshots through the paged pool
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gdn_model():
    return TextModel(tiny_config("qwen3_5"), dtype=jnp.float32,
                     max_cache_len=CTX)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_paged_gdn_parity_and_prefix_snapshot(gdn_model):
    """GDN hybrid (3 linear + 1 full layer): the paged pool pages only
    the full-attention layer; linear conv/recurrent state stays per-slot
    and prefix hits restore it from the share unit's boundary-exact
    snapshot. Greedy outputs are bit-identical to the sequential path,
    cold and spliced."""
    eng = _engine(gdn_model, prefix_cache_mb=8)
    try:
        pa = SYS + [9, 11]
        pb = SYS + [77, 31]
        ra = eng.submit(pa, max_new_tokens=8, sampling=GREEDY)
        assert ra.wait(600)
        assert "error" not in ra.result, ra.result.get("error")
        assert ra.result["tokens"] == _ref(gdn_model, pa, 8)
        rb = eng.submit(pb, max_new_tokens=8, sampling=GREEDY)
        assert rb.wait(600)
        assert rb.stats["prefix_hit_tokens"] == 32  # snapshot installed
        assert rb.result["tokens"] == _ref(gdn_model, pb, 8)
    finally:
        eng.close()
