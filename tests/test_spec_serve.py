"""Batched speculative decoding in the serve engine (ISSUE 11): ragged
multi-token verify over the occupied slot bucket, paged block-cursor
advance, drafter-free n-gram mode.

The invariants pinned here:
  * batched-spec greedy output is BIT-IDENTICAL to the plain engine /
    sequential path — llama (attention-only, truncate rollback) AND
    qwen3_5/GDN (linear state, valid_len-masked commit), contiguous AND
    paged KV layouts (speculation no longer stands down in paged mode);
  * ragged acceptance (one slot accepting, a neighbor abstaining or
    rejecting, in the same dispatch) compiles NOTHING in steady state —
    one executable per (slot-bucket, k);
  * rejection rollback survives preempt-by-swap: a swapped-out victim
    carries only committed KV (uncommitted speculative blocks are
    trimmed back to the pool) and resumes bit-identically;
  * sampled streams keep rng-rebase correctness on rejection: the rng
    carry advances exactly once per verify step regardless of the
    accepted length, so identical runs replay identical streams;
  * slot-bucket growth to 8/16 compiles ONLY the new bucket.

Pool shapes match tests/test_paged.py (12 x 8-token blocks, chunk 16,
ctx 128) so paged executables stay cheap on the timeout-capped tier-1
suite.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import ServeEngine
from cake_tpu.serve.slots import slot_bucket, slot_buckets

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16
BT = 8
BLOCKS = 12

# period-4 repetition: the n-gram drafter proposes real multi-token
# continuations, so ragged accepts actually exercise the rollback
REP = [5, 9, 17, 23] * 4 + [5, 9]
# all-distinct: the drafter abstains -> plain decode inside the same
# spec dispatch (the ragged no-draft slot)
P_B = [100, 2, 5, 9, 11, 40]


@pytest.fixture(scope="module")
def model():
    return TextModel(tiny_config("llama"), dtype=jnp.float32,
                     max_cache_len=CTX)


@pytest.fixture(scope="module")
def gdn_model():
    return TextModel(tiny_config("qwen3_5"), dtype=jnp.float32,
                     max_cache_len=CTX)


def _engine(model, paged: bool, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_queue", 8)
    kw.setdefault("ctx_len", CTX)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("prefix_cache_mb", 0)
    kw.setdefault("spec", "ngram")
    kw.setdefault("spec_k", 4)
    if paged:
        kw.setdefault("kv_blocks", BLOCKS)
        kw.setdefault("kv_block_tokens", BT)
    return ServeEngine(model, **kw)


def _ref(model, prompt, n, sampling=GREEDY):
    toks, _ = model.generate(list(prompt), max_new_tokens=n,
                             sampling=sampling, spec=False)
    return toks


# ---------------------------------------------------------------------------
# greedy bit-parity: llama + GDN, contiguous + paged
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "paged",
    [pytest.param(False, marks=pytest.mark.slow), pytest.param(True, marks=pytest.mark.slow)],  # tier-2 spec smokes cover llama; gdn[contig] is the tier-1 representative (870s cap)
    ids=["contig", "paged"],
)
def test_batched_spec_greedy_parity_llama(model, paged):
    """Concurrent greedy requests through the batched-spec engine —
    one slot with live drafts, one whose drafter abstains — reproduce
    the plain sequential path bit-for-bit, and multi-token accepts
    actually happened (the llama tiny model's greedy continuation of
    the repetitive prompt is n-gram-predictable)."""
    eng = _engine(model, paged)
    try:
        ra = eng.submit(REP, max_new_tokens=24, sampling=GREEDY)
        rb = eng.submit(P_B, max_new_tokens=10, sampling=GREEDY)
        assert ra.wait(600) and rb.wait(600)
        assert "error" not in ra.result, ra.result.get("error")
        assert "error" not in rb.result, rb.result.get("error")
        assert ra.tokens == _ref(model, REP, 24)
        assert rb.tokens == _ref(model, P_B, 10)
        h = eng.health()["spec"]
        assert h["accepted"] >= 1
        assert h["steps"] < len(ra.tokens) - 1   # >= 1 multi-token accept
        if paged:
            eng.paged.alloc.check()
    finally:
        eng.close()


@pytest.mark.parametrize(
    "paged",
    [False, pytest.param(True, marks=pytest.mark.slow)],  # tier-1 keeps one family per KV layout (llama covers paged)
    ids=["contig", "paged"],
)
def test_batched_spec_greedy_parity_gdn(gdn_model, paged):
    """GDN hybrid (linear + full attention): the rejected-suffix
    rollback is the valid_len-masked state commit, per slot inside the
    vmapped verify — greedy output stays bit-identical in both KV
    layouts (paged mode pages only the full-attention layer)."""
    eng = _engine(gdn_model, paged)
    try:
        ra = eng.submit(REP, max_new_tokens=14, sampling=GREEDY)
        rb = eng.submit(P_B, max_new_tokens=8, sampling=GREEDY)
        assert ra.wait(600) and rb.wait(600)
        assert "error" not in ra.result, ra.result.get("error")
        assert "error" not in rb.result, rb.result.get("error")
        assert ra.tokens == _ref(gdn_model, REP, 14)
        assert rb.tokens == _ref(gdn_model, P_B, 8)
        assert eng.health()["spec"]["steps"] >= 1
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# ragged acceptance: zero recompiles in steady state
# ---------------------------------------------------------------------------


def test_spec_steady_state_zero_recompiles(model):
    """>= 8 speculative verify steps with RAGGED per-slot acceptance
    (a drafting slot next to an abstaining one, accepts of every length,
    block-table-free contiguous advance) compile ZERO new executables:
    one program per (slot-bucket, k), nb the only static argument."""
    from cake_tpu.analysis.sanitizers import assert_no_recompiles
    eng = _engine(model, paged=False)
    try:
        # warm every executable the steady state touches: both slot
        # buckets, the spec program, the plain-decode program (all-
        # abstain iterations), prefill chunks and first-token sampling
        wa = eng.submit(REP, max_new_tokens=24, sampling=GREEDY)
        wb = eng.submit(P_B, max_new_tokens=10, sampling=GREEDY)
        assert wa.wait(600) and wb.wait(600)
        # ...including the all-abstain two-slot iteration (plain decode
        # at nb=2: both drafters empty -> the cheaper width-1 program)
        wc = eng.submit(P_B, max_new_tokens=8, sampling=GREEDY)
        wd = eng.submit(list(reversed(P_B)), max_new_tokens=8,
                        sampling=GREEDY)
        assert wc.wait(600) and wd.wait(600)
        before = eng.spec_steps
        with assert_no_recompiles(model._spec_slots, model._decode_slots,
                                  label="batched spec steady state"):
            ra = eng.submit(REP, max_new_tokens=24, sampling=GREEDY)
            ra2 = eng.submit(REP, max_new_tokens=24, sampling=GREEDY)
            rb = eng.submit(P_B, max_new_tokens=10, sampling=GREEDY)
            assert ra.wait(600) and ra2.wait(600) and rb.wait(600)
        assert ra.tokens == wa.tokens and ra2.tokens == wa.tokens
        assert rb.tokens == wb.tokens
        assert eng.spec_steps - before >= 8, \
            "not enough spec iterations to call it steady state"
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# rejection rollback under preempt-by-swap
# ---------------------------------------------------------------------------


def test_spec_rejection_rollback_under_preempt_swap(model):
    """Two speculating streams outgrow the 96-token pool: the victim is
    swapped out mid-speculation and must carry only COMMITTED state —
    its uncommitted draft-window blocks are rolled back to the pool
    before the blob is captured, every position stored in the blob sits
    below the committed frontier, and both continuations stay
    bit-identical to the sequential path."""
    from cake_tpu.obs import SERVE_PREEMPTIONS
    before = SERVE_PREEMPTIONS.value(mode="swap")
    ref_a = _ref(model, REP, 60)
    ref_b = _ref(model, P_B, 60)
    eng = _engine(model, paged=True, preempt_mode="swap", spec_k=6)
    blob_checks = []
    real_swap_out = eng.paged.swap_out

    def spying_swap_out(slot, carries):
        blob = real_swap_out(slot, carries)
        frontier = int(blob["carries"][1])      # pos carry == committed
        worst = max((int(saved["pos"].max()) for saved in blob["layers"]
                     if saved), default=-1)
        blob_checks.append((worst, frontier))
        return blob

    eng.paged.swap_out = spying_swap_out
    try:
        ra = eng.submit(REP, max_new_tokens=60, sampling=GREEDY)
        rb = eng.submit(P_B, max_new_tokens=60, sampling=GREEDY)
        assert ra.wait(600) and rb.wait(600)
        assert "error" not in ra.result, ra.result.get("error")
        assert "error" not in rb.result, rb.result.get("error")
        assert ra.tokens == ref_a
        assert rb.tokens == ref_b
        assert SERVE_PREEMPTIONS.value(mode="swap") > before, \
            "pool never exhausted - speculative preemption untested"
        assert blob_checks, "no swap blob captured"
        for worst, frontier in blob_checks:
            assert worst < frontier, \
                f"swap blob carries uncommitted position {worst} at " \
                f"committed frontier {frontier}"
        eng.paged.alloc.check()
    finally:
        eng.close()


def test_spec_degrades_to_decode_at_pool_edge(model):
    """A draft window that cannot be backed with blocks must DEGRADE to
    a plain decode step, not preempt a victim or fail the request: a
    single speculating stream pushed past the pool gets exactly as far
    as the non-speculating engine does (typed KVPoolExhausted only once
    the pool genuinely cannot grow), with no preemptions along the way."""
    from cake_tpu.obs import SERVE_PREEMPTIONS
    from cake_tpu.serve import KVPoolExhausted
    pre = {m: SERVE_PREEMPTIONS.value(mode=m)
           for m in ("swap", "recompute")}
    eng = _engine(model, paged=True, spec_k=6)
    try:
        r = eng.submit(REP, max_new_tokens=110, sampling=GREEDY)
        assert r.wait(600)
        assert isinstance(r.result.get("error"), KVPoolExhausted)
        # the 96-token pool minus the 18-token prompt leaves ~78 decode
        # steps: speculation must ride right up to the same edge
        assert len(r.tokens) > 70, len(r.tokens)
        for m, v in pre.items():
            assert SERVE_PREEMPTIONS.value(mode=m) == v, \
                "speculative over-reservation preempted a victim"
        # engine keeps serving
        r2 = eng.submit(P_B, max_new_tokens=6, sampling=GREEDY)
        assert r2.wait(180)
        assert r2.result["tokens"] == _ref(model, P_B, 6)
    finally:
        eng.close()


def test_paged_trim_to_rolls_back_tail(model):
    """trim_to unmaps exactly the table entries past the committed
    token count and returns them to the free pool (the speculative
    frontier rollback primitive)."""
    from cake_tpu.serve.paged import PagedKV
    pk = PagedKV.build(model, 2, CTX, 8, BT, CHUNK)
    assert pk.reserve_range(0, 0, 3 * BT + 2)       # blocks 0..3 mapped
    assert pk.alloc.free_count == 4
    # committed 10 tokens (2 blocks); blocks 2,3 are speculative tail
    assert pk.trim_to(0, 10) == 2
    assert pk.alloc.free_count == 6
    assert pk.alloc.tables[0][2] == pk.NULL
    assert pk.alloc.tables[0][3] == pk.NULL
    assert pk.alloc.tables[0][0] != pk.NULL         # committed kept
    assert int(np.asarray(pk.tables)[0, 2]) == pk.NULL  # device mirror
    assert pk.trim_to(0, 10) == 0                   # idempotent
    pk.alloc.check()


# ---------------------------------------------------------------------------
# sampled streams: rng-rebase correctness on rejection
# ---------------------------------------------------------------------------


def test_spec_sampled_rng_rebase_parity(model):
    """The rng carry advances exactly ONCE per verify step (one split)
    no matter how many drafts were accepted or rejected, so a sampled
    stream through the speculating engine is reproducible: two fresh
    engines with the same seed replay the identical token stream."""
    scfg = SamplingConfig(temperature=0.8, top_k=40)

    def run():
        eng = _engine(model, paged=False, spec_k=4, seed=7)
        try:
            r = eng.submit(REP, max_new_tokens=16, sampling=scfg)
            assert r.wait(600)
            assert "error" not in r.result, r.result.get("error")
            return list(r.tokens), eng.spec_steps
        finally:
            eng.close()

    a, steps_a = run()
    b, steps_b = run()
    assert a == b, "sampled spec stream is not reproducible"
    assert steps_a == steps_b
    assert len(a) <= 16


# ---------------------------------------------------------------------------
# slot-bucket growth: 8/16 slots, new-bucket-only compiles
# ---------------------------------------------------------------------------


def test_slot_buckets_ladder():
    assert slot_buckets(4) == (1, 2, 4)
    assert slot_buckets(8) == (1, 2, 4, 8)
    assert slot_buckets(16) == (1, 2, 4, 8, 16)
    assert slot_buckets(6) == (1, 2, 4, 6)      # cap itself always last
    for cap in (4, 8, 16):
        for n in range(1, cap + 1):
            assert slot_bucket(n, cap) in slot_buckets(cap)


def test_slot_bucket_growth_compiles_only_new_bucket(model):
    """Scaling occupancy past 4 into the 8-slot bucket compiles exactly
    the new buckets' executables — existing rungs of the ladder keep
    their programs (no churn), so raising CAKE_SERVE_SLOTS is O(new
    buckets) compile cost, not a recompile of the pool."""
    from cake_tpu.analysis.sanitizers import cache_size
    eng = ServeEngine(model, slots=8, max_queue=16, ctx_len=CTX,
                      prefill_chunk=CHUNK, prefix_cache_mb=0)
    try:
        # warm the low rungs: two concurrent requests touch nb=1 and 2
        w = [eng.submit(P_B, max_new_tokens=6, sampling=GREEDY)
             for _ in range(2)]
        assert all(r.wait(600) for r in w)
        low = cache_size(model._decode_slots)
        # 8 concurrent requests climb to nb=8: exactly the 4- and
        # 8-slot buckets are new
        rs = [eng.submit(P_B, max_new_tokens=8, sampling=GREEDY)
              for _ in range(8)]
        assert all(r.wait(600) for r in rs)
        for r in rs:
            assert "error" not in r.result, r.result.get("error")
            assert r.tokens == _ref(model, P_B, 8)
        grown = cache_size(model._decode_slots) - low
        assert grown == 2, \
            f"bucket growth compiled {grown} executables, expected the " \
            "2 new rungs (nb=4, nb=8) only"
        # and re-running at every occupancy compiles nothing further
        from cake_tpu.analysis.sanitizers import assert_no_recompiles
        with assert_no_recompiles(model._decode_slots,
                                  label="bucket ladder steady state"):
            rs = [eng.submit(P_B, max_new_tokens=4, sampling=GREEDY)
                  for _ in range(8)]
            assert all(r.wait(600) for r in rs)
    finally:
        eng.close()
