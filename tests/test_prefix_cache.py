"""Shared-prefix KV cache (ISSUE 3): block extract/splice cache ops, the
hash-chain LRU, hit-vs-miss bit parity through the serve engine, and
eviction-under-pressure correctness — all on the tiny CPU model."""
import time

import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import PrefixCache, ServeEngine

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = TextModel(tiny_config("llama"), dtype=jnp.float32,
                           max_cache_len=CTX)
    return _MODEL


@pytest.fixture(scope="module")
def model():
    return _model()


def _ref(model, prompt, n):
    toks, _ = model.generate(list(prompt), max_new_tokens=n, sampling=GREEDY)
    return toks


PROMPT = [3 + (i * 7) % 200 for i in range(50)]


# ---------------------------------------------------------------------------
# cache ops: extract / splice roundtrip (no engine)
# ---------------------------------------------------------------------------


def test_slot_extract_splice_roundtrip(model):
    """Blocks copied out of a prefilled row and spliced into a clean row of
    ANOTHER pool reproduce the original prefix bytes exactly, leave the
    rest of the row empty, and touch no neighbor."""
    chunk = 16
    layers = model.new_cache(3, kv_len=64)["layers"]
    for s in range(0, 32, chunk):
        _, layers = model.prefill_chunk(layers, 1, PROMPT[s:s + chunk], s)
    blocks = [model.slot_extract(layers, 1, b * chunk, chunk)
              for b in range(2)]
    for b, blk in enumerate(blocks):
        for lc in blk:
            np.testing.assert_array_equal(
                np.asarray(lc["pos"][0]),
                np.arange(b * chunk, (b + 1) * chunk))

    layers2 = model.new_cache(3, kv_len=64)["layers"]
    layers2 = model.slot_splice(layers2, blocks[0], 2, final=False)
    layers2 = model.slot_splice(layers2, blocks[1], 2, final=True)
    for lc_src, lc_dst in zip(layers, layers2):
        np.testing.assert_array_equal(np.asarray(lc_src["k"][1, :32]),
                                      np.asarray(lc_dst["k"][2, :32]))
        np.testing.assert_array_equal(np.asarray(lc_src["v"][1, :32]),
                                      np.asarray(lc_dst["v"][2, :32]))
        np.testing.assert_array_equal(np.asarray(lc_dst["pos"][2, :32]),
                                      np.arange(32))
        assert int(jnp.max(lc_dst["pos"][2, 32:])) == -1
        assert float(jnp.abs(lc_dst["k"][0]).max()) == 0.0   # neighbors
        assert float(jnp.abs(lc_dst["k"][1]).max()) == 0.0


def test_spliced_prefix_continues_bitwise(model):
    """Prefilling the SUFFIX on top of a spliced prefix yields the same
    final logits as prefilling the whole prompt into the row — the
    hit-path numerics are the miss-path numerics."""
    chunk = 16
    miss = model.new_cache(2, kv_len=64)["layers"]
    for s in range(0, len(PROMPT), chunk):
        ref_logits, miss = model.prefill_chunk(miss, 0,
                                               PROMPT[s:s + chunk], s)
    blocks = [model.slot_extract(miss, 0, b * chunk, chunk)
              for b in range(3)]
    hit = model.new_cache(2, kv_len=64)["layers"]
    for b, blk in enumerate(blocks):
        hit = model.slot_splice(hit, blk, 1, final=(b == 2))
    hit_logits, hit = model.prefill_chunk(hit, 1, PROMPT[48:], 48)
    np.testing.assert_array_equal(np.asarray(hit_logits),
                                  np.asarray(ref_logits))


# ---------------------------------------------------------------------------
# PrefixCache unit behavior
# ---------------------------------------------------------------------------


def test_prefix_cache_build_gating(model):
    assert PrefixCache.build(model, CTX, 16, 0) is None        # disabled
    assert PrefixCache.build(model, CTX, CTX * 2, 64) is None  # block > ctx
    pc = PrefixCache.build(model, CTX, 16, 64)
    assert pc is not None and pc.block == 16


def test_prefix_cache_match_requires_live_suffix(model):
    """Reuse is capped at n-1 tokens: a prompt exactly equal to a cached
    chain still prefills its final token live (its logits seed sampling)."""
    pc = PrefixCache.build(model, CTX, 16, 64)
    layers = model.new_cache(2, kv_len=64)["layers"]
    for s in range(0, 32, 16):
        _, layers = model.prefill_chunk(layers, 0, PROMPT[s:s + 16], s)
    keys = pc.chain_keys(PROMPT)
    pc.insert(layers, 0, PROMPT, 0, keys)
    pc.insert(layers, 0, PROMPT, 1, keys)
    assert len(pc._blocks) == 2

    def match(p):
        return pc.match(p, pc.chain_keys(p))
    assert match(PROMPT[:50]) == 2           # 32 < 50-1: both blocks usable
    assert match(PROMPT[:33]) == 2           # 32 == 33-1: still ok
    assert match(PROMPT[:32]) == 1           # full match would leave 0 live
    assert match(PROMPT[:16] + [9] * 16) == 1      # diverges after block 0
    assert match([9] * 40) == 0


# ---------------------------------------------------------------------------
# engine e2e: hit == miss, eviction under pressure
# ---------------------------------------------------------------------------


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_engine_prefix_hit_matches_miss(model):
    """The tentpole acceptance pin on the HIT side: greedy output is
    bit-identical whether the prefix was spliced from cache or computed,
    and the stats/metrics record the reuse."""
    ref = _ref(model, PROMPT, 10)
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=64)
    try:
        r1 = eng.submit(PROMPT, max_new_tokens=10, sampling=GREEDY)
        assert r1.wait(120)
        assert r1.result["tokens"] == ref
        assert r1.stats["prefix_hit_tokens"] == 0
        assert r1.stats["prefill_chunks"] == 4

        r2 = eng.submit(PROMPT, max_new_tokens=10, sampling=GREEDY)
        assert r2.wait(120)
        assert r2.result["tokens"] == ref                  # bit-identical
        assert r2.stats["prefix_hit_tokens"] == 48         # 3 blocks of 16
        assert r2.stats["prefill_chunks"] == 1             # suffix only

        # divergent suffix sharing 32 leading tokens: partial chain reuse
        p3 = PROMPT[:32] + [9, 9, 4, 4, 1]
        r3 = eng.submit(p3, max_new_tokens=10, sampling=GREEDY)
        assert r3.wait(120)
        assert r3.result["tokens"] == _ref(model, p3, 10)
        assert r3.stats["prefix_hit_tokens"] == 32

        occ = eng.health()["prefix_cache"]
        assert occ["hits"] == 2 and occ["blocks"] >= 3
        assert occ["bytes"] > 0
    finally:
        eng.close()


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_engine_prefix_eviction_under_pressure(model):
    """A capacity small enough for ~2 blocks forces LRU evictions while
    distinct prefixes stream through; outputs stay correct before, during
    and after eviction (a shortened chain only costs compute)."""
    eng = ServeEngine(model, slots=1, max_queue=8, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=0.04)
    try:
        prompts = [[5 + j] * 1 + [(j * 31 + i * 7) % 200 + 3
                                  for i in range(39)] for j in range(3)]
        refs = [_ref(model, p, 6) for p in prompts]
        for p, want in zip(prompts, refs):
            r = eng.submit(p, max_new_tokens=6, sampling=GREEDY)
            assert r.wait(120)
            assert r.result["tokens"] == want
        occ = eng.health()["prefix_cache"]
        assert occ["evictions"] > 0, occ
        assert occ["bytes"] <= occ["capacity_bytes"]
        # the first prefix was evicted: resubmitting it must still be
        # correct (miss or partial hit, never wrong)
        r = eng.submit(prompts[0], max_new_tokens=6, sampling=GREEDY)
        assert r.wait(120)
        assert r.result["tokens"] == refs[0]
    finally:
        eng.close()


def test_engine_prefix_cache_disabled(model):
    eng = ServeEngine(model, slots=1, max_queue=2, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=0)
    try:
        assert eng.prefix_cache is None
        r = eng.submit(PROMPT, max_new_tokens=6, sampling=GREEDY)
        assert r.wait(120)
        assert r.result["tokens"] == _ref(model, PROMPT, 6)
        assert "prefix_cache" not in eng.health()
    finally:
        eng.close()


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_engine_prefix_hit_matches_miss_gdn():
    """Same hit==miss pin through a qwen3_5-style model with LINEAR
    (GDN) layers: the per-block conv/recurrent-state snapshot — captured
    at the chunk boundary, installed only from the final matched block —
    must reproduce the sequential path bit-for-bit too."""
    m = TextModel(tiny_config("qwen3_5"), dtype=jnp.float32,
                  max_cache_len=CTX)
    prompt = [3 + (i * 11) % 200 for i in range(40)]
    ref, _ = m.generate(list(prompt), max_new_tokens=6, sampling=GREEDY)
    eng = ServeEngine(m, slots=2, max_queue=4, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=64)
    try:
        r1 = eng.submit(prompt, max_new_tokens=6, sampling=GREEDY)
        assert r1.wait(300)
        assert r1.result["tokens"] == ref
        assert r1.stats["prefix_hit_tokens"] == 0
        r2 = eng.submit(prompt, max_new_tokens=6, sampling=GREEDY)
        assert r2.wait(300)
        assert r2.result["tokens"] == ref                  # bit-identical
        assert r2.stats["prefix_hit_tokens"] == 32         # 2 blocks of 16
    finally:
        eng.close()


def test_engine_cancel_mid_prefill_frees_slot(model):
    """Cancelling a request while its CHUNKED prefill is still in flight
    aborts the admission, wipes the half-built row and frees the slot."""
    eng = ServeEngine(model, slots=1, max_queue=2, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=0)
    try:
        long_prompt = [3 + (i * 13) % 200 for i in range(120)]
        r = eng.submit(long_prompt, max_new_tokens=6, sampling=GREEDY)
        deadline = time.monotonic() + 30
        while not eng.health()["prefilling"] and time.monotonic() < deadline:
            time.sleep(0.001)
        r.cancel()
        assert r.wait(30)
        assert not r.tokens
        deadline = time.monotonic() + 30
        while eng.pool.busy_count and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.pool.busy_count == 0
        # the slot is clean: the next request reproduces the reference
        r2 = eng.submit(PROMPT, max_new_tokens=6, sampling=GREEDY)
        assert r2.wait(120)
        assert r2.result["tokens"] == _ref(model, PROMPT, 6)
    finally:
        eng.close()
