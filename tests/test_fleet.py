"""Fleet router: membership + failover (ISSUE 12 acceptance pins).

Units cover the registry state machine (join/leave, gray-eject /
half-open / readmit, draining, health-driven ejection) and the affinity
chain (same conversation -> same replica; ejected owner -> deterministic
next-best). HTTP-level tests drive a real router app over FAKE replica
servers (canned JSON/SSE — no model, no engine) and pin the failure
semantics: transparent failover, retry-budget exhaustion as a typed 503,
router-level 429 before any replica admits, SELF-HEALING mid-stream
resume (ISSUE 15: splice byte-identity, overlap strip, chunk-id rewrite,
budget-exhausted typed event with resume_token, client-disconnect during
resume, sampled-resume flagging, pre-commit stream hedging) and weighted
rendezvous placement.
"""
import asyncio
import base64
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from cake_tpu.fleet import (EJECTED, HALF_OPEN, HEALTHY, FleetRouter,
                            MembershipPolicy, Replica, ReplicaRegistry,
                            affinity_key, conversation_head,
                            create_router_app, rank_replicas)
from cake_tpu.fleet import faults as fleet_faults


def _policy(**kw):
    base = dict(eject_fails=3, err_window=16, err_rate=0.5,
                degraded_ttft_ms=0.0, eject_s=0.05, replica_inflight=0)
    base.update(kw)
    return MembershipPolicy(**base)


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------


def test_registry_join_leave():
    reg = ReplicaRegistry(_policy())
    r0 = reg.add("r0", "http://h:1/")
    assert r0.base_url == "http://h:1"          # trailing slash normalized
    reg.add("r1", "http://h:2")
    assert sorted(reg.names()) == ["r0", "r1"]
    # re-join refreshes the URL but keeps state (no eject laundering)
    r0.record_result(False, transport=True)
    again = reg.add("r0", "http://h:9")
    assert again is r0 and r0.base_url == "http://h:9"
    assert r0.snapshot()["consec_fails"] == 1
    assert reg.remove("r1") and not reg.remove("r1")
    assert reg.names() == ["r0"]


def test_eject_on_consecutive_transport_fails():
    rep = Replica("r0", "http://h:1", _policy(eject_fails=3))
    assert rep.record_result(False, transport=True) is None
    assert rep.record_result(False, transport=True) is None
    assert rep.routable()
    assert rep.record_result(False, transport=True) == "fails"
    assert rep.snapshot()["state"] == EJECTED and not rep.routable()
    # a success resets the consecutive counter
    rep2 = Replica("r1", "http://h:2", _policy(eject_fails=3))
    rep2.record_result(False, transport=True)
    rep2.record_result(False, transport=True)
    rep2.record_result(True, 5.0)
    assert rep2.record_result(False, transport=True) is None
    assert rep2.routable()


def test_eject_on_error_rate_window():
    rep = Replica("r0", "http://h:1",
                  _policy(err_rate=0.5, err_window=16))
    # HTTP 5xx (transport=False) never trips the consecutive-fail eject,
    # only the rolling error rate — and only past GRAY_MIN_SAMPLES
    for _ in range(3):
        assert rep.record_result(False) is None
    for _ in range(4):
        rep.record_result(True, 5.0)
    reason = rep.record_result(False)            # 8th sample, 50% errors
    assert reason == "error_rate"
    assert rep.snapshot()["state"] == EJECTED


def test_eject_on_ttfb_p95_gray():
    rep = Replica("r0", "http://h:1",
                  _policy(degraded_ttft_ms=50.0))
    reason = None
    for _ in range(10):
        reason = rep.record_result(True, 120.0) or reason
    assert reason == "ttft_p95"                  # slow-but-alive ejects
    # under the threshold: never ejected
    rep2 = Replica("r1", "http://h:2", _policy(degraded_ttft_ms=50.0))
    for _ in range(10):
        assert rep2.record_result(True, 10.0) is None


def test_half_open_trial_and_readmit_cycle():
    rep = Replica("r0", "http://h:1", _policy(eject_s=0.01))
    for _ in range(3):
        rep.record_result(False, transport=True)
    assert rep.snapshot()["state"] == EJECTED
    healthy = {"engine": {"alive": True, "slots": 4, "queue_depth": 0}}
    # probe before the hold expires: stays ejected
    rep.observe_health(200, healthy)
    assert rep.snapshot()["state"] == EJECTED
    import time
    time.sleep(0.02)
    rep.observe_health(200, healthy)
    assert rep.snapshot()["state"] == HALF_OPEN
    # exactly ONE trial request at a time
    lease = rep.try_acquire()
    assert lease == "trial"
    assert not rep.try_acquire()
    rep.record_result(True, 5.0, lease=lease)    # trial succeeded
    rep.release(lease)
    snap = rep.snapshot()
    assert snap["state"] == HEALTHY
    # readmission does NOT launder the backoff reputation: the streak
    # survives the heal (it expires only after a quiet forget window),
    # so a flap finds its next hold doubled
    assert snap["eject_streak"] == 1
    assert snap["eject_evidence"] is None        # episode closed


def test_half_open_failure_re_ejects_with_backoff():
    rep = Replica("r0", "http://h:1", _policy(eject_s=0.01))
    for _ in range(3):
        rep.record_result(False, transport=True)
    first_until = rep.eject_until
    import time
    time.sleep(0.02)
    rep.observe_health(200, {"engine": {"alive": True}})
    assert rep.snapshot()["state"] == HALF_OPEN
    lease = rep.try_acquire()
    assert lease == "trial"
    assert rep.record_result(False, transport=True,
                             lease=lease) == "fails"
    rep.release(lease)
    snap = rep.snapshot()
    assert snap["state"] == EJECTED and snap["eject_streak"] == 2
    assert rep.eject_until > first_until         # hold doubled


def test_stale_outcomes_do_not_move_half_open_or_ejected():
    """Outcomes of requests that STARTED before an ejection are stale
    evidence: a pre-eject failure landing during probation must not
    re-eject (it is the old incident, not the trial), a pre-eject
    success must not readmit without a trial, and an EJECTED replica
    ignores outcomes entirely."""
    rep = Replica("r0", "http://h:1", _policy(eject_s=0.0))
    for _ in range(3):
        rep.record_result(False, transport=True)
    assert rep.snapshot()["state"] == EJECTED
    assert rep.record_result(False, transport=True) is None   # ignored
    assert rep.snapshot()["eject_streak"] == 1                # no re-eject
    rep.observe_health(200, {"engine": {"alive": True}})
    assert rep.snapshot()["state"] == HALF_OPEN
    # stale pre-eject outcomes carry the default "slot" lease
    assert rep.record_result(False, transport=True) is None
    assert rep.snapshot()["state"] == HALF_OPEN               # survived
    rep.record_result(True, 5.0)                              # stale ok
    assert rep.snapshot()["state"] == HALF_OPEN               # no readmit
    trial = rep.try_acquire()
    rep.record_result(True, 5.0, lease=trial)                 # real trial
    rep.release(trial)
    assert rep.snapshot()["state"] == HEALTHY


def test_stale_release_cannot_clear_trial_lease():
    """A request acquired while HEALTHY and released after the replica
    went HALF_OPEN must not clear the trial flag of a probation request
    still in flight (the lease token carries who was the trial)."""
    rep = Replica("r0", "http://h:1", _policy(eject_s=0.0))
    old = rep.try_acquire()
    assert old == "slot"                         # in flight pre-eject
    for _ in range(3):
        rep.record_result(False, transport=True)
    rep.observe_health(200, {"engine": {"alive": True}})
    assert rep.snapshot()["state"] == HALF_OPEN
    trial = rep.try_acquire()
    assert trial == "trial"
    rep.release(old)                             # stale release lands
    assert not rep.try_acquire()                 # trial still exclusive
    rep.release(trial)


def test_half_open_probe_only_readmit():
    """An idle fleet still readmits on two consecutive healthy probes —
    but ONLY for probe-evidence ejects (the /health path produced the
    evidence, so the /health path may clear it)."""
    rep = Replica("r0", "http://h:1", _policy(eject_fails=2, eject_s=0.0))
    rep.observe_health(None, None)
    rep.observe_health(None, None)               # probe-evidence eject
    snap = rep.snapshot()
    assert snap["state"] == EJECTED
    assert snap["eject_evidence"] == "probe"
    healthy = {"engine": {"alive": True}}
    rep.observe_health(200, healthy)             # -> half_open
    assert rep.snapshot()["state"] == HALF_OPEN
    rep.observe_health(200, healthy)             # second in a row
    assert rep.snapshot()["state"] == HEALTHY


def test_probe_evidence_can_never_clear_data_evidence_eject():
    """The asymmetric-partition flap killer: a replica ejected on DATA
    evidence (the router's own requests failed) has a live probe path —
    healthy probes advance it to HALF_OPEN but may NEVER readmit it; only
    the data-path trial lease can."""
    rep = Replica("r0", "http://h:1", _policy(eject_s=0.0))
    for _ in range(3):
        rep.record_result(False, transport=True)
    snap = rep.snapshot()
    assert snap["state"] == EJECTED
    assert snap["eject_evidence"] == "data"
    assert snap["partition_s"] is not None       # episode open
    healthy = {"engine": {"alive": True}}
    for _ in range(5):                           # probes alone: stuck
        rep.observe_health(200, healthy)
    assert rep.snapshot()["state"] == HALF_OPEN
    assert rep.snapshot()["eject_evidence"] == "data"
    trial = rep.try_acquire()
    assert trial == "trial"
    rep.record_result(True, 5.0, lease=trial)    # data-path proof
    rep.release(trial)
    snap = rep.snapshot()
    assert snap["state"] == HEALTHY
    assert snap["eject_evidence"] is None
    assert snap["partition_s"] is None           # episode closed


def test_flap_damping_doubles_hold_each_heal_cycle(monkeypatch):
    """Repeated partition/heal flaps: each re-eject finds its hold
    DOUBLED even though the replica was fully readmitted in between —
    healing is not reputation laundering. Only a genuinely quiet
    stretch longer than the forget window resets the ladder.
    Fake clock: no sleeps, the holds are inspected arithmetically."""
    from cake_tpu.fleet import registry as regmod

    class Clock:
        t = 1000.0
    monkeypatch.setattr(regmod, "now", lambda: Clock.t)
    rep = Replica("r0", "http://h:1", _policy(eject_s=1.0))
    healthy = {"engine": {"alive": True}}

    def flap():
        """One partition/heal episode; returns the eject hold length."""
        for _ in range(3):
            rep.record_result(False, transport=True)
        assert rep.snapshot()["state"] == EJECTED
        hold = rep.eject_until - Clock.t
        Clock.t = rep.eject_until + 0.01         # hold expires
        rep.observe_health(200, healthy)         # -> half_open
        trial = rep.try_acquire()
        assert trial == "trial"
        rep.record_result(True, 5.0, lease=trial)  # data-path readmit
        rep.release(trial)
        assert rep.snapshot()["state"] == HEALTHY
        return hold

    assert flap() == pytest.approx(1.0)          # streak 1: base hold
    assert flap() == pytest.approx(2.0)          # streak 2: doubled
    assert flap() == pytest.approx(4.0)          # streak 3: doubled again
    # quiet longer than the forget window (eject_s * MAX_BACKOFF * 2
    # = 16s): the reputation finally expires and the ladder restarts
    Clock.t += 17.0
    assert flap() == pytest.approx(1.0)


def test_partition_episode_events_and_seconds_counter(monkeypatch):
    """A data-evidence eject opens a partition episode: the suspected /
    healed event pair is drained for the timeline, and the
    cake_fleet_partition_seconds_total counter climbs DURING the
    episode (per probe cycle), not in one jump at heal."""
    from cake_tpu.fleet import registry as regmod
    from cake_tpu.obs import FLEET_PARTITION_SECONDS

    class Clock:
        t = 500.0
    monkeypatch.setattr(regmod, "now", lambda: Clock.t)
    reg = ReplicaRegistry(_policy(eject_s=1.0))
    rep = reg.add("r-partsec", "http://h:1")
    base = FLEET_PARTITION_SECONDS.value(replica="r-partsec")
    for _ in range(3):
        rep.record_result(False, transport=True)
    ((kind, attrs),) = reg.drain_events()
    assert kind == "replica_partition_suspected"
    assert attrs["replica"] == "r-partsec" and attrs["reason"] == "fails"
    assert attrs["hold_s"] == pytest.approx(1.0)
    # mid-episode probe cycle: the counter has already accrued 2s
    Clock.t += 2.0
    rep.observe_health(200, {"engine": {"alive": True}})  # -> half_open
    assert (FLEET_PARTITION_SECONDS.value(replica="r-partsec") - base
            == pytest.approx(2.0))
    Clock.t += 1.0
    trial = rep.try_acquire()
    rep.record_result(True, 5.0, lease=trial)             # heal
    rep.release(trial)
    ((kind, attrs),) = reg.drain_events()
    assert kind == "partition_healed"
    assert attrs["episode_s"] == pytest.approx(3.0)
    assert (FLEET_PARTITION_SECONDS.value(replica="r-partsec") - base
            == pytest.approx(3.0))
    assert reg.drain_events() == []                       # drained clean


def test_health_down_and_wedged_eject():
    for block in ({"down": {"down_for_s": 3}}, {"wedged": True},
                  {"alive": False}):
        rep = Replica("r0", "http://h:1", _policy())
        rep.observe_health(503, {"engine": {**block, "slots": 4}})
        assert rep.snapshot()["state"] == EJECTED, block


def test_health_draining_stops_routing_without_eject():
    rep = Replica("r0", "http://h:1", _policy())
    rep.observe_health(200, {"engine": {"alive": True, "draining": True,
                                        "slots": 4}})
    snap = rep.snapshot()
    assert snap["state"] == "draining" and not rep.routable()
    assert rep.ejects == 0
    # drain ends (e.g. rolling restart came back): routable again
    rep.observe_health(200, {"engine": {"alive": True, "slots": 4}})
    assert rep.routable()


def test_health_mirrors_load_signals():
    rep = Replica("r0", "http://h:1", _policy())
    rep.observe_health(200, {"engine": {
        "alive": True, "slots": 4, "queue_depth": 7,
        "kv_pool": {"occupancy": 0.625}}})
    snap = rep.snapshot()
    assert snap["queue_depth"] == 7
    assert snap["occupancy"] == 0.625
    assert snap["cap"] == 8                      # auto: 2x slots
    # the REAL paged kv_pool block has used/blocks, no 'occupancy' key
    # (serve/paged/pool.py occupancy()) — block occupancy is derived:
    # 95% of blocks spoken for with half the slots busy must report
    # 0.95, not 0.5, or the autoscaling signal under-drives
    rep.observe_health(200, {"engine": {
        "alive": True, "slots": 4, "slots_busy": 2,
        "kv_pool": {"blocks": 64, "used": 61, "free": 3, "shared": 0}}})
    assert rep.snapshot()["occupancy"] == round(61 / 64, 4)
    # no kv_pool at all: busy-slot fraction
    rep.observe_health(200, {"engine": {
        "alive": True, "slots": 4, "slots_busy": 2}})
    assert rep.snapshot()["occupancy"] == 0.5


def test_unreachable_probes_eject():
    rep = Replica("r0", "http://h:1", _policy(eject_fails=2))
    rep.observe_health(None, None)
    assert rep.snapshot()["state"] == HEALTHY
    rep.observe_health(None, None)
    assert rep.snapshot()["state"] == EJECTED


# ---------------------------------------------------------------------------
# churn: leave + re-announce (ISSUE 17 pins)
# ---------------------------------------------------------------------------


def test_churn_keeps_eject_history_but_resets_warmup():
    # a replica that leaves and re-announces under the same name must
    # NOT launder its eject record (the backoff ladder carries over),
    # but its warm-up clock IS fresh — a new process instance
    reg = ReplicaRegistry(_policy(eject_fails=2, eject_s=60.0))
    rep = reg.add("r0", "http://h:1")
    rep.observe_health(None, None)
    rep.observe_health(None, None)
    assert rep.snapshot()["state"] == EJECTED and rep.ejects == 1
    assert reg.remove("r0")
    back = reg.add("r0", "http://h:1")
    assert back is not rep                       # a NEW replica object
    snap = back.snapshot()
    assert snap["ejects"] == 1 and snap["eject_streak"] == 1
    # the 60s ejection hold was still running at removal: re-applied
    assert snap["state"] == EJECTED
    assert snap["warm_age_s"] < 1.0              # warm-up clock reset


def test_churn_expired_hold_rejoins_healthy_with_history():
    reg = ReplicaRegistry(_policy(eject_fails=2, eject_s=0.0))
    rep = reg.add("r0", "http://h:1")
    rep.observe_health(None, None)
    rep.observe_health(None, None)
    assert rep.ejects == 1
    reg.remove("r0")
    back = reg.add("r0", "http://h:1")
    snap = back.snapshot()
    # hold already expired: joins routable, but the record survives
    assert snap["state"] == HEALTHY and snap["ejects"] == 1


def test_started_age_moving_backward_resets_warmup():
    import time
    rep = Replica("r0", "http://h:1", _policy())
    body = {"engine": {"alive": True, "slots": 4}}
    rep.observe_health(200, dict(body, started_at_age_s=100.0))
    rep.first_seen -= 50.0          # backdate: long-warm replica
    assert rep.warm_age_s() > 49.0
    # age moves FORWARD: same process, warm-up untouched
    rep.observe_health(200, dict(body, started_at_age_s=101.0))
    assert rep.warm_age_s() > 49.0
    # age moves BACKWARD: a new process answers behind the same URL
    rep.observe_health(200, dict(body, started_at_age_s=2.0))
    assert rep.warm_age_s() < 1.0


def test_cordon_stops_new_routing_one_way():
    rep = Replica("r0", "http://h:1", _policy())
    assert rep.routable() and rep.try_acquire() is not None
    rep.release()
    rep.cordon()
    assert not rep.routable() and rep.try_acquire() is None
    snap = rep.snapshot()
    assert snap["state"] == "draining" and snap["cordoned"]
    assert rep.ejects == 0          # cordon is lifecycle, not membership


# ---------------------------------------------------------------------------
# affinity units
# ---------------------------------------------------------------------------


SYSTEM = {"role": "system", "content": "You are a helpful assistant. " * 20}


def _convo(first_user: str, turns: int = 1) -> list:
    msgs = [SYSTEM, {"role": "user", "content": first_user}]
    for t in range(turns - 1):
        msgs.append({"role": "assistant", "content": f"answer {t}"})
        msgs.append({"role": "user", "content": f"follow-up {t}"})
    return msgs


def test_affinity_key_stable_across_turns():
    k1 = affinity_key(conversation_head(_convo("plan a trip", 1)), 4)
    k3 = affinity_key(conversation_head(_convo("plan a trip", 3)), 4)
    assert k1 == k3                              # follow-ups keep the key
    other = affinity_key(conversation_head(_convo("write a poem", 1)), 4)
    assert other != k1                           # conversations spread


def test_affinity_same_chain_same_replica_and_next_best():
    names = [f"r{i}" for i in range(5)]
    key = affinity_key(conversation_head(_convo("plan a trip")), 4)
    rank1 = rank_replicas(key, names)
    rank2 = rank_replicas(key, list(reversed(names)))
    assert rank1 == rank2                        # order-independent
    # ejecting the owner: every router agrees on the same next-best
    survivors = [n for n in names if n != rank1[0]]
    assert rank_replicas(key, survivors)[0] == rank1[1]


def test_affinity_spreads_conversations():
    names = [f"r{i}" for i in range(4)]
    owners = set()
    for i in range(32):
        key = affinity_key(conversation_head(_convo(f"topic {i} " * 10)),
                           64)
        owners.add(rank_replicas(key, names)[0])
    assert len(owners) >= 3                      # no single hotspot


def test_affinity_spreads_despite_long_system_prompt():
    """A fleet-wide system prompt longer than a small cap must not
    collapse every conversation onto one key: the default cap (64
    blocks = 16KB) covers system + first message, so conversations
    still diverge."""
    big_sys = {"role": "system", "content": "corporate policy text " * 150}
    names = [f"r{i}" for i in range(4)]
    keys, owners = set(), set()
    for i in range(16):
        msgs = [big_sys, {"role": "user", "content": f"question {i}"}]
        key = affinity_key(conversation_head(msgs), 64)
        keys.add(key)
        owners.add(rank_replicas(key, names)[0])
    assert len(keys) == 16                       # every convo distinct
    assert len(owners) >= 2                      # and they spread


# ---------------------------------------------------------------------------
# fault-plan units
# ---------------------------------------------------------------------------


def test_fleet_fault_plan_parse_and_refuse():
    inj = fleet_faults.parse_plan("replica=r1;refuse_after_ops=2")
    assert inj.on_attempt("r0") == 0.0           # other replicas untouched
    assert inj.on_attempt("r1") == 0.0           # op 1 passes
    with pytest.raises(ConnectionError):
        inj.on_attempt("r1")                     # op 2+ refuse
    with pytest.raises(ConnectionError):
        inj.on_attempt("r1")
    inj2 = fleet_faults.parse_plan(
        "replica=r0;refuse_after_ops=1;refuse_times=1")
    with pytest.raises(ConnectionError):
        inj2.on_attempt("r0")
    assert inj2.on_attempt("r0") == 0.0          # window passed
    with pytest.raises(ValueError):
        fleet_faults.parse_plan("refuse=1")      # replica= required
    assert fleet_faults.parse_plan(
        "replica=r2;break_stream_after=3").break_stream("r2", 3)


# ---------------------------------------------------------------------------
# HTTP-level: router over fake replicas
# ---------------------------------------------------------------------------


class FakeReplica:
    """Canned `cake serve` stand-in: JSON + SSE chat (role chunk,
    per-token content chunks carrying a replica-scoped completion id,
    finish chunk, [DONE]), CONTINUATION MODE (a final assistant message
    with "continue": true resumes at the token its partial content ends
    on — counting "tok" occurrences stands in for re-tokenizing), a
    /health engine block, a mutable behavior switch, and request logs."""

    N_TOKS = 4

    def __init__(self, name: str):
        self.name = name
        self.mode = "ok"        # ok | http500 | http429 | hang |
                                # slow_stream | abrupt (sever the
                                # transport after break_after content
                                # chunks, no [DONE]) | overlap_resume
                                # (continuations re-emit the last
                                # already-relayed token first)
        self.break_after = 2    # content chunks before an abrupt sever
        self.served = []        # prompts this replica actually admitted
        self.continuations = []  # partial contents it spliced
        self.server = None
        self.release = asyncio.Event()

    def app(self) -> web.Application:
        async def chat(request):
            body = await request.json()
            if self.mode == "http500":
                return web.json_response({"error": "boom"}, status=500)
            if self.mode == "http429":
                return web.json_response({"error": "queue full"},
                                         status=429,
                                         headers={"Retry-After": "3"})
            if self.mode == "hang":
                await self.release.wait()
            msgs = body["messages"]
            start = 0
            cont = bool(msgs and msgs[-1].get("continue"))
            if cont:
                if msgs[-1].get("role") != "assistant":
                    return web.json_response(
                        {"error": "continue needs an assistant tail"},
                        status=400)
                partial = msgs[-1]["content"]
                self.continuations.append(partial)
                start = partial.count("tok")
                if self.mode == "overlap_resume" and start > 0:
                    start -= 1      # round down: re-emit the boundary
            self.served.append(msgs[-1]["content"])
            if body.get("stream"):
                hdrs = {"Content-Type": "text/event-stream"}
                if cont:
                    # continuation handshake: chars of the partial this
                    # replica's continuation actually consumed
                    hdrs["X-Cake-Continuation-Chars"] = str(
                        len("".join(f"tok{i}" for i in range(start))))
                resp = web.StreamResponse(headers=hdrs)
                await resp.prepare(request)

                def chunk(delta, finish=None):
                    return b"data: " + json.dumps({
                        "id": f"chatcmpl-{self.name}", "created": 1000,
                        "choices": [{"index": 0, "delta": delta,
                                     "finish_reason": finish}],
                    }).encode() + b"\n\n"
                n = 12 if self.mode == "slow_stream" else self.N_TOKS
                try:
                    await resp.write(chunk({"role": "assistant"}))
                    for i in range(start, n):
                        if self.mode == "slow_stream":
                            await asyncio.sleep(0.05)
                        if self.mode == "abrupt" \
                                and i - start >= self.break_after:
                            request.transport.close()
                            return resp
                        await resp.write(chunk({"content": f"tok{i}"}))
                    if self.mode == "abrupt" \
                            and n - start <= self.break_after:
                        # content fit under the sever point: eat the
                        # finish/[DONE] tail instead
                        request.transport.close()
                        return resp
                    await resp.write(chunk({}, "stop"))
                    await resp.write(b"data: [DONE]\n\n")
                    await resp.write_eof()
                except ConnectionError:
                    return resp              # router/client went away
                return resp
            return web.json_response({
                "id": "x", "object": "chat.completion",
                "served_by": self.name,
                "choices": [{"index": 0, "message":
                             {"role": "assistant", "content": "hi"},
                             "finish_reason": "stop"}]})

        async def health(request):
            return web.json_response({"engine": {
                "alive": True, "slots": 2, "queue_depth": 0}})

        app = web.Application()
        app.router.add_post("/v1/chat/completions", chat)
        app.router.add_get("/health", health)
        return app

    async def start(self):
        self.server = TestServer(self.app())
        await self.server.start_server()
        return str(self.server.make_url(""))

    async def stop(self):
        if self.server is not None:
            await self.server.close()


def _fleet_client(n_replicas=2, **router_kw):
    """(replicas, registry, router, mk) where mk() builds the started
    TestClient — run inside asyncio.run."""
    replicas = [FakeReplica(f"r{i}") for i in range(n_replicas)]
    registry = ReplicaRegistry(_policy())

    async def mk():
        for rep in replicas:
            url = await rep.start()
            registry.add(rep.name, url)
        kw = dict(retries=2, backoff_s=0.001, probe_s=30.0, hedge_ms=0.0)
        kw.update(router_kw)
        router = FleetRouter(registry, **kw)
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()
        return client, router
    return replicas, registry, mk


def _chat_body(content="hello", stream=False):
    return {"messages": [SYSTEM, {"role": "user", "content": content}],
            "max_tokens": 8, "temperature": 0.0, "stream": stream}


def test_router_proxies_and_affinity_stickiness():
    replicas, registry, mk = _fleet_client(3)

    async def run():
        client, _router = await mk()
        try:
            for turn in range(4):
                r = await client.post("/v1/chat/completions",
                                      json=_chat_body("same convo"))
                assert r.status == 200, await r.text()
            served = [len(rep.served) for rep in replicas]
            # all four turns of one conversation land on ONE replica
            assert sorted(served) == [0, 0, 4], served
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_router_failover_transparent_and_ejects():
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, _router = await mk()
        try:
            # find the owner of this conversation, then break it
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("convo A"))
            assert r.status == 200
            owner = next(rep for rep in replicas if rep.served)
            owner.mode = "http500"
            # every later request fails over transparently: zero errors
            # (8 requests so the owner's rolling window crosses
            # GRAY_MIN_SAMPLES and the error-rate detector may trip)
            for _ in range(8):
                r = await client.post("/v1/chat/completions",
                                      json=_chat_body("convo A"))
                assert r.status == 200, await r.text()
            other = next(rep for rep in replicas if rep is not owner)
            assert len(other.served) >= 8
            # the rolling error rate ejected the broken owner
            assert registry.get(owner.name).snapshot()["state"] == EJECTED
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_retry_budget_exhaustion_is_typed_503():
    replicas, registry, mk = _fleet_client(3)

    async def run():
        client, _router = await mk()
        try:
            for rep in replicas:
                rep.mode = "http500"
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body())
            assert r.status == 503
            body = await r.json()
            assert "failover budget exhausted" in body["error"]
            assert body["shed_by"] == "router"
            assert int(r.headers["Retry-After"]) >= 1
            assert body["attempts"] == 3         # 1 + retries(2)
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_first_byte_deadline_bounds_blackholed_replica():
    """A black-holed replica (TCP connects fine, bytes vanish — the
    nastiest partition shape) no longer wedges an attempt forever even
    with the deprecated attempt timeout at its 0.0=forever default: the
    first-byte deadline converts the hang into a bounded transport
    failure and the request fails over with zero client-visible errors,
    on both the JSON and the streamed path."""
    replicas, registry, mk = _fleet_client(
        2, first_byte_timeout_s=0.25, retries=3)

    async def run():
        client, _router = await mk()
        try:
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("convo A"))
            assert r.status == 200
            owner = next(rep for rep in replicas if rep.served)
            owner.mode = "hang"                  # accepts, never answers
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("convo A"))
            assert r.status == 200, await r.text()
            assert loop.time() - t0 < 5.0        # bounded, not forever
            # streamed request: the headers wait is bounded the same way
            # (pre-commit — no byte relayed — so it retries from scratch)
            t0 = loop.time()
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("convo A", stream=True))
            assert r.status == 200
            text = (await r.read()).decode()
            assert "[DONE]" in text
            assert loop.time() - t0 < 5.0
            other = next(rep for rep in replicas if rep is not owner)
            assert len(other.served) >= 2        # both failed over
            owner.release.set()                  # unpark the wedged handler
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_router_sheds_429_before_replica_admission():
    replicas, registry, mk = _fleet_client(1, max_inflight=1)

    async def run():
        client, _router = await mk()
        try:
            replicas[0].mode = "hang"
            t1 = asyncio.ensure_future(client.post(
                "/v1/chat/completions", json=_chat_body("first")))
            await asyncio.sleep(0.05)            # t1 occupies the bound
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("second"))
            assert r.status == 429
            body = await r.json()
            assert body["shed_by"] == "router"   # router, not replica
            assert "Retry-After" in r.headers
            # the shed request NEVER reached the replica
            assert len(replicas[0].served) == 0
            replicas[0].mode = "ok"
            replicas[0].release.set()
            assert (await t1).status == 200
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_no_routable_replica_is_typed_503():
    replicas, registry, mk = _fleet_client(1)

    async def run():
        client, _router = await mk()
        try:
            for _ in range(3):                   # eject the only replica
                registry.get("r0").record_result(False, transport=True)
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body())
            assert r.status == 503
            assert "no routable replica" in (await r.json())["error"]
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_replica_429_fails_over_without_eject():
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, _router = await mk()
        try:
            replicas[0].mode = "http429"
            replicas[1].mode = "http429"
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body())
            assert r.status == 503               # budget exhausted
            # backpressure is not sickness: nobody got ejected
            for rep in replicas:
                assert registry.get(rep.name).snapshot()["state"] \
                    == HEALTHY
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_stream_pre_token_failover_and_mid_stream_typed_error():
    """With the resume budget at 0 the legacy semantics are preserved:
    pre-commit breaks fail over invisibly, post-commit breaks emit the
    typed error event — which now also carries the resume_token and the
    honest content accounting (chars + tokens, not just SSE events)."""
    replicas, registry, mk = _fleet_client(2, stream_resumes=0)

    async def run():
        client, _router = await mk()
        try:
            # pre-first-token failover: owner 500s, stream succeeds
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("s convo", stream=True))
            assert r.status == 200
            owner = next(rep for rep in replicas if rep.served)
            text = await r.text()
            assert "tok0" in text and "[DONE]" in text
            owner.mode = "http500"
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("s convo", stream=True))
            assert r.status == 200               # failed over pre-commit
            assert "tok0" in await r.text()
            owner.mode = "ok"

            # mid-stream break: typed error event + resume accounting
            victim = next(rep for rep in replicas if rep is not owner)
            target = owner if owner.served else victim
            fleet_faults.install(
                f"replica={target.name};break_stream_after=2")
            try:
                r = await client.post(
                    "/v1/chat/completions",
                    json=_chat_body("s convo", stream=True))
                assert r.status == 200
                text = await r.text()
                assert "replica_stream_broken" in text
                assert "chunks_relayed" in text
                assert text.rstrip().endswith("data: [DONE]")
                err = next(json.loads(line[6:])["error"]
                           for line in text.split("\n\n")
                           if line.startswith("data: ")
                           and "replica_stream_broken" in line)
                resume = err["resume"]
                # role chunk + 1 content chunk relayed before the sever
                assert resume["chunks_relayed"] == 2
                assert resume["tokens_generated"] == 1
                assert resume["content_chars"] == len("tok0")
                tok = json.loads(base64.urlsafe_b64decode(
                    resume["resume_token"]))
                assert tok["mode"] == "continue"
                assert tok["tokens_generated"] == 1
            finally:
                fleet_faults.clear()
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_router_health_and_fleet_views():
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, _router = await mk()
        try:
            h = await client.get("/health")
            assert h.status == 200
            body = await h.json()
            assert body["fleet"]["routable"] == 2
            f = await client.get("/fleet")
            snap = await f.json()
            assert {r["name"] for r in snap["replicas"]} == {"r0", "r1"}
            m = await client.get("/metrics")
            assert "cake_fleet_replicas" in await m.text()
            # every replica down -> router health degrades to 503
            for name in ("r0", "r1"):
                for _ in range(3):
                    registry.get(name).record_result(False, transport=True)
            h = await client.get("/health")
            assert h.status == 503
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_client_disconnect_not_recorded_as_replica_failure():
    """A client that vanishes mid-stream must not feed the replica's
    failure detector — repeat disconnects would gray-eject a healthy
    replica (found driving the real router with `curl | head`)."""
    replicas, registry, mk = _fleet_client(1)

    async def run():
        client, _router = await mk()
        try:
            replicas[0].mode = "slow_stream"
            resp = await client.post("/v1/chat/completions",
                                     json=_chat_body("bye", stream=True))
            assert resp.status == 200
            await resp.content.read(16)          # first bytes flowed
            resp.close()                         # client walks away
            await asyncio.sleep(0.8)             # relay notices + unwinds
            snap = registry.get("r0").snapshot()
            assert snap["state"] == HEALTHY, snap
            assert snap["consec_fails"] == 0
            assert snap["ejects"] == 0
            assert snap["inflight"] == 0         # slot released
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_tail_hedge_duplicates_to_next_best():
    """With hedging on, a stalled owner does not own the tail: the
    duplicate fired at the next-best replica answers first."""
    replicas, registry, mk = _fleet_client(2, hedge_ms=30.0)

    async def run():
        client, _router = await mk()
        try:
            from cake_tpu.obs import FLEET_HEDGES
            # find the owner, then make every attempt against it stall
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("hedge convo"))
            assert r.status == 200
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            pre = FLEET_HEDGES.value()
            fleet_faults.install(f"replica={owner.name};stall_ms=1500")
            try:
                t0 = asyncio.get_event_loop().time()
                r = await client.post("/v1/chat/completions",
                                      json=_chat_body("hedge convo"))
                wall = asyncio.get_event_loop().time() - t0
                assert r.status == 200
                assert wall < 1.0, wall      # did not wait out the stall
                assert FLEET_HEDGES.value() == pre + 1
                assert other.served          # duplicate served the win
            finally:
                fleet_faults.clear()
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_round_robin_mode_spreads():
    replicas, registry, mk = _fleet_client(2, affinity=False)

    async def run():
        client, _router = await mk()
        try:
            for i in range(6):
                r = await client.post("/v1/chat/completions",
                                      json=_chat_body("same convo"))
                assert r.status == 200
            assert all(rep.served for rep in replicas)   # both took load
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


# ---------------------------------------------------------------------------
# self-healing streams (ISSUE 15)
# ---------------------------------------------------------------------------


def _sse_chunks(text: str) -> list:
    return [json.loads(line[6:]) for line in text.split("\n\n")
            if line.startswith("data: ") and line.strip() != "data: [DONE]"]


def _sse_content(text: str) -> str:
    return "".join(c["choices"][0]["delta"].get("content") or ""
                   for c in _sse_chunks(text) if "choices" in c)


def _events(router, rid):
    tl = router.timelines.get(rid)
    assert tl is not None, f"no router timeline for {rid}"
    return tl["events"]


def test_stream_resume_spliced_byte_identical():
    """Kill the owner mid-stream with one resume in the budget: the
    client receives the full body byte-identical to an unbroken run on
    the SAME socket — no error event, exactly one role chunk, every
    spliced chunk rewritten onto the original stream's id — and the
    router timeline shows stream_broken -> stream_resume ->
    resume_spliced -> done."""
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, router = await mk()
        try:
            from cake_tpu.obs import FLEET_STREAM_RESUMES
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("heal convo",
                                                  stream=True))
            assert r.status == 200
            base = await r.text()
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            assert _sse_content(base) == "tok0tok1tok2tok3"

            pre_ok = FLEET_STREAM_RESUMES.value(outcome="ok")
            owner.mode = "abrupt"       # sever after 2 content chunks
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("heal convo",
                                                  stream=True))
            assert r.status == 200
            rid = r.headers["X-Cake-Request-Id"]
            text = await r.text()
            # zero client-visible errors, full greedy body, clean end
            assert "replica_stream_broken" not in text
            assert _sse_content(text) == _sse_content(base)
            assert text.rstrip().endswith("data: [DONE]")
            chunks = [c for c in _sse_chunks(text) if "choices" in c]
            assert sum(1 for c in chunks
                       if "role" in c["choices"][0]["delta"]) == 1
            # spliced chunks are renumbered onto the FIRST stream's id
            assert {c["id"] for c in chunks} \
                == {f"chatcmpl-{owner.name}"}
            # the survivor served the splice in continuation mode
            assert other.continuations == ["tok0tok1"]
            assert FLEET_STREAM_RESUMES.value(outcome="ok") == pre_ok + 1
            kinds = [e["kind"] for e in _events(router, rid)]
            for k in ("commit", "stream_broken", "stream_resume",
                      "resume_spliced", "done"):
                assert k in kinds, (k, kinds)
            assert kinds.index("stream_broken") \
                < kinds.index("stream_resume") \
                < kinds.index("resume_spliced") < kinds.index("done")
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_stream_resume_overlap_strip():
    """A resumed replica that re-emits the splice-boundary token (the
    retokenization overlap case) has the duplicate stripped — the
    client still sees the body exactly once."""
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, router = await mk()
        try:
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("ov convo",
                                                  stream=True))
            assert r.status == 200
            await r.text()
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            owner.mode = "abrupt"
            other.mode = "overlap_resume"
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("ov convo",
                                                  stream=True))
            assert r.status == 200
            rid = r.headers["X-Cake-Request-Id"]
            text = await r.text()
            assert "replica_stream_broken" not in text
            assert _sse_content(text) == "tok0tok1tok2tok3"
            spliced = next(e for e in _events(router, rid)
                           if e["kind"] == "resume_spliced")
            assert spliced["overlap_chars"] == len("tok1")
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_stream_resume_budget_exhausted_typed_event_with_token():
    """When the resumed stream breaks again past the budget, the typed
    error event fires with the resume_token carrying the FULL splice
    accounting (text relayed across both legs)."""
    replicas, registry, mk = _fleet_client(2, stream_resumes=1)

    async def run():
        client, router = await mk()
        try:
            from cake_tpu.obs import FLEET_STREAM_RESUMES
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("ex convo",
                                                  stream=True))
            assert r.status == 200
            await r.text()
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            owner.mode = "abrupt"       # breaks after 2 content chunks
            other.mode = "abrupt"
            other.break_after = 1       # the splice breaks too
            pre = FLEET_STREAM_RESUMES.value(outcome="exhausted")
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("ex convo",
                                                  stream=True))
            assert r.status == 200
            text = await r.text()
            assert "replica_stream_broken" in text
            assert text.rstrip().endswith("data: [DONE]")
            # the client still got everything both legs relayed
            assert _sse_content(text) == "tok0tok1tok2"
            err = next(json.loads(line[6:])["error"]
                       for line in text.split("\n\n")
                       if line.startswith("data: ")
                       and "replica_stream_broken" in line)
            resume = err["resume"]
            assert resume["tokens_generated"] == 3
            assert resume["content_chars"] == len("tok0tok1tok2")
            assert resume["resumes_attempted"] == 1
            tok = json.loads(base64.urlsafe_b64decode(
                resume["resume_token"]))
            assert tok == {"v": 1, "mode": "continue",
                           "content_chars": 12, "tokens_generated": 3,
                           "chunks_relayed": resume["chunks_relayed"],
                           "resumes_attempted": 1}
            assert FLEET_STREAM_RESUMES.value(outcome="exhausted") \
                == pre + 1
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_client_disconnect_during_resume_not_replica_failure():
    """A client that walks away while the SPLICED stream is relaying
    must not count against the replica serving the resume."""
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, router = await mk()
        try:
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("cd convo",
                                                  stream=True))
            assert r.status == 200
            await r.text()
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            owner.mode = "abrupt"
            other.mode = "slow_stream"  # resume crawls: time to vanish
            resp = await client.post("/v1/chat/completions",
                                     json=_chat_body("cd convo",
                                                     stream=True))
            assert resp.status == 200
            await resp.content.read(16)          # first bytes flowed
            await asyncio.sleep(0.2)             # resume under way
            resp.close()                         # client walks away
            await asyncio.sleep(0.8)             # relay notices + unwinds
            snap = registry.get(other.name).snapshot()
            assert snap["state"] == HEALTHY, snap
            assert snap["consec_fails"] == 0
            assert snap["ejects"] == 0
            assert snap["inflight"] == 0         # slot released
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_sampled_stream_resume_flagged():
    """Sampled (temperature > 0) streams still resume, but the timeline
    flags the rng-fold parity exception."""
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, router = await mk()
        try:
            body = _chat_body("sa convo", stream=True)
            body["temperature"] = 0.8
            r = await client.post("/v1/chat/completions", json=body)
            assert r.status == 200
            await r.text()
            owner = next(rep for rep in replicas if rep.served)
            owner.mode = "abrupt"
            r = await client.post("/v1/chat/completions", json=body)
            assert r.status == 200
            rid = r.headers["X-Cake-Request-Id"]
            text = await r.text()
            assert "replica_stream_broken" not in text
            assert _sse_content(text) == "tok0tok1tok2tok3"
            ev = next(e for e in _events(router, rid)
                      if e["kind"] == "stream_resume")
            assert ev.get("sampled") is True
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


def test_stream_hedge_pre_commit_first_byte_wins():
    """Streamed tail hedge up to the commit point: a stalled owner does
    not own the socket — the duplicate's first body byte claims it, the
    loser is cancelled, and the client sees ONE clean stream."""
    replicas, registry, mk = _fleet_client(2, hedge_ms=30.0)

    async def run():
        client, router = await mk()
        try:
            from cake_tpu.obs import FLEET_HEDGES
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("hs convo",
                                                  stream=True))
            assert r.status == 200
            await r.text()
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            pre = FLEET_HEDGES.value()
            fleet_faults.install(f"replica={owner.name};stall_ms=1500")
            try:
                t0 = asyncio.get_event_loop().time()
                r = await client.post("/v1/chat/completions",
                                      json=_chat_body("hs convo",
                                                      stream=True))
                text = await r.text()
                wall = asyncio.get_event_loop().time() - t0
                assert r.status == 200
                assert wall < 1.0, wall      # did not wait out the stall
                assert FLEET_HEDGES.value() == pre + 1
                assert _sse_content(text) == "tok0tok1tok2tok3"
                chunks = [c for c in _sse_chunks(text)
                          if "choices" in c]
                assert sum(1 for c in chunks
                           if "role" in c["choices"][0]["delta"]) == 1
                assert text.rstrip().endswith("data: [DONE]")
                assert other.served              # duplicate won the race
            finally:
                fleet_faults.clear()
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())


# ---------------------------------------------------------------------------
# weighted rendezvous
# ---------------------------------------------------------------------------


def test_weighted_rendezvous_share_distribution():
    """Owner share tracks capacity: weight 3 vs 1 converges to a 3:1
    conversation split (0.75 +- sampling noise over 2000 keys)."""
    names = ["big", "small"]
    weights = {"big": 3.0, "small": 1.0}
    big = 0
    for i in range(2000):
        key = affinity_key(conversation_head(_convo(f"wconvo {i}")), 4)
        if rank_replicas(key, names, weights)[0] == "big":
            big += 1
    assert 0.70 <= big / 2000 <= 0.80, big / 2000


def test_weighted_rendezvous_equal_weights_match_unweighted():
    """Uniform weights reproduce the classic unweighted ranking exactly
    (the log-score is monotone in the hash), so homogeneous fleets keep
    their placement across the upgrade."""
    names = [f"r{i}" for i in range(5)]
    for i in range(64):
        key = affinity_key(conversation_head(_convo(f"eq {i}")), 4)
        assert rank_replicas(key, names) \
            == rank_replicas(key, names, {n: 2.0 for n in names}) \
            == rank_replicas(key, names, {})


def test_weighted_rendezvous_affinity_stability():
    """Raising ONE replica's weight only moves conversations TO it;
    every key it does not win keeps its previous ranking among the
    others — the affinity-stability property that keeps a weight bump
    from cold-starting the whole fleet's caches."""
    names = [f"r{i}" for i in range(4)]
    w1 = {n: 1.0 for n in names}
    w2 = dict(w1, r2=2.5)
    moved = 0
    for i in range(300):
        key = affinity_key(conversation_head(_convo(f"st {i}")), 4)
        a = rank_replicas(key, names, w1)
        b = rank_replicas(key, names, w2)
        if a[0] != b[0]:
            moved += 1
            assert b[0] == "r2"              # only r2 gains owners
        assert [n for n in a if n != "r2"] \
            == [n for n in b if n != "r2"]   # relative order preserved
    assert 0 < moved < 300


def test_stream_break_after_budget_complete_synthesizes_finish():
    """A break that eats only the finish/[DONE] tail — every budgeted
    token was already delivered — must NOT splice (a resume would decode
    past max_tokens): the router closes the stream with a synthesized
    finish chunk in the original stream's identity instead."""
    replicas, registry, mk = _fleet_client(2)

    async def run():
        client, router = await mk()
        try:
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("bf convo",
                                                  stream=True))
            assert r.status == 200
            await r.text()
            owner = next(rep for rep in replicas if rep.served)
            other = next(rep for rep in replicas if rep is not owner)
            owner.mode = "abrupt"
            owner.break_after = FakeReplica.N_TOKS  # sever before finish
            body = _chat_body("bf convo", stream=True)
            body["max_tokens"] = FakeReplica.N_TOKS  # budget delivered
            r = await client.post("/v1/chat/completions", json=body)
            assert r.status == 200
            text = await r.text()
            assert "replica_stream_broken" not in text
            assert _sse_content(text) == "tok0tok1tok2tok3"
            chunks = [c for c in _sse_chunks(text) if "choices" in c]
            assert chunks[-1]["choices"][0]["finish_reason"] == "length"
            assert chunks[-1]["id"] == f"chatcmpl-{owner.name}"
            assert text.rstrip().endswith("data: [DONE]")
            assert not other.continuations       # no splice happened
        finally:
            await client.close()
            for rep in replicas:
                await rep.stop()
    asyncio.run(run())
