"""VibeVoice release-checkpoint loading: synthesize an HF-layout dir with
the REAL tensor names (model.language_model / model.tts_language_model /
model.prediction_head / model.acoustic_tokenizer.decoder / ... — the
prefixes the reference wires in vibevoice.rs) and load through the public
path, including a precomputed voice-prompt file (voice_prompt.rs format).
"""
import pytest
import json

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.audio import (detect_vibevoice_checkpoint,
                                   load_vibevoice, tiny_tts_config)
from cake_tpu.models.audio.vibevoice import (init_connector_params,
                                             init_eos_params,
                                             init_head_params,
                                             init_vae_decoder_params,
                                             init_vae_encoder_params)
from cake_tpu.models.audio.vibevoice_loader import (connector_mapping,
                                                    eos_mapping,
                                                    head_mapping,
                                                    vae_decoder_mapping,
                                                    vae_encoder_mapping)
from cake_tpu.utils.mapping import flatten_tree
from cake_tpu.utils.safetensors_io import save_safetensors


def _lm_tensors(cfg, params, prefix):
    """Emit HF Qwen2-style names for an LM stack pytree."""
    out = {}
    out[f"{prefix}.embed_tokens.weight"] = params["embed_tokens"]["weight"]
    out[f"{prefix}.norm.weight"] = params["norm"]["weight"]
    for i, lp in enumerate(params["layers"]):
        lpfx = f"{prefix}.layers.{i}"
        at = lp["self_attn"]
        for proj in ("q_proj", "k_proj", "v_proj"):
            out[f"{lpfx}.self_attn.{proj}.weight"] = at[proj]["weight"]
            if "bias" in at[proj]:
                out[f"{lpfx}.self_attn.{proj}.bias"] = at[proj]["bias"]
        out[f"{lpfx}.self_attn.o_proj.weight"] = at["o_proj"]["weight"]
        for proj in ("gate_proj", "up_proj", "down_proj"):
            out[f"{lpfx}.mlp.{proj}.weight"] = lp["mlp"][proj]["weight"]
        out[f"{lpfx}.input_layernorm.weight"] = \
            lp["input_layernorm"]["weight"]
        out[f"{lpfx}.post_attention_layernorm.weight"] = \
            lp["post_attention_layernorm"]["weight"]
    return out


def synth_vibevoice_dir(tmp_path):
    cfg = tiny_tts_config()
    ks = jax.random.split(jax.random.PRNGKey(0), 8)
    from cake_tpu.models.common.layers import init_params
    tensors = {}
    tensors.update(_lm_tensors(
        cfg.lm_base, init_params(cfg.lm_base, ks[0], jnp.float32),
        "model.language_model"))
    tensors.update(_lm_tensors(
        cfg.lm_tts, init_params(cfg.lm_tts, ks[1], jnp.float32),
        "model.tts_language_model"))
    for pytree, mapping in (
            (init_head_params(cfg, ks[2], jnp.float32), head_mapping(cfg)),
            (init_connector_params(cfg, ks[3], jnp.float32, bias=True),
             connector_mapping(True)),
            (init_eos_params(cfg, ks[4], jnp.float32), eos_mapping()),
            (init_vae_decoder_params(cfg, ks[5], jnp.float32),
             vae_decoder_mapping(cfg)),
            (init_vae_encoder_params(cfg, ks[7], jnp.float32),
             vae_encoder_mapping(cfg))):
        flat = flatten_tree(pytree)
        for path, name in mapping.items():
            tensors[name] = np.asarray(flat[path], np.float32)
    tensors["model.tts_input_types.weight"] = \
        np.asarray(jax.random.normal(ks[6], (2, cfg.hidden)), np.float32) * .02
    tensors["model.speech_scaling_factor"] = np.asarray(1.5, np.float32)
    tensors["model.speech_bias_factor"] = np.asarray(0.1, np.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     {k: np.asarray(v, np.float32) if np.asarray(v).dtype
                      != np.float32 else np.asarray(v)
                      for k, v in tensors.items()})
    raw = {
        "acoustic_vae_dim": cfg.acoustic_dim,
        "tts_backbone_num_hidden_layers": cfg.lm_tts.num_hidden_layers,
        "decoder_config": {
            "hidden_size": cfg.lm_base.hidden_size,
            "intermediate_size": cfg.lm_base.intermediate_size,
            "num_attention_heads": cfg.lm_base.num_attention_heads,
            "num_hidden_layers": cfg.lm_base.num_hidden_layers,
            "num_key_value_heads": cfg.lm_base.num_key_value_heads,
            "rms_norm_eps": cfg.lm_base.rms_norm_eps,
            "rope_theta": cfg.lm_base.rope_theta,
            "vocab_size": cfg.lm_base.vocab_size,
            "max_position_embeddings": 128,
            "tie_word_embeddings": True,
        },
        "diffusion_head_config": {
            "ddpm_num_inference_steps": cfg.solver_steps,
            "ddpm_num_steps": cfg.ddpm_num_steps,
            "head_layers": cfg.head_layers,
            "hidden_size": cfg.hidden,
            "latent_size": cfg.acoustic_dim,
            "head_ffn_ratio": cfg.head_ffn_ratio,
            "prediction_type": "v_prediction",
            "rms_norm_eps": cfg.head_eps,
        },
        "acoustic_tokenizer_config": {
            "vae_dim": cfg.acoustic_dim,
            "encoder_n_filters": cfg.vae_n_filters,
            "decoder_n_filters": cfg.vae_n_filters,
            "encoder_ratios": list(cfg.vae_ratios),
            "decoder_ratios": list(cfg.vae_ratios),
            "decoder_depths": "-".join(str(d) for d in cfg.vae_depths),
            "layernorm": "RMSNorm", "layernorm_eps": cfg.vae_eps,
            "causal": True,
        },
    }
    with open(tmp_path / "config.json", "w") as f:
        json.dump(raw, f)
    return cfg


EXPECTED_NAMES = [
    "model.language_model.embed_tokens.weight",
    "model.language_model.layers.0.self_attn.q_proj.bias",
    "model.tts_language_model.layers.1.mlp.gate_proj.weight",
    "model.tts_language_model.norm.weight",
    "model.tts_input_types.weight",
    "model.prediction_head.t_embedder.mlp.0.weight",
    "model.prediction_head.noisy_images_proj.weight",
    "model.prediction_head.layers.0.adaLN_modulation.1.weight",
    "model.prediction_head.layers.1.ffn.gate_proj.weight",
    "model.prediction_head.final_layer.adaLN_modulation.1.weight",
    "model.prediction_head.final_layer.linear.weight",
    "model.acoustic_connector.fc1.weight",
    "model.acoustic_connector.norm.weight",
    "tts_eos_classifier.fc1.weight",
    "model.acoustic_tokenizer.decoder.upsample_layers.0.0.conv.conv.weight",
    "model.acoustic_tokenizer.decoder.upsample_layers.1.0.convtr.convtr"
    ".weight",
    "model.acoustic_tokenizer.decoder.stages.0.0.mixer.conv.conv.conv"
    ".weight",
    "model.acoustic_tokenizer.decoder.stages.2.0.ffn.linear1.weight",
    "model.acoustic_tokenizer.decoder.head.conv.conv.weight",
    "model.acoustic_tokenizer.encoder.downsample_layers.0.0.conv.conv"
    ".weight",
    "model.acoustic_tokenizer.encoder.downsample_layers.1.0.conv.conv"
    ".weight",
    "model.acoustic_tokenizer.encoder.stages.0.0.mixer.conv.conv.conv"
    ".weight",
    "model.acoustic_tokenizer.encoder.head.conv.conv.weight",
    "model.speech_scaling_factor",
]


def test_names_and_detection(tmp_path):
    synth_vibevoice_dir(tmp_path)
    from cake_tpu.utils.safetensors_io import index_file
    names = set(index_file(str(tmp_path / "model.safetensors")))
    missing = [n for n in EXPECTED_NAMES if n not in names]
    assert not missing, f"missing names: {missing}"
    assert detect_vibevoice_checkpoint(str(tmp_path))


def test_load_and_generate(tmp_path):
    cfg = synth_vibevoice_dir(tmp_path)
    tts = load_vibevoice(str(tmp_path), dtype=jnp.float32, max_frames=4)
    audio = tts.generate_speech("hello world", max_frames=3, steps=2)
    assert audio.sample_rate == cfg.sample_rate
    assert len(audio.samples) == 3 * cfg.hop       # frames x hop samples
    assert np.isfinite(audio.samples).all()
    # scaling factors came from the checkpoint
    assert float(tts.params["speech_scaling_factor"]) == 1.5


def test_voice_prompt_kv_injection(tmp_path):
    cfg = synth_vibevoice_dir(tmp_path)
    tts = load_vibevoice(str(tmp_path), dtype=jnp.float32, max_frames=4)
    # synthesize a voice prompt in the reference format
    rng = np.random.default_rng(0)
    seq, hkv, d = 3, cfg.lm_tts.num_key_value_heads, cfg.lm_tts.head_dim
    vp = {}
    for pfx, layers in (("lm", cfg.lm_base.num_hidden_layers),
                        ("tts_lm", cfg.lm_tts.num_hidden_layers),
                        ("neg_tts_lm", cfg.lm_tts.num_hidden_layers)):
        for i in range(layers):
            vp[f"{pfx}.kv.{i}.key"] = rng.standard_normal(
                (1, hkv, seq, d)).astype(np.float32)
            vp[f"{pfx}.kv.{i}.value"] = rng.standard_normal(
                (1, hkv, seq, d)).astype(np.float32)
        vp[f"{pfx}.last_hidden_state"] = rng.standard_normal(
            (1, seq, cfg.hidden)).astype(np.float32)
    save_safetensors(str(tmp_path / "voice.safetensors"), vp)
    a = tts.generate_speech("hi", max_frames=2, steps=2)
    b = tts.generate_speech("hi", voice=str(tmp_path / "voice.safetensors"),
                            max_frames=2, steps=2)
    assert not np.allclose(a.samples, b.samples)


def test_runtime_detection(tmp_path):
    synth_vibevoice_dir(tmp_path)
    from cake_tpu.runtime import build_audio_model
    tts = build_audio_model(str(tmp_path), dtype="f32")
    assert type(tts).__name__ == "VibeVoiceTTS"


def test_vae_encoder_frame_count(tmp_path):
    """Encoder frame count matches the reference's stride-grid arithmetic
    and the encode->scale->connector chain produces hidden-width embeds."""
    cfg = synth_vibevoice_dir(tmp_path)
    tts = load_vibevoice(str(tmp_path), dtype=jnp.float32, max_frames=4)
    assert "vae_enc" in tts.params
    samples = np.sin(np.linspace(0, 40, cfg.hop * 8)).astype(np.float32)
    feats, connected = tts.encode_voice_reference(samples)
    assert feats.shape[0] == 1 and feats.shape[2] == cfg.acoustic_dim
    # alignment right-padding can add a frame per strided conv, never drop
    assert feats.shape[1] >= 8
    assert connected.shape == (1, feats.shape[1], cfg.hidden)
    assert np.isfinite(np.asarray(connected)).all()
    # scaling applied: features = (latents + bias) * scale with the
    # checkpoint's scalars
    lat = tts._encode_audio(tts.params["vae_enc"],
                            jnp.asarray(samples[None]))
    np.testing.assert_allclose(np.asarray(feats),
                               np.asarray((lat + 0.1) * 1.5), rtol=1e-5)
    # a clip shorter than the compile grid: bucket-padding silence frames
    # are sliced off, and (causal convs) the kept frames equal the frames
    # of an exact-length encode
    short = samples[:cfg.hop * 5 + 13]
    feats_s, _ = tts.encode_voice_reference(short)
    from cake_tpu.models.audio.vibevoice import _encoder_frames
    assert feats_s.shape[1] == _encoder_frames(cfg, len(short))
    lat_exact = tts._encode_audio(tts.params["vae_enc"],
                                  jnp.asarray(short[None]))
    assert lat_exact.shape[1] == feats_s.shape[1]
    # frames whose conv windows stay inside the clip match the exact-length
    # encode; the last ~2 frames may deviate ~1% (documented bucket-padding
    # boundary effect)
    np.testing.assert_allclose(np.asarray(feats_s)[:, :-2],
                               np.asarray((lat_exact + 0.1) * 1.5)[:, :-2],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(feats_s),
                               np.asarray((lat_exact + 0.1) * 1.5),
                               rtol=0.15, atol=0.02)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_raw_wav_voice_cloning(tmp_path):
    """generate_speech(voice_wav=...) must condition on the encoded
    reference: output differs from the no-voice path, and the encoder
    missing from the checkpoint raises a clear error."""
    cfg = synth_vibevoice_dir(tmp_path)
    tts = load_vibevoice(str(tmp_path), dtype=jnp.float32, max_frames=4)
    from cake_tpu.utils.wav import encode_wav
    wav = encode_wav(np.sin(np.linspace(0, 60, cfg.hop * 8))
                     .astype(np.float32), cfg.sample_rate)
    a = tts.generate_speech("hi", max_frames=2, steps=2)
    b = tts.generate_speech("hi", voice_wav=wav, max_frames=2, steps=2)
    assert len(b.samples) > 0
    assert not np.allclose(a.samples, b.samples)
    # clear error when the encoder is absent
    del tts.params["vae_enc"]
    import pytest
    with pytest.raises(ValueError, match="acoustic encoder"):
        tts.generate_speech("hi", voice_wav=wav, max_frames=2, steps=2)
