"""Network chaos layer (fleet/netem.py): plan grammar + ChaosProxy.

The grammar tests mirror test_fleet's faults.parse_plan coverage; the
proxy tests run real asyncio sockets against a local echo upstream —
in-process, sub-second, tier-1 cheap. The full router-through-proxy
drill lives in scripts/partition_smoke.py (tier 2)."""
import asyncio

import pytest

from cake_tpu.fleet.netem import (ChaosProxy, NetemPlan, control_send,
                                  parse_plan)

# ---------------------------------------------------------------------------
# plan grammar
# ---------------------------------------------------------------------------


def test_parse_flags_and_values():
    p = parse_plan("partition")
    assert p.partition and p.faulty()
    assert not (p.partition_in or p.partition_out or p.blackhole)
    p = parse_plan("partition_out;match=/v1/chat")
    assert p.partition_out and p.match == "/v1/chat"
    p = parse_plan("delay_ms=75;jitter_ms=25;heal_after_s=1.5")
    assert (p.delay_ms, p.jitter_ms, p.heal_after_s) == (75.0, 25.0, 1.5)
    p = parse_plan("reset_after_bytes=512")
    assert p.reset_after_bytes == 512
    p = parse_plan("blackhole;heal_after_s=2")
    assert p.blackhole and p.heal_after_s == 2.0


def test_parse_explicit_flag_values():
    assert parse_plan("partition=1").partition
    assert parse_plan("partition=true").partition
    assert not parse_plan("partition=0").partition


def test_zero_plan_is_not_faulty():
    assert not NetemPlan().faulty()
    assert NetemPlan().snapshot() == {}
    # heal_after_s alone does not misbehave either
    assert not parse_plan("heal_after_s=5").faulty()


def test_parse_rejects_unknown_keys_and_missing_values():
    with pytest.raises(ValueError, match="unknown netem key"):
        parse_plan("partittion")
    with pytest.raises(ValueError, match="needs a value"):
        parse_plan("delay_ms")
    with pytest.raises(ValueError, match="needs a value"):
        parse_plan("reset_after_bytes=")
    with pytest.raises(ValueError):
        parse_plan("delay_ms=fast")


def test_parse_plan_exactly_one_clause():
    with pytest.raises(ValueError, match="exactly one clause"):
        parse_plan("partition,blackhole")
    with pytest.raises(ValueError, match="exactly one clause"):
        parse_plan("")


def test_snapshot_round_trips_the_interesting_fields():
    p = parse_plan("partition_in;delay_ms=10;match=/x")
    assert p.snapshot() == {"partition_in": True, "delay_ms": 10.0,
                            "match": "/x"}


# ---------------------------------------------------------------------------
# proxy data path (real sockets, echo upstream)
# ---------------------------------------------------------------------------


async def _echo_upstream():
    """Echo server: replies b"echo:" + whatever arrived."""
    async def handle(reader, writer):
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    return
                writer.write(b"echo:" + data)
                await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass
    srv = await asyncio.start_server(handle, "127.0.0.1", 0)
    return srv, srv.sockets[0].getsockname()[1]


async def _roundtrip(port: int, payload: bytes,
                     timeout: float = 2.0) -> bytes:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        writer.write(payload)
        await writer.drain()
        return await asyncio.wait_for(reader.read(65536), timeout)
    finally:
        writer.close()


def test_proxy_relays_clean_without_a_plan():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            assert await _roundtrip(proxy.port, b"hi") == b"echo:hi"
            st = proxy.status()
            assert st["accepted"] == 1 and st["plan"] == {}
            assert st["relayed_in"] > 0 and st["relayed_out"] > 0
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_partition_refuses_new_and_severs_live():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            # live connection mid-conversation
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port)
            writer.write(b"a")
            await writer.drain()
            assert await asyncio.wait_for(reader.read(64), 2.0) \
                == b"echo:a"
            proxy.apply("partition")
            # the live connection is severed (EOF or reset)
            try:
                tail = await asyncio.wait_for(reader.read(64), 2.0)
            except (ConnectionError, OSError):
                tail = b""
            assert tail == b""
            writer.close()
            # new connections die before any byte comes back
            with pytest.raises((ConnectionError, OSError,
                                asyncio.TimeoutError)):
                out = await _roundtrip(proxy.port, b"b", timeout=0.5)
                assert out == b""           # EOF-shaped refusal
                raise ConnectionResetError  # normalize for the assert
            assert proxy.severed >= 1
            # heal: traffic flows again
            proxy.heal()
            assert await _roundtrip(proxy.port, b"c") == b"echo:c"
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_blackhole_accepts_then_never_responds():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("blackhole")
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port)       # accept succeeds
            writer.write(b"anyone home?")
            await writer.drain()
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(reader.read(64), 0.3)
            writer.close()
            assert proxy.relayed_out == 0      # nothing ever came back
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_partition_out_with_match_is_probe_alive_data_dead():
    """The asymmetric drill: connections whose first bytes carry the
    match substring lose the server->client direction; everything else
    relays clean through the same port."""
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("partition_out;match=/v1/chat")
            # probe-shaped traffic: unmatched, flows both ways
            assert await _roundtrip(proxy.port, b"GET /health") \
                == b"echo:GET /health"
            # data-shaped traffic: request reaches upstream, reply dies
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port)
            writer.write(b"POST /v1/chat/completions")
            await writer.drain()
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(reader.read(64), 0.3)
            writer.close()
            assert proxy.relayed_in > 0        # inbound still crossed
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_match_reclassifies_a_kept_alive_connection():
    """Routers POOL connections: a socket whose first request was a
    probe can later carry data traffic. The sniff is continuous — the
    moment matching bytes cross, the connection becomes subject."""
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("partition_out;match=/v1/chat")
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port)
            writer.write(b"GET /health")          # probe-shaped first
            await writer.drain()
            assert await asyncio.wait_for(reader.read(64), 2.0) \
                == b"echo:GET /health"
            writer.write(b"POST /v1/chat/completions")  # same socket
            await writer.drain()
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(reader.read(64), 0.3)
            writer.close()
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_partition_in_drops_requests_silently():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("partition_in")
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port)
            writer.write(b"into the void")
            await writer.drain()
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(reader.read(64), 0.3)
            writer.close()
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_delay_brownout_paces_but_delivers():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("delay_ms=120")
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            assert await _roundtrip(proxy.port, b"slow") == b"echo:slow"
            # two faulted hops (in + out), each delayed >= 120ms
            assert loop.time() - t0 >= 0.2
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_reset_after_bytes_severs_mid_response():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("reset_after_bytes=4")
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", proxy.port)
            writer.write(b"x" * 64)
            await writer.drain()
            got = b""
            try:
                while True:
                    piece = await asyncio.wait_for(reader.read(64), 2.0)
                    if not piece:
                        break
                    got += piece
            except (ConnectionError, OSError):
                pass                           # reset is the point
            assert len(got) < 64 + 5           # response truncated
            writer.close()
            assert proxy.severed >= 1
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


def test_heal_after_s_auto_heals():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port, control=False)
        await proxy.start()
        try:
            proxy.apply("partition;heal_after_s=0.2")
            with pytest.raises((ConnectionError, OSError,
                                asyncio.TimeoutError)):
                out = await _roundtrip(proxy.port, b"a", timeout=0.4)
                assert out == b""
                raise ConnectionResetError
            deadline = asyncio.get_running_loop().time() + 3.0
            while True:                         # deadline poll, no sleeps
                try:
                    if await _roundtrip(proxy.port, b"b",
                                        timeout=0.4) == b"echo:b":
                        break
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass
                assert asyncio.get_running_loop().time() < deadline, \
                    "auto-heal never landed"
                await asyncio.sleep(0.05)
            assert not proxy.plan.faulty()
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())


# ---------------------------------------------------------------------------
# control socket
# ---------------------------------------------------------------------------


def test_control_socket_set_status_heal():
    async def run():
        srv, port = await _echo_upstream()
        proxy = ChaosProxy("127.0.0.1", port)
        await proxy.start()
        try:
            cp = proxy.control_port
            assert cp is not None
            out = await control_send("127.0.0.1", cp,
                                     "SET partition_out;match=/v1/chat")
            assert out["ok"] and out["plan"]["partition_out"]
            st = await control_send("127.0.0.1", cp, "STATUS")
            assert st["ok"] and st["plan"]["match"] == "/v1/chat"
            out = await control_send("127.0.0.1", cp, "HEAL")
            assert out["ok"] and out["plan"] == {}
            assert not proxy.plan.faulty()
            # errors answer ok=false and keep the proxy alive
            out = await control_send("127.0.0.1", cp, "SET bogus=1")
            assert not out["ok"] and "unknown netem key" in out["error"]
            out = await control_send("127.0.0.1", cp, "FROB")
            assert not out["ok"]
            assert await _roundtrip(proxy.port, b"ok") == b"echo:ok"
        finally:
            await proxy.close()
            srv.close()
            await srv.wait_closed()
    asyncio.run(run())
