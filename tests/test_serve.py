"""Continuous-batching serve engine: slot/queue units (no model), batched
slot-decode cache ops, and end-to-end concurrent serving through the
aiohttp API on a tiny CPU model — the tier-1 pin for ISSUE 2's acceptance:
concurrent requests interleave, greedy outputs match the sequential path
exactly, backpressure answers 429, and disconnects reclaim slots."""
import asyncio
import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import (AdmissionQueue, QueueFull, ServeEngine,
                            SlotPool, maybe_engine)

GREEDY = SamplingConfig(temperature=0.0)


# ---------------------------------------------------------------------------
# units: no model required
# ---------------------------------------------------------------------------


def test_slot_pool_lowest_first():
    p = SlotPool(3)
    assert [p.alloc(), p.alloc(), p.alloc()] == [0, 1, 2]
    assert p.alloc() is None and p.free_count == 0
    p.free(1)
    assert p.alloc() == 1                 # lowest free index, not LIFO
    p.free(0)
    p.free(2)
    assert p.busy() == [1] and p.prefix_len() == 2
    p.free(1)
    assert p.prefix_len() == 0
    with pytest.raises(ValueError):
        p.free(1)                         # double free


def test_slot_bucket_powers_of_two():
    from cake_tpu.serve.slots import slot_bucket
    assert [slot_bucket(n, 8) for n in (1, 2, 3, 4, 5, 8)] == \
        [1, 2, 4, 4, 8, 8]
    assert slot_bucket(3, 4) == 4 and slot_bucket(1, 1) == 1
    # the whole point vs bucket_for: a lone request decodes 1 row, not 32
    assert slot_bucket(1, 4) == 1


def test_admission_queue_purge():
    q = AdmissionQueue(maxsize=4)
    for x in ("a", "bb", "c", "dd"):
        q.put(x)
    dropped = q.purge(lambda s: len(s) == 2)
    assert dropped == ["bb", "dd"]
    assert q.pop() == "a" and q.pop() == "c" and q.pop() is None
    from cake_tpu.obs import SERVE_QUEUE_DEPTH
    assert SERVE_QUEUE_DEPTH.value() == 0


def test_admission_queue_fifo_and_bound():
    from cake_tpu.obs import SERVE_QUEUE_DEPTH
    q = AdmissionQueue(maxsize=2)
    q.put("a")
    q.put("b")
    assert SERVE_QUEUE_DEPTH.value() == 2
    with pytest.raises(QueueFull) as ei:
        q.put("c")
    assert ei.value.retry_after_s >= 1
    assert q.pop() == "a" and q.pop() == "b" and q.pop() is None
    assert SERVE_QUEUE_DEPTH.value() == 0
    q.put("d")
    assert q.drain() == ["d"] and q.depth() == 0


def test_slot_assign_and_reset_rehome():
    """slot_assign re-homes a batch-1 bucketed cache into one pool row
    (position -> slot remap, padding dropped) leaving other rows alone;
    slot_reset clears exactly one row. Pure cache ops, no model."""
    from cake_tpu.models.common.cache import (init_cache, slot_assign_layers,
                                              slot_reset_layers)
    cfg = tiny_config("llama")
    pool = init_cache(cfg, 3, 64, jnp.float32)
    # make row 0 and 2 recognizably non-empty
    layers = pool["layers"]
    layers = [{**lc, "k": lc["k"].at[0].set(7.0).at[2].set(9.0),
               "pos": lc["pos"].at[0, :4].set(jnp.arange(4))}
              for lc in layers]

    src = init_cache(cfg, 1, 32, jnp.float32)
    n = 5
    src_layers = []
    for lc in src["layers"]:
        k = lc["k"].at[0, :n].set(
            jnp.arange(n, dtype=jnp.float32)[:, None, None] + 1.0)
        pos = lc["pos"].at[0, :n].set(jnp.arange(n))
        src_layers.append({**lc, "k": k, "v": lc["v"], "pos": pos})

    out = slot_assign_layers(cfg, layers, src_layers, jnp.asarray(1))
    for lc in out:
        np.testing.assert_array_equal(np.asarray(lc["pos"][1, :n]),
                                      np.arange(n))
        assert int(jnp.max(lc["pos"][1, n:])) == -1      # rest of row empty
        np.testing.assert_allclose(np.asarray(lc["k"][1, :n, 0, 0]),
                                   np.arange(n) + 1.0)
        # neighbors untouched
        assert float(lc["k"][0, 0, 0, 0]) == 7.0
        assert float(lc["k"][2, 0, 0, 0]) == 9.0
        np.testing.assert_array_equal(np.asarray(lc["pos"][0, :4]),
                                      np.arange(4))

    out = slot_reset_layers(out, jnp.asarray(1))
    for lc in out:
        assert int(jnp.max(lc["pos"][1])) == -1
        assert float(jnp.abs(lc["k"][1]).max()) == 0.0
        assert float(lc["k"][0, 0, 0, 0]) == 7.0         # row 0 survives


def test_sample_traced_matches_static_greedy():
    """The traced sampler (one executable for every per-slot config mix)
    must agree with the static dispatch on greedy, incl. repeat penalty
    and tie-breaking; stochastic draws must respect the top-k set."""
    from cake_tpu.ops.sampling import sample, sample_traced
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(jax.random.PRNGKey(1), (97,)) * 3
    recent = jnp.full((8,), -1, jnp.int32).at[:3].set(jnp.asarray([5, 9, 5]))
    for pen in (1.0, 1.3):
        a = sample(logits, rng,
                   SamplingConfig(temperature=0.0, repeat_penalty=pen),
                   recent)
        b = sample_traced(logits, rng, jnp.float32(0.0), jnp.int32(97),
                          jnp.float32(1.0), jnp.float32(pen), recent)
        assert int(a) == int(b)
    tie = jnp.zeros((10,)).at[3].set(5.0).at[7].set(5.0)
    none = jnp.full((4,), -1, jnp.int32)
    assert int(sample_traced(tie, rng, jnp.float32(0.0), jnp.int32(10),
                             jnp.float32(1.0), jnp.float32(1.0), none)) == 3
    topk = set(np.asarray(jax.lax.top_k(logits, 5)[1]).tolist())
    for i in range(20):
        t = sample_traced(logits, jax.random.PRNGKey(100 + i),
                          jnp.float32(0.8), jnp.int32(5), jnp.float32(1.0),
                          jnp.float32(1.0), recent)
        assert int(t) in topk


def test_sample_traced_topk_topp_renormalizes():
    """Combined top_k+top_p must measure top-p mass on the top-k-truncated
    RENORMALIZED distribution (sample_top_k_top_p semantics): 5 equal-top
    logits with k=5, p=0.5 keep ranks 0-2 (prev mass 0, .2, .4), never
    ranks 3-4 — under full-vocab mass all 5 would pass."""
    from cake_tpu.ops.sampling import sample_traced
    v = 64
    logits = jnp.full((v,), 1.9).at[:5].set(2.0)   # spread the tail mass
    none = jnp.full((4,), -1, jnp.int32)
    seen = set()
    for i in range(60):
        t = sample_traced(logits, jax.random.PRNGKey(i), jnp.float32(1.0),
                          jnp.int32(5), jnp.float32(0.5), jnp.float32(1.0),
                          none)
        seen.add(int(t))
    assert seen <= {0, 1, 2}, seen
    assert len(seen) > 1                           # actually stochastic


def test_maybe_engine_gating(monkeypatch):
    """Only plain TextModels get an engine; CAKE_SERVE_SLOTS=0 disables."""
    class NotATextModel:
        pass
    assert maybe_engine(NotATextModel()) is None
    monkeypatch.setenv("CAKE_SERVE_SLOTS", "0")
    # a real TextModel with slots=0 must also be None — checked via the
    # env without building a model (slots resolves before the isinstance
    # fails), so construct the cheapest possible one
    m = _model()
    assert maybe_engine(m) is None
    monkeypatch.setenv("CAKE_SERVE_SLOTS", "2")
    eng = maybe_engine(m, ctx_len=64)
    try:
        assert eng is not None and eng.slots == 2 and eng.ctx == 64
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# e2e: tiny CPU model
# ---------------------------------------------------------------------------

CTX = 256


class TinyTok:
    """Deterministic toy tokenizer: per-token decode concatenates exactly
    like whole-sequence decode, so streamed and blocking text agree."""

    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:24] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = TextModel(tiny_config("llama"), dtype=jnp.float32,
                           max_cache_len=CTX)
        _MODEL.tokenizer = TinyTok()
    return _MODEL


@pytest.fixture(scope="module")
def model():
    return _model()


@pytest.fixture(scope="module")
def engine(model):
    eng = ServeEngine(model, slots=4, max_queue=8, ctx_len=CTX)
    yield eng
    eng.close()


def _ref(model, prompt, n, sampling=GREEDY):
    toks, _ = model.generate(list(prompt), max_new_tokens=n,
                             sampling=sampling)
    return toks


P_LONG = [3, 17, 42, 99, 7]
P_A = [8, 8, 1, 30]
P_B = [100, 2, 5, 9, 11, 40]


def test_engine_greedy_matches_sequential(model, engine):
    """3 concurrent greedy requests each reproduce the sequential path
    bit-for-bit (masked pool slots contribute exactly-zero attention)."""
    reqs = [engine.submit(p, max_new_tokens=n, sampling=GREEDY)
            for p, n in ((P_LONG, 12), (P_A, 6), (P_B, 9))]
    for r, (p, n) in zip(reqs, ((P_LONG, 12), (P_A, 6), (P_B, 9))):
        assert r.wait(120)
        assert r.result["tokens"] == _ref(model, p, n)
        assert r.result["stats"]["ttft_s"] > 0


def test_engine_repeat_penalty_parity(model, engine):
    """Traced per-slot repeat penalty matches the static sequential path
    (same recent-token window seeding: generated tokens only)."""
    scfg = SamplingConfig(temperature=0.0, repeat_penalty=1.3)
    r = engine.submit(P_LONG, max_new_tokens=10, sampling=scfg)
    assert r.wait(120)
    assert r.result["tokens"] == _ref(model, P_LONG, 10, scfg)


def test_engine_interleaves_short_past_long(model, engine):
    """Iteration-level scheduling: two short requests admitted after a
    long one finish while it is still decoding — impossible on the
    serialized locked path."""
    long_ref = _ref(model, P_LONG, 48)
    assert len(long_ref) >= 24            # precondition: no early EOS
    r_long = engine.submit(P_LONG, max_new_tokens=48, sampling=GREEDY)
    while not r_long.tokens:              # admitted and decoding
        time.sleep(0.005)
    r_a = engine.submit(P_A, max_new_tokens=4, sampling=GREEDY)
    r_b = engine.submit(P_B, max_new_tokens=4, sampling=GREEDY)
    assert r_a.wait(60) and r_b.wait(60)
    assert not r_long.done.is_set(), \
        "short requests should complete while the long one still decodes"
    assert r_long.wait(120)
    assert r_long.result["tokens"] == long_ref


def test_engine_concurrent_overlap(model, engine):
    """All 4 concurrent requests decode SIMULTANEOUSLY under iteration-
    level batching: a moment exists where every request has emitted >= 1
    token and none has finished. This is the scheduling property the old
    wall-clock-ratio assert (t_four / t_single < 2) inferred from
    timing — which flaked under CI machine load while passing standalone.
    Occupancy is load-immune: contention slows the scheduler and the
    poller together, and the overlap window only WIDENS (admissions
    stagger by ~1 iteration, completions sit ~36 iterations later)."""
    ref = _ref(model, P_LONG, 36)
    assert len(ref) == 36                 # precondition: no early EOS
    reqs = [engine.submit(P_LONG, max_new_tokens=36, sampling=GREEDY)
            for _ in range(4)]
    overlap = False
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        counts = [len(r.tokens) for r in reqs]
        done = [r.done.is_set() for r in reqs]
        if all(done):
            break
        if all(c > 0 for c in counts) and not any(done):
            overlap = True
            break
        time.sleep(0.002)
    assert overlap, \
        "4 concurrent requests never decoded simultaneously"
    for r in reqs:
        assert r.wait(300)
        assert r.result["tokens"] == ref  # batching never costs parity


def test_engine_cancel_frees_slot(model, engine):
    """Client disconnect mid-stream reclaims the slot: slots_busy returns
    to 0 and the generation stops well short of its budget."""
    from cake_tpu.obs import SERVE_SLOTS_BUSY
    r = engine.submit(P_LONG, max_new_tokens=180, sampling=GREEDY)
    while len(r.tokens) < 3:
        time.sleep(0.005)
    assert SERVE_SLOTS_BUSY.value() >= 1
    r.cancel()
    deadline = time.monotonic() + 10
    while SERVE_SLOTS_BUSY.value() != 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert SERVE_SLOTS_BUSY.value() == 0
    assert r.done.is_set()
    assert len(r.tokens) < 170            # budget was NOT decoded out


def test_engine_backpressure_queue_full(model):
    """slots=1 + max_queue=1: one decoding, one queued, the third submit
    raises QueueFull with a retry hint."""
    eng = ServeEngine(model, slots=1, max_queue=1, ctx_len=CTX)
    try:
        r_busy = eng.submit(P_LONG, max_new_tokens=180, sampling=GREEDY)
        while not r_busy.tokens:
            time.sleep(0.005)
        r_queued = eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
        with pytest.raises(QueueFull) as ei:
            eng.submit(P_B, max_new_tokens=4, sampling=GREEDY)
        assert ei.value.retry_after_s >= 1
        r_busy.cancel()
        assert r_queued.wait(120)         # queued one still served
        assert r_queued.result["tokens"] == _ref(model, P_A, 4)
    finally:
        eng.close()


def test_engine_burst_fills_idle_slots_without_429(model):
    """A burst of slots+queue submissions against an IDLE pool is fully
    admitted: the bound counts requests waiting beyond free slots, so
    arrivals outpacing the one-admission-per-iteration drain don't shed
    load while capacity sits idle (found by driving the live server)."""
    eng = ServeEngine(model, slots=4, max_queue=1, ctx_len=CTX)
    try:
        rs = [eng.submit(P_A, max_new_tokens=6, sampling=GREEDY)
              for _ in range(5)]               # 4 slots + 1 queued: all in
        assert all(r.wait(120) for r in rs)
        ref = _ref(model, P_A, 6)
        assert all(r.result["tokens"] == ref for r in rs)
    finally:
        eng.close()


def test_engine_cancelled_queued_purged(model):
    """A request abandoned while QUEUED stops pinning queue capacity at
    the next iteration — live clients are not 429ed behind ghosts."""
    eng = ServeEngine(model, slots=1, max_queue=1, ctx_len=CTX)
    try:
        r_busy = eng.submit(P_LONG, max_new_tokens=180, sampling=GREEDY)
        while not r_busy.tokens:
            time.sleep(0.005)
        r_ghost = eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
        r_ghost.cancel()                  # client vanished while waiting
        deadline = time.monotonic() + 10
        while eng.queue.depth() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.queue.depth() == 0
        assert r_ghost.done.is_set()
        # capacity is back: a live client gets in instead of a 429
        r_live = eng.submit(P_B, max_new_tokens=4, sampling=GREEDY)
        r_busy.cancel()
        assert r_live.wait(120)
        assert r_live.result["tokens"] == _ref(model, P_B, 4)
    finally:
        eng.close()


def test_engine_rejects_oversize_prompt(model, engine):
    with pytest.raises(ValueError):
        engine.submit(list(range(CTX)), max_new_tokens=4, sampling=GREEDY)


# ---------------------------------------------------------------------------
# chunked prefill (ISSUE 3 tentpole)
# ---------------------------------------------------------------------------

P_CHUNKY = [3 + (i * 7) % 200 for i in range(50)]


def test_prefill_chunk_matches_monolithic_logits(model):
    """A prompt prefilled chunk-by-chunk straight into a pool row matches
    the monolithic bucketed prefill's last-position logits to within one
    ulp (chunk matmuls have a different width, so the last bit can round
    differently; the greedy ARGMAX — what decode consumes — is pinned
    exact, and the engine-level test below pins the full token stream),
    and touches no other row."""
    from cake_tpu.models.common.text_model import bucket_for
    n, chunk = len(P_CHUNKY), 16
    c1 = model.new_cache(1, kv_len=bucket_for(n, CTX))
    ref_logits, _ = model.prefill(c1, P_CHUNKY)
    layers = model.new_cache(3, kv_len=64)["layers"]
    for s in range(0, n, chunk):
        logits, layers = model.prefill_chunk(
            layers, 1, P_CHUNKY[s:s + chunk], s)
    a, b = np.asarray(logits), np.asarray(ref_logits)
    np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)
    assert a.argmax() == b.argmax()
    # a chunk whose bucket equals the monolithic bucket IS bit-identical
    layers1 = model.new_cache(3, kv_len=64)["layers"]
    one_shot, layers1 = model.prefill_chunk(layers1, 1, P_CHUNKY, 0)
    np.testing.assert_array_equal(np.asarray(one_shot), b)
    for lc in layers:
        np.testing.assert_array_equal(np.asarray(lc["pos"][1, :n]),
                                      np.arange(n))
        assert int(jnp.max(lc["pos"][1, n:])) == -1
        assert float(jnp.abs(lc["k"][0]).max()) == 0.0   # neighbors clean
        assert float(jnp.abs(lc["k"][2]).max()) == 0.0


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_engine_chunked_long_prompt_parity(model):
    """Greedy output with a multi-chunk admission is bit-identical to the
    sequential (monolithic-prefill) path — the tentpole acceptance pin on
    the MISS side."""
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=0)
    try:
        r = eng.submit(P_CHUNKY, max_new_tokens=10, sampling=GREEDY)
        assert r.wait(120)
        assert r.result["tokens"] == _ref(model, P_CHUNKY, 10)
        assert r.stats["prefill_chunks"] == 4            # ceil(50 / 16)
        assert r.stats["prefix_hit_tokens"] == 0
    finally:
        eng.close()


def test_engine_decode_not_stalled_by_long_admission(model):
    """The head-of-line-blocking kill: while a LONG prompt is admitted
    chunk-by-chunk, an already-active request keeps emitting tokens — one
    decode step per chunk iteration — instead of stalling for the whole
    prefill as the monolithic path did. Pinned on token ORDER (tokens
    gained before the long request's first token), not wall time."""
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=0)
    try:
        r_short = eng.submit(P_A, max_new_tokens=200, sampling=GREEDY)
        while len(r_short.tokens) < 3:          # active and decoding
            time.sleep(0.005)
        long_prompt = [3 + (i * 13) % 200 for i in range(120)]  # 8 chunks
        gained_at_submit = len(r_short.tokens)
        r_long = eng.submit(long_prompt, max_new_tokens=6, sampling=GREEDY)
        deadline = time.monotonic() + 60
        while not r_long.tokens and time.monotonic() < deadline:
            time.sleep(0.002)
        assert r_long.tokens, r_long.result.get("error")
        gained = len(r_short.tokens) - gained_at_submit
        assert gained >= 4, \
            f"short request gained only {gained} tokens across an 8-chunk " \
            "admission — decode stalled behind the prefill"
        r_short.cancel()
        assert r_long.wait(120)
        assert r_long.result["tokens"] == _ref(model, long_prompt, 6)
    finally:
        eng.close()


def test_engine_round_robin_concurrent_admissions(model):
    """Admission fairness: two long prompts prefill CONCURRENTLY (both in
    flight at once, chunks round-robined) instead of the second waiting
    for the first's entire prefill; both reproduce the sequential path."""
    p1 = [3 + (i * 5) % 200 for i in range(100)]    # 7 chunks each
    p2 = [3 + (i * 9) % 200 for i in range(100)]
    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                      prefill_chunk=16, prefix_cache_mb=0)
    try:
        r1 = eng.submit(p1, max_new_tokens=5, sampling=GREEDY)
        r2 = eng.submit(p2, max_new_tokens=5, sampling=GREEDY)
        saw_both = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if eng.health()["prefilling"] == 2:
                saw_both = True
                break
            if r1.done.is_set() and r2.done.is_set():
                break
            time.sleep(0.001)
        assert saw_both, "second admission waited out the first's prefill"
        assert r1.wait(120) and r2.wait(120)
        assert r1.result["tokens"] == _ref(model, p1, 5)
        assert r2.result["tokens"] == _ref(model, p2, 5)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# e2e through the aiohttp API
# ---------------------------------------------------------------------------


def _api_state(model, engine):
    from cake_tpu.api import ApiState
    st = ApiState(model=model, tokenizer=model.tokenizer,
                  model_id="tiny-serve")
    st.engine = engine
    return st


def _run(coro):
    asyncio.new_event_loop().run_until_complete(coro)


def test_api_concurrent_chat_parity(model, engine):
    """3 concurrent API chats through the engine: all 200, greedy text
    identical to the sequential reference, shorts finish before the long
    one (wall-clock interleaving at the HTTP layer)."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app
    from cake_tpu.models.common.text_model import chat_prompt_ids

    msgs = [[{"role": "user", "content": f"hello world {i}"}]
            for i in range(3)]
    # wide long-vs-short margin (~76 decode iterations): the assertion
    # below compares HTTP completion ORDER, and on a loaded single-core
    # box the event loop can lag the engine by ~100ms of GIL starvation
    budgets = [80, 4, 4]
    refs = []
    for mm, n in zip(msgs, budgets):
        ids = chat_prompt_ids(model.tokenizer, mm)
        toks = _ref(model, ids, n)
        ended = model.cfg.is_eos(toks[-1])
        refs.append(model.tokenizer.decode(toks[:-1] if ended else toks))

    done_at = {}

    async def scenario():
        app = create_app(_api_state(model, engine))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            async def one(i):
                r = await client.post("/v1/chat/completions", json={
                    "messages": msgs[i], "max_tokens": budgets[i],
                    "temperature": 0.0})
                assert r.status == 200, await r.text()
                done_at[i] = time.monotonic()
                return await r.json()
            # long request first so it is admitted before the shorts
            t_long = asyncio.ensure_future(one(0))
            await asyncio.sleep(0.05)
            d1, d2 = await asyncio.gather(one(1), one(2))
            d0 = await t_long
            for i, d in enumerate((d0, d1, d2)):
                assert d["choices"][0]["message"]["content"] == refs[i], i
                assert d["usage"]["completion_tokens"] >= 1
            assert done_at[1] < done_at[0] and done_at[2] < done_at[0], \
                "short chats must complete while the long one decodes"
        finally:
            await client.close()
    _run(scenario())


def test_api_stream_engine_path(model, engine):
    """SSE through the engine: chunked content equals the blocking text,
    stream terminates with finish_reason + [DONE]."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app
    from cake_tpu.models.common.text_model import chat_prompt_ids

    msg = [{"role": "user", "content": "stream me"}]
    ids = chat_prompt_ids(model.tokenizer, msg)
    toks = _ref(model, ids, 8)
    ended = model.cfg.is_eos(toks[-1])
    want = model.tokenizer.decode(toks[:-1] if ended else toks)

    async def scenario():
        app = create_app(_api_state(model, engine))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/chat/completions", json={
                "messages": msg, "max_tokens": 8, "temperature": 0.0,
                "stream": True})
            assert r.status == 200
            body = (await r.read()).decode()
            chunks = [json.loads(line[6:]) for line in body.split("\n\n")
                      if line.startswith("data: ") and line != "data: [DONE]"]
            text = "".join(c["choices"][0]["delta"].get("content", "")
                           for c in chunks)
            assert text == want
            assert chunks[-1]["choices"][0]["finish_reason"] in ("stop",
                                                                 "length")
            assert body.strip().endswith("data: [DONE]")
        finally:
            await client.close()
    _run(scenario())


def test_api_backpressure_429(model):
    """Queue saturation answers 429 + Retry-After instead of waiting."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app

    eng = ServeEngine(model, slots=1, max_queue=1, ctx_len=CTX)
    try:
        r_busy = eng.submit(P_LONG, max_new_tokens=180, sampling=GREEDY)
        while not r_busy.tokens:
            time.sleep(0.005)
        r_queued = eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)

        async def scenario():
            app = create_app(_api_state(model, eng))
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.post("/v1/chat/completions", json={
                    "messages": [{"role": "user", "content": "x"}]})
                assert r.status == 429
                assert int(r.headers["Retry-After"]) >= 1
                assert "overloaded" in (await r.json())["error"]
            finally:
                await client.close()
        _run(scenario())
        r_busy.cancel()
        assert r_queued.wait(120)
    finally:
        eng.close()


def test_api_disconnect_mid_stream_frees_slot(model, engine):
    """Closing the SSE connection mid-generation cancels the request and
    the engine's busy gauge returns to 0 (the acceptance assertion)."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app
    from cake_tpu.obs import SERVE_SLOTS_BUSY

    async def scenario():
        app = create_app(_api_state(model, engine))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "disconnect"}],
                "max_tokens": 200, "temperature": 0.0, "stream": True})
            assert r.status == 200
            await r.content.read(64)          # a few chunks, then vanish
            deadline = time.monotonic() + 10  # poll past the admission race
            while SERVE_SLOTS_BUSY.value() < 1 and time.monotonic() < deadline:
                await asyncio.sleep(0.005)
            assert SERVE_SLOTS_BUSY.value() >= 1
            r.close()                          # client disconnect
        finally:
            await client.close()
        deadline = time.monotonic() + 15
        while SERVE_SLOTS_BUSY.value() != 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert SERVE_SLOTS_BUSY.value() == 0
    _run(scenario())


def test_api_health_and_metrics_engine(model, engine):
    """/health exposes engine liveness; /metrics carries the serve series
    after traffic."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app

    async def scenario():
        app = create_app(_api_state(model, engine))
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            r = await client.get("/health")
            assert r.status == 200
            h = await r.json()
            assert h["engine"]["alive"] is True
            assert h["engine"]["slots"] == 4
            assert h["engine"]["last_step_age_s"] < 30
            r = await client.get("/metrics")
            text = await r.text()
            assert "cake_serve_slots_busy" in text
            assert "cake_serve_queue_wait_seconds_count" in text
            assert "cake_serve_batch_occupancy_count" in text
        finally:
            await client.close()
    _run(scenario())


def test_stream_leak_fix_cancel_event():
    """Legacy locked path: abandoning the stream iterator stops the
    generation worker (no executor thread parked on q.get forever, no
    decode-to-budget after disconnect)."""
    from cake_tpu.api.state import run_generation_streamed
    from cake_tpu.models.common.text_model import Token

    produced = []
    release = threading.Event()

    class SlowModel:
        def chat_generate(self, messages, on_token=None, **kw):
            for i in range(500):
                release.wait(0.002)
                on_token(Token(id=i, text=f"t{i}", is_end_of_stream=False))
                produced.append(i)
            return list(range(500)), {}

    async def scenario():
        aiter, result, cancel = run_generation_streamed(
            SlowModel(), [{"role": "user", "content": "x"}], {})
        seen = 0
        async for tok in aiter:
            seen += 1
            if seen >= 3:
                break                     # client walks away mid-stream
        await aiter.aclose()              # finalizer must cancel the worker
        assert cancel.is_set()
        return seen
    asyncio.new_event_loop().run_until_complete(scenario())
    n_at_close = len(produced)
    time.sleep(0.3)
    assert len(produced) <= n_at_close + 2, "worker kept generating"
    assert len(produced) < 500


# ---------------------------------------------------------------------------
# graceful drain + per-request queue deadline (fault-tolerance satellites)
# ---------------------------------------------------------------------------


def test_engine_queue_deadline_expires_waiters(model):
    """slots=1: a request stuck in the admission queue past
    CAKE_QUEUE_DEADLINE_S is failed with QueueDeadlineExceeded (503 at
    the API layer) instead of eventually occupying a slot for a client
    that already gave up; the busy request is unaffected and the timeout
    counter ticks."""
    from cake_tpu.obs import SERVE_QUEUE_TIMEOUTS
    from cake_tpu.serve import QueueDeadlineExceeded

    eng = ServeEngine(model, slots=1, max_queue=4, ctx_len=CTX,
                      queue_deadline_s=5.0)
    try:
        before = SERVE_QUEUE_TIMEOUTS.value()
        r_busy = eng.submit(P_LONG, max_new_tokens=180, sampling=GREEDY)
        while not r_busy.tokens:
            time.sleep(0.005)
        r_queued = eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
        # backdate the enqueue stamp rather than really sleeping out the
        # deadline: deterministic regardless of how fast the busy slot
        # decodes (the sweep must expire it at the next iteration)
        r_queued.t_enqueue -= 60.0
        assert r_queued.wait(30), "expired request never finished"
        err = r_queued.result.get("error")
        assert isinstance(err, QueueDeadlineExceeded), err
        assert err.waited_s >= 5.0
        assert SERVE_QUEUE_TIMEOUTS.value() == before + 1
        # the slot owner decodes on unharmed
        r_busy.cancel()
        assert r_busy.wait(120)
    finally:
        eng.close()


def test_engine_drain_stops_admission_and_finishes_active(model):
    """drain(): new submits are shed with EngineDraining while the active
    request runs to its normal completion; drain returns True once idle
    and health() reports draining."""
    from cake_tpu.serve import EngineDraining

    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX)
    try:
        r = eng.submit(P_A, max_new_tokens=6, sampling=GREEDY)
        while not r.tokens:
            time.sleep(0.005)
        done = {}

        def do_drain():
            done["clean"] = eng.drain(timeout=120)
        t = threading.Thread(target=do_drain, daemon=True)
        t.start()
        while not eng.health()["draining"]:
            time.sleep(0.005)
        with pytest.raises(EngineDraining) as ei:
            eng.submit(P_B, max_new_tokens=4, sampling=GREEDY)
        assert ei.value.retry_after_s >= 1
        t.join(timeout=120)
        assert done.get("clean") is True
        assert r.wait(10)           # drain observes idle a hair before the
                                    # finisher fires done — wait, don't poll
        assert r.result["tokens"] == _ref(model, P_A, 6)  # finished, not cut
    finally:
        eng.close()


def test_api_graceful_drain_on_shutdown(model):
    """The serve() entry registers graceful_drain on_shutdown: while
    draining, chat requests answer 503 + Retry-After; at shutdown the
    active work finishes and the engine is closed — Ctrl-C mid-decode no
    longer abandons in-flight requests without final chunks."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app
    from cake_tpu.api.server import graceful_drain

    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX)
    state = _api_state(model, eng)
    app = create_app(state)
    app.on_shutdown.append(graceful_drain)   # what serve() wires up

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi there"}],
            "max_tokens": 4, "temperature": 0.0})
        assert r.status == 200

        # draining: requests on kept-alive connections are shed
        state.draining = True
        r2 = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "late"}],
            "max_tokens": 4, "temperature": 0.0})
        assert r2.status == 503
        assert int(r2.headers.get("Retry-After", "0")) >= 1
        state.draining = False

        await client.close()                 # shutdown -> graceful_drain
    _run(scenario())

    assert state.draining is True            # drain ran at shutdown
    assert not eng._thread.is_alive()        # engine closed cleanly
    with pytest.raises(RuntimeError):
        eng.submit(P_A, max_new_tokens=2, sampling=GREEDY)


def test_graceful_drain_flips_health_before_engine_drains(model):
    """graceful_drain flips the engine's draining flag SYNCHRONOUSLY —
    /health's engine block says draining while in-flight work is still
    finishing, so a fleet router probing it stops routing here before
    the first request bounces (ISSUE 12 satellite: the router could not
    previously distinguish draining from healthy until 503s flew)."""
    from cake_tpu.api import create_app
    from cake_tpu.api.server import graceful_drain
    from cake_tpu.serve import EngineDraining, faults

    eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX)
    state = _api_state(model, eng)
    app = create_app(state)

    async def scenario():
        # keep the engine busy so the drain cannot finish instantly —
        # the assertion below must observe draining=True mid-drain
        faults.install("delay_ms=20")
        busy = eng.submit(P_LONG, max_new_tokens=60, sampling=GREEDY)
        while not busy.tokens:
            await asyncio.sleep(0.005)
        drain_task = asyncio.ensure_future(graceful_drain(app))
        try:
            deadline = time.monotonic() + 5
            while not eng.health()["draining"]:
                assert time.monotonic() < deadline, \
                    "engine block never reported draining"
                await asyncio.sleep(0.002)
            assert not drain_task.done()      # flag flipped mid-drain
            assert eng.pool.busy_count        # work still in flight
            # new submits are refused with a DERIVED Retry-After hint
            with pytest.raises(EngineDraining) as ei:
                eng.submit(P_A, max_new_tokens=2, sampling=GREEDY)
            assert ei.value.retry_after_s >= 1
        finally:
            faults.clear()
            busy.cancel()
            await drain_task
    _run(scenario())
    eng.close()


def test_retry_after_hint_scales_with_backlog(model):
    """Derived Retry-After (ISSUE 12 satellite): idle engine invites a
    near-immediate retry; a deep queue pushes clients out
    proportionally."""
    eng = ServeEngine(model, slots=2, max_queue=64, ctx_len=CTX)
    try:
        assert eng.retry_after_hint() == 1           # idle
        from cake_tpu.serve import faults
        faults.install("delay_ms=50")
        try:
            reqs = [eng.submit(P_A, max_new_tokens=4, sampling=GREEDY)
                    for _ in range(20)]
            deep = eng.retry_after_hint()
            assert deep > 1                           # backlog-derived
            assert deep <= 30                         # capped
            for r in reqs:
                r.cancel()
        finally:
            faults.clear()
    finally:
        eng.close()


def test_api_stream_queue_deadline_503(model):
    """A stream:true request shed by the queue deadline answers 503 +
    Retry-After BEFORE any SSE commits to a 200 — the same contract as
    the blocking path, so balancers see the shed-load signal."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import create_app
    from cake_tpu.serve import faults

    eng = ServeEngine(model, slots=1, max_queue=4, ctx_len=CTX,
                      queue_deadline_s=0.1)
    state = _api_state(model, eng)

    async def scenario():
        app = create_app(state)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # occupy the single slot with a long decode. delay_ms paces
            # it deterministically: the ctx cap bounds the busy request
            # at ~122 decode steps, which a WARM executable finishes in
            # under the 0.1s deadline — the queued request then got
            # ADMITTED instead of shed (the in-suite flake this pacing
            # fixes); at 5 ms/iteration the slot is held for >0.5s no
            # matter how warm the cache is
            r_busy = eng.submit(P_LONG, max_new_tokens=180, sampling=GREEDY)
            while not r_busy.tokens:
                await asyncio.sleep(0.005)
            faults.install("delay_ms=5")
            # ...then a streaming request that must expire while queued
            resp = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "will expire"}],
                "max_tokens": 4, "temperature": 0.0, "stream": True})
            assert resp.status == 503, await resp.text()
            assert int(resp.headers.get("Retry-After", "0")) >= 1
            r_busy.cancel()
        finally:
            faults.clear()
            await client.close()
    _run(scenario())
    eng.close()


def test_engine_continuation_splice_bit_identical(model, engine):
    """The mid-stream resume contract at the engine level: prefilling
    prompt + the first k generated tokens (a continuation splice) and
    decoding the remainder reproduces the unbroken greedy run
    bit-for-bit — and the continuation flag rides the stats."""
    full = engine.submit(P_LONG, max_new_tokens=10, sampling=GREEDY)
    assert full.wait(120)
    toks = full.result["tokens"]
    assert toks == _ref(model, P_LONG, 10)
    k = 4
    resumed = engine.submit(P_LONG + toks[:k], max_new_tokens=10 - k,
                            sampling=GREEDY, continuation=True)
    assert resumed.wait(120)
    assert resumed.result["tokens"] == toks[k:]
    assert resumed.result["stats"].get("continuation") is True
