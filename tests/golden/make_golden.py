"""Generate the committed golden-logit fixtures (run on CPU, f32).

    python tests/golden/make_golden.py

Records, for each family: prefill logits, per-step incremental decode
logits, a chunked-prefill logit row, and a greedy token sequence — from
seeded random weights. test_golden.py asserts the current implementation
reproduces these within atol 1e-3 (the BASELINE.json north-star bar), so
any silent numerics change in norms/rope/attention/cache/sampling shows
up as a diff against a committed artifact rather than passing self-
consistency tests.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cake_tpu.models import TextModel, tiny_config  # noqa: E402
from cake_tpu.ops.sampling import SamplingConfig  # noqa: E402

FAMILIES = ("llama", "qwen2", "qwen3", "qwen3_moe", "phi4", "mistral",
            "gemma3", "falcon3", "olmo2", "exaone4", "qwen3_5")
SEED = 7
PROMPT = [11, 23, 5, 190, 77, 3, 149, 66, 20]


import contextlib  # noqa: E402


@contextlib.contextmanager
def fixture_prng():
    """Pin the PRNG implementation the committed fixtures were generated
    under. The fixtures come from SEEDED RANDOM WEIGHTS, and jax's
    threefry stream for a given key differs between partitionable (the
    default on newer jax) and non-partitionable (the default on the jax
    this container ships) — without the pin every golden comparison fails
    with ~0.5-magnitude diffs that look like a numerics regression but
    are simply different weights. The flag only affects random-bit
    generation, never matmul/attention numerics, so pinning it keeps the
    regression test honest."""
    old = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        yield
    finally:
        jax.config.update("jax_threefry_partitionable", old)


def build(fam: str) -> dict[str, np.ndarray]:
    with fixture_prng():
        return _build(fam)


def _build(fam: str) -> dict[str, np.ndarray]:
    cfg = tiny_config(fam, eos_token_id=255)
    model = TextModel(cfg, dtype=jnp.float32, seed=SEED, max_cache_len=64)
    out: dict[str, np.ndarray] = {}

    logits, cache = model.prefill(model.new_cache(), PROMPT)
    out["prefill_logits"] = np.asarray(logits[0], np.float32)

    dec = []
    tid = int(np.argmax(out["prefill_logits"]))
    for _ in range(5):
        logits, cache = model.decode_logits(cache, tid)
        dec.append(np.asarray(logits[0], np.float32))
        tid = int(np.argmax(dec[-1]))
    out["decode_logits"] = np.stack(dec)

    # chunked prefill across a bucket boundary (5 then 4 tokens)
    cache2 = model.new_cache()
    _, cache2 = model.prefill(cache2, PROMPT[:5])
    logits2, _ = model.prefill(cache2, PROMPT[5:], pos0=5)
    out["chunked_prefill_logits"] = np.asarray(logits2[0], np.float32)

    toks, _ = model.generate(PROMPT, max_new_tokens=16,
                             sampling=SamplingConfig(temperature=0.0),
                             chunk=8)
    out["greedy_tokens"] = np.asarray(toks, np.int64)
    return out


def main():
    # CPU forcing only when run as a script — importing this module from
    # the test suite must not re-platform the whole pytest process
    jax.config.update("jax_platforms", "cpu")
    here = os.path.dirname(os.path.abspath(__file__))
    for fam in FAMILIES:
        arrs = build(fam)
        path = os.path.join(here, f"{fam}.npz")
        np.savez_compressed(path, **arrs)
        print(f"{fam}: greedy={arrs['greedy_tokens'][:6]}... -> {path}")


if __name__ == "__main__":
    main()
