"""Unit tests for the observability subsystem: metrics registry semantics,
Prometheus text exposition, span recorder / Chrome-trace export, request-id
propagation, PhaseTimer, and the cluster client's RTT phase splits."""
import json
import threading

import pytest

from cake_tpu.obs import (PhaseTimer, REGISTRY, MetricsRegistry,
                          SpanRecorder, current_request_id, request_scope)
from cake_tpu.obs.metrics import _fmt


# -- metrics ----------------------------------------------------------------

def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "help", labelnames=("k",))
    c.inc(k="a")
    c.inc(2, k="a")
    c.inc(k="b")
    assert c.value(k="a") == 3
    assert c.value(k="b") == 1
    assert c.value(k="missing") == 0
    with pytest.raises(ValueError):
        c.inc(-1, k="a")                 # counters only go up
    with pytest.raises(ValueError):
        c.inc(wrong="a")                 # undeclared label


def test_gauge_semantics():
    reg = MetricsRegistry()
    g = reg.gauge("g")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4


def test_histogram_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count() == 3
    assert h.sum() == pytest.approx(5.55)
    text = reg.render()
    # cumulative buckets: 1 <= 0.1, 2 <= 1.0, 3 <= +Inf
    assert 'h_seconds_bucket{le="0.1"} 1' in text
    assert 'h_seconds_bucket{le="1"} 2' in text
    assert 'h_seconds_bucket{le="+Inf"} 3' in text
    assert "h_seconds_count 3" in text


def test_registry_idempotent_and_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("x_total", labelnames=("k",))
    assert reg.counter("x_total", labelnames=("k",)) is a
    with pytest.raises(ValueError):
        reg.gauge("x_total")             # type conflict
    with pytest.raises(ValueError):
        reg.counter("x_total")           # label conflict


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    c = reg.counter("req_total", 'count of "requests"', labelnames=("p",))
    c.inc(p='va"l\\ue')
    text = reg.render()
    lines = text.splitlines()
    assert '# HELP req_total count of \\"requests\\"' in lines
    assert "# TYPE req_total counter" in lines
    assert 'req_total{p="va\\"l\\\\ue"} 1' in lines
    assert text.endswith("\n")
    # integers render without a trailing .0; floats keep precision
    assert _fmt(3.0) == "3"
    assert _fmt(0.25) == "0.25"


def test_registry_reset_keeps_registrations():
    reg = MetricsRegistry()
    c = reg.counter("y_total")
    c.inc()
    reg.reset()
    assert c.value() == 0
    c.inc()                              # same handle still live
    assert c.value() == 1


def test_global_registry_has_canonical_series():
    text = REGISTRY.render()
    for name in ("cake_ttft_seconds", "cake_decode_token_seconds",
                 "cake_api_requests_total", "cake_cluster_hop_seconds"):
        assert f"# TYPE {name}" in text


# -- spans ------------------------------------------------------------------

def test_span_recorder_chrome_trace_roundtrip():
    rec = SpanRecorder(enabled=True)
    with rec.span("prefill", cat="gen", tokens=4):
        with rec.span("embed"):
            pass
    rec.instant("mark")
    blob = json.dumps(rec.to_chrome_trace())
    data = json.loads(blob)              # must round-trip
    evs = data["traceEvents"]
    assert len(evs) == 3
    x_events = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in x_events} == {"prefill", "embed"}
    for e in x_events:
        assert e["dur"] >= 0 and isinstance(e["ts"], int)
    # child span completes (and is appended) before its parent
    embed, prefill = x_events[0], x_events[1]
    assert embed["name"] == "embed"
    assert prefill["ts"] <= embed["ts"]
    assert prefill["ts"] + prefill["dur"] >= embed["ts"] + embed["dur"]


def test_span_recorder_monotonic_ts_and_bound():
    rec = SpanRecorder(max_events=8, enabled=True)
    for i in range(20):
        with rec.span(f"s{i}"):
            pass
    evs = rec.events()
    assert len(evs) == 8                 # ring buffer bound
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)              # monotonic clock


def test_span_recorder_disabled_records_nothing():
    rec = SpanRecorder(enabled=False)
    with rec.span("x"):
        pass
    rec.add("y", 0, 1)
    assert len(rec) == 0


def test_span_export_writes_loadable_json(tmp_path):
    rec = SpanRecorder(enabled=True)
    with rec.span("a"):
        pass
    path = rec.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        data = json.load(f)
    assert data["traceEvents"][0]["name"] == "a"


def test_request_id_propagation_into_threads():
    rec = SpanRecorder(enabled=True)
    seen = {}

    with request_scope() as rid:
        assert current_request_id() == rid
        with rec.span("in_scope"):
            pass

        import contextvars
        ctx = contextvars.copy_context()

        def worker():
            seen["rid"] = ctx.run(current_request_id)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["rid"] == rid
    assert current_request_id() is None   # scope restored
    assert rec.events()[0]["args"]["request_id"] == rid


# -- PhaseTimer -------------------------------------------------------------

def test_phase_timer_accumulates_and_emits_spans():
    rec = SpanRecorder(enabled=True)
    t = PhaseTimer(recorder=rec)
    for _ in range(3):
        with t("fwd"):
            pass
    t.add("read", 0.5)
    rep = t.report()
    assert rep["fwd"]["count"] == 3
    assert rep["read"]["total_ms"] == 500.0
    assert "fwd=" in str(t) and "read=" in str(t)
    # every accumulated phase also landed in the recorder
    names = [e["name"] for e in rec.events()]
    assert names.count("fwd") == 3 and names.count("read") == 1
    t.reset()
    assert t.report() == {}


# -- cluster client phase splits --------------------------------------------

def test_rtt_stats_phase_splits():
    from cake_tpu.cluster.client import RemoteStage
    rs = RemoteStage("127.0.0.1", 0, "k", name="w0")   # no connect
    for _ in range(10):
        rs.rtts.append((0.010, {"read_ms": 1.0, "deser_ms": 1.0,
                                "fwd_ms": 4.0, "ser_ms": 1.0}))
    st = rs.rtt_stats()
    assert st["count"] == 10
    assert st["p50_ms"] == 10.0
    assert st["fwd_p50_ms"] == 4.0
    assert st["read_p50_ms"] == 1.0
    assert st["ser_p50_ms"] == 1.0
    # wire = rtt - (read + deser + fwd + ser) = 10 - 7 = 3 ms
    assert st["wire_p50_ms"] == pytest.approx(3.0)


def test_rtt_stats_pre_echo_workers():
    """A worker that only sends top-level fwd_ms (no tm dict) still splits
    fwd/wire; one that sends nothing contributes to the raw RTT only."""
    from cake_tpu.cluster.client import RemoteStage
    rs = RemoteStage("127.0.0.1", 0, "k", name="w0")
    rs.rtts.append((0.010, {"fwd_ms": 6.0}))
    rs.rtts.append((0.020, {}))
    st = rs.rtt_stats()
    assert st["count"] == 2
    assert st["fwd_p50_ms"] == 6.0
    assert st["wire_p50_ms"] == pytest.approx(4.0)
    assert "read_p50_ms" not in st


def test_worker_info_heartbeat_fields():
    from cake_tpu.cluster import proto
    msg = proto.worker_info("w0", [0, 1], "cpu", "cpu", 1 << 30, 1.0,
                            heartbeat_age_s=1.23456, ops=7)
    assert msg["heartbeat_age_s"] == 1.235
    assert msg["ops"] == 7
    legacy = proto.worker_info("w0", [0, 1], "cpu", "cpu", 1 << 30, 1.0)
    assert "heartbeat_age_s" not in legacy


def test_tensor_result_timing_echo():
    import numpy as np
    from cake_tpu.cluster import proto
    arr = np.ones((1, 2), np.float32)
    tm = {"read_ms": 0.5, "deser_ms": 0.25, "fwd_ms": 3.0, "ser_ms": 0.125}
    msg = proto.tensor_result(arr, 3, fwd_ms=3.0, timing=tm)
    assert msg["tm"] == tm and msg["fwd_ms"] == 3.0 and msg["rid"] == 3
    assert (proto.unpack_tensor(msg["x"]) == arr).all()
    # pre-packed tensors pass through without re-packing
    packed = proto.pack_tensor(arr)
    msg2 = proto.tensor_result(packed, 4)
    assert msg2["x"] is packed and "tm" not in msg2
