"""FLUX.1 release-checkpoint loading: synthesize a tiny on-disk bundle
with the REAL tensor names (ComfyUI layout the reference loads —
ref: flux/config.rs flux1_prefixes, flux1_model.rs name wiring), then
load it through the public path and generate an image.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.image import (load_flux_image_model, mmdit_mapping,
                                   vae_decoder_mapping)
from cake_tpu.models.image.flux import tiny_flux_config
from cake_tpu.models.image.flux_loader import (CLIP_PREFIX, T5_PREFIX,
                                               TRANSFORMER_PREFIX, VAE_PREFIX,
                                               detect_flux_checkpoint)
from cake_tpu.models.image.mmdit import init_mmdit_params
from cake_tpu.models.image.vae import init_vae_decoder_params
from cake_tpu.models.text_encoders import (clip_mapping, init_clip_params,
                                           init_t5_params, t5_mapping,
                                           tiny_clip_config, tiny_t5_config)
from cake_tpu.utils.mapping import flatten_tree
from cake_tpu.utils.safetensors_io import save_safetensors


def _word_level_tokenizer_json(path, vocab_size):
    """Minimal tokenizers-format file: whitespace word-level."""
    vocab = {f"w{i}": i for i in range(vocab_size - 2)}
    vocab["<unk>"] = vocab_size - 2
    vocab["<eot>"] = vocab_size - 1
    tok = {
        "version": "1.0", "truncation": None, "padding": None,
        "added_tokens": [], "normalizer": None,
        "pre_tokenizer": {"type": "Whitespace"},
        "post_processor": None, "decoder": None,
        "model": {"type": "WordLevel", "vocab": vocab, "unk_token": "<unk>"},
    }
    with open(path, "w") as f:
        json.dump(tok, f)


def synth_bundle(tmp_path, fp8_transformer=False, fp8_scaled=False):
    """Write a tiny ComfyUI-style FLUX bundle + tokenizers + sidecar.
    fp8_scaled adds per-tensor `.scale_weight` (Comfy scaled-fp8)."""
    pipe = tiny_flux_config()
    clip_cfg, t5_cfg = tiny_clip_config(), tiny_t5_config()
    rng = jax.random.PRNGKey(0)
    ks = jax.random.split(rng, 4)
    comp = {
        TRANSFORMER_PREFIX: (
            mmdit_mapping(pipe.mmdit),
            init_mmdit_params(pipe.mmdit, ks[0], jnp.float32)),
        VAE_PREFIX: (
            vae_decoder_mapping(pipe.vae, "") ,
            init_vae_decoder_params(pipe.vae, ks[1], jnp.float32)),
        CLIP_PREFIX + "text_model.": (
            clip_mapping(clip_cfg, ""),
            init_clip_params(clip_cfg, ks[2], jnp.float32)),
        T5_PREFIX: (
            t5_mapping(t5_cfg, ""),
            init_t5_params(t5_cfg, ks[3], jnp.float32)),
    }
    tensors = {}
    for prefix, (mapping, params) in comp.items():
        flat = flatten_tree(params)
        for path, name in mapping.items():
            arr = np.asarray(flat[path], np.float32)
            if fp8_transformer and prefix == TRANSFORMER_PREFIX \
                    and name.endswith(".weight") and arr.ndim == 2:
                if fp8_scaled:
                    # store w/2 in fp8 with scale_weight 2.0 so a dropped
                    # scale is a visible numeric error, not a no-op
                    arr2 = (arr / 2.0).astype(jnp.float8_e4m3fn)
                    tensors[prefix + name[:-len(".weight")]
                            + ".scale_weight"] = np.float32(2.0)
                    arr = arr2
                else:
                    arr = arr.astype(jnp.float8_e4m3fn)
            tensors[prefix + name] = arr
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    # non-shape-derivable dims for the tiny fixtures
    with open(tmp_path / "flux_config.json", "w") as f:
        json.dump({"clip": {"num_heads": clip_cfg.num_heads,
                            "eot_token_id": clip_cfg.eot_token_id},
                   "t5": {"relative_max_distance":
                          t5_cfg.relative_max_distance}}, f)
    _word_level_tokenizer_json(tmp_path / "clip_tokenizer.json",
                               clip_cfg.vocab_size)
    _word_level_tokenizer_json(tmp_path / "t5_tokenizer.json",
                               t5_cfg.vocab_size)
    return pipe, clip_cfg, t5_cfg


# literal spot-checks: one name per pattern family, written out verbatim so
# a systematic mapping bug cannot hide behind synthesize-with-the-same-map
EXPECTED_NAMES = [
    "model.diffusion_model.img_in.weight",
    "model.diffusion_model.time_in.in_layer.bias",
    "model.diffusion_model.vector_in.out_layer.weight",
    "model.diffusion_model.guidance_in.in_layer.weight",
    "model.diffusion_model.double_blocks.0.img_mod.lin.weight",
    "model.diffusion_model.double_blocks.1.txt_attn.qkv.bias",
    "model.diffusion_model.double_blocks.0.img_attn.norm.query_norm.scale",
    "model.diffusion_model.double_blocks.0.txt_mlp.2.weight",
    "model.diffusion_model.single_blocks.1.modulation.lin.bias",
    "model.diffusion_model.single_blocks.0.linear1.weight",
    "model.diffusion_model.single_blocks.0.norm.key_norm.scale",
    "model.diffusion_model.final_layer.adaLN_modulation.1.weight",
    "model.diffusion_model.final_layer.linear.bias",
    "vae.decoder.conv_in.weight",
    "vae.decoder.mid.block_1.norm1.weight",
    "vae.decoder.mid.attn_1.proj_out.bias",
    "vae.decoder.up.1.block.0.conv1.weight",
    "vae.decoder.up.1.upsample.conv.weight",
    "vae.decoder.norm_out.weight",
    "text_encoders.clip_l.transformer.text_model.embeddings."
    "token_embedding.weight",
    "text_encoders.clip_l.transformer.text_model.encoder.layers.0."
    "self_attn.q_proj.weight",
    "text_encoders.clip_l.transformer.text_model.encoder.layers.1."
    "mlp.fc1.bias",
    "text_encoders.clip_l.transformer.text_model.final_layer_norm.weight",
    "text_encoders.t5xxl.transformer.shared.weight",
    "text_encoders.t5xxl.transformer.encoder.block.0.layer.0."
    "SelfAttention.relative_attention_bias.weight",
    "text_encoders.t5xxl.transformer.encoder.block.1.layer.1."
    "DenseReluDense.wi_0.weight",
    "text_encoders.t5xxl.transformer.encoder.final_layer_norm.weight",
]


def test_bundle_names_and_detection(tmp_path):
    synth_bundle(tmp_path)
    from cake_tpu.utils.safetensors_io import index_file
    names = set(index_file(str(tmp_path / "model.safetensors")).keys())
    missing = [n for n in EXPECTED_NAMES if n not in names]
    assert not missing, f"missing checkpoint names: {missing}"
    ckpt = detect_flux_checkpoint(str(tmp_path))
    assert ckpt is not None and ckpt.kind == "bundle"
    assert ckpt.clip is not None and ckpt.t5 is not None


def test_load_and_generate(tmp_path):
    synth_bundle(tmp_path)
    model = load_flux_image_model(str(tmp_path), dtype=jnp.float32)
    img = model.generate_image("a tiny test w1 w2", width=32, height=32,
                               steps=2, seed=0)
    assert img.size == (32, 32)
    arr = np.asarray(img)
    assert arr.shape == (32, 32, 3) and np.isfinite(arr).all()


def test_load_fp8_transformer(tmp_path):
    synth_bundle(tmp_path, fp8_transformer=True)
    model = load_flux_image_model(str(tmp_path), dtype=jnp.float32)
    img = model.generate_image("w3 w4", width=16, height=16, steps=1, seed=1)
    assert np.isfinite(np.asarray(img)).all()


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_fp8_native_scaled_variant(tmp_path):
    """Comfy scaled-fp8 bundles (per-tensor scale_weight): the native path
    must broadcast the scalar into its blockwise scale_inv — identical
    output to the dequant-at-load read, which multiplies it directly."""
    synth_bundle(tmp_path, fp8_transformer=True, fp8_scaled=True)
    dense = load_flux_image_model(str(tmp_path), dtype=jnp.float32)
    native = load_flux_image_model(str(tmp_path), dtype=jnp.float32,
                                   fp8_native=True)
    img_d = dense.generate_image("w3 w4", width=16, height=16, steps=2,
                                 seed=1)
    img_n = native.generate_image("w3 w4", width=16, height=16, steps=2,
                                  seed=1)
    np.testing.assert_array_equal(np.asarray(img_d), np.asarray(img_n))


def test_fp8_native_residency_matches_dequant_at_load(tmp_path):
    """--fp8-native on the image path: every float8-stored 2D transformer
    weight stays a 1-byte/param {"fp8","scale_inv"} marker dict in HBM
    (ref: native_dtype_backend.rs — the reference's flux1-dev 13.3-vs-24 GB
    headline) and generation is identical to dequant-at-load."""
    import jax

    synth_bundle(tmp_path, fp8_transformer=True)
    dense = load_flux_image_model(str(tmp_path), dtype=jnp.float32)
    native = load_flux_image_model(str(tmp_path), dtype=jnp.float32,
                                   fp8_native=True)

    leaves = jax.tree.leaves(native.params["transformer"])
    f8 = [l for l in leaves if str(l.dtype) == "float8_e4m3fn"]
    assert f8, "no fp8-resident leaves survived the native load"
    # every 2D matmul weight that was stored fp8 must still BE fp8
    dense_2d = [l for l in jax.tree.leaves(dense.params["transformer"])
                if getattr(l, "ndim", 0) == 2]
    assert len(f8) == len(dense_2d)
    # byte accounting: fp8 leaves cost exactly 1 byte/param
    assert all(l.nbytes == l.size for l in f8)

    img_d = dense.generate_image("w3 w4", width=16, height=16, steps=2,
                                 seed=1)
    img_n = native.generate_image("w3 w4", width=16, height=16, steps=2,
                                  seed=1)
    np.testing.assert_array_equal(np.asarray(img_d), np.asarray(img_n))


def test_missing_tensor_is_reported(tmp_path):
    synth_bundle(tmp_path)
    from cake_tpu.utils.safetensors_io import index_file
    tensors = {n: np.zeros(r.shape, np.float32) for n, r in
               index_file(str(tmp_path / "model.safetensors")).items()}
    victim = "model.diffusion_model.double_blocks.1.img_attn.qkv.weight"
    del tensors[victim]
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    with pytest.raises(ValueError, match="img_attn.qkv"):
        load_flux_image_model(str(tmp_path), dtype=jnp.float32)


def test_shape_mismatch_is_reported(tmp_path):
    synth_bundle(tmp_path)
    from cake_tpu.utils.safetensors_io import index_file
    tensors = {n: np.zeros(r.shape, np.float32) for n, r in
               index_file(str(tmp_path / "model.safetensors")).items()}
    victim = "model.diffusion_model.txt_in.weight"
    tensors[victim] = np.zeros((3, 3), np.float32)
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    with pytest.raises(ValueError, match="txt_in"):
        load_flux_image_model(str(tmp_path), dtype=jnp.float32)


def test_missing_encoders_clear_error(tmp_path):
    """Transformer+VAE-only bundle must name the missing encoders."""
    pipe = tiny_flux_config()
    rng = jax.random.PRNGKey(0)
    tensors = {}
    flat = flatten_tree(init_mmdit_params(pipe.mmdit, rng, jnp.float32))
    for path, name in mmdit_mapping(pipe.mmdit).items():
        tensors[TRANSFORMER_PREFIX + name] = np.asarray(flat[path],
                                                        np.float32)
    flatv = flatten_tree(init_vae_decoder_params(pipe.vae, rng, jnp.float32))
    for path, name in vae_decoder_mapping(pipe.vae).items():
        tensors[VAE_PREFIX + name] = np.asarray(flatv[path], np.float32)
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    with pytest.raises(ValueError, match="text encoders"):
        load_flux_image_model(str(tmp_path))
