"""Op library parity tests vs numpy references
(mirrors ref tests/unit_tests/test_backend_ops.rs cross-checks)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu import ops


def np_rms_norm(x, w, eps):
    x = x.astype(np.float32)
    var = np.mean(x * x, axis=-1, keepdims=True)
    return x / np.sqrt(var + eps) * w


def test_rms_norm(rng):
    x = rng.standard_normal((2, 5, 64)).astype(np.float32)
    w = rng.standard_normal(64).astype(np.float32)
    got = ops.rms_norm(jnp.asarray(x), jnp.asarray(w), 1e-6)
    np.testing.assert_allclose(got, np_rms_norm(x, w, 1e-6), atol=1e-5)


def test_rms_norm_residual_weight():
    w = jnp.asarray([0.5, -0.25], dtype=jnp.float32)
    got = ops.load_rms_norm_weight(w, residual=True)
    np.testing.assert_allclose(got, [1.5, 0.75])
    same = ops.load_rms_norm_weight(w, residual=False)
    np.testing.assert_allclose(same, [0.5, -0.25])


def test_add_rms_norm(rng):
    x = rng.standard_normal((2, 3, 16)).astype(np.float32)
    r = rng.standard_normal((2, 3, 16)).astype(np.float32)
    w = np.ones(16, np.float32)
    y, s = ops.add_rms_norm(jnp.asarray(x), jnp.asarray(r), jnp.asarray(w))
    np.testing.assert_allclose(s, x + r, atol=1e-6)
    np.testing.assert_allclose(y, np_rms_norm(x + r, w, 1e-6), atol=1e-5)


def test_layer_norm(rng):
    x = rng.standard_normal((4, 32)).astype(np.float32)
    w = rng.standard_normal(32).astype(np.float32)
    b = rng.standard_normal(32).astype(np.float32)
    got = ops.layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), 1e-5)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_group_norm(rng):
    x = rng.standard_normal((2, 8, 5)).astype(np.float32)
    w = rng.standard_normal(8).astype(np.float32)
    b = rng.standard_normal(8).astype(np.float32)
    got = ops.group_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                         num_groups=4, eps=1e-5)
    xr = x.reshape(2, 4, 2, 5)
    mean = xr.mean((2, 3), keepdims=True)
    var = xr.var((2, 3), keepdims=True)
    want = ((xr - mean) / np.sqrt(var + 1e-5)).reshape(2, 8, 5)
    want = want * w[None, :, None] + b[None, :, None]
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_silu_mul_gelu_mul(rng):
    g = rng.standard_normal((3, 8)).astype(np.float32)
    u = rng.standard_normal((3, 8)).astype(np.float32)
    want = g / (1 + np.exp(-g)) * u
    np.testing.assert_allclose(ops.silu_mul(jnp.asarray(g), jnp.asarray(u)),
                               want, atol=1e-5)
    got = ops.gelu_mul(jnp.asarray(g), jnp.asarray(u))
    assert got.shape == (3, 8)


def test_fused_elementwise(rng):
    a, b, c = (rng.standard_normal(7).astype(np.float32) for _ in range(3))
    ja, jb, jc = map(jnp.asarray, (a, b, c))
    np.testing.assert_allclose(ops.add3(ja, jb, jc), a + b + c, atol=1e-6)
    np.testing.assert_allclose(ops.exp_mul(ja, jb), np.exp(a) * b, rtol=1e-5)
    np.testing.assert_allclose(ops.sub_mul(ja, jb, jc), (a - b) * c, atol=1e-6)
    np.testing.assert_allclose(ops.add_scaled(ja, jb, 0.5), a + 0.5 * b, atol=1e-6)
    np.testing.assert_allclose(ops.adaln_modulate(ja, jb, jc),
                               a * (1 + c) + b, atol=1e-5)
    np.testing.assert_allclose(ops.stable_softplus(jnp.asarray([800.0]))[0],
                               800.0, rtol=1e-6)


def test_rope_rotation_property(rng):
    """RoPE must preserve norms and depend only on relative positions in QK dots."""
    d = 32
    cos, sin = ops.rope_tables(64, d, 10000.0)
    x = rng.standard_normal((1, 4, 2, d)).astype(np.float32)
    pos = jnp.arange(4, dtype=jnp.int32)
    y = ops.apply_rope(jnp.asarray(x), cos, sin, pos)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(y), axis=-1),
                               np.linalg.norm(x, axis=-1), rtol=1e-4)
    # relative-position invariance: <R_m q, R_n k> == <R_{m+s} q, R_{n+s} k>
    q = rng.standard_normal((1, 1, 1, d)).astype(np.float32)
    k = rng.standard_normal((1, 1, 1, d)).astype(np.float32)

    def dot_at(pq, pk):
        rq = ops.apply_rope(jnp.asarray(q), cos, sin, jnp.asarray([pq], jnp.int32))
        rk = ops.apply_rope(jnp.asarray(k), cos, sin, jnp.asarray([pk], jnp.int32))
        return float(jnp.sum(rq * rk))

    assert abs(dot_at(5, 3) - dot_at(25, 23)) < 1e-3


def test_rope_partial(rng):
    d = 16
    rd = 8
    cos, sin = ops.rope_tables(32, rd, 10000.0)
    x = rng.standard_normal((1, 2, 1, d)).astype(np.float32)
    pos = jnp.arange(2, dtype=jnp.int32)
    y = ops.apply_rope(jnp.asarray(x), cos, sin, pos, rotary_dim=rd)
    # pass-through channels untouched
    np.testing.assert_allclose(np.asarray(y)[..., rd:], x[..., rd:], atol=1e-6)
    assert not np.allclose(np.asarray(y)[0, 1, 0, :rd], x[0, 1, 0, :rd])


def test_rope_llama3_scaling():
    sc = ops.RopeScaling(factor=8.0, high_freq_factor=4.0, low_freq_factor=1.0,
                         original_max_position_embeddings=8192, rope_type="llama3")
    inv_plain = ops.inv_frequencies(128, 500000.0)
    inv_scaled = ops.inv_frequencies(128, 500000.0, sc)
    # high-frequency (short wavelength) components unchanged
    np.testing.assert_allclose(inv_scaled[0], inv_plain[0])
    # low-frequency components divided by factor
    np.testing.assert_allclose(inv_scaled[-1], inv_plain[-1] / 8.0, rtol=1e-6)


def np_attention(q, k, v, mask):
    hq, hkv = q.shape[2], k.shape[2]
    rep = hq // hkv
    k = np.repeat(k, rep, axis=2)
    v = np.repeat(v, rep, axis=2)
    qt = q.transpose(0, 2, 1, 3).astype(np.float32)
    kt = k.transpose(0, 2, 1, 3).astype(np.float32)
    vt = v.transpose(0, 2, 1, 3).astype(np.float32)
    scores = qt @ kt.transpose(0, 1, 3, 2) / np.sqrt(q.shape[-1])
    scores = np.where(mask[:, None, :, :], scores, -1e30)
    m = scores.max(-1, keepdims=True)
    e = np.exp(scores - m)
    p = e / e.sum(-1, keepdims=True)
    return (p @ vt).transpose(0, 2, 1, 3)


def test_attention_matches_reference(rng):
    b, sq, skv, hq, hkv, d = 2, 5, 9, 4, 2, 8
    q = rng.standard_normal((b, sq, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, skv, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, skv, hkv, d)).astype(np.float32)
    qpos = np.broadcast_to(np.arange(4, 4 + sq, dtype=np.int32), (b, sq))
    kpos = np.broadcast_to(np.arange(skv, dtype=np.int32), (b, skv))
    mask = ops.make_attention_mask(jnp.asarray(qpos), jnp.asarray(kpos))
    got = ops.multi_head_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                   mask)
    want = np_attention(q, k, v, np.asarray(mask))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_attention_mask_semantics():
    qpos = jnp.asarray([[3]], jnp.int32)
    kpos = jnp.asarray([[0, 1, 2, 3, 4, -1]], jnp.int32)
    m = np.asarray(ops.make_attention_mask(qpos, kpos))
    # causal: sees 0..3, not 4; -1 slot invisible
    assert m[0, 0].tolist() == [True, True, True, True, False, False]
    m2 = np.asarray(ops.make_attention_mask(qpos, kpos, window=2))
    # window=2: only positions {2,3} visible
    assert m2[0, 0].tolist() == [False, False, True, True, False, False]


def test_causal_sdpa_is_causal(rng):
    b, s, h, d = 1, 6, 2, 4
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    out1 = ops.causal_sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    # changing future keys must not affect earlier outputs
    k2 = k.copy()
    k2[:, -1] += 10.0
    v2 = v.copy()
    v2[:, -1] -= 5.0
    out2 = ops.causal_sdpa(jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2))
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], atol=1e-5)


def test_fp8_roundtrip(rng):
    w = rng.standard_normal((200, 300)).astype(np.float32)
    wq, scale_inv = ops.quant_fp8_blockwise(jnp.asarray(w))
    assert wq.dtype == jnp.float8_e4m3fn
    assert scale_inv.shape == (2, 3)
    back = ops.dequant_fp8_blockwise(wq, scale_inv, out_dtype=jnp.float32)
    err = np.abs(np.asarray(back) - w).mean()
    assert err < 0.05


def test_conv1d_and_depthwise(rng):
    x = rng.standard_normal((1, 4, 10)).astype(np.float32)
    w = rng.standard_normal((6, 4, 3)).astype(np.float32)
    b = rng.standard_normal(6).astype(np.float32)
    y = ops.conv1d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), padding=1)
    assert y.shape == (1, 6, 10)
    # torch cross-check
    import torch
    want = torch.nn.functional.conv1d(torch.from_numpy(x), torch.from_numpy(w),
                                      torch.from_numpy(b), padding=1).numpy()
    np.testing.assert_allclose(y, want, atol=1e-4)

    wd = rng.standard_normal((4, 1, 3)).astype(np.float32)
    yd = ops.depthwise_conv1d(jnp.asarray(x), jnp.asarray(wd), padding=2)
    wantd = torch.nn.functional.conv1d(torch.from_numpy(x), torch.from_numpy(wd),
                                       padding=2, groups=4).numpy()
    np.testing.assert_allclose(yd, wantd, atol=1e-4)


def test_causal_depthwise_conv_update_matches_full(rng):
    """Streaming single-step conv must equal the full causal conv."""
    b, c, t, k = 1, 3, 6, 4
    x = rng.standard_normal((b, c, t)).astype(np.float32)
    w = rng.standard_normal((c, 1, k)).astype(np.float32)
    # full causal conv: left-pad k-1
    import torch
    xp = torch.nn.functional.pad(torch.from_numpy(x), (k - 1, 0))
    full = torch.nn.functional.conv1d(xp, torch.from_numpy(w), groups=c).numpy()
    state = jnp.zeros((b, c, k - 1), jnp.float32)
    outs = []
    for i in range(t):
        y, state = ops.causal_depthwise_conv1d_update(
            jnp.asarray(x[:, :, i]), state, jnp.asarray(w), activation=None)
        outs.append(np.asarray(y))
    got = np.stack(outs, axis=-1)
    np.testing.assert_allclose(got, full, atol=1e-5)


def test_conv_transpose1d_matches_torch(rng):
    x = rng.standard_normal((1, 3, 5)).astype(np.float32)
    w = rng.standard_normal((3, 4, 8)).astype(np.float32)
    b = rng.standard_normal(4).astype(np.float32)
    y = ops.conv_transpose1d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                             stride=4, padding=2)
    import torch
    want = torch.nn.functional.conv_transpose1d(
        torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
        stride=4, padding=2).numpy()
    assert y.shape == want.shape == (1, 4, (5 - 1) * 4 + 8 - 4)
    np.testing.assert_allclose(y, want, atol=1e-4)


def test_conv2d(rng):
    x = rng.standard_normal((1, 3, 8, 8)).astype(np.float32)
    w = rng.standard_normal((5, 3, 3, 3)).astype(np.float32)
    y = ops.conv2d(jnp.asarray(x), jnp.asarray(w), stride=2, padding=1)
    import torch
    want = torch.nn.functional.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                                      stride=2, padding=1).numpy()
    np.testing.assert_allclose(y, want, atol=1e-4)


class TestSampling:
    def test_argmax(self):
        logits = jnp.asarray([0.1, 5.0, -2.0])
        cfg = ops.SamplingConfig(temperature=0.0)
        tok = ops.sample(logits, jax.random.PRNGKey(0), cfg)
        assert int(tok) == 1

    def test_gumbel_distribution(self):
        logits = jnp.log(jnp.asarray([0.7, 0.2, 0.1]))
        cfg = ops.SamplingConfig(temperature=1.0)
        keys = jax.random.split(jax.random.PRNGKey(0), 400)
        toks = jax.vmap(lambda k: ops.sample(logits, k, cfg))(keys)
        freq = np.bincount(np.asarray(toks), minlength=3) / 400
        np.testing.assert_allclose(freq, [0.7, 0.2, 0.1], atol=0.08)

    def test_top_k_restricts(self):
        logits = jnp.asarray([1.0, 0.9, 0.8, -10.0, -10.0])
        cfg = ops.SamplingConfig(temperature=1.0, top_k=2)
        keys = jax.random.split(jax.random.PRNGKey(1), 100)
        toks = np.asarray(jax.vmap(lambda k: ops.sample(logits, k, cfg))(keys))
        assert set(toks.tolist()) <= {0, 1}

    def test_top_p_restricts(self):
        logits = jnp.log(jnp.asarray([0.6, 0.3, 0.05, 0.05]))
        cfg = ops.SamplingConfig(temperature=1.0, top_p=0.8)
        keys = jax.random.split(jax.random.PRNGKey(2), 100)
        toks = np.asarray(jax.vmap(lambda k: ops.sample(logits, k, cfg))(keys))
        assert set(toks.tolist()) <= {0, 1}

    def test_top_k_then_top_p(self):
        logits = jnp.log(jnp.asarray([0.5, 0.3, 0.1, 0.1]))
        cfg = ops.SamplingConfig(temperature=1.0, top_k=3, top_p=0.6)
        keys = jax.random.split(jax.random.PRNGKey(3), 100)
        toks = np.asarray(jax.vmap(lambda k: ops.sample(logits, k, cfg))(keys))
        assert set(toks.tolist()) <= {0, 1}

    def test_repeat_penalty_sign_aware(self):
        logits = jnp.asarray([2.0, -2.0, 1.0])
        recent = jnp.asarray([0, 1, -1, -1], jnp.int32)
        out = np.asarray(ops.apply_repeat_penalty(logits, recent, 2.0))
        np.testing.assert_allclose(out, [1.0, -4.0, 1.0])

    def test_repeat_penalty_in_sample(self):
        logits = jnp.asarray([5.0, 4.9, 0.0])
        recent = jnp.asarray([0], jnp.int32)
        cfg = ops.SamplingConfig(temperature=0.0, repeat_penalty=3.0)
        tok = ops.sample(logits, jax.random.PRNGKey(0), cfg, recent)
        assert int(tok) == 1

    def test_push_recent_token(self):
        ring = jnp.asarray([-1, -1, 7], jnp.int32)
        out = ops.push_recent_token(ring, jnp.asarray(9, jnp.int32))
        assert out.tolist() == [-1, 7, 9]
