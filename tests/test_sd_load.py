"""SD release-checkpoint loading: synthesize a tiny diffusers-layout
directory (unet/ vae/ text_encoder/ tokenizer/ with real tensor names and
config.json files — the format the reference downloads per component,
ref: models/sd/sd.rs ModelFile) and load it through the public path.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.image import (load_sd_image_model, sd_unet_mapping,
                                   sd_vae_decoder_mapping)
from cake_tpu.models.image.sd import UNetConfig, init_unet_params
from cake_tpu.models.image.vae import VaeConfig, init_vae_decoder_params
from cake_tpu.models.text_encoders import (clip_mapping, init_clip_params,
                                           tiny_clip_config)
from cake_tpu.utils.mapping import flatten_tree
from cake_tpu.utils.safetensors_io import save_safetensors
from test_flux_load import _word_level_tokenizer_json

TINY_UNET = UNetConfig(base_channels=32, channel_mults=(1, 2),
                       num_res_blocks=1, attn_levels=(1,), num_heads=2,
                       context_dim=32, time_dim=128)
TINY_VAE = VaeConfig(latent_channels=4, base_channels=32, channel_mults=(1, 2),
                     num_res_blocks=2, scaling_factor=0.18215,
                     shift_factor=0.0)


def _inv_transform(path, name, arr):
    """Store in checkpoint-native layout: conv kernels where diffusers uses
    them (proj_in/out; vae post_quant/attention linears stay linear)."""
    if name.endswith(("proj_in.weight", "proj_out.weight")) \
            and "transformer" not in name and arr.ndim == 2:
        return arr.reshape(*arr.shape, 1, 1)
    return arr


def synth_sd_dir(tmp_path):
    clip_cfg = tiny_clip_config()
    rng = jax.random.PRNGKey(0)
    ks = jax.random.split(rng, 4)     # ks[3]: the VAE encoder synth

    os.makedirs(tmp_path / "unet")
    u_params = init_unet_params(TINY_UNET, ks[0], jnp.float32)
    um, _ = sd_unet_mapping(TINY_UNET)
    flat = flatten_tree(u_params)
    tensors = {}
    for path, name in um.items():
        tensors[name] = _inv_transform(path, name, np.asarray(flat[path],
                                                              np.float32))
    save_safetensors(str(tmp_path / "unet" /
                         "diffusion_pytorch_model.safetensors"), tensors)
    with open(tmp_path / "unet" / "config.json", "w") as f:
        json.dump({
            "in_channels": 4, "block_out_channels": [32, 64],
            "layers_per_block": 1, "cross_attention_dim": 32,
            "attention_head_dim": 2,
            "down_block_types": ["DownBlock2D", "CrossAttnDownBlock2D"],
            "up_block_types": ["CrossAttnUpBlock2D", "UpBlock2D"],
        }, f)

    os.makedirs(tmp_path / "vae")
    v_params = init_vae_decoder_params(TINY_VAE, ks[1], jnp.float32)
    # post_quant_conv is part of the diffusers checkpoint
    v_params["post_quant_conv"] = {
        "weight": np.random.default_rng(0).standard_normal(
            (4, 4, 1, 1)).astype(np.float32) * 0.1,
        "bias": np.zeros((4,), np.float32)}

    vm, _ = sd_vae_decoder_mapping({}, TINY_VAE)   # old-style names (no to_q)
    flatv = flatten_tree(v_params)
    tensors = {}
    for path, name in vm.items():
        arr = np.asarray(flatv[path], np.float32)
        if path.startswith("mid_attn") and not path.endswith("norm.weight") \
                and not path.endswith("norm.bias") and arr.ndim == 4:
            arr = arr.reshape(arr.shape[0], arr.shape[1])   # linear-style
        tensors[name] = arr
    # full AutoencoderKL dumps ship the ENCODER too (img2img entry point)
    from cake_tpu.models.image.sd_loader import sd_vae_encoder_mapping
    from cake_tpu.models.image.vae import init_vae_encoder_params
    e_params = init_vae_encoder_params(TINY_VAE, ks[3], jnp.float32)
    em, _ = sd_vae_encoder_mapping({}, TINY_VAE)
    flate = flatten_tree(e_params)
    for path, name in em.items():
        arr = np.asarray(flate[path], np.float32)
        if path.startswith("mid_attn") and not path.endswith("norm.weight") \
                and not path.endswith("norm.bias") and arr.ndim == 4:
            arr = arr.reshape(arr.shape[0], arr.shape[1])   # linear-style
        tensors[name] = arr
    save_safetensors(str(tmp_path / "vae" /
                         "diffusion_pytorch_model.safetensors"), tensors)
    with open(tmp_path / "vae" / "config.json", "w") as f:
        json.dump({"latent_channels": 4, "block_out_channels": [32, 64],
                   "layers_per_block": 1, "scaling_factor": 0.18215}, f)

    os.makedirs(tmp_path / "text_encoder")
    c_params = init_clip_params(clip_cfg, ks[2], jnp.float32)
    flat_c = flatten_tree(c_params)
    tensors = {name: np.asarray(flat_c[path], np.float32)
               for path, name in clip_mapping(clip_cfg).items()}
    save_safetensors(str(tmp_path / "text_encoder" / "model.safetensors"),
                     tensors)
    with open(tmp_path / "text_encoder" / "config.json", "w") as f:
        json.dump({"vocab_size": clip_cfg.vocab_size,
                   "hidden_size": clip_cfg.hidden_size,
                   "num_hidden_layers": clip_cfg.num_layers,
                   "num_attention_heads": clip_cfg.num_heads,
                   "intermediate_size": clip_cfg.intermediate_size,
                   "max_position_embeddings": clip_cfg.max_positions,
                   "eot_token_id": clip_cfg.eot_token_id}, f)

    os.makedirs(tmp_path / "tokenizer")
    _word_level_tokenizer_json(tmp_path / "tokenizer" / "tokenizer.json",
                               clip_cfg.vocab_size)


EXPECTED_UNET_NAMES = [
    "conv_in.weight",
    "time_embedding.linear_1.weight",
    "down_blocks.0.resnets.0.time_emb_proj.weight",
    "down_blocks.0.downsamplers.0.conv.weight",
    "down_blocks.1.resnets.0.conv_shortcut.weight",
    "down_blocks.1.attentions.0.proj_in.weight",
    "down_blocks.1.attentions.0.transformer_blocks.0.attn1.to_q.weight",
    "down_blocks.1.attentions.0.transformer_blocks.0.attn2.to_out.0.bias",
    "down_blocks.1.attentions.0.transformer_blocks.0.ff.net.0.proj.weight",
    "mid_block.resnets.1.conv1.weight",
    "mid_block.attentions.0.transformer_blocks.0.norm3.weight",
    "up_blocks.0.resnets.1.conv_shortcut.weight",
    "up_blocks.0.upsamplers.0.conv.weight",
    "up_blocks.1.resnets.0.conv1.weight",
    "conv_norm_out.weight",
]
EXPECTED_VAE_NAMES = [
    "post_quant_conv.weight",
    "decoder.conv_in.weight",
    "decoder.mid_block.resnets.0.norm1.weight",
    "decoder.mid_block.attentions.0.group_norm.weight",
    "decoder.mid_block.attentions.0.query.weight",
    "decoder.mid_block.attentions.0.proj_attn.bias",
    "decoder.up_blocks.0.resnets.0.conv1.weight",
    "decoder.up_blocks.0.upsamplers.0.conv.weight",
    "decoder.up_blocks.1.resnets.0.conv_shortcut.weight",
    "decoder.conv_norm_out.weight",
]


def test_sd_names(tmp_path):
    synth_sd_dir(tmp_path)
    from cake_tpu.utils.safetensors_io import index_file
    unet_names = set(index_file(
        str(tmp_path / "unet" / "diffusion_pytorch_model.safetensors")))
    missing = [n for n in EXPECTED_UNET_NAMES if n not in unet_names]
    assert not missing, f"missing unet names: {missing}"
    vae_names = set(index_file(
        str(tmp_path / "vae" / "diffusion_pytorch_model.safetensors")))
    missing = [n for n in EXPECTED_VAE_NAMES if n not in vae_names]
    assert not missing, f"missing vae names: {missing}"


def test_sd_load_and_generate(tmp_path):
    synth_sd_dir(tmp_path)
    model = load_sd_image_model(str(tmp_path), dtype=jnp.float32)
    # the diffusers-only 1x1 latent conv must survive the mapped load
    assert "post_quant_conv" in model.params["vae"]
    img = model.generate_image("w1 w2", width=32, height=32, steps=2, seed=0)
    assert img.size == (32, 32)
    assert np.isfinite(np.asarray(img)).all()


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_sd_img2img(tmp_path):
    synth_sd_dir(tmp_path)
    model = load_sd_image_model(str(tmp_path), dtype=jnp.float32)
    init = np.random.default_rng(0).standard_normal((1, 4, 16, 16)) * 0.1
    img = model.generate_image("w1", width=32, height=32, steps=3,
                               init_image=init, strength=0.6, seed=1)
    assert np.isfinite(np.asarray(img)).all()

    # real-image img2img: the loaded checkpoint ships the VAE encoder,
    # so pixels -> encode_image -> generate (the CLI --init-image path)
    assert "vae_enc" in model.params
    px = np.random.default_rng(1).integers(0, 256, (32, 32, 3),
                                           dtype=np.uint8)
    z0 = model.encode_image(px)
    assert z0.shape == (1, 4, 16, 16)
    img2 = model.generate_image("w1", width=32, height=32, steps=2,
                                init_image=z0, strength=0.5, seed=2)
    assert np.isfinite(np.asarray(img2)).all()


def test_sd_runtime_detection(tmp_path):
    synth_sd_dir(tmp_path)
    from cake_tpu.runtime import build_image_model
    model = build_image_model(str(tmp_path), dtype="f32")
    assert type(model).__name__ == "SDImageModel"


def synth_sd2_dir(tmp_path):
    """SD2.x-shaped synth: per-level head counts, linear spatial-transformer
    projections (use_linear_projection), gelu text encoder, v-prediction
    scheduler config."""
    unet2 = UNetConfig(base_channels=32, channel_mults=(1, 2),
                       num_res_blocks=1, attn_levels=(0, 1), num_heads=(2, 4),
                       context_dim=32, time_dim=128)
    rng = jax.random.PRNGKey(7)
    ks = jax.random.split(rng, 3)

    os.makedirs(tmp_path / "unet")
    u_params = init_unet_params(unet2, ks[0], jnp.float32)
    um, _ = sd_unet_mapping(unet2)
    flat = flatten_tree(u_params)
    # use_linear_projection: proj_in/out stored 2D, no conv expansion
    tensors = {name: np.asarray(flat[path], np.float32)
               for path, name in um.items()}
    save_safetensors(str(tmp_path / "unet" /
                         "diffusion_pytorch_model.safetensors"), tensors)
    with open(tmp_path / "unet" / "config.json", "w") as f:
        json.dump({
            "in_channels": 4, "block_out_channels": [32, 64],
            "layers_per_block": 1, "cross_attention_dim": 32,
            "attention_head_dim": [2, 4], "use_linear_projection": True,
            "down_block_types": ["CrossAttnDownBlock2D",
                                 "CrossAttnDownBlock2D"],
            "up_block_types": ["CrossAttnUpBlock2D", "CrossAttnUpBlock2D"],
        }, f)

    os.makedirs(tmp_path / "vae")
    v_params = init_vae_decoder_params(TINY_VAE, ks[1], jnp.float32)
    v_params["post_quant_conv"] = {
        "weight": np.random.default_rng(0).standard_normal(
            (4, 4, 1, 1)).astype(np.float32) * 0.1,
        "bias": np.zeros((4,), np.float32)}
    vm, _ = sd_vae_decoder_mapping({"decoder.mid_block.attentions.0.to_q.weight": 1},
                                   TINY_VAE)   # new-style names
    flatv = flatten_tree(v_params)
    tensors = {}
    for path, name in vm.items():
        arr = np.asarray(flatv[path], np.float32)
        if path.startswith("mid_attn") and arr.ndim == 4:
            arr = arr.reshape(arr.shape[0], arr.shape[1])
        tensors[name] = arr
    save_safetensors(str(tmp_path / "vae" /
                         "diffusion_pytorch_model.safetensors"), tensors)
    with open(tmp_path / "vae" / "config.json", "w") as f:
        json.dump({"latent_channels": 4, "block_out_channels": [32, 64],
                   "layers_per_block": 1, "scaling_factor": 0.18215}, f)

    os.makedirs(tmp_path / "scheduler")
    with open(tmp_path / "scheduler" / "scheduler_config.json", "w") as f:
        json.dump({"prediction_type": "v_prediction",
                   "beta_start": 0.00085, "beta_end": 0.012,
                   "beta_schedule": "scaled_linear"}, f)

    os.makedirs(tmp_path / "text_encoder")
    from cake_tpu.models.text_encoders import CLIPTextConfig
    clip_cfg = CLIPTextConfig(vocab_size=96, hidden_size=32, num_layers=2,
                              num_heads=2, intermediate_size=64,
                              max_positions=16, eot_token_id=95,
                              hidden_act="gelu")
    c_params = init_clip_params(clip_cfg, ks[2], jnp.float32)
    flat_c = flatten_tree(c_params)
    tensors = {name: np.asarray(flat_c[path], np.float32)
               for path, name in clip_mapping(clip_cfg).items()}
    save_safetensors(str(tmp_path / "text_encoder" / "model.safetensors"),
                     tensors)
    with open(tmp_path / "text_encoder" / "config.json", "w") as f:
        json.dump({"vocab_size": 96, "hidden_size": 32,
                   "num_hidden_layers": 2, "num_attention_heads": 2,
                   "intermediate_size": 64, "max_position_embeddings": 16,
                   "eot_token_id": 95, "hidden_act": "gelu"}, f)

    os.makedirs(tmp_path / "tokenizer")
    _word_level_tokenizer_json(tmp_path / "tokenizer" / "tokenizer.json", 96)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_sd2_load_and_generate(tmp_path):
    synth_sd2_dir(tmp_path)
    model = load_sd_image_model(str(tmp_path), dtype=jnp.float32)
    assert model.cfg.unet.num_heads == (2, 4)
    assert model.cfg.prediction_type == "v_prediction"
    assert model.scheduler.prediction_type == "v_prediction"
    img = model.generate_image("w1 w2", width=32, height=32, steps=2, seed=0)
    assert img.size == (32, 32)
    assert np.isfinite(np.asarray(img)).all()


def test_sd2_gelu_text_encoder_differs_from_quick_gelu(tmp_path):
    """hidden_act must actually change the activation: same weights, the
    two activations give different hidden states."""
    from cake_tpu.models.text_encoders import CLIPTextConfig, clip_text_forward
    import dataclasses as dc
    cfg = CLIPTextConfig(vocab_size=96, hidden_size=32, num_layers=2,
                         num_heads=2, intermediate_size=64, max_positions=16,
                         eot_token_id=95, hidden_act="gelu")
    params = init_clip_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jnp.asarray([[1, 2, 3, 95]], jnp.int32)
    h_gelu, _, _ = clip_text_forward(cfg, params, ids)
    h_quick, _, _ = clip_text_forward(dc.replace(cfg, hidden_act="quick_gelu"),
                                      params, ids)
    assert not np.allclose(np.asarray(h_gelu), np.asarray(h_quick))


def _synth_clip_dir(tmp_path, subdir, key, hidden_act="quick_gelu",
                    projection_dim=None):
    from cake_tpu.models.text_encoders import CLIPTextConfig
    cfg = CLIPTextConfig(vocab_size=96, hidden_size=32, num_layers=2,
                         num_heads=2, intermediate_size=64, max_positions=16,
                         eot_token_id=95, hidden_act=hidden_act,
                         projection_dim=projection_dim)
    os.makedirs(tmp_path / subdir)
    params = init_clip_params(cfg, key, jnp.float32)
    flat = flatten_tree(params)
    tensors = {name: np.asarray(flat[path], np.float32)
               for path, name in clip_mapping(cfg).items()}
    save_safetensors(str(tmp_path / subdir / "model.safetensors"), tensors)
    raw = {"vocab_size": 96, "hidden_size": 32, "num_hidden_layers": 2,
           "num_attention_heads": 2, "intermediate_size": 64,
           "max_position_embeddings": 16, "eot_token_id": 95,
           "hidden_act": hidden_act}
    if projection_dim:
        raw["projection_dim"] = projection_dim
    with open(tmp_path / subdir / "config.json", "w") as f:
        json.dump(raw, f)


def synth_sdxl_dir(tmp_path):
    """SDXL-shaped synth: dual text encoders (encoder 2 with
    text_projection), per-level transformer depth, text_time addition
    embeddings, 2048-style concat context (here 32+32=64)."""
    unet_xl = UNetConfig(base_channels=32, channel_mults=(1, 2),
                         num_res_blocks=1, attn_levels=(1,), num_heads=(2, 4),
                         context_dim=64, time_dim=128,
                         transformer_depth=(1, 2),
                         addition_embed_dim=16 + 6 * 8,
                         addition_time_embed_dim=8)
    rng = jax.random.PRNGKey(11)
    ks = jax.random.split(rng, 4)

    os.makedirs(tmp_path / "unet")
    u_params = init_unet_params(unet_xl, ks[0], jnp.float32)
    um, _ = sd_unet_mapping(unet_xl)
    flat = flatten_tree(u_params)
    tensors = {name: np.asarray(flat[path], np.float32)
               for path, name in um.items()}
    save_safetensors(str(tmp_path / "unet" /
                         "diffusion_pytorch_model.safetensors"), tensors)
    with open(tmp_path / "unet" / "config.json", "w") as f:
        json.dump({
            "in_channels": 4, "block_out_channels": [32, 64],
            "layers_per_block": 1, "cross_attention_dim": 64,
            "attention_head_dim": [2, 4], "use_linear_projection": True,
            "transformer_layers_per_block": [1, 2],
            "addition_embed_type": "text_time",
            "addition_time_embed_dim": 8,
            "projection_class_embeddings_input_dim": 16 + 6 * 8,
            "down_block_types": ["DownBlock2D", "CrossAttnDownBlock2D"],
            "up_block_types": ["CrossAttnUpBlock2D", "UpBlock2D"],
        }, f)

    os.makedirs(tmp_path / "vae")
    v_params = init_vae_decoder_params(TINY_VAE, ks[1], jnp.float32)
    v_params["post_quant_conv"] = {
        "weight": np.random.default_rng(0).standard_normal(
            (4, 4, 1, 1)).astype(np.float32) * 0.1,
        "bias": np.zeros((4,), np.float32)}
    vm, _ = sd_vae_decoder_mapping(
        {"decoder.mid_block.attentions.0.to_q.weight": 1}, TINY_VAE)
    flatv = flatten_tree(v_params)
    tensors = {}
    for path, name in vm.items():
        arr = np.asarray(flatv[path], np.float32)
        if path.startswith("mid_attn") and arr.ndim == 4:
            arr = arr.reshape(arr.shape[0], arr.shape[1])
        tensors[name] = arr
    save_safetensors(str(tmp_path / "vae" /
                         "diffusion_pytorch_model.safetensors"), tensors)
    with open(tmp_path / "vae" / "config.json", "w") as f:
        json.dump({"latent_channels": 4, "block_out_channels": [32, 64],
                   "layers_per_block": 1, "scaling_factor": 0.13025}, f)

    _synth_clip_dir(tmp_path, "text_encoder", ks[2])
    _synth_clip_dir(tmp_path, "text_encoder_2", ks[3], hidden_act="gelu",
                    projection_dim=16)
    os.makedirs(tmp_path / "tokenizer")
    _word_level_tokenizer_json(tmp_path / "tokenizer" / "tokenizer.json", 96)
    os.makedirs(tmp_path / "tokenizer_2")
    _word_level_tokenizer_json(tmp_path / "tokenizer_2" / "tokenizer.json", 96)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_sdxl_load_and_generate(tmp_path):
    synth_sdxl_dir(tmp_path)
    model = load_sd_image_model(str(tmp_path), dtype=jnp.float32)
    assert type(model).__name__ == "SDXLImageModel"
    assert model.cfg.unet.transformer_depth == (1, 2)
    assert model.cfg.unet.addition_embed_dim == 64
    assert "add_mlp1" in model.params["unet"]
    assert "text_projection" in model.text_encoder2.params
    # pooled of encoder 2 must be projected to projection_dim
    _, pooled2, pen2 = model.text_encoder2.encode3("w1 w2")
    assert pooled2.shape == (1, 16)
    assert pen2.shape[-1] == 32
    img = model.generate_image("w1 w2", width=32, height=32, steps=2, seed=0)
    assert img.size == (32, 32)
    assert np.isfinite(np.asarray(img)).all()


def test_sdxl_unknown_addition_embed_clear_error(tmp_path):
    synth_sd_dir(tmp_path)
    cfg_path = tmp_path / "unet" / "config.json"
    with open(cfg_path) as f:
        cfg = json.load(f)
    cfg["addition_embed_type"] = "image_time"
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    with pytest.raises(NotImplementedError, match="addition_embed_type"):
        load_sd_image_model(str(tmp_path))
