"""Test configuration: force an 8-device virtual CPU mesh so every sharding
path (TP/DP/SP/EP) is exercised without TPU hardware, mirroring the
reference's everything-runs-on-CPU-CI test strategy (SURVEY §4).

Note: the env may pre-import jax with JAX_PLATFORMS pointing at a TPU
plugin (sitecustomize), so the env var alone is not enough — we override
through jax.config before any backend is initialized.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
