"""Sharding tests on the 8-device virtual CPU mesh: TP/DP inference parity,
ring attention vs single-device reference, sharded train step, EP MoE."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import TextModel, init_params, tiny_config
from cake_tpu.models.common.cache import init_cache
from cake_tpu.models.common.layers import forward_train
from cake_tpu.ops.attention import causal_sdpa
from cake_tpu.parallel import (make_mesh, make_train_step, params_shardings,
                               ring_attention, shard_cache, shard_params)


def test_mesh_creation():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.shape == {"dp": 2, "tp": 4}
    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 4})


def test_tp_sharded_forward_matches_single_device():
    """The SAME forward jitted with tp-sharded params must produce the same
    logits as unsharded execution (GSPMD inserts the collectives)."""
    cfg = tiny_config("qwen2", num_key_value_heads=4)   # kv 4 % tp 4 == 0
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 255, (2, 16)))

    ref = forward_train(cfg, params, toks)

    mesh = make_mesh({"dp": 2, "tp": 4})
    sharded = shard_params(params, mesh)
    got = jax.jit(lambda p, t: forward_train(cfg, p, t))(sharded, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-3,
                               rtol=1e-3)
    # weights really are distributed
    w = sharded["layers"][0]["self_attn"]["q_proj"]["weight"]
    assert len(w.sharding.device_set) == 8 or len(w.addressable_shards) > 1


def test_tp_sharded_decode_with_cache():
    cfg = tiny_config("llama", num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=32)
    logits_ref, _ = model.prefill(model.new_cache(), [1, 2, 3, 4, 5])

    mesh = make_mesh({"tp": 4}, devices=jax.devices()[:4])
    model_sh = TextModel(cfg, shard_params(params, mesh), dtype=jnp.float32,
                         max_cache_len=32)
    cache = shard_cache(model_sh.new_cache(), mesh)
    logits_sh, _ = model_sh.prefill(cache, [1, 2, 3, 4, 5])
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               atol=2e-3, rtol=1e-3)


def test_ring_attention_matches_causal_sdpa():
    mesh = make_mesh({"sp": 8})
    b, s, h, hkv, d = 2, 64, 4, 2, 16
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.float32)
    ref = causal_sdpa(q, k, v)
    got = ring_attention(q, k, v, mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4,
                               rtol=1e-3)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_train_step_dp_tp():
    cfg = tiny_config("llama", num_key_value_heads=4, vocab_size=64)
    mesh = make_mesh({"dp": 2, "tp": 4})
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    params = shard_params(params, mesh)
    step, opt_state = make_train_step(cfg, mesh, params)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 63, (4, 17)))
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, toks)
        losses.append(float(loss))
    assert losses[2] < losses[0]          # it actually optimizes
    assert np.isfinite(losses).all()


def test_ep_moe_sharded_forward():
    cfg = tiny_config("qwen3_moe", num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 255, (1, 8)))
    ref = forward_train(cfg, params, toks)
    mesh = make_mesh({"ep": 4, "tp": 2})
    sharded = shard_params(params, mesh)
    w = sharded["layers"][0]["mlp"]["experts"]["gate_proj"]
    assert len(w.addressable_shards) > 1
    got = jax.jit(lambda p, t: forward_train(cfg, p, t))(sharded, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-3,
                               rtol=1e-3)


def test_sp_ring_prefill_serving_parity():
    """Product-path sequence-parallel prefill: TextModel over an sp mesh
    takes the ring-attention branch for fresh prefill (last_prefill_mode
    == "ring") and must match the meshless model's logits AND the
    subsequent greedy decode exactly — the cache scatter gathers K/V back
    so decode is byte-for-byte the ordinary path."""
    from cake_tpu.models import SamplingConfig

    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    prompt = [(i * 7 + 3) % 250 for i in range(40)]

    ref_model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
    want, _ = ref_model.generate(prompt, max_new_tokens=8,
                                 sampling=SamplingConfig(temperature=0.0))
    assert ref_model.last_prefill_mode == "fresh"

    mesh = make_mesh({"sp": 8})
    sp_model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64,
                         mesh=mesh)
    got, _ = sp_model.generate(prompt, max_new_tokens=8,
                               sampling=SamplingConfig(temperature=0.0))
    assert sp_model.last_prefill_mode == "ring"
    assert got == want


def test_sp_tp_composed_ring_prefill_parity():
    """tp x sp composed mesh: heads sharded over tp INSIDE the ring
    (parallel/ring_attention head_axis) while the sequence shards over sp."""
    from cake_tpu.models import SamplingConfig

    cfg = tiny_config("qwen3", num_key_value_heads=2)
    params = init_params(cfg, jax.random.PRNGKey(8), jnp.float32)
    prompt = [(i * 11 + 5) % 250 for i in range(32)]

    ref_model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
    want, _ = ref_model.generate(prompt, max_new_tokens=6,
                                 sampling=SamplingConfig(temperature=0.0))

    mesh = make_mesh({"sp": 4, "tp": 2})
    sp_model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64,
                         mesh=mesh)
    got, _ = sp_model.generate(prompt, max_new_tokens=6,
                               sampling=SamplingConfig(temperature=0.0))
    assert sp_model.last_prefill_mode == "ring"
    assert got == want


def test_sp_cache_length_sharded():
    """On an sp mesh the KV buffers shard over the LENGTH axis — context
    memory scales across devices, the actual reason to serve with sp."""
    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(9), jnp.float32)
    mesh = make_mesh({"sp": 8})
    model = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64,
                      mesh=mesh)
    cache = model.new_cache(1, kv_len=64)
    k = cache["layers"][0]["k"]
    shard_shapes = {s.data.shape for s in k.addressable_shards}
    assert shard_shapes == {(1, 64 // 8, *k.shape[2:])}, shard_shapes


@pytest.mark.parametrize(
    "arch",
    [  # tier-1 keeps one family; the rest ride tier-2 under the 870s cap
        "llama",
        pytest.param("qwen2", marks=pytest.mark.slow),
        pytest.param("olmo2", marks=pytest.mark.slow),
        pytest.param("phi4", marks=pytest.mark.slow),
    ],
)
def test_sp_ring_prefill_across_families(arch):
    """Ring prefill parity across norm styles (pre/post), QKV bias,
    partial RoPE — families whose layer stacks are all-full attention.
    Greedy output must match the meshless model exactly."""
    from cake_tpu.models import SamplingConfig

    cfg = tiny_config(arch)
    params = init_params(cfg, jax.random.PRNGKey(13), jnp.float32)
    prompt = [(i * 5 + 2) % 250 for i in range(40)]

    ref = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
    want, _ = ref.generate(prompt, max_new_tokens=6,
                           sampling=SamplingConfig(temperature=0.0))

    mesh = make_mesh({"sp": 8})
    spm = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64,
                    mesh=mesh)
    got, _ = spm.generate(prompt, max_new_tokens=6,
                          sampling=SamplingConfig(temperature=0.0))
    assert spm.last_prefill_mode == "ring"
    assert got == want
