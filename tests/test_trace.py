"""Request-scoped tracing plane (ISSUE 13): timeline store semantics,
trace-id propagation router -> replica -> engine, timeline completeness
for a preempted + replayed request, SLO exemplars, and the flight
recorder dumping on an injected wedge."""
import asyncio
import glob
import json
import os
import threading

import jax.numpy as jnp
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.obs import TIMELINES, TRACE_HEADER, TimelineStore
from cake_tpu.obs.metrics import MetricsRegistry
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import ServeEngine
from cake_tpu.serve import faults
from cake_tpu.serve.flight import FlightRecorder

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16

P_A = [3, 17, 42, 99, 7]
P_B = [100, 2, 5, 9, 11, 40]


# ---------------------------------------------------------------------------
# units: no model required
# ---------------------------------------------------------------------------


def test_timeline_store_ring_and_event_cap():
    st = TimelineStore(capacity=2, max_events=3)
    st.begin("a")
    st.begin("b")
    st.begin("c")                       # evicts a (ring of 2)
    assert st.get("a") is None and st.ids() == ["b", "c"]
    for _ in range(5):
        st.event("b", "decode", bucket=1)
    st.event("b", "finish", outcome="ok")   # terminal bypasses the cap
    tl = st.get("b")
    assert len(tl["events"]) == 4
    assert tl["events"][-1]["kind"] == "finish"
    assert tl["dropped"] == 2
    # monotonic offsets
    ts = [e["t_ms"] for e in tl["events"]]
    assert ts == sorted(ts)


def test_timeline_alias_and_unknown_ids():
    st = TimelineStore(capacity=4)
    st.begin("trace-1", tier="router")
    st.alias("chatcmpl-9", "trace-1")
    st.event("chatcmpl-9", "received")      # alias records into trace-1
    assert st.get("chatcmpl-9")["request_id"] == "trace-1"
    assert st.get("trace-1")["tier"] == "router"
    st.event("never-begun", "received")     # unknown id: silent no-op
    assert st.get("never-begun") is None
    with pytest.raises(ValueError):
        st.event("trace-1", "not_a_kind")   # vocabulary is closed


def test_timeline_chrome_export_shape():
    st = TimelineStore(capacity=2)
    st.begin("r")
    st.event("r", "enqueue", depth=3)
    trace = st.to_chrome("r")
    (ev,) = trace["traceEvents"]
    assert ev["ph"] == "i" and ev["name"] == "enqueue"
    assert ev["args"]["depth"] == 3 and ev["args"]["request_id"] == "r"
    assert st.to_chrome("missing") is None


def test_histogram_exemplars_per_bucket():
    reg = MetricsRegistry()
    h = reg.histogram("cake_test_ex_seconds", "t", labelnames=("outcome",),
                      buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="req-fast", outcome="ok")
    h.observe(0.5, exemplar="req-mid", outcome="ok")
    h.observe(0.6, exemplar="req-mid2", outcome="ok")   # last wins
    h.observe(5.0, exemplar="req-slow", outcome="ok")
    ex = h.exemplars(outcome="ok")
    assert ex["0.1"]["exemplar"] == "req-fast"
    assert ex["1"]["exemplar"] == "req-mid2"
    assert ex["+Inf"]["exemplar"] == "req-slow"
    assert h.exemplars(outcome="error") == {}
    h.clear()
    assert h.exemplars(outcome="ok") == {}


def test_flight_recorder_ring_and_dump(tmp_path, monkeypatch):
    fr = FlightRecorder(capacity=3)
    for i in range(5):
        fr.record(occupancy=i)
    snap = fr.snapshot()
    assert [r["occupancy"] for r in snap] == [2, 3, 4]
    assert [r["seq"] for r in snap] == [3, 4, 5]
    monkeypatch.delenv("CAKE_TRACE_DIR", raising=False)
    assert fr.dump("wedge") is None         # no trace dir: no file
    monkeypatch.setenv("CAKE_TRACE_DIR", str(tmp_path))
    path = fr.dump("down", extra={"last_failure": {"kind": "oom"}})
    with open(path) as f:
        body = json.load(f)
    assert body["reason"] == "down"
    assert len(body["iterations"]) == 3
    assert body["last_failure"]["kind"] == "oom"


# ---------------------------------------------------------------------------
# engine + API: adoption, completeness, SLO exemplars
# ---------------------------------------------------------------------------


class TinyTok:
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:24] \
            or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = TextModel(tiny_config("llama"), dtype=jnp.float32,
                           max_cache_len=CTX)
        _MODEL.tokenizer = TinyTok()
    return _MODEL


@pytest.fixture(scope="module")
def model():
    return _model()


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_api_adopts_trace_header_into_engine_timeline(model):
    """The replica API adopts X-Cake-Request-Id as THE request id: the
    engine's lifecycle events land on it, /api/v1/requests resolves it
    (and the completion-id alias), the response echoes it, and the SLO
    endpoint's exemplars point at it."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import ApiState, create_app

    engine = ServeEngine(model, slots=2, max_queue=8, ctx_len=CTX,
                         prefill_chunk=CHUNK)
    state = ApiState(model=model, tokenizer=model.tokenizer,
                     model_id="trace-test")
    state.engine = engine
    rid = "trace-feedc0ffee123456"

    async def drive():
        client = TestClient(TestServer(create_app(state)))
        await client.start_server()
        try:
            r = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user",
                                    "content": "hello trace"}],
                      "max_tokens": 5, "temperature": 0.0},
                headers={TRACE_HEADER: rid})
            assert r.status == 200, await r.text()
            assert r.headers.get(TRACE_HEADER) == rid
            cid = (await r.json())["id"]
            t1 = await client.get(f"/api/v1/requests/{rid}")
            assert t1.status == 200
            tl = await t1.json()
            t2 = await client.get(f"/api/v1/requests/{cid}")
            assert t2.status == 200          # completion-id alias
            assert (await t2.json())["request_id"] == rid
            perf = await client.get(f"/api/v1/requests/{rid}"
                                    "?format=perfetto")
            assert perf.status == 200
            assert (await perf.json())["traceEvents"]
            t404 = await client.get("/api/v1/requests/nope")
            assert t404.status == 404
            idx = await client.get("/api/v1/requests")
            assert rid in (await idx.json())["requests"]
            slo = await client.get("/api/v1/slo")
            return tl, await slo.json()
        finally:
            await client.close()

    try:
        tl, slo = _run(drive())
    finally:
        engine.close()
    kinds = [e["kind"] for e in tl["events"]]
    for k in ("received", "enqueue", "admit", "prefill_chunk",
              "prefill_done", "first_token", "decode", "finish"):
        assert k in kinds, (k, kinds)
    assert kinds.index("enqueue") < kinds.index("admit") \
        < kinds.index("prefill_done") < kinds.index("first_token")
    finish = [e for e in tl["events"] if e["kind"] == "finish"][0]
    assert finish["outcome"] == "ok" and finish["tokens"] > 0
    assert finish["e2e_ms"] >= finish["ttft_ms"] > 0
    exemplars = [ex["exemplar"]
                 for hist in slo.values() for series in hist["series"]
                 for ex in series["exemplars"].values()]
    assert rid in exemplars


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_timeline_preempted_and_replayed_request_is_complete(model):
    """A request preempted under paged-pool pressure (recompute mode)
    keeps one coherent timeline: enqueue -> admit -> prefill ->
    first_token -> preempt -> resume -> replay -> finish, with the
    output still bit-identical to the sequential path."""
    ref_a = model.generate(P_A, max_new_tokens=60, sampling=GREEDY)[0]
    ref_b = model.generate(P_B, max_new_tokens=60, sampling=GREEDY)[0]
    eng = ServeEngine(model, slots=2, max_queue=8, ctx_len=CTX,
                      prefill_chunk=CHUNK, prefix_cache_mb=0,
                      kv_blocks=12, kv_block_tokens=8,
                      preempt_mode="recompute")
    try:
        ra = eng.submit(P_A, max_new_tokens=60, sampling=GREEDY)
        rb = eng.submit(P_B, max_new_tokens=60, sampling=GREEDY)
        assert ra.wait(600) and rb.wait(600)
        assert "error" not in ra.result and "error" not in rb.result
        assert ra.result["tokens"] == ref_a
        assert rb.result["tokens"] == ref_b
    finally:
        eng.close()
    kinds_by_req = {rid: [e["kind"] for e in TIMELINES.get(rid)["events"]]
                    for rid in (ra.id, rb.id)}
    preempted = [ks for ks in kinds_by_req.values() if "preempt" in ks]
    assert preempted, f"pool never preempted: {kinds_by_req}"
    ks = preempted[0]
    for k in ("enqueue", "admit", "first_token", "preempt", "resume",
              "replay", "finish"):
        assert k in ks, (k, ks)
    assert ks.index("preempt") < ks.index("resume") < ks.index("replay")
    assert ks[-1] == "finish"


def test_cancelled_request_records_error_outcome(model):
    from cake_tpu.obs import SERVE_E2E_SECONDS
    eng = ServeEngine(model, slots=1, max_queue=4, ctx_len=CTX,
                      prefill_chunk=CHUNK)
    before = SERVE_E2E_SECONDS.count(outcome="cancelled")
    try:
        req = eng.submit(P_A, max_new_tokens=200, sampling=GREEDY)
        # wait until it is actually decoding, then cancel
        deadline = 60.0
        while not req.tokens and deadline > 0 and not req.done.is_set():
            threading.Event().wait(0.01)
            deadline -= 0.01
        req.cancel()
        assert req.wait(60)
    finally:
        eng.close()
    kinds = [e["kind"] for e in TIMELINES.get(req.id)["events"]]
    assert kinds[-1] == "finish"
    finish = [e for e in TIMELINES.get(req.id)["events"]
              if e["kind"] == "finish"][0]
    assert finish["outcome"] == "cancelled"
    assert SERVE_E2E_SECONDS.count(outcome="cancelled") > before


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    yield
    faults.clear()


def test_flight_recorder_dumps_on_injected_wedge(model, tmp_path,
                                                 monkeypatch):
    """A stalled dispatch (CAKE_SERVE_FAULT_PLAN stall) past the wedge
    watchdog limit must leave a flight-recorder dump in CAKE_TRACE_DIR
    carrying the last iterations' records — the black box the operator
    reads after the process is killed."""
    monkeypatch.setenv("CAKE_TRACE_DIR", str(tmp_path))
    faults.install("stall_on_step=2;stall_step_ms=600")
    eng = ServeEngine(model, slots=1, max_queue=4, ctx_len=CTX,
                      prefill_chunk=CHUNK, step_watchdog_s=0.1)
    try:
        req = eng.submit(P_A, max_new_tokens=8, sampling=GREEDY)
        assert req.wait(600)
        assert "error" not in req.result
        # the stall returned, so the wedge flag cleared (gray
        # semantics) — but the dump must have been written while the
        # dispatch was stuck
        deadline = 30.0
        while deadline > 0:
            dumps = glob.glob(os.path.join(str(tmp_path),
                                           "cake-flight-*-wedge.json"))
            if dumps:
                break
            threading.Event().wait(0.05)
            deadline -= 0.05
        assert dumps, "watchdog never dumped the flight recorder"
        with open(dumps[0]) as f:
            body = json.load(f)
        assert body["reason"] == "wedge"
        assert body["iterations"], "dump carries no iteration records"
        rec = body["iterations"][-1]
        assert {"seq", "t", "occupancy", "bucket", "dispatch_ms",
                "queued"} <= set(rec)
        assert eng.supervisor.wedge_count >= 1
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# router tier: propagation + stitching over a fake replica
# ---------------------------------------------------------------------------


class _FakeReplica:
    """Minimal replica: records the trace header it received, serves a
    canned completion, and answers /api/v1/requests/<id> with a
    replica-tier timeline for ids it saw."""

    def __init__(self, name="r0"):
        self.name = name
        self.seen_headers: list = []
        self.server = None

    def app(self):
        from aiohttp import web

        async def chat(request):
            self.seen_headers.append(request.headers.get(TRACE_HEADER))
            return web.json_response(
                {"id": "chatcmpl-fake", "object": "chat.completion",
                 "choices": [{"index": 0, "message":
                              {"role": "assistant", "content": "hi"},
                              "finish_reason": "stop"}]})

        async def timeline(request):
            rid = request.match_info["rid"]
            if rid not in self.seen_headers:
                return web.json_response({"error": "unknown"}, status=404)
            return web.json_response(
                {"request_id": rid, "tier": "replica", "start_unix": 0.0,
                 "events": [{"t_ms": 0.0, "kind": "received"},
                            {"t_ms": 1.0, "kind": "finish",
                             "outcome": "ok"}],
                 "dropped": 0})

        async def health(request):
            return web.json_response({"engine": {
                "alive": True, "slots": 2, "queue_depth": 0}})

        from aiohttp import web as w
        app = w.Application()
        app.router.add_post("/v1/chat/completions", chat)
        app.router.add_get("/api/v1/requests/{rid}", timeline)
        app.router.add_get("/health", health)
        return app

    async def start(self):
        from aiohttp.test_utils import TestServer
        self.server = TestServer(self.app())
        await self.server.start_server()
        return str(self.server.make_url("")).rstrip("/")

    async def stop(self):
        if self.server is not None:
            await self.server.close()


def test_router_injects_trace_id_and_stitches_tiers():
    """cake route mints a trace id, injects it into the replica attempt,
    echoes it on the response, and /api/v1/requests/<id> on the router
    returns the stitched router + replica timeline."""
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.fleet.registry import MembershipPolicy, ReplicaRegistry
    from cake_tpu.fleet.router import FleetRouter, create_router_app

    rep = _FakeReplica()
    registry = ReplicaRegistry(MembershipPolicy())

    async def drive():
        url = await rep.start()
        registry.add(rep.name, url)
        router = FleetRouter(registry, retries=1, backoff_s=0.001,
                             probe_s=30.0, hedge_ms=0.0)
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()
        try:
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hello"}]})
            assert r.status == 200, await r.text()
            rid = r.headers.get(TRACE_HEADER)
            assert rid and rid.startswith("trace-")
            # the replica received the SAME id the client got back
            assert rep.seen_headers == [rid]
            st = await client.get(f"/api/v1/requests/{rid}")
            assert st.status == 200
            stitched = await st.json()
            # a client-supplied id is adopted, not replaced
            r2 = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "again"}]},
                headers={TRACE_HEADER: "trace-client-chosen"})
            assert r2.headers.get(TRACE_HEADER) == "trace-client-chosen"
            assert rep.seen_headers[-1] == "trace-client-chosen"
            missing = await client.get("/api/v1/requests/trace-unknown")
            assert missing.status == 404
            return stitched
        finally:
            await client.close()
            await rep.stop()

    stitched = _run(drive())
    tiers = {t["tier"]: t for t in stitched["tiers"]}
    assert set(tiers) == {"router", "replica"}
    router_kinds = [e["kind"] for e in tiers["router"]["events"]]
    assert ["route", "attempt", "done"] == router_kinds
    attempt = [e for e in tiers["router"]["events"]
               if e["kind"] == "attempt"][0]
    assert attempt["replica"] == rep.name and attempt["status"] == 200
    assert tiers["replica"]["replica"] == rep.name
