"""Fleet telemetry plane (ISSUE 16 acceptance pins).

Units drive the pure-math layer with a FAKE CLOCK — no sleeps: Series
increase/rate (reset-safe), the Prometheus text parser, bucket-wise
histogram merging (merged p95 within 10% of the true pooled percentile
on synthetic data — the acceptance bar), multi-window burn rates,
capacity headroom, and the MAD outlier rule, each pinned to hand-computed
values through FleetTelemetry.ingest(). The HTTP-level test probes a
real router app over fake replicas serving canned /metrics text and
pins the stale-mirror semantics: a dead replica's mirrored gauges are
RETRACTED (labelsets deleted, stale companion set) and its frozen
numbers never enter the rollup. `cake top`'s renderer is pure
text-from-dict and is pinned over a canned body.
"""
import asyncio
import json
import math

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from cake_tpu.fleet import (FleetRouter, MembershipPolicy, ReplicaRegistry,
                            create_router_app)
from cake_tpu.fleet.telemetry import (FleetTelemetry, _HistRing,
                                      bucket_quantile, detect_outliers,
                                      merge_histograms, parse_prom_text,
                                      replica_signals, ttft_over_slo)
from cake_tpu.fleet.top import render_screen
from cake_tpu.obs import Series, SeriesBank

INF = float("inf")


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _policy(**kw):
    base = dict(eject_fails=3, err_window=16, err_rate=0.5,
                degraded_ttft_ms=0.0, eject_s=0.05, replica_inflight=0)
    base.update(kw)
    return MembershipPolicy(**base)


def _le_str(e):
    return "+Inf" if e == INF else repr(float(e))


def prom_text(*, ttft=None, itl=None, e2e=None,
              edges=(0.1, 0.25, 0.5, 1.0, INF),
              ok=0.0, err=0.0, tokens=0.0, queue_depth=0.0,
              slots_busy=None, kv_free=None, kv_used=None,
              spec=(0.0, 0.0)) -> str:
    """Synthetic replica /metrics text with exactly the families the
    rollup consumes. ttft/itl/e2e are CUMULATIVE bucket vectors over
    `edges` (outcome=ok)."""
    lines = ["# HELP synthetic fixture", "# TYPE whatever counter"]
    for sem, cum in (("ttft", ttft), ("itl", itl), ("e2e", e2e)):
        if cum is None:
            continue
        for e, c in zip(edges, cum):
            lines.append(f'cake_serve_{sem}_seconds_bucket'
                         f'{{outcome="ok",le="{_le_str(e)}"}} {c}')
        lines.append(f'cake_serve_{sem}_seconds_sum{{outcome="ok"}} 1.0')
    lines.append(f'cake_serve_e2e_seconds_count{{outcome="ok"}} {ok}')
    if err:
        lines.append(f'cake_serve_e2e_seconds_count{{outcome="error"}} {err}')
    lines.append(f'cake_generated_tokens_total{{path="serve"}} {tokens}')
    lines.append(f"cake_serve_queue_depth {queue_depth}")
    if slots_busy is not None:
        lines.append(f"cake_serve_slots_busy {slots_busy}")
    if kv_free is not None:
        lines.append(f"cake_serve_kv_blocks_free {kv_free}")
    if kv_used is not None:
        lines.append(f"cake_serve_kv_blocks_used {kv_used}")
    lines.append(f"cake_serve_spec_proposed_total {spec[0]}")
    lines.append(f"cake_serve_spec_accepted_total {spec[1]}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# series rings
# ---------------------------------------------------------------------------


def test_series_increase_rate_and_reset():
    clk = FakeClock()
    s = Series("x", window_s=120.0, clock=clk)
    s.record(0.0, t=0.0)
    s.record(100.0, t=10.0)
    assert s.increase(120.0) == 100.0
    assert s.rate(120.0) == 10.0            # 100 over a 10s span
    # counter reset mid-window (replica restart): the drop contributes
    # nothing, counting resumes from the new baseline
    s.record(10.0, t=20.0)
    s.record(30.0, t=30.0)
    assert s.increase(120.0) == 120.0       # 100 + 0 + 20
    assert s.latest() == 30.0 and len(s) == 4


def test_series_window_prunes_by_age():
    clk = FakeClock()
    s = Series("x", window_s=50.0, clock=clk)
    for i in range(10):
        s.record(float(i), t=i * 10.0)
    # samples older than t=90-50 are pruned on append
    assert all(t >= 40.0 for t, _ in s.samples())
    # sub-window read narrows further
    assert s.values(20.0) == [7.0, 8.0, 9.0]


def test_series_bank_namespacing_and_drop():
    bank = SeriesBank(60.0, clock=FakeClock())
    bank.record("req/r0", 1.0, t=0.0)
    bank.record("req/r1", 2.0, t=0.0)
    bank.record("fleet/headroom", 3.0, t=0.0)
    assert bank.names() == ["fleet/headroom", "req/r0", "req/r1"]
    assert bank.get("req/r0").latest() == 1.0
    bank.drop("req/")
    assert bank.names() == ["fleet/headroom"]
    assert bank.get("req/r0") is None


# ---------------------------------------------------------------------------
# prometheus text parsing
# ---------------------------------------------------------------------------


def test_parse_prom_text_labels_prefix_and_garbage():
    text = (
        '# HELP cake_x stuff\n'
        'cake_x_total{a="1",b="with,comma",c="q\\"uote"} 3\n'
        'cake_bare 2.5\n'
        'other_family_total{a="1"} 9\n'        # foreign prefix: skipped
        'cake_broken{unclosed 1\n'             # tolerated, skipped
        'cake_nan_free notanumber\n')
    got = parse_prom_text(text)
    assert ("cake_x_total",
            {"a": "1", "b": "with,comma", "c": 'q"uote'}, 3.0) in got
    assert ("cake_bare", {}, 2.5) in got
    assert len(got) == 2


def test_replica_signals_reduction():
    text = prom_text(ttft=(5, 8, 9, 10, 10), ok=9.0, err=1.0,
                     tokens=1234.0, queue_depth=3, slots_busy=2,
                     kv_free=60, kv_used=20, spec=(100, 80))
    sig = replica_signals(text)
    assert sig["hist"]["ttft"] == ((0.1, 0.25, 0.5, 1.0, INF),
                                   (5.0, 8.0, 9.0, 10.0, 10.0))
    assert sig["requests"] == 10.0 and sig["errors"] == 1.0
    assert sig["tokens"] == 1234.0
    assert sig["queue_depth"] == 3.0 and sig["slots_busy"] == 2.0
    assert sig["kv_free"] == 60.0 and sig["kv_used"] == 20.0
    assert sig["spec_proposed"] == 100.0 and sig["spec_accepted"] == 80.0


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------


def _bucketize(samples, edges):
    """Cumulative bucket vector of `samples` over `edges` (le
    semantics), the shape a replica's /metrics exposes."""
    cum = []
    for e in edges:
        cum.append(float(sum(1 for s in samples if s <= e)))
    return tuple(cum)


def test_merged_p95_within_10pct_of_true_percentile():
    """The acceptance bar: merge three replicas' bucketized latency
    histograms and the interpolated fleet p95 must sit within 10% of
    the true percentile of the pooled samples."""
    import random
    rng = random.Random(16)
    edges = tuple(round(0.05 * i, 2) for i in range(1, 25)) + (INF,)
    per_replica = [
        [rng.uniform(0.10, 0.50) for _ in range(400)],
        [rng.uniform(0.20, 0.80) for _ in range(300)],
        [rng.uniform(0.40, 1.00) for _ in range(300)],
    ]
    merged = merge_histograms(
        [(edges, _bucketize(s, edges)) for s in per_replica])
    assert merged is not None
    got = bucket_quantile(*merged, 0.95)
    pooled = sorted(x for s in per_replica for x in s)
    true_p95 = pooled[math.ceil(0.95 * len(pooled)) - 1]
    assert abs(got - true_p95) / true_p95 < 0.10, (got, true_p95)
    # count conservation: the +Inf bucket is the pooled sample count
    assert merged[1][-1] == float(len(pooled))


def test_merge_skips_mismatched_edges():
    a = ((0.1, 1.0, INF), (1.0, 2.0, 2.0))
    b = ((0.2, 1.0, INF), (5.0, 5.0, 5.0))     # different boundaries
    c = ((0.1, 1.0, INF), (0.0, 1.0, 3.0))
    edges, counts = merge_histograms([a, b, c])
    assert edges == (0.1, 1.0, INF)
    assert counts == (1.0, 3.0, 5.0)           # b skipped, not summed
    assert merge_histograms([]) is None


def test_bucket_quantile_interpolation_and_inf_clamp():
    edges = (1.0, 2.0, 4.0, INF)
    # 10 obs <=1, 10 in (1,2], none in (2,4], 5 beyond the last edge
    cum = (10.0, 20.0, 20.0, 25.0)
    assert bucket_quantile(edges, cum, 0.5) == 1.25   # 12.5th obs
    assert bucket_quantile(edges, cum, 0.95) == 4.0   # +Inf clamps
    assert bucket_quantile(edges, (0.0,) * 4, 0.5) is None
    assert bucket_quantile((), (), 0.5) is None


def test_ttft_over_slo_bucket_resolution():
    edges = (0.1, 0.5, 1.0, INF)
    cum = (10.0, 60.0, 90.0, 100.0)
    assert ttft_over_slo(edges, cum, 0.5) == 40.0     # exact boundary
    assert ttft_over_slo(edges, cum, 0.6) == 10.0     # straddling: good
    assert ttft_over_slo(edges, cum, 5.0) == 0.0
    assert ttft_over_slo((), (), 0.5) == 0.0


def test_hist_ring_window_delta_and_reset():
    clk = FakeClock()
    ring = _HistRing(window_s=100.0, max_samples=64, clock=clk)
    edges = (0.5, INF)
    assert ring.window_delta(100.0) is None
    ring.record(edges, (10.0, 20.0), t=0.0)
    # single sample: cumulative counts ARE the delta (implicit zero)
    assert ring.window_delta(100.0) == (edges, (10.0, 20.0))
    ring.record(edges, (15.0, 30.0), t=10.0)
    assert ring.window_delta(100.0) == (edges, (5.0, 10.0))
    # replica restart: totals drop, baseline restarts from zero
    ring.record(edges, (2.0, 4.0), t=20.0)
    assert ring.window_delta(100.0) == (edges, (7.0, 14.0))
    # boundary change (rolling upgrade): incomparable, start over
    ring.record((0.9, INF), (1.0, 1.0), t=30.0)
    assert ring.edges == (0.9, INF)
    assert ring.window_delta(100.0) == ((0.9, INF), (1.0, 1.0))


# ---------------------------------------------------------------------------
# outlier rule
# ---------------------------------------------------------------------------


def test_outlier_divergent_ttft_flagged_jitter_not():
    base = {f"r{i}": {"ttft_p95_s": 0.100 + 0.001 * i, "err_rate": 0.0}
            for i in range(4)}
    assert detect_outliers(base, k=3.0, min_n=3) == {}
    bad = dict(base, r9={"ttft_p95_s": 1.5, "err_rate": 0.0})
    assert detect_outliers(bad, k=3.0, min_n=3) == {"r9": "ttft_p95"}


def test_outlier_err_rate_and_min_n():
    stats = {"r0": {"ttft_p95_s": None, "err_rate": 0.00},
             "r1": {"ttft_p95_s": None, "err_rate": 0.01},
             "r2": {"ttft_p95_s": None, "err_rate": 0.50}}
    assert detect_outliers(stats, k=3.0, min_n=3) == {"r2": "err_rate"}
    # below min_n the median cannot say which side is wrong
    two = {k: stats[k] for k in ("r0", "r2")}
    assert detect_outliers(two, k=3.0, min_n=3) == {}


# ---------------------------------------------------------------------------
# FleetTelemetry.ingest — fake clock, hand-computed pins
# ---------------------------------------------------------------------------


def _plane(n=1, *, clock=None, slots=4, **kw):
    reg = ReplicaRegistry(_policy())
    for i in range(n):
        rep = reg.add(f"t{i}", f"http://h:{i + 1}")
        rep.observe_health(200, {"engine": {"alive": True, "slots": slots,
                                            "queue_depth": 1}})
    base = dict(fast_window_s=300.0, slow_window_s=3600.0,
                slo_ttft_ms=2000.0, slo_err_rate=0.01,
                outlier_k=3.0, outlier_min_n=3, ring=256)
    base.update(kw)
    return reg, FleetTelemetry(reg, clock=clock or FakeClock(), **base)


def test_ingest_burn_rate_pinned():
    clk = FakeClock()
    reg, tel = _plane(1, clock=clk)
    tel.ingest({"t0": prom_text(ok=100.0)}, t=0.0)
    body = tel.ingest({"t0": prom_text(ok=195.0, err=5.0)}, t=60.0)
    # 100 new requests, 5 bad -> 5% bad / 1% budget = 5x in both windows
    assert body["burn_rate"] == {"fast": 5.0, "slow": 5.0}
    from cake_tpu.obs import FLEET_SLO_BURN_RATE
    assert FLEET_SLO_BURN_RATE.value(window="fast") == 5.0
    assert body["replicas"]["t0"]["err_rate"] == 0.05


def test_ingest_burn_counts_ttft_over_objective():
    clk = FakeClock()
    reg, tel = _plane(1, clock=clk, slo_ttft_ms=500.0)
    edges = (0.1, 0.5, 1.0, INF)
    tel.ingest({"t0": prom_text(ttft=(0, 0, 0, 0), edges=edges)}, t=0.0)
    # 100 requests, none outcome=error, but 20 finished past 0.5s TTFT
    body = tel.ingest(
        {"t0": prom_text(ttft=(50, 80, 95, 100), edges=edges, ok=100.0)},
        t=60.0)
    # bad = 100 - cum(0.5) = 20 -> 20% / 1% budget
    assert body["burn_rate"]["fast"] == 20.0


def test_ingest_headroom_pinned():
    clk = FakeClock()
    reg, tel = _plane(1, clock=clk, slots=4)
    tel.ingest({"t0": prom_text(tokens=0.0, slots_busy=2,
                                kv_free=50, kv_used=50)}, t=0.0)
    body = tel.ingest({"t0": prom_text(tokens=1000.0, slots_busy=2,
                                       kv_free=50, kv_used=50)}, t=100.0)
    row = body["replicas"]["t0"]
    # 1000 tok over 100s = 10 tok/s on avg 2 busy slots -> 5 tok/s/slot;
    # 2 free slots x 0.5 KV-free fraction -> 5 tok/s headroom
    assert row["tokens_per_s"] == 10.0
    assert row["headroom_tokens_per_s"] == 5.0
    assert body["headroom_tokens_per_s"] == 5.0
    from cake_tpu.obs import FLEET_HEADROOM_TOKENS
    assert FLEET_HEADROOM_TOKENS.value() == 5.0
    # headroom persists after the burst ends (learned per-slot rate
    # applied to the now-idle replica's 4 free slots + full KV)
    body = tel.ingest({"t0": prom_text(tokens=1000.0, slots_busy=0,
                                       kv_free=100, kv_used=0)}, t=110.0)
    assert body["headroom_tokens_per_s"] > 5.0


def test_ingest_accept_rate_and_spec_counters():
    clk = FakeClock()
    reg, tel = _plane(1, clock=clk)
    tel.ingest({"t0": prom_text(spec=(0, 0))}, t=0.0)
    body = tel.ingest({"t0": prom_text(spec=(100, 75))}, t=60.0)
    assert body["replicas"]["t0"]["accept_rate"] == 0.75


def test_ingest_merged_percentiles_and_mismatch_counter():
    clk = FakeClock()
    reg, tel = _plane(3, clock=clk)
    edges = (0.1, 0.5, 1.0, INF)
    odd = (0.2, 0.5, 1.0, INF)                 # t2: mismatched boundaries
    tel.ingest({"t0": prom_text(ttft=(0, 0, 0, 0), edges=edges),
                "t1": prom_text(ttft=(0, 0, 0, 0), edges=edges),
                "t2": prom_text(ttft=(0, 0, 0, 0), edges=odd)}, t=0.0)
    body = tel.ingest(
        {"t0": prom_text(ttft=(10, 20, 20, 20), edges=edges),
         "t1": prom_text(ttft=(0, 20, 40, 40), edges=edges),
         "t2": prom_text(ttft=(5, 5, 5, 5), edges=odd)}, t=60.0)
    ttft = body["percentiles"]["ttft"]
    # merged deltas: (10, 40, 60, 60) over the shared edges; t2 skipped
    assert ttft["count"] == 60.0
    assert body["mismatched_histograms_skipped"] == 1
    assert ttft["p50"] == bucket_quantile(edges,
                                          (10.0, 40.0, 60.0, 60.0), 0.5)


def test_ingest_stale_excluded_and_flagged_as_outlier():
    clk = FakeClock()
    reg, tel = _plane(3, clock=clk)
    good = prom_text(ok=50.0, queue_depth=1)
    tel.ingest({"t0": good, "t1": good, "t2": good}, t=0.0)
    # t2 dies: scrape fails this cycle
    body = tel.ingest({"t0": prom_text(ok=60.0, queue_depth=1),
                       "t1": prom_text(ok=60.0, queue_depth=1),
                       "t2": None}, t=30.0)
    assert body["stale"] == ["t2"]
    assert body["outliers"]["t2"] == "stale"
    row = body["replicas"]["t2"]
    assert row["stale"] and row["outlier"]
    assert row["outlier_reason"] == "stale"
    # the membership view carries the advisory flag without ejecting
    (rep,) = [r for r in reg.replicas() if r.name == "t2"]
    snap = rep.snapshot()
    assert snap["outlier"] and snap["outlier_reason"] == "stale"
    assert rep.routable()                      # advisory, never membership
    # fleet queue depth sums LIVE replicas only
    assert body["fleet_queue_depth"] == 2


def test_ingest_excludes_ejected_replica_from_headroom():
    """An EJECTED replica leaves the capacity math even while its
    scrape still answers (the asymmetric-partition shape: probe path
    alive, data path dead) — otherwise the autoscaler sees phantom
    headroom the router cannot actually route to, and a heal would
    double-count the capacity the moment it readmits."""
    clk = FakeClock()
    reg, tel = _plane(2, clock=clk, slots=4)

    def text(tok):
        return prom_text(tokens=tok, slots_busy=2, kv_free=50, kv_used=50)

    tel.ingest({"t0": text(0.0), "t1": text(0.0)}, t=0.0)
    body = tel.ingest({"t0": text(1000.0), "t1": text(1000.0)}, t=100.0)
    both = body["headroom_tokens_per_s"]
    assert both > 0
    assert body["replicas"]["t1"]["headroom_tokens_per_s"] > 0
    # t1 ejects on DATA evidence; its scrape keeps answering
    (rep,) = [r for r in reg.replicas() if r.name == "t1"]
    for _ in range(3):
        rep.record_result(False, transport=True)
    body = tel.ingest({"t0": text(2000.0), "t1": text(2000.0)}, t=200.0)
    row = body["replicas"]["t1"]
    assert row["eject_evidence"] == "data"
    assert row["partition_s"] is not None      # open episode, visible
    assert row["headroom_tokens_per_s"] == 0.0
    assert (body["headroom_tokens_per_s"]
            == body["replicas"]["t0"]["headroom_tokens_per_s"])
    # heal via the data-path trial: capacity returns exactly once
    import time as _time
    _time.sleep(0.06)                          # eject_s=0.05 hold
    rep.observe_health(200, {"engine": {"alive": True, "slots": 4}})
    trial = rep.try_acquire()
    rep.record_result(True, 5.0, lease=trial)
    rep.release(trial)
    body = tel.ingest({"t0": text(3000.0), "t1": text(3000.0)}, t=300.0)
    assert body["replicas"]["t1"]["headroom_tokens_per_s"] > 0
    assert body["headroom_tokens_per_s"] == pytest.approx(
        body["replicas"]["t0"]["headroom_tokens_per_s"]
        + body["replicas"]["t1"]["headroom_tokens_per_s"])


def test_ingest_series_and_overhead_exposed():
    clk = FakeClock()
    reg, tel = _plane(1, clock=clk)
    tel.ingest({"t0": prom_text(ok=10.0)}, t=0.0)
    body = tel.ingest({"t0": prom_text(ok=20.0)}, t=30.0)
    assert body["cycles"] == 2
    assert set(body["series"]) >= {"fleet/headroom", "fleet/burn_fast",
                                   "fleet/burn_slow", "fleet/queue_depth"}
    ages = [a for a, _ in body["series"]["fleet/burn_fast"]]
    assert ages == [30.0, 0.0]                 # ages, not raw clocks
    assert body["rollup_ms"]["mean"] >= 0.0
    assert body["rollup_ms"]["max"] >= body["rollup_ms"]["last"]


def test_snapshot_before_first_cycle_is_typed_empty():
    reg, tel = _plane(1)
    body = tel.snapshot()
    assert body["cycles"] == 0 and body["replicas"] == {}
    assert body["burn_rate"] == {"fast": 0.0, "slow": 0.0}
    assert body["slo"]["ttft_ms"] == 2000.0
    json.dumps(body)                           # endpoint-serializable


# ---------------------------------------------------------------------------
# HTTP: router endpoint + stale-mirror retraction over fake replicas
# ---------------------------------------------------------------------------


class FakeTelemReplica:
    """Canned `cake serve` stand-in for the telemetry path: /health with
    an engine block and /metrics with mutable synthetic exposition."""

    def __init__(self, name):
        self.name = name
        self.metrics_text = prom_text()
        self.server = None

    def app(self):
        async def health(request):
            return web.json_response({"engine": {
                "alive": True, "slots": 4, "queue_depth": 2,
                "kv_pool": {"occupancy": 0.25, "blocks": 100,
                            "blocks_free": 75}}})

        async def metrics(request):
            return web.Response(text=self.metrics_text)

        app = web.Application()
        app.router.add_get("/health", health)
        app.router.add_get("/metrics", metrics)
        return app

    async def start(self):
        self.server = TestServer(self.app())
        await self.server.start_server()
        return str(self.server.make_url(""))

    async def stop(self):
        if self.server is not None:
            await self.server.close()
            self.server = None


def test_router_telemetry_endpoint_and_stale_mirror_retraction():
    fakes = [FakeTelemReplica("tm0"), FakeTelemReplica("tm1")]
    registry = ReplicaRegistry(_policy())

    async def run():
        for f in fakes:
            registry.add(f.name, await f.start())
        router = FleetRouter(registry, retries=2, backoff_s=0.001,
                             probe_s=30.0, hedge_ms=0.0)
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()     # on_startup probed once already
        try:
            edges = (0.1, 0.5, 1.0, INF)
            fakes[0].metrics_text = prom_text(
                ttft=(10, 18, 20, 20), edges=edges, ok=20.0,
                tokens=100.0, queue_depth=2, slots_busy=1,
                kv_free=75, kv_used=25)
            fakes[1].metrics_text = prom_text(
                ttft=(5, 9, 10, 10), edges=edges, ok=10.0,
                tokens=60.0, queue_depth=2, slots_busy=1,
                kv_free=75, kv_used=25)
            await router._probe_once()
            r = await client.get("/api/v1/fleet/telemetry")
            assert r.status == 200
            body = await r.json()
            assert body["cycles"] >= 2
            assert set(body["replicas"]) == {"tm0", "tm1"}
            # merged fleet percentiles cover BOTH replicas' counts
            assert body["percentiles"]["ttft"]["count"] == 30.0
            # mirrored gauges live while the replica is
            m = await (await client.get("/metrics")).text()
            assert 'cake_fleet_replica_queue_depth{replica="tm1"} 2' in m
            assert 'cake_fleet_replica_stale{replica="tm1"} 0' in m

            # tm1 dies; one probe window later it is stale + outlier and
            # its mirrored gauges are RETRACTED, not frozen
            await fakes[1].stop()
            await router._probe_once()
            body = await (await client.get(
                "/api/v1/fleet/telemetry")).json()
            assert "tm1" in body["stale"]
            assert body["outliers"].get("tm1") == "stale"
            m = await (await client.get("/metrics")).text()
            assert 'cake_fleet_replica_queue_depth{replica="tm1"}' not in m
            assert 'cake_fleet_replica_occupancy{replica="tm1"}' not in m
            assert 'cake_fleet_replica_stale{replica="tm1"} 1' in m
            assert 'cake_fleet_replica_outlier{replica="tm1"} 1' in m
            # the LIVE replica's mirror is untouched
            assert 'cake_fleet_replica_queue_depth{replica="tm0"} 2' in m
            # registry removal retracts the whole mirror
            registry.remove("tm1")
            m = await (await client.get("/metrics")).text()
            assert 'replica="tm1"' not in m
        finally:
            await client.close()
            for f in fakes:
                await f.stop()
    asyncio.run(run())


# ---------------------------------------------------------------------------
# cake top renderer
# ---------------------------------------------------------------------------


def test_top_render_screen_plain():
    body = {
        "cycles": 7,
        "slo": {"ttft_ms": 2000.0, "err_rate": 0.01},
        "burn_rate": {"fast": 1.25, "slow": 0.4},
        "headroom_tokens_per_s": 123.4, "sheds_per_s": 0.5,
        "fleet_queue_depth": 3,
        "percentiles": {"ttft": {"p50": 0.2, "p95": 0.9, "p99": 1.4,
                                 "count": 42}},
        "replicas": {
            "r0": {"state": "healthy", "stale": False, "queue_depth": 1,
                   "occupancy": 0.25, "inflight": 2, "ttft_p95_ms": 850.0,
                   "err_rate": 0.02, "tokens_per_s": 55.5,
                   "accept_rate": 0.8, "headroom_tokens_per_s": 100.0,
                   "outlier": False, "outlier_reason": None},
            "r1": {"state": "ejected", "stale": True, "queue_depth": 0,
                   "occupancy": None, "inflight": 0, "ttft_p95_ms": None,
                   "err_rate": None, "tokens_per_s": None,
                   "accept_rate": None, "headroom_tokens_per_s": 0.0,
                   "outlier": True, "outlier_reason": "stale"},
        },
    }
    lines = render_screen(body, "http://router:8100")
    text = "\n".join(lines)
    assert "burn fast 1.25x" in text and "slow 0.40x" in text
    assert "headroom 123 tok/s" in text
    assert "p95 900ms" in text
    r0 = next(ln for ln in lines if ln.startswith("r0"))
    assert "healthy" in r0 and "850" in r0 and "25%" in r0 and "80%" in r0
    r1 = next(ln for ln in lines if ln.startswith("r1"))
    assert "stale" in r1 and "outlier" in r1
    # absent window data renders as dashes, not zeros
    assert " - " in r1 or r1.rstrip().endswith("-") or "  -" in r1
    # no-replica body still renders
    empty = render_screen({"cycles": 0})
    assert any("no replicas" in ln for ln in empty)
