"""Cluster-plane tests without a cluster (mirrors ref tests/protocol.rs
MockWorker + unit_tests/test_{topology,client_worker}.rs): wire round-trips,
auth success/failure, topology parsing, strategy math, discovery on
loopback, weight streaming, and a REAL master<->worker end-to-end
distributed generation over localhost TCP."""
import asyncio
import json
import os
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.cluster import proto
from cake_tpu.cluster.auth import (AuthError, authenticate_as_master,
                                   authenticate_as_worker, cluster_hash)
from cake_tpu.cluster.discovery import WorkerAdvertiser, discover_workers
from cake_tpu.cluster.strategy import (DefaultStrategy, WorkerCapacity,
                                       estimate_layer_bytes)
from cake_tpu.cluster.topology import Topology, expand_layer_specs
from cake_tpu.cluster import transfer
from cake_tpu.models import init_params, tiny_config
from cake_tpu.utils.export import params_to_hf_tensors
from cake_tpu.utils.safetensors_io import TensorStorage, save_safetensors


# ---------------------------------------------------------------- protocol

def test_tensor_roundtrip(rng):
    for dt in (np.float32, np.float16, np.int32, np.uint8):
        a = (rng.standard_normal((3, 5)) * 10).astype(dt)
        b = proto.unpack_tensor(proto.pack_tensor(a))
        np.testing.assert_array_equal(a, b)
    bf = jnp.asarray(rng.standard_normal((2, 7)), jnp.bfloat16)
    b = proto.unpack_tensor(proto.pack_tensor(np.asarray(bf)))
    np.testing.assert_array_equal(np.asarray(bf), b)


def test_frame_roundtrip():
    msg = proto.forward(np.ones((1, 2, 4), np.float32), 5, 2, request_id=9)
    frame = proto.encode_frame(msg)
    # decode via the sync socket reader over a socketpair
    a, b = socket.socketpair()
    a.sendall(frame)
    got = proto.read_frame_sync(b)
    assert got["t"] == "forward" and got["pos0"] == 5 and got["rid"] == 9
    np.testing.assert_array_equal(proto.unpack_tensor(got["x"]),
                                  np.ones((1, 2, 4), np.float32))
    a.close(); b.close()


def test_frame_bad_magic():
    a, b = socket.socketpair()
    a.sendall(b"\x00\x00\x00\x00\x04\x00\x00\x00abcd")
    with pytest.raises(proto.ProtocolError, match="bad magic"):
        proto.read_frame_sync(b)
    a.close(); b.close()


# -------------------------------------------------------------------- auth

def _run_auth(key_master, key_worker):
    async def go():
        server_done = asyncio.get_running_loop().create_future()

        async def on_conn(r, w):
            try:
                await authenticate_as_worker(r, w, key_worker)
                server_done.set_result(True)
            except Exception as e:
                server_done.set_result(e)
            finally:
                w.close()   # wait_closed below needs every transport gone

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        r, w = await asyncio.open_connection("127.0.0.1", port)
        try:
            await authenticate_as_master(r, w, key_master)
            client_ok = True
        except AuthError as e:
            client_ok = e
        sres = await asyncio.wait_for(server_done, 5)
        # close the client transport BEFORE wait_closed: 3.12's wait_closed
        # blocks until every server-side transport is gone
        w.close()
        server.close()
        await asyncio.wait_for(server.wait_closed(), 5)
        return client_ok, sres
    return asyncio.run(go())


def test_auth_success():
    c, s = _run_auth("secret", "secret")
    assert c is True and s is True


def test_auth_wrong_key():
    c, s = _run_auth("secret", "other")
    assert isinstance(c, AuthError) or isinstance(s, AuthError)


def test_cluster_hash_stable():
    assert cluster_hash("k") == cluster_hash("k")
    assert cluster_hash("k") != cluster_hash("k2")
    assert len(cluster_hash("k")) == 8


# ---------------------------------------------------------------- topology

def test_expand_layer_specs():
    assert expand_layer_specs(["model.layers.0-5"]) == [0, 1, 2, 3, 4, 5]
    assert expand_layer_specs(["layers.7", 9]) == [7, 9]
    with pytest.raises(ValueError):
        expand_layer_specs(["nope"])
    with pytest.raises(ValueError):
        expand_layer_specs(["model.layers.5-2"])


def test_topology_yaml(tmp_path):
    p = tmp_path / "topo.yml"
    p.write_text("""
w0:
  host: 10.0.0.2:10128
  layers: ["model.layers.0-13"]
  tflops: 394
w1:
  host: 10.0.0.3:10128
  layers: ["model.layers.14-27"]
  memory_bytes: 17179869184
""")
    t = Topology.from_path(str(p))
    assert t.nodes["w0"].layer_range == (0, 14)
    assert t.nodes["w1"].layer_range == (14, 28)
    assert t.get_node_for_layer(20).name == "w1"
    assert t.get_node_for_layer(99) is None
    assert t.assigned_layers() == set(range(28))
    rt = Topology.from_dict(t.to_dict())
    assert rt.nodes["w0"].layers == t.nodes["w0"].layers


def test_topology_duplicate_layer_rejected():
    t = Topology.from_dict({
        "a": {"host": "x:1", "layers": ["layers.0-3"]},
        "b": {"host": "y:1", "layers": ["layers.3-5"]},
    })
    with pytest.raises(ValueError, match="assigned twice"):
        t.assigned_layers()


# ---------------------------------------------------------------- strategy

def test_strategy_proportional():
    ws = [WorkerCapacity("fast", 0, 300.0), WorkerCapacity("slow", 0, 100.0)]
    plan = DefaultStrategy().assign_layers(ws, list(range(16)), [0] * 16)
    assert len(plan["fast"]) == 12 and len(plan["slow"]) == 4
    assert plan["fast"] == list(range(12))
    assert plan["slow"] == list(range(12, 16))


def test_strategy_memory_cap():
    ws = [WorkerCapacity("small", 10_000, 300.0, backend="tpu"),
          WorkerCapacity("big", 10_000_000, 100.0, backend="tpu")]
    layer_bytes = [4000] * 8
    plan = DefaultStrategy().assign_layers(ws, list(range(8)), layer_bytes)
    # small usable = 9000 -> only 2 layers fit
    assert len(plan["small"]) == 2
    assert len(plan["big"]) == 6          # last worker takes the rest


def test_strategy_overflow_stays_unassigned():
    ws = [WorkerCapacity("tiny", 5_000, 100.0)]
    plan = DefaultStrategy().assign_layers(ws, list(range(8)), [4000] * 8)
    assert len(plan["tiny"]) == 1         # master keeps the other 7


def test_estimate_layer_bytes(tmp_path):
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_safetensors(str(tmp_path / "m.safetensors"),
                     params_to_hf_tensors(cfg, params))
    st = TensorStorage.from_model_dir(str(tmp_path))
    sizes = estimate_layer_bytes(st, cfg.num_hidden_layers)
    assert len(sizes) == 4 and all(s > 0 for s in sizes)
    assert sizes[0] == sizes[1]
    doubled = estimate_layer_bytes(st, 4, quant_factor=2.0)
    assert doubled[0] == 2 * sizes[0]


# --------------------------------------------------------------- discovery

def test_discovery_loopback():
    port = 19000 + os.getpid() % 500
    adv = WorkerAdvertiser("w-test", "key1", 12345, discovery_port=port,
                           caps={"backend": "tpu", "device": "TPU v5 lite",
                                 "n_devices": 1, "memory_bytes": 16 << 30,
                                 "tflops": 394.0}).start()
    try:
        found = discover_workers("key1", timeout=1.5, discovery_port=port,
                                 expected=1)
        assert len(found) == 1
        w = found[0]
        assert w["name"] == "w-test" and w["port"] == 12345
        assert w["caps"]["backend"] == "tpu"
        # wrong key sees nothing
        none = discover_workers("other-key", timeout=0.5, discovery_port=port)
        assert none == []
    finally:
        adv.stop()


# ---------------------------------------------------------------- transfer

def test_weight_streaming_roundtrip(tmp_path, rng):
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    mdir = tmp_path / "model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    (mdir / "config.json").write_text(json.dumps(
        {"architectures": ["LlamaForCausalLM"]}))
    st = TensorStorage.from_model_dir(str(mdir))

    names = transfer.subset_tensor_names(st, 1, 3, cfg.num_hidden_layers)
    assert all(".layers.1." in n or ".layers.2." in n for n in names)
    total, chunks = transfer.synthesize_safetensors(st, names, chunk_size=4096)

    recv = transfer.ModelReceiver(str(tmp_path / "cache"), "abc-def")
    n_chunks = 0
    for msg in transfer.encode_chunks("model.safetensors", total, chunks):
        recv.on_chunk(msg)
        n_chunks += 1
    assert n_chunks >= 2
    recv.finalize()

    out = TensorStorage.from_model_dir(recv.dir)
    for n in names:
        np.testing.assert_array_equal(out.read(n), st.read(n))
    assert transfer.has_valid_model_cache(
        str(tmp_path / "cache"), "abc-def", {"model.safetensors": total})
    assert not transfer.has_valid_model_cache(
        str(tmp_path / "cache"), "abc-def", {"model.safetensors": total + 1})


def test_chunk_crc_rejected():
    msg = proto.model_chunk("f", 0, 1, b"hello", 12345, False, 0)
    recv = transfer.ModelReceiver("/tmp/cake-test-crc", "k")
    with pytest.raises(proto.ProtocolError, match="CRC"):
        recv.on_chunk(msg)


# ----------------------------------------------- end-to-end master<->worker

@pytest.fixture
def cluster_model_dir(tmp_path):
    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    mdir = tmp_path / "model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    d = dict(architectures=["Qwen3ForCausalLM"], vocab_size=256,
             hidden_size=64, intermediate_size=128, num_hidden_layers=4,
             num_attention_heads=4, num_key_value_heads=2, rms_norm_eps=1e-5,
             rope_theta=10000.0, max_position_embeddings=128, eos_token_id=2)
    (mdir / "config.json").write_text(json.dumps(d))
    return cfg, params, str(mdir), str(tmp_path / "wcache")


def _start_worker_thread(name, key, cache_root, ready, tp=None, port=0):
    """Run a WorkerServer on its own event loop thread; returns (holder,
    thread). Shared with test_cluster_faults (same import idiom as
    test_obs_api's reuse of test_api helpers)."""
    from cake_tpu.cluster.worker import WorkerServer
    holder = {}

    def run():
        async def main():
            server = WorkerServer(name, key, port=port,
                                  cache_root=cache_root,
                                  advertise=False, tp=tp)
            await server.start()
            holder["port"] = server.port
            holder["server"] = server
            ready.set()
            try:
                await server.serve_forever()
            except asyncio.CancelledError:
                pass

        loop = asyncio.new_event_loop()
        holder["loop"] = loop
        try:
            loop.run_until_complete(main())
        finally:
            loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return holder, t


def _stop_worker(holder, t):
    loop, srv = holder.get("loop"), holder.get("server")
    if loop and srv and loop.is_running():
        try:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(
                timeout=5)
        except Exception:
            pass
    t.join(timeout=10)


def test_distributed_generation_matches_local(cluster_model_dir):
    """Master + one real worker over localhost TCP, weights streamed, greedy
    generation must match the fully-local model exactly."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel

    cfg, params, mdir, wcache = cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "testkey", wcache, ready)
    assert ready.wait(10)
    port = holder["port"]

    try:
        setup = master_setup(
            mdir, "testkey", cfg,
            workers=[{"name": "w0", "host": "127.0.0.1", "port": port,
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"w0": (1, 3)},      # worker takes middle layers
            dtype_str="f32", max_cache_len=64)
        assert [s.kind for s in setup.stages] == ["local", "remote", "local"]

        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64)
        got, stats = dist.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                                   sampling=SamplingConfig(temperature=0.0))

        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                                 sampling=SamplingConfig(temperature=0.0))
        assert got == want
        assert stats["decode_tokens"] == len(got) - 1

        # second generation on the same cluster (cache reset path)
        got2, _ = dist.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                                sampling=SamplingConfig(temperature=0.0))
        assert got2 == want

        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)


def test_worker_cache_hit_skips_push(cluster_model_dir):
    """Second master_setup against the same worker cache must not re-stream
    (ref: content-keyed cache validation)."""
    from cake_tpu.cluster.master import master_setup

    cfg, _, mdir, wcache = cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "testkey", wcache, ready)
    assert ready.wait(10)
    port = holder["port"]
    workers = [{"name": "w0", "host": "127.0.0.1", "port": port,
                "caps": {"backend": "cpu", "device": "cpu",
                         "memory_bytes": 8 << 30, "tflops": 1.0}}]
    try:
        s1 = master_setup(mdir, "testkey", cfg, workers,
                          assignments={"w0": (1, 3)}, dtype_str="f32",
                          max_cache_len=64)
        for c in s1.clients:
            c.close()
        # second setup: worker should report cached=True
        from cake_tpu.cluster.client import RemoteStage
        from cake_tpu.cluster import proto as P, transfer as T
        from cake_tpu.cluster.auth import cluster_hash
        client = RemoteStage("127.0.0.1", port, "testkey", "w0").connect()
        st = __import__("cake_tpu.utils.safetensors_io",
                        fromlist=["TensorStorage"]).TensorStorage.from_model_dir(mdir)
        names = T.subset_tensor_names(st, 1, 3, cfg.num_hidden_layers)
        total, _ = T.synthesize_safetensors(st, names)
        with open(os.path.join(mdir, "config.json")) as f:
            cfg_raw = json.load(f)
        a = P.layer_assignment(
            model_id=T.model_hash(mdir), arch=cfg.arch,
            config=cfg_raw,
            start=1, end=3, dtype="f32",
            cache_key=T.cache_key(cluster_hash("testkey"), T.model_hash(mdir)),
            push_weights=True)
        a["max_cache_len"] = 64
        a["expected_files"] = {"model.safetensors": total}
        resp = client.assign(a)
        assert resp.get("cached") is True
        client.wait_ready()
        client.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_two_worker_auto_assignment_cluster(cluster_model_dir):
    """Two workers with unequal TFLOPS: plan_assignments splits 3:1, both
    ranges stream + serve, generation matches fully-local (the mixed-cluster
    configuration from BASELINE.json, on localhost)."""
    from cake_tpu.cluster.master import (DistributedTextModel, master_setup,
                                         plan_assignments)
    from cake_tpu.models import SamplingConfig, TextModel
    from cake_tpu.utils.safetensors_io import TensorStorage

    cfg, params, mdir, wcache = cluster_model_dir
    r0, r1 = threading.Event(), threading.Event()
    h0, t0 = _start_worker_thread("w-fast", "k2", wcache + "0", r0)
    h1, t1 = _start_worker_thread("w-slow", "k2", wcache + "1", r1)
    assert r0.wait(10) and r1.wait(10)
    workers = [
        {"name": "w-fast", "host": "127.0.0.1", "port": h0["port"],
         "caps": {"backend": "tpu", "device": "x", "memory_bytes": 8 << 30,
                  "tflops": 300.0}},
        {"name": "w-slow", "host": "127.0.0.1", "port": h1["port"],
         "caps": {"backend": "cpu", "device": "cpu", "memory_bytes": 8 << 30,
                  "tflops": 100.0}},
    ]
    try:
        st = TensorStorage.from_model_dir(mdir)
        plan = plan_assignments(cfg, st, workers)
        st.close()
        assert plan == {"w-fast": (0, 3), "w-slow": (3, 4)}

        setup = master_setup(mdir, "k2", cfg, workers, assignments=plan,
                             dtype_str="f32", max_cache_len=64)
        # all four layers remote; master keeps embed + head only
        assert [(s.kind, s.start, s.end) for s in setup.stages] == \
            [("remote", 0, 3), ("remote", 3, 4)]
        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64)
        got, _ = dist.generate([1, 2, 3, 4], max_new_tokens=6,
                               sampling=SamplingConfig(temperature=0.0))
        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate([1, 2, 3, 4], max_new_tokens=6,
                                 sampling=SamplingConfig(temperature=0.0))
        assert got == want
        for c in setup.clients:
            c.close()
    finally:
        for holder, t in ((h0, t0), (h1, t1)):
            loop, srv = holder.get("loop"), holder.get("server")
            if loop and srv:
                asyncio.run_coroutine_threadsafe(srv.stop(), loop)
            t.join(timeout=5)


@pytest.fixture
def fp8_cluster_model_dir(tmp_path):
    """Model dir whose mlp weights are stored f8e4m3 + weight_scale_inv."""
    from cake_tpu.ops.fp8 import quant_fp8_blockwise
    cfg = tiny_config("llama", num_attention_heads=4, num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    tensors = params_to_hf_tensors(cfg, params)
    for name in list(tensors):
        if ".mlp." in name and name.endswith(".weight"):
            w = tensors.pop(name)
            wq, si = quant_fp8_blockwise(jnp.asarray(w))
            tensors[name] = np.asarray(wq)
            tensors[name.replace(".weight", ".weight_scale_inv")] = \
                np.asarray(si)
    mdir = tmp_path / "model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"), tensors)
    d = dict(architectures=["LlamaForCausalLM"], vocab_size=256,
             hidden_size=64, intermediate_size=128, num_hidden_layers=4,
             num_attention_heads=4, num_key_value_heads=4, rms_norm_eps=1e-5,
             rope_theta=10000.0, max_position_embeddings=128, eos_token_id=2,
             quantization_config={"quant_method": "fp8"})
    (mdir / "config.json").write_text(json.dumps(d))
    return cfg, str(mdir), str(tmp_path / "wcache")


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_fp8_native_through_cluster_streaming(fp8_cluster_model_dir):
    """--fp8-native in distributed mode: f8e4m3 tensors stream verbatim to
    the worker (1 byte/param on the wire AND in worker HBM — the params
    pytree holds fp8 marker dicts) and greedy generation matches the
    all-local dequant-at-load model (ref: native_dtype_backend.rs through
    push_model_data)."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel
    from cake_tpu.utils.loaders import load_model_params

    cfg, mdir, wcache = fp8_cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "testkey", wcache, ready)
    assert ready.wait(10)
    port = holder["port"]
    try:
        setup = master_setup(
            mdir, "testkey", cfg,
            workers=[{"name": "w0", "host": "127.0.0.1", "port": port,
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"w0": (1, 3)},
            dtype_str="f32", max_cache_len=64, fp8_native=True)

        # the worker's loaded stage holds NATIVE f8 weights
        srv = holder["server"]
        wstage = srv.state.stage
        wmlp = wstage.params["layers"][0]["mlp"]["gate_proj"]["weight"]
        assert isinstance(wmlp, dict) and "fp8" in wmlp
        assert wmlp["fp8"].dtype == jnp.float8_e4m3fn
        # ... and the streamed file on disk kept the f8 dtype (1 B/param)
        from cake_tpu.utils.safetensors_io import TensorStorage
        wst = TensorStorage.from_model_dir(
            os.path.join(wcache, os.listdir(wcache)[0]))
        rec = wst.records["model.layers.1.mlp.gate_proj.weight"]
        assert rec.dtype == "float8_e4m3fn"
        assert rec.nbytes == rec.shape[0] * rec.shape[1]
        wst.close()

        # master's local stages are fp8-native too
        mmlp = [s for s in setup.stages if s.kind == "local"][0] \
            .runner.params["layers"][0]["mlp"]["gate_proj"]["weight"]
        assert isinstance(mmlp, dict) and "fp8" in mmlp

        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64)
        got, _ = dist.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                               sampling=SamplingConfig(temperature=0.0))
        local = TextModel(cfg, load_model_params(cfg, mdir, jnp.float32),
                          dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate([1, 2, 3, 4, 5], max_new_tokens=8,
                                 sampling=SamplingConfig(temperature=0.0))
        assert got == want
        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)


def test_warm_covers_every_serving_bucket_combo():
    """The worker's assignment-time warm compiles prefill width w against
    cache buckets {w, next(w)} (worker._warm). This pins the invariant it
    relies on: for ANY prompt length and max_new_tokens, the master's
    initial KV bucket (bucket_for(prompt + 1 + min(max_new,
    DECODE_HEADROOM))) is at most ONE bucket above the prefill width
    bucket (bucket_for(prompt)) — i.e. serving can never request a
    (width, cache) combo the warm sweep did not compile."""
    from cake_tpu.models.common.text_model import (DECODE_HEADROOM,
                                                   PREFILL_BUCKETS,
                                                   bucket_for)

    max_len = PREFILL_BUCKETS[-1]
    for prompt_len in range(1, 2049):
        pb = bucket_for(prompt_len, max_len)
        for max_new in (1, DECODE_HEADROOM, 10 * DECODE_HEADROOM):
            span = 1 + min(max_new, DECODE_HEADROOM)
            kv = bucket_for(prompt_len + span, max_len)
            i_pb = PREFILL_BUCKETS.index(pb)
            i_kv = PREFILL_BUCKETS.index(kv)
            assert 0 <= i_kv - i_pb <= 1, (
                f"prompt {prompt_len} max_new {max_new}: width bucket {pb} "
                f"but kv bucket {kv} — warm sweep would miss this combo")


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_pipelined_prefill_matches_local(cluster_model_dir):
    """Long-prompt greedy parity through the pipelined chunked prefill:
    a 70-token prompt with prefill_chunk=32 flows through the stage chain
    as 3 chunks (fresh + 2 append) and must produce exactly the tokens of
    the fully-local single-shot model."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel

    cfg, params, mdir, wcache = cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("wp", "testkey", wcache + "-pp", ready)
    assert ready.wait(10)
    port = holder["port"]

    prompt = [(i * 7 + 3) % 250 for i in range(70)]
    try:
        setup = master_setup(
            mdir, "testkey", cfg,
            workers=[{"name": "wp", "host": "127.0.0.1", "port": port,
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"wp": (1, 3)},
            dtype_str="f32", max_cache_len=128)
        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=128,
                                    prefill_chunk=32)
        got, stats = dist.generate(prompt, max_new_tokens=8,
                                   sampling=SamplingConfig(temperature=0.0))
        assert stats["prefill"] == {"pipelined": True, "chunks": 3,
                                    "width": 32}

        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=128)
        want, _ = local.generate(prompt, max_new_tokens=8,
                                 sampling=SamplingConfig(temperature=0.0))
        assert got == want

        # short prompt falls back to the single-shot path on the same chain
        got2, stats2 = dist.generate(prompt[:20], max_new_tokens=6,
                                     sampling=SamplingConfig(temperature=0.0))
        assert stats2["prefill"]["pipelined"] is False
        want2, _ = local.generate(prompt[:20], max_new_tokens=6,
                                  sampling=SamplingConfig(temperature=0.0))
        assert got2 == want2

        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_worker_error_keeps_connection_alive(cluster_model_dir):
    """A failed forward must produce a worker_error reply (raised master-
    side) WITHOUT killing the worker loop — the next valid request on the
    same connection succeeds (ref behavior: per-op WorkerError keeps the
    worker alive, worker.rs:425-431). A dead worker must then raise, not
    hang."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig

    cfg, params, mdir, wcache = cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("we", "testkey", wcache + "-err", ready)
    assert ready.wait(10)
    port = holder["port"]

    try:
        setup = master_setup(
            mdir, "testkey", cfg,
            workers=[{"name": "we", "host": "127.0.0.1", "port": port,
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"we": (1, 3)},
            dtype_str="f32", max_cache_len=64)
        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64)
        stage = next(s for s in dist.stages if s.kind == "remote")

        # malformed request: hidden width 7 != hidden_size -> worker-side
        # failure -> worker_error reply raised here
        bad = np.zeros((1, 2, 7), np.float32)
        with pytest.raises(RuntimeError, match="worker we"):
            stage.runner.forward_hidden(bad, None, 0, 2)

        # same connection, next valid generation succeeds
        toks, _ = dist.generate([1, 2, 3, 4, 5], max_new_tokens=6,
                                sampling=SamplingConfig(temperature=0.0))
        assert len(toks) >= 1

        # dead worker: raises promptly instead of hanging. stop() closes
        # the live connection synchronously before its first await, so the
        # assertion below holds even if the worker loop winds down before
        # the stop future resolves.
        loop, srv = holder["loop"], holder["server"]
        try:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(
                timeout=5)
        except Exception:
            pass
        with pytest.raises(Exception):
            dist.generate([1, 2, 3], max_new_tokens=4,
                          sampling=SamplingConfig(temperature=0.0))
        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            try:
                asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(
                    timeout=5)
            except Exception:
                pass
        t.join(timeout=5)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_master_setup_partial_failure_closes_connections(cluster_model_dir):
    """If a later worker fails during master_setup, the already-connected
    workers' sockets must be closed, not leaked (the worker would keep
    per-connection state for a master that no longer exists)."""
    from cake_tpu.cluster.master import master_setup

    cfg, params, mdir, wcache = cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("wa", "testkey", wcache + "-pf", ready)
    assert ready.wait(10)
    port = holder["port"]

    caps = {"backend": "cpu", "device": "cpu", "memory_bytes": 8 << 30,
            "tflops": 1.0}
    # second worker points at a dead port -> connect fails mid-setup
    workers = [{"name": "wa", "host": "127.0.0.1", "port": port,
                "caps": caps},
               {"name": "wdead", "host": "127.0.0.1", "port": 1,
                "caps": caps}]
    try:
        with pytest.raises(Exception):
            master_setup(mdir, "testkey", cfg, workers,
                         assignments={"wa": (1, 2), "wdead": (2, 3)},
                         dtype_str="f32", max_cache_len=64)
        # wa's connection must drain to zero (close propagated)
        deadline = time.time() + 10
        srv = holder["server"]
        while time.time() < deadline and srv._writers:
            time.sleep(0.2)
        assert not srv._writers, "leaked master connection on the worker"
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            try:
                asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(
                    timeout=5)
            except Exception:
                pass
        t.join(timeout=5)


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_distributed_moe_matches_local(tmp_path):
    """MoE over the wire: workers load expert banks for their layer subset;
    greedy distributed == local (pins the subset-synthesized safetensors
    streaming of stacked expert tensors + routing over TCP)."""
    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel

    cfg = tiny_config("qwen3_moe")
    params = init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    mdir = tmp_path / "moe-model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    (mdir / "config.json").write_text(json.dumps(
        {"architectures": ["Qwen3MoeForCausalLM"], "vocab_size": 256,
         "hidden_size": 64, "intermediate_size": 128,
         "num_hidden_layers": 4, "num_attention_heads": 4,
         "num_key_value_heads": 2, "rms_norm_eps": 1e-5,
         "rope_theta": 10000.0, "max_position_embeddings": 128,
         "num_experts": 8, "num_experts_per_tok": 2,
         "moe_intermediate_size": 32, "eos_token_id": 2}))

    ready = threading.Event()
    holder, t = _start_worker_thread("wm", "testkey",
                                     str(tmp_path / "wc-moe"), ready)
    assert ready.wait(10)
    try:
        setup = master_setup(
            str(mdir), "testkey", cfg,
            workers=[{"name": "wm", "host": "127.0.0.1",
                      "port": holder["port"],
                      "caps": {"backend": "cpu", "device": "cpu",
                               "memory_bytes": 8 << 30, "tflops": 1.0}}],
            assignments={"wm": (1, 3)},
            dtype_str="f32", max_cache_len=64)
        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=64)
        got, _ = dist.generate([3, 1, 4, 1, 5], max_new_tokens=8,
                               sampling=SamplingConfig(temperature=0.0))
        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate([3, 1, 4, 1, 5], max_new_tokens=8,
                                 sampling=SamplingConfig(temperature=0.0))
        assert got == want
        for c in setup.clients:
            c.close()
    finally:
        loop = holder.get("loop")
        srv = holder.get("server")
        if loop and srv:
            try:
                asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(
                    timeout=5)
            except Exception:
                pass
        t.join(timeout=5)
