"""Fault-tolerant distributed generation: deterministic chaos via
cluster/faults.py against a REAL master<->worker pair on localhost TCP.

Pins the recovery contract: a worker killed mid-decode costs exactly one
replay prefill and the greedy continuation is bit-identical to the
unfailed run; retry-budget exhaustion fails fast with a typed
ClusterDegradedError and 503s /health until the background restore loop
revives the worker; a gray (slow-but-alive) hop is flagged without
aborting anything. Plus the auth/teardown hardening the recovery path
leans on: truncated handshakes are AuthErrors, goodbye never raises.
"""
import asyncio
import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu import obs
from cake_tpu.cluster import faults, proto
from cake_tpu.cluster.auth import (AuthError, authenticate_as_master,
                                   authenticate_as_worker)
from cake_tpu.cluster.client import RemoteStage, StageFailure
from cake_tpu.cluster.master import (ClusterDegradedError,
                                     DistributedTextModel, master_setup)
from cake_tpu.models import SamplingConfig, TextModel, init_params, tiny_config
from cake_tpu.utils.export import params_to_hf_tensors
from cake_tpu.utils.safetensors_io import save_safetensors

GREEDY = SamplingConfig(temperature=0.0)

# fast-recovery knobs for tests: real defaults back off for seconds
FAST = dict(recovery_retries=4, recovery_backoff_s=0.05,
            restore_interval_s=0.15)


@pytest.fixture(autouse=True)
def _isolated_faults():
    """Every test starts and ends without an installed fault plan."""
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------------- plan parsing

def test_fault_plan_parsing():
    inj = faults.parse_plan(
        "w0:drop_after_ops=5;delay_ms=12.5, @w1:crash_after_ops=2,"
        "corrupt_after_ops=1")
    assert len(inj.plans) == 3
    p0, p1, p2 = inj.plans
    assert (p0.target, p0.drop_after_ops, p0.delay_ms) == ("w0", 5, 12.5)
    assert (p1.target, p1.crash_after_ops) == ("@w1", 2)
    assert (p2.target, p2.corrupt_after_ops) == ("*", 1)  # no target = all
    assert p0.matches("w0") and not p0.matches("@w0")
    with pytest.raises(ValueError, match="unknown fault key"):
        faults.parse_plan("w0:explode=1")
    with pytest.raises(ValueError, match="key=value"):
        faults.parse_plan("w0:drop_after_ops")
    with pytest.raises(ValueError, match="empty"):
        faults.parse_plan(" , ")


def test_install_and_clear_toggle_proto_hook():
    assert proto.FAULT_HOOK is None
    inj = faults.install("*:delay_ms=1")
    assert proto.FAULT_HOOK is inj and faults.active() is inj
    faults.clear()
    assert proto.FAULT_HOOK is None


# -------------------------------------------------------- teardown hardening

def test_goodbye_never_raises():
    """goodbye() is teardown: no channel, a dead peer, and a protocol
    desync must all be swallowed (a raise here masks the error that
    actually killed the setup/generation)."""
    rs = RemoteStage("127.0.0.1", 1, "k", "w")
    assert rs.sock is None
    rs.goodbye()                                 # no channel: no-op

    import socket as socket_mod
    a, b = socket_mod.socketpair()
    rs.sock = a
    b.close()                                    # peer gone mid-teardown
    rs.goodbye()                                 # EOF/RST swallowed
    assert rs.sock is None                       # unknown-state channel dropped

    a2, b2 = socket_mod.socketpair()
    rs.sock = a2
    b2.sendall(b"\x00\x00\x00\x00\x10\x00\x00\x00")   # bad magic reply
    rs.goodbye()                                 # ProtocolError swallowed
    assert rs.sock is None
    b2.close()


def test_forward_without_channel_is_classified():
    rs = RemoteStage("127.0.0.1", 1, "k", "w")
    with pytest.raises(StageFailure) as ei:
        rs.forward_hidden(np.zeros((1, 1, 4), np.float32), None, 0, None)
    assert ei.value.kind == "conn" and ei.value.worker == "w"


# ------------------------------------------------------------- auth hardening

def _auth_scenario(server_side, client_side):
    """Run worker-side (server) and master-side (client) auth coroutines
    against each other; each side may be a saboteur. Returns both results
    (True or the exception)."""
    async def go():
        done = asyncio.get_running_loop().create_future()

        async def on_conn(r, w):
            try:
                await server_side(r, w)
                done.set_result(True)
            except Exception as e:
                done.set_result(e)
            finally:
                w.close()

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        r, w = await asyncio.open_connection("127.0.0.1", port)
        try:
            await client_side(r, w)
            cres = True
        except Exception as e:
            cres = e
        sres = await asyncio.wait_for(done, 5)
        w.close()
        server.close()
        await asyncio.wait_for(server.wait_closed(), 5)
        return cres, sres
    return asyncio.run(go())


def test_auth_wrong_psk_both_sides_fail_typed():
    """Wrong PSK: BOTH ends must surface AuthError (worker detects the bad
    MAC; the master sees the worker bail), never a bare socket error."""
    c, s = _auth_scenario(
        lambda r, w: authenticate_as_worker(r, w, "right-key"),
        lambda r, w: authenticate_as_master(r, w, "wrong-key"))
    assert isinstance(s, AuthError)
    assert isinstance(c, AuthError)


def test_auth_truncated_by_master():
    """Master closes mid-handshake (after reading the challenge): the
    worker side must classify the truncation as an AuthError."""
    async def bad_master(r, w):
        await r.readexactly(32)                  # take the challenge...
        w.close()                                # ...and vanish
        raise AuthError("saboteur done")

    c, s = _auth_scenario(
        lambda r, w: authenticate_as_worker(r, w, "k"), bad_master)
    assert isinstance(s, AuthError)
    assert "closed" in str(s) or "timeout" in str(s)


def test_auth_truncated_by_worker():
    """Worker sends a short challenge then closes: the master side must
    classify the truncation as an AuthError."""
    async def bad_worker(r, w):
        w.write(b"\x01" * 7)                     # truncated challenge
        await w.drain()
        w.close()
        raise AuthError("saboteur done")

    c, s = _auth_scenario(
        bad_worker, lambda r, w: authenticate_as_master(r, w, "k"))
    assert isinstance(c, AuthError)
    assert "closed" in str(c) or "timeout" in str(c)


def test_sync_master_auth_truncation_is_auth_error(monkeypatch):
    """RemoteStage's sync handshake: a peer that closes mid-auth surfaces
    through connect() as ConnectionError (wrapping AuthError), promptly."""
    import socket as socket_mod
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def peer():
        conn, _ = srv.accept()
        conn.sendall(b"\x02" * 32)               # full challenge...
        conn.recv(64)
        conn.close()                             # ...but never answer back

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    rs = RemoteStage("127.0.0.1", port, "k", "w", timeout=2.0)
    with pytest.raises(ConnectionError, match="auth"):
        rs.connect(attempts=1)
    t.join(timeout=5)
    srv.close()


def test_encode_chunks_resume_starts_at_file_byte_zero():
    """Resume semantics of the (re)push path: the chunk stream always
    begins at file byte 0, so with start_offset=X the encoder must SKIP
    the first X bytes and label the first emitted chunk with off=X — a
    running offset initialized to X instead of 0 shifted the whole file
    by X on the worker (corrupted safetensors after a resumed push)."""
    from cake_tpu.cluster import transfer

    msgs = list(transfer.encode_chunks("f", 8, iter([b"aaaa", b"bbbb"]),
                                       start_offset=6))
    assert [(m["off"], m["z"] or m["d"]) for m in msgs] == [(6, b"bb")]
    msgs = list(transfer.encode_chunks("f", 8, iter([b"aaaa", b"bbbb"])))
    assert [m["off"] for m in msgs] == [0, 4]
    # whole-chunk skip: resume exactly at a chunk boundary
    msgs = list(transfer.encode_chunks("f", 8, iter([b"aaaa", b"bbbb"]),
                                       start_offset=4))
    assert [(m["off"], m["z"] or m["d"]) for m in msgs] == [(4, b"bbbb")]


# --------------------------------------------------- live-cluster fixtures
# Everything below shares ONE tiny model checkpoint, ONE local reference
# model (greedy refs memoized), and — for the connection-fault tests —
# ONE worker + master chain: those tests sever connections, never the
# worker, and every test starts from a cleared fault plan and a healthy
# (possibly freshly revived) channel. Only the retry-exhaustion test
# boots its own worker, because it kills it. This keeps the tier-1 cost
# of the file low: the suite runs under a hard wall-clock cap, and every
# master_setup + jit warm repeated per-test is paid out of that budget.

PROMPT = [1, 2, 3, 4, 5]


@pytest.fixture(scope="module")
def cluster_model_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("faults")
    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    mdir = tmp / "model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    d = dict(architectures=["Qwen3ForCausalLM"], vocab_size=256,
             hidden_size=64, intermediate_size=128, num_hidden_layers=4,
             num_attention_heads=4, num_key_value_heads=2, rms_norm_eps=1e-5,
             rope_theta=10000.0, max_position_embeddings=128, eos_token_id=2)
    (mdir / "config.json").write_text(json.dumps(d))
    return cfg, params, str(mdir), str(tmp / "wcache")


@pytest.fixture(scope="module")
def local_ref(cluster_model_dir):
    """Memoized greedy references from the fully-local model — the ground
    truth every recovered run must match bit-for-bit."""
    cfg, params, _, _ = cluster_model_dir
    local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
    cache: dict = {}

    def ref(prompt, n):
        key = (tuple(prompt), n)
        if key not in cache:
            cache[key], _ = local.generate(list(prompt), max_new_tokens=n,
                                           sampling=GREEDY)
        return cache[key]
    return ref


# worker-on-event-loop-thread helpers shared with test_cluster (same
# cross-module reuse idiom as test_obs_api importing test_api helpers)
from tests.test_cluster import _start_worker_thread, _stop_worker  # noqa: E402


def _setup(cfg, mdir, port, **model_kw):
    # warm="decode": skip the full compile sweep — these tests pay
    # master_setup (and a recovery re-assign) on a budgeted clock, and
    # the tiny CPU model's in-band compiles are cheap
    setup = master_setup(
        mdir, "faultkey", cfg,
        workers=[{"name": "w0", "host": "127.0.0.1", "port": port,
                  "caps": {"backend": "cpu", "device": "cpu",
                           "memory_bytes": 8 << 30, "tflops": 1.0}}],
        assignments={"w0": (1, 3)},
        dtype_str="f32", max_cache_len=64, warm="decode")
    dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                dtype=jnp.float32, max_cache_len=64,
                                **{**FAST, **model_kw})
    return setup, dist


@pytest.fixture(scope="module")
def live(cluster_model_dir):
    """Shared worker + master chain for the connection-fault tests."""
    cfg, params, mdir, wcache = cluster_model_dir
    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "faultkey", wcache, ready)
    assert ready.wait(10)
    setup, dist = _setup(cfg, mdir, holder["port"])
    yield dist
    for c in setup.clients:
        c.close()
    _stop_worker(holder, t)


def _remote(dist):
    return next(s for s in dist.stages if s.kind == "remote").runner


# ----------------------------------------------- mid-stream worker recovery

def test_drop_mid_decode_recovers_bit_identical(live, local_ref):
    """Connection to the worker severed after 4 forward ops (mid-decode):
    the master must quarantine, reconnect (cached weights => no re-push),
    rebuild via EXACTLY ONE replay prefill, and finish with greedy output
    bit-identical to a run with no fault at all."""
    want = local_ref(PROMPT, 8)
    reconnects0 = obs.CLUSTER_RECONNECTS.value(worker="w0")
    replays0 = obs.CLUSTER_REPLAYS.value()

    faults.install("w0:drop_after_ops=4")
    got, stats = live.generate(PROMPT, max_new_tokens=8, sampling=GREEDY)
    assert got == want, "recovered continuation diverged from unfailed run"
    assert stats["replays"] == 1, "recovery must cost exactly one prefill"
    assert stats["recoveries"] == 1
    assert obs.CLUSTER_RECONNECTS.value(worker="w0") == reconnects0 + 1
    assert obs.CLUSTER_REPLAYS.value() == replays0 + 1
    assert obs.CLUSTER_STAGE_FAILURES.value(worker="w0", kind="eof") >= 1

    # the revived channel serves the NEXT generation with no recovery
    got2, stats2 = live.generate(PROMPT, max_new_tokens=8, sampling=GREEDY)
    assert got2 == want
    assert stats2["replays"] == 0 and stats2["recoveries"] == 0


@pytest.mark.slow
def test_drop_during_prefill_recovers(live, local_ref):
    """Fault on the very FIRST forward (the prefill op): recovery replays
    the prompt and the whole generation still matches the unfailed run."""
    faults.install("w0:drop_after_ops=0")        # first forward dies
    got, stats = live.generate([9, 8, 7, 6], max_new_tokens=6,
                               sampling=GREEDY)
    assert got == local_ref([9, 8, 7, 6], 6)
    assert stats["replays"] == 1


@pytest.mark.slow
def test_corrupt_frame_classified_and_recovered(live, local_ref):
    """A corrupted response frame surfaces as a classified `corrupt`
    failure (undecodable payload => ProtocolError), and recovery rides the
    same reconnect+replay path to a bit-identical finish."""
    faults.install("w0:corrupt_after_ops=2")
    got, stats = live.generate(PROMPT, max_new_tokens=8, sampling=GREEDY)
    assert got == local_ref(PROMPT, 8)
    assert stats["replays"] == 1
    assert obs.CLUSTER_STAGE_FAILURES.value(worker="w0",
                                            kind="corrupt") >= 1


@pytest.mark.slow
def test_stall_trips_per_op_deadline_and_recovers(live, local_ref):
    """A worker stalled past the per-op deadline is a classified `timeout`
    — detection does not wait for TCP to notice (it wouldn't) — and the
    generation still completes bit-identically via recovery."""
    runner = _remote(live)
    old_timeout = runner.timeout
    runner.timeout = 0.6                 # what CAKE_HOP_TIMEOUT_S would set
    if runner.sock is not None:
        runner.sock.settimeout(0.6)      # live socket predates the override
    try:
        faults.install("@w0:stall_once_ms=1500;stall_after_ops=3")
        got, stats = live.generate(PROMPT, max_new_tokens=6, sampling=GREEDY)
        assert got == local_ref(PROMPT, 6)
        assert stats["recoveries"] >= 1
        assert obs.CLUSTER_STAGE_FAILURES.value(worker="w0",
                                                kind="timeout") >= 1
    finally:
        runner.timeout = old_timeout
        if runner.sock is not None:
            runner.sock.settimeout(old_timeout)


def test_gray_failure_flagged_without_abort(live, local_ref):
    """delay_ms on every hop op pushes the rolling RTT p95 over the
    degraded threshold (CAKE_HOP_DEGRADED_MS): the stage is flagged gray
    in worker_health (and the gauge) while the generation runs to
    completion with ZERO recoveries — slow is not dead."""
    runner = _remote(live)
    runner.degraded_ms = 10              # what CAKE_HOP_DEGRADED_MS would set
    try:
        faults.install("w0:delay_ms=40")
        got, stats = live.generate(PROMPT, max_new_tokens=8, sampling=GREEDY)
        assert got == local_ref(PROMPT, 8)
        assert stats["recoveries"] == 0 and stats["replays"] == 0

        assert runner.gray_degraded is True
        assert runner.rtt_p95_ms() > 10

        from cake_tpu.api.obs_routes import worker_health
        entry = worker_health(live)[0]
        assert entry["degraded"] is True and entry["failing"] is False
        assert obs.CLUSTER_HOP_DEGRADED.value(worker="w0") == 1.0
    finally:
        runner.degraded_ms = 0.0


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_retry_exhaustion_degrades_health_then_restores(cluster_model_dir,
                                                        local_ref):
    """Worker hard-crashes (listener gone): the retry budget drains, the
    request fails FAST with ClusterDegradedError, /health answers 503 with
    the quarantined worker named — and once the worker comes back, the
    background restore loop revives it so the next request succeeds."""
    cfg, params, mdir, wcache = cluster_model_dir
    want = local_ref(PROMPT, 6)

    ready = threading.Event()
    holder, t = _start_worker_thread("w0", "faultkey", wcache, ready)
    assert ready.wait(10)
    port = holder["port"]
    setup, dist = _setup(cfg, mdir, port, recovery_retries=2,
                         recovery_backoff_s=0.02, restore_interval_s=0.15)
    holder2 = t2 = None
    try:
        faults.install("@w0:crash_after_ops=3")
        with pytest.raises(ClusterDegradedError):
            dist.generate(PROMPT, max_new_tokens=6, sampling=GREEDY)
        assert dist.degraded is not None and dist.degraded["worker"] == "w0"
        assert obs.CLUSTER_DEGRADED.value() == 1.0

        # degraded cluster fails FAST — no reconnect-loop latency tax
        t0 = time.monotonic()
        with pytest.raises(ClusterDegradedError):
            dist.generate(PROMPT, max_new_tokens=6, sampling=GREEDY)
        assert time.monotonic() - t0 < 0.5

        # /health: 503 + the quarantined worker named
        from aiohttp.test_utils import TestClient, TestServer
        from cake_tpu.api import ApiState, create_app

        async def check_health():
            client = TestClient(TestServer(create_app(
                ApiState(model=dist, model_id="faults"))))
            await client.start_server()
            try:
                r = await client.get("/health")
                body = await r.json()
                assert r.status == 503, body
                assert body["status"] == "degraded"
                assert body["cluster"]["worker"] == "w0"
                # chat requests — streaming included — shed with the same
                # 503 BEFORE any SSE stream commits to a 200
                rc = await client.post("/v1/chat/completions", json={
                    "messages": [{"role": "user", "content": "hi"}],
                    "stream": True})
                assert rc.status == 503
                assert int(rc.headers.get("Retry-After", "0")) >= 1
            finally:
                await client.close()
        asyncio.run(check_health())

        # worker returns on the SAME port; the restore loop must notice
        # (the crash fault is one-shot — it does not re-fire) and clear
        # the quarantine so the next request succeeds
        faults.clear()
        _stop_worker(holder, t)
        ready2 = threading.Event()
        holder2, t2 = _start_worker_thread("w0", "faultkey", wcache, ready2,
                                           port=port)
        assert ready2.wait(10)
        deadline = time.monotonic() + 30
        while dist.degraded is not None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert dist.degraded is None, "restore loop never revived the worker"
        assert obs.CLUSTER_DEGRADED.value() == 0.0

        got, stats = dist.generate(PROMPT, max_new_tokens=6, sampling=GREEDY)
        assert got == want
        for c in setup.clients:
            c.close()
    finally:
        _stop_worker(holder, t)
        if holder2 is not None:
            _stop_worker(holder2, t2)
