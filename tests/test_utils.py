"""Hub/model-manager/splitter tests (ref: utils/{hf,models,split}.rs)."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import init_params, tiny_config
from cake_tpu.utils.export import params_to_hf_tensors
from cake_tpu.utils.hub import looks_like_repo_id, probe_cached_repo, resolve_model
from cake_tpu.utils.models import delete_model, find_model, list_models
from cake_tpu.utils.safetensors_io import TensorStorage, save_safetensors
from cake_tpu.utils.split import split_model


def test_looks_like_repo_id(tmp_path):
    assert looks_like_repo_id("Qwen/Qwen3-0.6B")
    assert not looks_like_repo_id("not-a-repo")
    assert not looks_like_repo_id("a/b/c")
    assert not looks_like_repo_id(str(tmp_path))


def test_resolve_model_local(tmp_path):
    assert resolve_model(str(tmp_path)) == str(tmp_path)


def test_hub_cache_probe_and_manager(tmp_path, monkeypatch):
    monkeypatch.setenv("HF_HUB_CACHE", str(tmp_path / "hub"))
    monkeypatch.setenv("CAKE_TPU_CACHE", str(tmp_path / "cake"))
    snap = tmp_path / "hub" / "models--org--tiny" / "snapshots" / "abc"
    snap.mkdir(parents=True)
    save_safetensors(str(snap / "model.safetensors"),
                     {"w": np.ones((2, 2), np.float32)})
    (snap / "config.json").write_text("{}")

    assert probe_cached_repo("org/tiny") == str(snap)
    models = list_models()
    assert len(models) == 1
    m = models[0]
    assert m.repo_id == "org/tiny" and m.complete and m.size_bytes > 0
    assert find_model("org/tiny") is not None
    assert resolve_model("org/tiny") == str(snap)

    assert delete_model("org/tiny")
    assert find_model("org/tiny") is None


def test_incomplete_model_detected(tmp_path, monkeypatch):
    monkeypatch.setenv("HF_HUB_CACHE", str(tmp_path / "hub"))
    monkeypatch.setenv("CAKE_TPU_CACHE", str(tmp_path / "nope"))
    snap = tmp_path / "hub" / "models--org--broken" / "snapshots" / "abc"
    snap.mkdir(parents=True)
    (snap / "model.safetensors").write_text("")   # zero-byte weight
    (snap / "config.json").write_text("{}")
    m = list_models()[0]
    assert not m.complete


def test_split_model(tmp_path):
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tensors = params_to_hf_tensors(cfg, params)
    mdir = tmp_path / "model"
    mdir.mkdir()
    save_safetensors(str(mdir / "model.safetensors"), tensors)
    (mdir / "config.json").write_text(json.dumps(
        {"architectures": ["LlamaForCausalLM"]}))
    (mdir / "tokenizer.json").write_text("{}")

    out = split_model(str(mdir), {"w0": (0, 2), "w1": (2, 4)},
                      str(tmp_path / "out"), cfg.num_hidden_layers)
    st0 = TensorStorage.from_model_dir(os.path.dirname(out["w0"]))
    st1 = TensorStorage.from_model_dir(os.path.dirname(out["w1"]))
    assert "model.layers.0.self_attn.q_proj.weight" in st0
    assert "model.layers.1.self_attn.q_proj.weight" in st0
    assert "model.layers.2.self_attn.q_proj.weight" not in st0
    assert "model.layers.2.self_attn.q_proj.weight" in st1
    # embed goes with layer 0, head/norm with the last layer
    assert "model.embed_tokens.weight" in st0
    assert "model.norm.weight" in st1
    assert "lm_head.weight" in st1
    # bundles carry config/tokenizer
    assert os.path.exists(os.path.join(os.path.dirname(out["w0"]), "config.json"))
    assert os.path.exists(os.path.join(os.path.dirname(out["w1"]),
                                       "tokenizer.json"))
