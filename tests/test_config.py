"""Config normalization tests (mirrors ref per-family config.rs tests)."""
import pytest

from cake_tpu.models.common.config import (config_from_hf_dict, tiny_config)


def base_dict(**over):
    d = dict(
        architectures=["LlamaForCausalLM"],
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        rms_norm_eps=1e-5, rope_theta=500000.0,
        max_position_embeddings=8192,
        eos_token_id=[128001, 128008, 128009],
        bos_token_id=128000,
    )
    d.update(over)
    return d


def test_llama3():
    c = config_from_hf_dict(base_dict(rope_scaling=dict(
        factor=8.0, high_freq_factor=4.0, low_freq_factor=1.0,
        original_max_position_embeddings=8192, rope_type="llama3")))
    assert c.arch == "llama"
    assert c.head_dim == 128
    assert c.is_eos(128008) and not c.is_eos(0)
    assert c.rope_scaling.factor == 8.0
    assert all(s.kind == "full" for s in c.layer_specs())


def test_unknown_arch_falls_back_to_llama():
    c = config_from_hf_dict(base_dict(architectures=["SomethingNew"]))
    assert c.arch == "llama"


def test_qwen2_bias():
    c = config_from_hf_dict(base_dict(architectures=["Qwen2ForCausalLM"]))
    assert c.qkv_bias and not c.qk_norm


def test_qwen3_qk_norm_and_head_dim():
    c = config_from_hf_dict(base_dict(architectures=["Qwen3ForCausalLM"],
                                      head_dim=64))
    assert c.qk_norm and not c.qk_norm_pre_reshape
    assert c.head_dim == 64 and not c.qkv_bias


def test_qwen3_moe():
    c = config_from_hf_dict(base_dict(
        architectures=["Qwen3MoeForCausalLM"], num_experts=128,
        num_experts_per_tok=8, moe_intermediate_size=768, norm_topk_prob=True))
    assert c.num_experts == 128 and c.num_experts_per_tok == 8
    assert all(s.is_moe for s in c.layer_specs())


def test_phi4_fused_partial_rope():
    c = config_from_hf_dict(base_dict(architectures=["Phi3ForCausalLM"],
                                      partial_rotary_factor=0.25))
    assert c.fused_qkv and c.fused_gate_up
    assert c.rotary_dim == int(c.head_dim * 0.25)


def test_mistral_sliding_window():
    c = config_from_hf_dict(base_dict(architectures=["MistralForCausalLM"],
                                      sliding_window=4096))
    assert all(s.kind == "swa" and s.window == 4096 for s in c.layer_specs())


def test_gemma3_pattern():
    """Every 6th layer global; local = SWA + RoPE at rope_local_base_freq
    (HF ground truth — tests/test_hf_parity.py; the reference skips local
    RoPE, which real Gemma3 checkpoints were not trained with)."""
    c = config_from_hf_dict(base_dict(
        architectures=["Gemma3ForCausalLM"], num_hidden_layers=12,
        sliding_window=1024, query_pre_attn_scalar=256,
        rope_local_base_freq=10000.0, rope_theta=1_000_000.0))
    specs = c.layer_specs()
    assert [s.kind for s in specs] == (["swa"] * 5 + ["full"]) * 2
    assert specs[0].use_rope and specs[0].local_rope_table
    assert specs[5].use_rope and not specs[5].local_rope_table
    assert c.local_rope_theta == 10000.0 and c.rope_theta == 1_000_000.0
    assert c.norm_style == "sandwich" and c.residual_rms_norm
    assert c.hidden_act == "gelu_tanh" and c.tie_word_embeddings
    assert abs(c.embed_scale - 4096 ** 0.5) < 1e-6
    assert abs(c.attn_scale - 256 ** -0.5) < 1e-9


def test_olmo2_post_norm():
    c = config_from_hf_dict(base_dict(architectures=["OLMo2ForCausalLM"]))
    assert c.norm_style == "post" and c.qk_norm_pre_reshape


def test_exaone4_pattern():
    """3 local (SWA+RoPE) : 1 global (NoPE) — ref exaone4/config.rs tests."""
    c = config_from_hf_dict(base_dict(
        architectures=["ExaoneForCausalLM"], num_hidden_layers=32,
        sliding_window=4096))
    specs = c.layer_specs()
    assert not specs[0].kind == "full" and specs[3].kind == "full"
    assert specs[7].kind == "full" and specs[30].kind == "swa"
    assert specs[0].use_rope and not specs[3].use_rope   # global = NoPE
    assert c.qk_norm
    # HF Exaone4DecoderLayer is post-norm (tests/test_hf_parity.py)
    assert c.norm_style == "post"


def test_exaone4_string_pattern():
    """Released EXAONE-4.0 configs ship sliding_window_pattern='LLLG'."""
    c = config_from_hf_dict(base_dict(
        architectures=["Exaone4ForCausalLM"], num_hidden_layers=8,
        sliding_window=4096, sliding_window_pattern="LLLG"))
    assert c.global_layers == (False, False, False, True) * 2


def test_qwen3_next_flat_rope_fields():
    """Qwen3-Next ships rope_theta / partial_rotary_factor flat at the top
    level (no rope_parameters dict) — they must not fall back to defaults."""
    c = config_from_hf_dict(base_dict(
        architectures=["Qwen3NextForCausalLM"], rope_theta=10_000_000.0,
        partial_rotary_factor=0.5, head_dim=16,
        layer_types=["linear_attention", "full_attention"] * 2,
        linear_num_key_heads=2, linear_key_head_dim=16,
        linear_num_value_heads=4, linear_value_head_dim=16))
    assert c.rope_theta == 10_000_000.0
    assert c.partial_rotary_factor == 0.5
    assert c.model_prefix == "model"


def test_qwen3_5_nested_text_config():
    d = dict(
        architectures=["Qwen3_5ForConditionalGeneration"],
        tie_word_embeddings=True,
        text_config=dict(
            hidden_size=1024, intermediate_size=3584, vocab_size=248320,
            num_hidden_layers=8, num_attention_heads=8, num_key_value_heads=2,
            head_dim=256, rms_norm_eps=1e-6,
            rope_parameters=dict(rope_theta=5000000.0, partial_rotary_factor=0.25),
            max_position_embeddings=32768,
            layer_types=["linear_attention", "linear_attention",
                         "linear_attention", "full_attention"] * 2,
            linear_conv_kernel_dim=4, linear_num_key_heads=16,
            linear_key_head_dim=128, linear_num_value_heads=32,
            linear_value_head_dim=128,
            eos_token_id=248045,
        ))
    c = config_from_hf_dict(d)
    assert c.arch == "qwen3_5"
    assert c.model_prefix == "model.language_model"
    assert c.residual_rms_norm and c.tie_word_embeddings
    assert c.rope_theta == 5000000.0 and c.partial_rotary_factor == 0.25
    assert c.linear_attn.num_value_heads == 32
    specs = c.layer_specs()
    assert [s.kind for s in specs] == ["linear"] * 3 + ["full"] + ["linear"] * 3 + ["full"]


def test_tiny_configs_build():
    for fam in ("llama", "qwen2", "qwen3", "qwen3_moe", "phi4", "mistral",
                "gemma3", "falcon3", "olmo2", "exaone4", "qwen3_5",
                "qwen3_5_moe"):
        c = tiny_config(fam)
        assert c.num_hidden_layers == 4
        assert len(c.layer_specs()) == 4
