"""Speculative decoding tests (cake_tpu/spec/ + the traced pieces in
ops/sampling.spec_accept, TextModel's verify programs and the cache
truncate ops).

The two invariants everything else hangs off:
  * greedy speculation is BIT-IDENTICAL to plain decoding (pinned for
    llama — attention-only, truncate rollback — and qwen3_5/GDN — linear
    state, valid_len-masked commit rollback);
  * sampled speculation preserves the target distribution (acceptance
    rule checked against hand-computed probabilities, plus an empirical
    marginal-distribution test at a fixed seed).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig, filtered_probs, spec_accept
from cake_tpu.spec import DraftModelDrafter, NGramDrafter, resolve_drafter

GREEDY = SamplingConfig(temperature=0.0)
# period-4 repetition: the n-gram drafter finds the continuation, and the
# verify step has real multi-token accepts to exercise
REP_PROMPT = [5, 9, 17, 23] * 4 + [5, 9]
RAND_PROMPT = list(range(3, 43))          # all-distinct: no bigram repeats


@pytest.fixture(scope="module")
def llama():
    return TextModel(tiny_config("llama"), dtype=jnp.float32,
                     max_cache_len=128, seed=3)


@pytest.fixture(scope="module")
def gdn():
    return TextModel(tiny_config("qwen3_5"), dtype=jnp.float32,
                     max_cache_len=128, seed=3)


# -- n-gram drafter -----------------------------------------------------------


def test_ngram_proposes_on_repetitive_prompt():
    d = NGramDrafter()
    # suffix [23, 5, 9] last occurred at index 7; continuation follows it
    assert d.propose(REP_PROMPT, 4) == [17, 23, 5, 9]
    assert d.propose(REP_PROMPT, 2) == [17, 23]


def test_ngram_abstains_on_random_prompt():
    assert NGramDrafter().propose(RAND_PROMPT, 4) == []
    assert NGramDrafter().propose([1, 2], 4) == []      # too short
    assert NGramDrafter().propose(REP_PROMPT, 0) == []  # no budget


def test_ngram_prefers_longest_match():
    # [7, 8] repeats with continuation 9; the 1-gram [8] also repeats with
    # a different continuation — min_ngram=1 must still take the longer
    # (more specific) match first
    ids = [7, 8, 9, 1, 8, 2, 7, 8]
    assert NGramDrafter(max_ngram=3, min_ngram=1).propose(ids, 1) == [9]


def test_ngram_validates_bounds():
    with pytest.raises(ValueError):
        NGramDrafter(max_ngram=2, min_ngram=3)


# -- acceptance rule against hand-computed probabilities ----------------------


def _accept(logits, draft, n_draft, key=0, temp=1.0, top_k=None, top_p=1.0,
            pen=1.0, recent_n=4):
    logits = jnp.asarray(logits, jnp.float32)
    v = logits.shape[-1]
    n_acc, nxt, recent = spec_accept(
        logits, jnp.asarray(draft, jnp.int32), jnp.asarray(n_draft,
                                                           jnp.int32),
        jax.random.PRNGKey(key), jnp.float32(temp),
        jnp.int32(top_k if top_k is not None else v), jnp.float32(top_p),
        jnp.float32(pen), jnp.full((recent_n,), -1, jnp.int32))
    return int(n_acc), int(nxt), recent


def test_accept_certain_draft_always_accepted():
    # p(draft token) ~= 1 at every position -> accept prob min(1, p) ~= 1
    big = 50.0
    logits = np.zeros((3, 4), np.float32)
    logits[0, 2] = big          # token after input 0 is surely 2
    logits[1, 1] = big          # after draft 2, surely 1
    logits[2, 3] = big          # bonus token: surely 3
    for key in range(8):
        n_acc, nxt, _ = _accept(logits, [2, 1], 2, key=key)
        assert n_acc == 2
        assert nxt == 3          # all accepted -> bonus sample from row 2


def test_accept_impossible_draft_always_rejected():
    # p(draft) ~= 0 -> reject; the correction comes from the residual,
    # which is p with the rejected token's mass removed -> surely token 2
    logits = np.zeros((2, 4), np.float32)
    logits[0, 2] = 50.0
    for key in range(8):
        n_acc, nxt, _ = _accept(logits, [1, 0], 2, key=key)
        assert n_acc == 0
        assert nxt == 2


def test_accept_rate_and_marginal_distribution():
    """Empirical check of the Leviathan delta-q rule: with p =
    [0.5, 0.3, 0.2] and draft token 0, accepts happen ~50% of the time
    and — the theorem — the emitted token's MARGINAL distribution is
    exactly p (accept contributes p(0) * delta_0, rejection contributes
    (1 - p(0)) * renorm(p without 0) = p elsewhere)."""
    p = np.array([0.5, 0.3, 0.2], np.float64)
    logits = jnp.asarray(np.log(p)[None, :].repeat(2, 0), jnp.float32)
    n = 4000

    def one(key):
        n_acc, nxt, _ = spec_accept(
            logits, jnp.asarray([0, 0], jnp.int32), jnp.asarray(1, jnp.int32),
            key, jnp.float32(1.0), jnp.int32(3), jnp.float32(1.0),
            jnp.float32(1.0), jnp.full((4,), -1, jnp.int32))
        first = jnp.where(n_acc > 0, 0, nxt)    # token emitted at position 0
        return n_acc, first

    keys = jax.random.split(jax.random.PRNGKey(1234), n)
    n_accs, firsts = jax.jit(jax.vmap(one))(keys)
    accept_rate = float(jnp.mean((n_accs > 0).astype(jnp.float32)))
    assert abs(accept_rate - 0.5) < 0.04
    counts = np.bincount(np.asarray(firsts), minlength=3) / n
    np.testing.assert_allclose(counts, p, atol=0.04)


def test_accept_greedy_is_exact_prefix_match():
    logits = np.zeros((3, 4), np.float32)
    logits[0, 1] = 2.0          # argmax chain: 1, 3, then bonus 0
    logits[1, 3] = 2.0
    logits[2, 0] = 2.0
    n_acc, nxt, _ = _accept(logits, [1, 3], 2, temp=0.0)
    assert (n_acc, nxt) == (2, 0)
    n_acc, nxt, _ = _accept(logits, [1, 2], 2, temp=0.0)   # mismatch at 1
    assert (n_acc, nxt) == (1, 3)                          # correction
    n_acc, nxt, _ = _accept(logits, [0, 3], 2, temp=0.0)   # mismatch at 0
    assert (n_acc, nxt) == (0, 1)


def test_accept_repeat_penalty_sees_accepted_prefix():
    """Position i's penalty window must contain the tokens accepted
    earlier in the SAME verify step (parity with one-at-a-time decode):
    token 1 leads everywhere, but after accepting it once a strong
    penalty flips the greedy choice to token 0 at the next position."""
    logits = np.full((3, 4), -1.0, np.float32)
    logits[:, 1] = 1.0
    logits[:, 0] = 0.9
    n_acc, nxt, _ = _accept(logits, [1, 1], 2, temp=0.0, pen=1.9)
    # draft[0]=1 accepted (fresh window); draft[1]=1 rejected (1 now
    # penalized: 1.0/1.9 < 0.9) with correction 0
    assert (n_acc, nxt) == (1, 0)


def test_accept_ignores_draft_padding():
    logits = np.zeros((3, 4), np.float32)
    logits[0, 1] = 50.0
    # n_draft=1: the pad entry (even if it "matches") can never accept
    n_acc, nxt, _ = _accept(logits, [1, 0], 1)
    assert n_acc == 1
    # n_draft=0 degenerates to a plain decode step
    n_acc, nxt, _ = _accept(logits, [0, 0], 0)
    assert n_acc == 0 and nxt == 1


def test_filtered_probs_matches_softmax():
    logits = jnp.asarray([0.3, -1.2, 2.0, 0.0], jnp.float32)
    p = filtered_probs(logits, jnp.float32(1.0), jnp.int32(4),
                       jnp.float32(1.0), jnp.float32(1.0),
                       jnp.full((4,), -1, jnp.int32))
    np.testing.assert_allclose(np.asarray(p),
                               np.asarray(jax.nn.softmax(logits)),
                               atol=1e-6)
    # top-k=1 concentrates all mass on the argmax
    p1 = filtered_probs(logits, jnp.float32(1.0), jnp.int32(1),
                        jnp.float32(1.0), jnp.float32(1.0),
                        jnp.full((4,), -1, jnp.int32))
    np.testing.assert_allclose(np.asarray(p1), [0, 0, 1, 0], atol=1e-6)


# -- bit-identity with the plain decode path ----------------------------------


@pytest.mark.parametrize(
    "fam", ["llama", pytest.param("gdn", marks=pytest.mark.slow)]  # tier-2 spec smokes cover gdn; 870s cap
)
def test_greedy_spec_bit_identical(fam, llama, gdn):
    m = {"llama": llama, "gdn": gdn}[fam]
    base, _ = m.generate(REP_PROMPT, max_new_tokens=24, sampling=GREEDY,
                         spec=False)
    spec, st = m.generate(REP_PROMPT, max_new_tokens=24, sampling=GREEDY,
                          spec="ngram")
    assert spec == base
    assert st["spec_steps"] > 0
    # and with a penalty in the greedy config (recent-window parity)
    pen = SamplingConfig(temperature=0.0, repeat_penalty=1.3)
    base_p, _ = m.generate(REP_PROMPT, max_new_tokens=16, sampling=pen,
                           spec=False)
    spec_p, _ = m.generate(REP_PROMPT, max_new_tokens=16, sampling=pen,
                           spec="ngram")
    assert spec_p == base_p


def test_greedy_spec_streaming_matches(llama):
    got = []
    base, _ = llama.generate(REP_PROMPT, max_new_tokens=20, sampling=GREEDY,
                             spec=False)
    spec, _ = llama.generate(REP_PROMPT, max_new_tokens=20, sampling=GREEDY,
                             spec="ngram", on_token=lambda t: got.append(t.id))
    assert spec == base
    assert got == spec          # every token streamed, first included


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_draft_model_drafter_perfect_draft(llama):
    """Draft model == target model -> every proposal accepts (the
    strongest end-to-end check of verify + rollback + re-proposal)."""
    d = TextModel(tiny_config("llama"), dtype=jnp.float32,
                  max_cache_len=128, seed=3)
    base, _ = llama.generate(REP_PROMPT, max_new_tokens=20, sampling=GREEDY,
                             spec=False)
    spec, st = llama.generate(REP_PROMPT, max_new_tokens=20, sampling=GREEDY,
                              spec=DraftModelDrafter(d))
    assert spec == base
    assert st["spec_accept_rate"] == 1.0
    assert st["spec_tokens_per_step"] > 2.0


def test_sampled_spec_deterministic_and_bounded(llama):
    scfg = SamplingConfig(temperature=0.9, top_k=40)
    k0 = jax.random.PRNGKey(7)
    a, st = llama.generate(REP_PROMPT, max_new_tokens=20, sampling=scfg,
                           spec="ngram", rng=k0)
    b, _ = llama.generate(REP_PROMPT, max_new_tokens=20, sampling=scfg,
                          spec="ngram", rng=k0)
    assert a == b               # same key -> same stream
    assert len(a) <= 20
    assert st["spec_steps"] >= 1


# -- KV rollback --------------------------------------------------------------


@pytest.mark.parametrize("fam", ["llama", "gdn"])
def test_kv_rollback_after_rejection(fam, llama, gdn):
    """After a verify step that REJECTS drafts, the cache must hold
    exactly the accepted prefix: the next decode step's logits must match
    a reference cache that never saw the rejected tokens. Covers both
    rollback strategies (truncate for attention-only, valid_len-masked
    commit for GDN)."""
    m = {"llama": llama, "gdn": gdn}[fam]
    prompt = REP_PROMPT[:8]
    k = 4

    cache = m.new_cache(1, kv_len=32)
    logits, cache = m.prefill(cache, prompt)
    first = int(np.argmax(np.asarray(logits[0])))
    # drafts chosen to be wrong: greedy acceptance rejects at position 0
    wrong = [(first + 3) % 250 + 1] * k
    recent = jnp.full((4,), -1, jnp.int32)
    packed, cache, _ = m.verify_tokens(cache, first, wrong, k, len(prompt),
                                       jax.random.PRNGKey(0), recent, GREEDY)
    n_acc, nxt = int(np.asarray(packed)[0]), int(np.asarray(packed)[1])
    assert n_acc == 0

    ref = m.new_cache(1, kv_len=32)
    _, ref = m.prefill(ref, prompt)
    ref_logits, ref = m.decode_logits(ref, first)
    assert int(np.argmax(np.asarray(ref_logits[0]))) == nxt

    # both caches now hold prompt + first; the next step must agree
    a, _ = m.decode_logits(cache, nxt)
    b, _ = m.decode_logits(ref, nxt)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_truncate_cache_drops_suffix(llama):
    from cake_tpu.models.common.cache import truncate_cache
    m = llama
    prompt = REP_PROMPT[:8]
    cache = m.new_cache(1, kv_len=32)
    logits, cache = m.prefill(cache, prompt)
    t = int(np.argmax(np.asarray(logits[0])))
    _, cache = m.decode_logits(cache, t)        # position 8
    _, cache = m.decode_logits(cache, t)        # position 9
    cache = truncate_cache(m.cfg, cache, len(prompt))
    assert int(cache["pos"]) == len(prompt)
    for lc in cache["layers"]:
        assert int(np.asarray(lc["pos"]).max()) < len(prompt)
    # a truncated cache continues exactly like a never-extended one
    ref = m.new_cache(1, kv_len=32)
    _, ref = m.prefill(ref, prompt)
    a, _ = m.decode_logits(cache, t)
    b, _ = m.decode_logits(ref, t)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_truncate_cache_rejects_linear(gdn):
    from cake_tpu.models.common.cache import truncate_cache
    cache = gdn.new_cache(1, kv_len=32)
    with pytest.raises(ValueError, match="linear"):
        truncate_cache(gdn.cfg, cache, 4)


def test_draft_model_drafter_consistent_after_rejection(llama):
    """The drafter's cache must hold exactly the confirmed prefix after a
    proposal round whose tokens the caller rejected: proposals for an
    extended sequence must match a FRESH drafter's."""
    d1 = DraftModelDrafter(TextModel(tiny_config("llama"), dtype=jnp.float32,
                                     max_cache_len=128, seed=11))
    d2 = DraftModelDrafter(TextModel(tiny_config("llama"), dtype=jnp.float32,
                                     max_cache_len=128, seed=11))
    ids = REP_PROMPT[:10]
    d1.propose(ids, 4)                   # speculates, then rolls back
    ext = ids + [42, 7]                  # caller went a different way
    assert d1.propose(ext, 4) == d2.propose(ext, 4)


def test_draft_model_drafter_rejects_linear(gdn):
    with pytest.raises(ValueError, match="linear"):
        DraftModelDrafter(gdn)


# -- resolve + engine ---------------------------------------------------------


def test_resolve_drafter(monkeypatch, llama):
    assert resolve_drafter(False)[0] is None
    assert resolve_drafter(None)[0] is None          # env unset -> off
    monkeypatch.setenv("CAKE_SPEC", "ngram")
    monkeypatch.setenv("CAKE_SPEC_K", "4")
    d, k = resolve_drafter(None)
    assert isinstance(d, NGramDrafter) and k == 4
    monkeypatch.setenv("CAKE_SPEC", "off")
    assert resolve_drafter(None)[0] is None
    with pytest.raises(ValueError):
        resolve_drafter("no-such-drafter")
    d, _ = resolve_drafter(llama)
    assert isinstance(d, DraftModelDrafter)


def test_engine_spec_e2e_multi_token_accept(llama):
    """Engine end-to-end with speculation on: greedy output bit-identical
    to the sequential path, with at least one MULTI-token accept (fewer
    verify steps than emitted tokens) and non-zero accept counters."""
    from cake_tpu.serve import ServeEngine
    base, _ = llama.generate(REP_PROMPT, max_new_tokens=24, sampling=GREEDY,
                             spec=False)
    eng = ServeEngine(llama, slots=2, max_queue=8, ctx_len=128,
                      prefix_cache_mb=0, spec="ngram", spec_k=6)
    try:
        r = eng.submit(REP_PROMPT, max_new_tokens=24, sampling=GREEDY)
        assert r.wait(300)
        assert "error" not in r.result, r.result.get("error")
        assert r.tokens == base
        h = eng.health()["spec"]
        assert h["accepted"] >= 1
        # fewer steps than decode tokens <=> >= 1 multi-token accept
        assert h["steps"] < len(r.tokens) - 1
    finally:
        eng.close()


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_engine_spec_sampled_slots_speculate(llama):
    """Sampled slots ride the batched verify too (each slot verifies
    with its own traced sampling params; spec_accept preserves the
    target distribution). top_k=1 makes the sampled pipeline a point
    mass, so the stochastic accept/resample path must reproduce the
    greedy stream exactly while actually taking verify steps."""
    from cake_tpu.serve import ServeEngine
    base, _ = llama.generate(REP_PROMPT, max_new_tokens=12, sampling=GREEDY,
                             spec=False)
    eng = ServeEngine(llama, slots=2, max_queue=8, ctx_len=128,
                      prefix_cache_mb=0, spec="ngram", spec_k=4)
    try:
        scfg = SamplingConfig(temperature=0.8, top_k=1)
        r1 = eng.submit(REP_PROMPT, max_new_tokens=12, sampling=scfg)
        r2 = eng.submit(REP_PROMPT, max_new_tokens=12, sampling=scfg)
        assert r1.wait(300) and r2.wait(300)
        assert "error" not in r1.result and "error" not in r2.result
        assert r1.tokens == base and r2.tokens == base
        assert eng.spec_steps > 0           # sampled slots speculate now
    finally:
        eng.close()


def test_engine_rejects_stateful_drafter(llama):
    from cake_tpu.serve import ServeEngine
    d = DraftModelDrafter(TextModel(tiny_config("llama"), dtype=jnp.float32,
                                    max_cache_len=64))
    with pytest.raises(ValueError, match="shareable|per-sequence"):
        ServeEngine(llama, slots=2, ctx_len=64, prefix_cache_mb=0, spec=d)
