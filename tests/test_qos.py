"""Unified admission plane (ISSUE 14): weighted-fair dequeue invariants,
per-class backpressure, tenant quotas, QoS-aware preemption policy, job
executor drain semantics, and engine-level preempt-resume bit-parity for
a batch slot evicted under interactive pressure (swap AND recompute).

The engine tests reuse test_paged's pool shape (12 blocks x 8 tokens,
chunk 16, ctx 128) so the paged executables compile once per model."""
import threading
import time
from types import SimpleNamespace

import jax.numpy as jnp
import pytest

from cake_tpu.models import TextModel, tiny_config
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve import ServeEngine
from cake_tpu.serve.admission import (AdmissionQueue, GenerationJob,
                                      JobCancelled, JobExecutor,
                                      JobsDraining, QueueFull,
                                      TenantQuotaExceeded, TenantRegistry,
                                      resolve_class, retry_after_for)
from cake_tpu.serve.paged import choose_victim

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16
BT = 8
BLOCKS = 12
WEIGHTS = {"interactive": 8.0, "standard": 4.0, "batch": 1.0}


def _item(qos):
    return SimpleNamespace(qos=qos)


# ---------------------------------------------------------------------------
# weighted-fair dequeue (pure host)
# ---------------------------------------------------------------------------


def test_weighted_fair_ratio_under_saturation():
    """With both lanes saturated, dequeues converge to the weight ratio
    — and batch is served at least once per replenish round (no
    starvation)."""
    q = AdmissionQueue(64, weights=WEIGHTS)
    for _ in range(32):
        q.put(_item("interactive"))
        q.put(_item("batch"))
    first_27 = [q.pop().qos for _ in range(27)]     # 3 full rounds
    assert first_27.count("batch") == 3             # 1 per 9, exactly
    assert first_27.count("interactive") == 24      # 8 per 9
    # batch appears within every round of 9 — never starved
    for r in range(3):
        assert "batch" in first_27[r * 9:(r + 1) * 9]


def test_batch_progresses_under_continuous_interactive_arrivals():
    """Interactive arrivals that never stop cannot starve batch: each
    replenish round still credits the batch lane."""
    q = AdmissionQueue(256, weights=WEIGHTS)
    for _ in range(4):
        q.put(_item("batch"))
    served_batch = 0
    for _ in range(50):
        q.put(_item("interactive"))     # keep the fast lane saturated
        it = q.pop()
        if it.qos == "batch":
            served_batch += 1
    assert served_batch == 4, "batch starved behind interactive arrivals"


def test_deficit_resets_when_class_empties():
    """DRR reset-on-empty: an idle class banks no credit, so a burst
    after idling is served at its weight ratio, not its backlog age."""
    q = AdmissionQueue(64, weights=WEIGHTS)
    q.put(_item("batch"))
    assert q.pop().qos == "batch"       # round replenished, batch drains
    # batch lane idles through many interactive rounds
    for _ in range(20):
        q.put(_item("interactive"))
    for _ in range(20):
        assert q.pop().qos == "interactive"
        assert q._deficit["batch"] == 0.0   # reset while empty
    # now a mixed burst: interactive still gets its 8:1 share first
    for _ in range(9):
        q.put(_item("interactive"))
        q.put(_item("batch"))
    assert [q.pop().qos for _ in range(8)] == ["interactive"] * 8


def test_fifo_preserved_within_class():
    q = AdmissionQueue(64, weights=WEIGHTS)
    items = [SimpleNamespace(qos="interactive", n=i) for i in range(5)]
    for it in items:
        q.put(it)
    assert [q.pop().n for _ in range(5)] == [0, 1, 2, 3, 4]


def test_per_class_bound_and_class_aware_retry_after():
    """Bounds are per class: a full batch lane sheds batch (typed, with
    a LONGER Retry-After than the same depth would earn interactive)
    while interactive admission stays open."""
    q = AdmissionQueue(4, weights=WEIGHTS,
                       bounds={"interactive": 4, "standard": 4, "batch": 2})
    q.put(_item("batch"))
    q.put(_item("batch"))
    with pytest.raises(QueueFull) as ei:
        q.put(_item("batch"))
    assert ei.value.qos == "batch"
    assert ei.value.retry_after_s >= 1
    q.put(_item("interactive"))         # other lanes unaffected
    # the hint scales inversely with the class's service share
    assert retry_after_for(40, "batch", WEIGHTS) \
        > retry_after_for(40, "interactive", WEIGHTS)


def test_queue_depth_gauges_sum_across_queues():
    """The engine queue and the job queue publish into the SAME depth
    instruments — per class and in total."""
    import gc
    from cake_tpu.obs import SERVE_QOS_QUEUE_DEPTH, SERVE_QUEUE_DEPTH
    gc.collect()        # drop earlier tests' queues from the weak board
    qa = AdmissionQueue(64, weights=WEIGHTS)
    qb = AdmissionQueue(64, weights=WEIGHTS)
    qa.put(_item("interactive"))
    qb.put(_item("batch"))
    qb.put(_item("batch"))
    assert SERVE_QUEUE_DEPTH.value() == 3
    assert SERVE_QOS_QUEUE_DEPTH.value(qos="interactive") == 1
    assert SERVE_QOS_QUEUE_DEPTH.value(qos="batch") == 2
    qa.drain()
    qb.drain()
    assert SERVE_QUEUE_DEPTH.value() == 0


# ---------------------------------------------------------------------------
# class resolution + tenants (pure host)
# ---------------------------------------------------------------------------


def test_resolve_class_default_override_clamp():
    assert resolve_class("batch") == "batch"
    assert resolve_class("batch", header="interactive") == "interactive"
    assert resolve_class("interactive", body_value="batch") == "batch"
    # header wins over body
    assert resolve_class("batch", header="standard",
                         body_value="interactive") == "standard"
    # tenant ceiling clamps upward requests, never downward ones
    assert resolve_class("batch", header="interactive",
                         max_class="standard") == "standard"
    assert resolve_class("batch", max_class="standard") == "batch"
    with pytest.raises(ValueError):
        resolve_class("interactive", header="premium")


def test_tenant_bucket_refill_and_inflight():
    clock = [0.0]
    tr = TenantRegistry("acme:rps=2,burst=2,inflight=8;free:inflight=1",
                        clock=lambda: clock[0])
    rel = [tr.acquire("acme"), tr.acquire("acme")]      # burst of 2
    with pytest.raises(TenantQuotaExceeded) as ei:
        tr.acquire("acme")
    assert ei.value.reason == "rate"
    assert ei.value.retry_after_s >= 1
    assert ei.value.body()["type"] == "tenant_quota"
    clock[0] += 0.5                                     # refills 1 token
    rel.append(tr.acquire("acme"))
    for r in rel:
        r()
    # inflight cap, released on terminal
    r1 = tr.acquire("free")
    with pytest.raises(TenantQuotaExceeded) as ei:
        tr.acquire("free")
    assert ei.value.reason == "inflight"
    r1()
    r1()                                                # idempotent
    tr.acquire("free")()
    # default-open: unknown tenants and anonymous requests are unlimited
    for _ in range(50):
        tr.acquire("someone-else")
        tr.acquire(None)


def test_tenant_max_class_and_wildcard():
    tr = TenantRegistry("acme:max_class=standard;*:max_class=batch")
    assert tr.max_class("acme") == "standard"
    assert tr.max_class("anyone") == "batch"            # wildcard
    assert TenantRegistry("").max_class("anyone") is None


# ---------------------------------------------------------------------------
# QoS-aware victim choice (policy unit)
# ---------------------------------------------------------------------------


def test_choose_victim_lowest_class_first_lifo_within():
    def req(qos, t):
        return SimpleNamespace(qos=qos, t_enqueue=t)
    cands = [(0, req("interactive", 3.0)),   # newest overall
             (1, req("batch", 1.0)),
             (2, req("batch", 2.0)),
             (3, req("standard", 4.0))]
    # batch first even though interactive/standard are newer; LIFO
    # within batch picks slot 2
    assert choose_victim(cands)[0] == 2
    # exclude the preferred victim: the other batch slot goes
    assert choose_victim(cands, exclude=2)[0] == 1
    # no batch left: standard before interactive
    assert choose_victim([c for c in cands if c[1].qos != "batch"])[0] == 3
    # single class degrades to the pre-QoS LIFO rule
    only_i = [(0, req("interactive", 1.0)), (1, req("interactive", 9.0))]
    assert choose_victim(only_i)[0] == 1
    # foreign objects without .qos rank as interactive (never
    # preferentially evicted)
    mixed = [(0, SimpleNamespace(t_enqueue=9.0)), (1, req("batch", 1.0))]
    assert choose_victim(mixed)[0] == 1


# ---------------------------------------------------------------------------
# job executor: weighted lanes, checkpoint cancel, drain semantics
# ---------------------------------------------------------------------------


def test_job_executor_runs_and_reports():
    ex = JobExecutor(workers=1, max_queue=8)
    try:
        job = ex.submit(GenerationJob("image", lambda j: 42, qos="batch"))
        assert job.wait(10)
        assert job.result["value"] == 42
        from cake_tpu.obs import TIMELINES
        tl = TIMELINES.get(job.id)
        kinds = [e["kind"] for e in tl["events"]]
        assert kinds == ["enqueue", "admit", "finish"]
        assert all(e.get("qos") == "batch" for e in tl["events"])
    finally:
        ex.close()


def test_job_checkpoint_cancellation():
    ex = JobExecutor(workers=1, max_queue=8)
    started = threading.Event()

    def fn(job):
        started.set()
        for _ in range(2000):
            job.checkpoint()
            time.sleep(0.005)
        return "finished"
    try:
        job = ex.submit(GenerationJob("image", fn))
        assert started.wait(10)
        job.cancel()
        assert job.wait(10)
        assert isinstance(job.result["error"], JobCancelled)
    finally:
        ex.close()


def test_drain_refuses_new_batch_jobs_finishes_running():
    """The acceptance-criteria drain contract: a running batch job
    finishes across the drain; a NEW batch job is refused typed."""
    ex = JobExecutor(workers=1, max_queue=8)
    release = threading.Event()
    started = threading.Event()

    def fn(job):
        started.set()
        assert release.wait(10)
        return "done"
    try:
        running = ex.submit(GenerationJob("image", fn, qos="batch"))
        assert started.wait(10)
        ex.begin_drain()
        with pytest.raises(JobsDraining) as ei:
            ex.submit(GenerationJob("image", lambda j: 1, qos="batch"))
        assert ei.value.retry_after_s >= 1
        release.set()
        assert ex.drain(10), "running job did not finish under drain"
        assert running.result["value"] == "done"
    finally:
        ex.close()


# ---------------------------------------------------------------------------
# engine e2e: batch preempted under interactive pressure, bit-identical
# resume (swap + recompute) — the acceptance-criteria parity pin
# ---------------------------------------------------------------------------

def _model():
    # SHARE test_paged's module-level model (same CTX/CHUNK/BT/BLOCKS
    # shapes, same process, test_paged runs first alphabetically): the
    # paged decode/prefill executables compile once for both files —
    # a second TextModel instance here cost the tier-1 budget ~40s of
    # duplicate XLA compiles
    from tests.test_paged import _model as paged_model
    return paged_model()


def _engine(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_queue", 8)
    kw.setdefault("ctx_len", CTX)
    kw.setdefault("prefill_chunk", CHUNK)
    kw.setdefault("kv_blocks", BLOCKS)
    kw.setdefault("kv_block_tokens", BT)
    kw.setdefault("prefix_cache_mb", 0)
    return ServeEngine(_model(), **kw)


def _ref(prompt, n):
    toks, _ = _model().generate(list(prompt), max_new_tokens=n,
                                sampling=GREEDY)
    return toks


P_BATCH = [3, 17, 42, 99, 7]
# 78-token prompt → 10 of the 12 pool blocks for the prefill alone, so
# admitting it while the batch slot holds blocks deterministically
# exhausts the pool mid-prefill (choose_victim runs with the batch slot
# as the decoding candidate)
P_INTER = [5 + (i * 11) % 180 for i in range(78)]


# swap mode stays tier-1; recompute rides tier-2 (slow) — the suite sits
# near the 870s cap on this 1-core box and the two modes share every
# code path except the resume mechanism, which test_paged's own
# exhaustion parity already pins for recompute
@pytest.mark.parametrize("mode", [
    "swap",
    pytest.param("recompute", marks=pytest.mark.slow),
])
def test_qos_preempt_batch_slot_resumes_bit_identical(mode):
    """A decoding BATCH request is preempted when an interactive
    admission's prefill exhausts the 96-token pool (the batch slot is
    the policy victim), parks, resumes after the interactive request
    finishes, and completes bit-identical to the sequential path — for
    swap (exact bytes) and recompute (replay). The interactive request
    is never preempted."""
    from cake_tpu.obs import SERVE_PREEMPTIONS, TIMELINES
    ref_b = _ref(P_BATCH, 28)
    ref_i = _ref(P_INTER, 6)
    before = SERVE_PREEMPTIONS.value(mode=mode)
    eng = _engine(preempt_mode=mode)
    try:
        rb = eng.submit(P_BATCH, max_new_tokens=28, sampling=GREEDY,
                        qos="batch", tenant="acme")
        deadline = time.monotonic() + 60
        while len(rb.tokens) < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert rb.tokens, "batch request never started decoding"
        ri = eng.submit(P_INTER, max_new_tokens=6, sampling=GREEDY,
                        qos="interactive")
        assert ri.wait(300) and rb.wait(300)
        assert "error" not in ri.result, ri.result.get("error")
        assert "error" not in rb.result, rb.result.get("error")
        assert ri.result["tokens"] == ref_i
        assert rb.result["tokens"] == ref_b
        assert SERVE_PREEMPTIONS.value(mode=mode) > before, \
            "pool never exhausted — QoS preemption untested"
        kinds_b = [e["kind"] for e in TIMELINES.get(rb.id)["events"]]
        kinds_i = [e["kind"] for e in TIMELINES.get(ri.id)["events"]]
        assert "preempt" in kinds_b, "batch slot was not the victim"
        assert "preempt" not in kinds_i, "interactive request preempted"
        # class + tenant attrs ride the timeline (enqueue + finish)
        ev_b = TIMELINES.get(rb.id)["events"]
        assert any(e.get("qos") == "batch" and e.get("tenant") == "acme"
                   for e in ev_b if e["kind"] == "enqueue")
    finally:
        eng.close()


def test_engine_qos_slo_instruments_labeled():
    """The per-class SLO histograms observe engine terminals with the
    request's class label."""
    from cake_tpu.obs import SERVE_QOS_E2E_SECONDS, SERVE_QOS_TTFT_SECONDS
    b_e2e = SERVE_QOS_E2E_SECONDS.count(qos="standard", outcome="ok")
    b_ttft = SERVE_QOS_TTFT_SECONDS.count(qos="standard", outcome="ok")
    eng = _engine()
    try:
        r = eng.submit(P_BATCH, max_new_tokens=4, sampling=GREEDY,
                       qos="standard")
        assert r.wait(120)
        assert "error" not in r.result
    finally:
        eng.close()
    assert SERVE_QOS_E2E_SECONDS.count(qos="standard", outcome="ok") \
        > b_e2e
    assert SERVE_QOS_TTFT_SECONDS.count(qos="standard", outcome="ok") \
        > b_ttft


# ---------------------------------------------------------------------------
# API integration: tenant 429 body, image job timeline, size clamp
# ---------------------------------------------------------------------------


def _api_state():
    from tests.test_api import (MockAudioModel, MockImageModel,
                                MockTextModel, MockTokenizer)
    from cake_tpu.api import ApiState
    return ApiState(model=MockTextModel(), tokenizer=MockTokenizer(),
                    model_id="mock-model", image_model=MockImageModel(),
                    audio_model=MockAudioModel())


def _with_client(state, fn):
    from tests.test_api import with_client
    with_client(state, fn)


def test_api_image_job_traced_end_to_end():
    """An image request adopts X-Cake-Request-Id, echoes it, and its
    enqueue→admit→finish lifecycle is retrievable from
    GET /api/v1/requests/<id> with class + workload attrs."""
    state = _api_state()

    async def scenario(client):
        rid = "trace-img-e2e-1"
        r = await client.post("/v1/images/generations",
                              json={"prompt": "a cake", "size": "32x32"},
                              headers={"X-Cake-Request-Id": rid})
        assert r.status == 200
        assert r.headers["X-Cake-Request-Id"] == rid
        t = await client.get(f"/api/v1/requests/{rid}")
        assert t.status == 200
        tl = await t.json()
        kinds = [e["kind"] for e in tl["events"]]
        assert kinds[:2] == ["received", "enqueue"]
        assert "admit" in kinds and "finish" in kinds
        admit = next(e for e in tl["events"] if e["kind"] == "admit")
        assert admit["qos"] == "batch" and admit["workload"] == "image"
    _with_client(state, scenario)


def test_api_image_qos_override_and_invalid():
    state = _api_state()

    async def scenario(client):
        r = await client.post("/v1/images/generations",
                              json={"prompt": "x", "size": "16x16",
                                    "qos": "interactive"},
                              headers={"X-Cake-Request-Id": "img-q1"})
        assert r.status == 200
        t = await (await client.get("/api/v1/requests/img-q1")).json()
        admit = next(e for e in t["events"] if e["kind"] == "admit")
        assert admit["qos"] == "interactive"
        r = await client.post("/v1/images/generations",
                              json={"prompt": "x", "size": "16x16"},
                              headers={"X-Cake-QoS": "premium"})
        assert r.status == 400
    _with_client(state, scenario)


def test_api_image_size_clamped():
    state = _api_state()

    async def scenario(client):
        for size in ("999999x64", "64x999999", "0x64", "-2x32", "axb"):
            r = await client.post("/v1/images/generations",
                                  json={"prompt": "x", "size": size})
            assert r.status == 400, size
        # the knob widens/narrows the clamp
        import os
        os.environ["CAKE_IMAGE_MAX_SIZE"] = "64"
        try:
            r = await client.post("/v1/images/generations",
                                  json={"prompt": "x", "size": "65x32"})
            assert r.status == 400
            r = await client.post("/v1/images/generations",
                                  json={"prompt": "x", "size": "64x32"})
            assert r.status == 200
        finally:
            del os.environ["CAKE_IMAGE_MAX_SIZE"]
    _with_client(state, scenario)


def test_api_tenant_quota_429_all_endpoints(monkeypatch):
    """An over-quota tenant is answered the typed 429 tenant_quota body
    on chat, images AND audio — before any queue slot is consumed."""
    monkeypatch.setenv("CAKE_QOS_TENANTS", "acme:rps=1000,inflight=1")
    state = _api_state()
    # hold the tenant's single inflight slot via a stuck image job
    from cake_tpu.serve.admission import get_plane
    plane = get_plane(state)
    release = plane.admit("acme")

    async def scenario(client):
        hdrs = {"X-Cake-Tenant": "acme"}
        for path, body in (
                ("/v1/chat/completions",
                 {"messages": [{"role": "user", "content": "hi"}]}),
                ("/v1/images/generations",
                 {"prompt": "x", "size": "16x16"}),
                ("/v1/audio/speech", {"input": "hello"})):
            r = await client.post(path, json=body, headers=hdrs)
            assert r.status == 429, path
            data = await r.json()
            assert data["type"] == "tenant_quota"
            assert data["tenant"] == "acme"
            assert int(r.headers["Retry-After"]) >= 1
        # anonymous requests are untouched (default-open)
        r = await client.post("/v1/images/generations",
                              json={"prompt": "x", "size": "16x16"})
        assert r.status == 200
    try:
        _with_client(state, scenario)
    finally:
        release()


def test_api_audio_traced_and_draining(monkeypatch):
    state = _api_state()

    async def scenario(client):
        r = await client.post("/v1/audio/speech",
                              json={"input": "hello"},
                              headers={"X-Cake-Request-Id": "tts-1"})
        assert r.status == 200
        assert r.headers["X-Cake-Request-Id"] == "tts-1"
        t = await (await client.get("/api/v1/requests/tts-1")).json()
        admit = next(e for e in t["events"] if e["kind"] == "admit")
        assert admit["workload"] == "audio"
        # drain: new image/audio work refused typed while state drains
        state.draining = True
        r = await client.post("/v1/audio/speech", json={"input": "x"})
        assert r.status == 503
        r = await client.post("/v1/images/generations",
                              json={"prompt": "x", "size": "16x16"})
        assert r.status == 503
    _with_client(state, scenario)
