"""GGUF loading tests: binary header round-trip, name mapping, block
dequantizers vs scalar reference implementations of the ggml layouts."""
import struct

import numpy as np
import pytest

from cake_tpu.utils.gguf import (GGUF_MAGIC, GgufReader, GgufStorage,
                                 dequant_q4_0, dequant_q4_k, dequant_q6_k,
                                 dequant_q8_0, gguf_config_dict,
                                 gguf_to_hf_name)


def _w_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<Q", len(b)) + b


def _w_kv_u32(key, val) -> bytes:
    return _w_str(key) + struct.pack("<II", 4, val)


def _w_kv_f32(key, val) -> bytes:
    return _w_str(key) + struct.pack("<If", 6, val)


def _w_kv_str(key, val) -> bytes:
    return _w_str(key) + struct.pack("<I", 8) + _w_str(val)


def write_tiny_gguf(path, tensors: dict[str, np.ndarray], meta_arch="llama"):
    """Minimal GGUF v3 writer for tests (F32 tensors only)."""
    kvs = [
        _w_kv_str("general.architecture", meta_arch),
        _w_kv_u32("general.alignment", 32),
        _w_kv_u32(f"{meta_arch}.embedding_length", 64),
        _w_kv_u32(f"{meta_arch}.block_count", 2),
        _w_kv_u32(f"{meta_arch}.attention.head_count", 4),
        _w_kv_u32(f"{meta_arch}.attention.head_count_kv", 2),
        _w_kv_u32(f"{meta_arch}.feed_forward_length", 128),
        _w_kv_u32(f"{meta_arch}.context_length", 512),
        _w_kv_f32(f"{meta_arch}.rope.freq_base", 10000.0),
        _w_kv_f32(f"{meta_arch}.attention.layer_norm_rms_epsilon", 1e-5),
        _w_kv_u32("tokenizer.ggml.eos_token_id", 2),
    ]
    infos = []
    data = b""
    for name, arr in tensors.items():
        # ggml dims reversed: innermost first
        dims = tuple(reversed(arr.shape))
        infos.append(_w_str(name)
                     + struct.pack("<I", len(dims))
                     + struct.pack(f"<{len(dims)}Q", *dims)
                     + struct.pack("<IQ", 0, len(data)))      # F32
        blob = np.ascontiguousarray(arr, np.float32).tobytes()
        data += blob + b"\0" * ((-len(blob)) % 32)
    header = struct.pack("<IIQQ", GGUF_MAGIC, 3, len(tensors), len(kvs))
    body = header + b"".join(kvs) + b"".join(infos)
    pad = (-len(body)) % 32
    with open(path, "wb") as f:
        f.write(body + b"\0" * pad + data)


def test_gguf_read_roundtrip(tmp_path, rng):
    w = rng.standard_normal((8, 64)).astype(np.float32)
    e = rng.standard_normal((256, 64)).astype(np.float32)
    p = str(tmp_path / "m.gguf")
    write_tiny_gguf(p, {"blk.0.attn_q.weight": w, "token_embd.weight": e})
    r = GgufReader(p)
    assert r.metadata["llama.embedding_length"] == 64
    np.testing.assert_array_equal(r.read_tensor("blk.0.attn_q.weight"), w)
    np.testing.assert_array_equal(r.read_tensor("token_embd.weight"), e)
    cfg = gguf_config_dict(r)
    assert cfg["architectures"] == ["LlamaForCausalLM"]
    assert cfg["vocab_size"] == 256 and cfg["num_key_value_heads"] == 2
    assert cfg["eos_token_id"] == 2 and cfg["tie_word_embeddings"]

    st = GgufStorage(p)
    assert "model.layers.0.self_attn.q_proj.weight" in st
    np.testing.assert_array_equal(
        st.read("model.layers.0.self_attn.q_proj.weight"), w)


def test_name_mapping():
    assert gguf_to_hf_name("blk.3.ffn_gate.weight") == \
        "model.layers.3.mlp.gate_proj.weight"
    assert gguf_to_hf_name("blk.0.attn_norm.weight") == \
        "model.layers.0.input_layernorm.weight"
    assert gguf_to_hf_name("output.weight") == "lm_head.weight"
    assert gguf_to_hf_name("rope_freqs.weight") is None


def test_q4_0_dequant():
    # one block: d=0.5, qs nibbles 0..15 repeating
    d = np.float16(0.5).tobytes()
    qs = bytes(range(16))
    got = dequant_q4_0(d + qs, 32)
    lo = np.array([q & 0xF for q in range(16)], np.float32)
    hi = np.array([q >> 4 for q in range(16)], np.float32)
    want = np.concatenate([lo, hi])
    np.testing.assert_allclose(got, (want - 8) * 0.5)


def test_q8_0_dequant():
    d = np.float16(0.25).tobytes()
    q = np.arange(-16, 16, dtype=np.int8)
    got = dequant_q8_0(d + q.tobytes(), 32)
    np.testing.assert_allclose(got, q.astype(np.float32) * 0.25)


def _scalar_q4k(block: bytes) -> np.ndarray:
    """Scalar reference following ggml dequantize_row_q4_K."""
    d = np.frombuffer(block[0:2], np.float16)[0].astype(np.float32)
    dmin = np.frombuffer(block[2:4], np.float16)[0].astype(np.float32)
    scales = block[4:16]
    qs = block[16:144]
    def sm(j):
        if j < 4:
            return scales[j] & 63, scales[j + 4] & 63
        sc = (scales[j + 4] & 0xF) | ((scales[j - 4] >> 6) << 4)
        m = (scales[j + 4] >> 4) | ((scales[j] >> 6) << 4)
        return sc, m
    y = np.zeros(256, np.float32)
    is_ = 0
    qoff = 0
    for j in range(0, 256, 64):
        sc1, m1 = sm(is_)
        sc2, m2 = sm(is_ + 1)
        for l in range(32):
            y[j + l] = d * sc1 * (qs[qoff + l] & 0xF) - dmin * m1
            y[j + 32 + l] = d * sc2 * (qs[qoff + l] >> 4) - dmin * m2
        is_ += 2
        qoff += 32
    return y


def test_q4_k_dequant_vs_scalar(rng):
    block = bytes(np.float16(0.33).tobytes()) + bytes(np.float16(0.11).tobytes()) \
        + bytes(rng.integers(0, 256, 12, dtype=np.uint32).astype(np.uint8)) \
        + bytes(rng.integers(0, 256, 128, dtype=np.uint32).astype(np.uint8))
    got = dequant_q4_k(block, 256)
    np.testing.assert_allclose(got, _scalar_q4k(block), atol=1e-4)


def _scalar_q6k(block: bytes) -> np.ndarray:
    ql = block[0:128]
    qh = block[128:192]
    sc = np.frombuffer(block[192:208], np.int8)
    d = np.frombuffer(block[208:210], np.float16)[0].astype(np.float32)
    y = np.zeros(256, np.float32)
    for n in range(2):
        yo, qlo, qho, so = n * 128, n * 64, n * 32, n * 8
        for l in range(32):
            is_ = l // 16
            q1 = ((ql[qlo + l] & 0xF) | (((qh[qho + l] >> 0) & 3) << 4)) - 32
            q2 = ((ql[qlo + l + 32] & 0xF) | (((qh[qho + l] >> 2) & 3) << 4)) - 32
            q3 = ((ql[qlo + l] >> 4) | (((qh[qho + l] >> 4) & 3) << 4)) - 32
            q4 = ((ql[qlo + l + 32] >> 4) | (((qh[qho + l] >> 6) & 3) << 4)) - 32
            y[yo + l] = d * sc[so + is_] * q1
            y[yo + l + 32] = d * sc[so + is_ + 2] * q2
            y[yo + l + 64] = d * sc[so + is_ + 4] * q3
            y[yo + l + 96] = d * sc[so + is_ + 6] * q4
    return y


def test_q6_k_dequant_vs_scalar(rng):
    block = bytes(rng.integers(0, 256, 208, dtype=np.uint32).astype(np.uint8)) \
        + np.float16(0.77).tobytes()
    got = dequant_q6_k(block, 256)
    np.testing.assert_allclose(got, _scalar_q6k(block), atol=1e-4)
