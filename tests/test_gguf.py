"""GGUF loading tests: binary header round-trip, name mapping, block
dequantizers vs scalar reference implementations of the ggml layouts."""
import struct

import numpy as np
import pytest

from cake_tpu.utils.gguf import (GGUF_MAGIC, GgufReader, GgufStorage,
                                 dequant_q4_0, dequant_q4_k, dequant_q6_k,
                                 dequant_q8_0, gguf_config_dict,
                                 gguf_to_hf_name)


def _w_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<Q", len(b)) + b


def _w_kv_u32(key, val) -> bytes:
    return _w_str(key) + struct.pack("<II", 4, val)


def _w_kv_f32(key, val) -> bytes:
    return _w_str(key) + struct.pack("<If", 6, val)


def _w_kv_str(key, val) -> bytes:
    return _w_str(key) + struct.pack("<I", 8) + _w_str(val)


def write_tiny_gguf(path, tensors: dict[str, np.ndarray], meta_arch="llama",
                    kv_overrides: dict | None = None):
    """Minimal GGUF v3 writer for tests (F32 tensors only).
    kv_overrides: unprefixed key -> int/float, merged over the defaults."""
    meta = {
        "embedding_length": 64, "block_count": 2,
        "attention.head_count": 4, "attention.head_count_kv": 2,
        "feed_forward_length": 128, "context_length": 512,
        "rope.freq_base": 10000.0,
        "attention.layer_norm_rms_epsilon": 1e-5,
    }
    meta.update(kv_overrides or {})
    kvs = [
        _w_kv_str("general.architecture", meta_arch),
        _w_kv_u32("general.alignment", 32),
        _w_kv_u32("tokenizer.ggml.eos_token_id", 2),
    ]
    for k, v in meta.items():
        key = f"{meta_arch}.{k}"
        kvs.append(_w_kv_f32(key, v) if isinstance(v, float)
                   else _w_kv_u32(key, int(v)))
    infos = []
    data = b""
    for name, arr in tensors.items():
        # ggml dims reversed: innermost first
        dims = tuple(reversed(arr.shape))
        infos.append(_w_str(name)
                     + struct.pack("<I", len(dims))
                     + struct.pack(f"<{len(dims)}Q", *dims)
                     + struct.pack("<IQ", 0, len(data)))      # F32
        blob = np.ascontiguousarray(arr, np.float32).tobytes()
        data += blob + b"\0" * ((-len(blob)) % 32)
    header = struct.pack("<IIQQ", GGUF_MAGIC, 3, len(tensors), len(kvs))
    body = header + b"".join(kvs) + b"".join(infos)
    pad = (-len(body)) % 32
    with open(path, "wb") as f:
        f.write(body + b"\0" * pad + data)


def test_gguf_read_roundtrip(tmp_path, rng):
    w = rng.standard_normal((8, 64)).astype(np.float32)
    e = rng.standard_normal((256, 64)).astype(np.float32)
    p = str(tmp_path / "m.gguf")
    write_tiny_gguf(p, {"blk.0.attn_q.weight": w, "token_embd.weight": e})
    r = GgufReader(p)
    assert r.metadata["llama.embedding_length"] == 64
    np.testing.assert_array_equal(r.read_tensor("blk.0.attn_q.weight"), w)
    np.testing.assert_array_equal(r.read_tensor("token_embd.weight"), e)
    cfg = gguf_config_dict(r)
    assert cfg["architectures"] == ["LlamaForCausalLM"]
    assert cfg["vocab_size"] == 256 and cfg["num_key_value_heads"] == 2
    assert cfg["eos_token_id"] == 2 and cfg["tie_word_embeddings"]

    st = GgufStorage(p)
    assert "model.layers.0.self_attn.q_proj.weight" in st
    np.testing.assert_array_equal(
        st.read("model.layers.0.self_attn.q_proj.weight"), w)


def test_name_mapping():
    assert gguf_to_hf_name("blk.3.ffn_gate.weight") == \
        "model.layers.3.mlp.gate_proj.weight"
    assert gguf_to_hf_name("blk.0.attn_norm.weight") == \
        "model.layers.0.input_layernorm.weight"
    assert gguf_to_hf_name("output.weight") == "lm_head.weight"
    assert gguf_to_hf_name("rope_freqs.weight") is None


def test_q4_0_dequant():
    # one block: d=0.5, qs nibbles 0..15 repeating
    d = np.float16(0.5).tobytes()
    qs = bytes(range(16))
    got = dequant_q4_0(d + qs, 32)
    lo = np.array([q & 0xF for q in range(16)], np.float32)
    hi = np.array([q >> 4 for q in range(16)], np.float32)
    want = np.concatenate([lo, hi])
    np.testing.assert_allclose(got, (want - 8) * 0.5)


def test_q8_0_dequant():
    d = np.float16(0.25).tobytes()
    q = np.arange(-16, 16, dtype=np.int8)
    got = dequant_q8_0(d + q.tobytes(), 32)
    np.testing.assert_allclose(got, q.astype(np.float32) * 0.25)


def _scalar_q4k(block: bytes) -> np.ndarray:
    """Scalar reference following ggml dequantize_row_q4_K."""
    d = np.frombuffer(block[0:2], np.float16)[0].astype(np.float32)
    dmin = np.frombuffer(block[2:4], np.float16)[0].astype(np.float32)
    scales = block[4:16]
    qs = block[16:144]
    def sm(j):
        if j < 4:
            return scales[j] & 63, scales[j + 4] & 63
        sc = (scales[j + 4] & 0xF) | ((scales[j - 4] >> 6) << 4)
        m = (scales[j + 4] >> 4) | ((scales[j] >> 6) << 4)
        return sc, m
    y = np.zeros(256, np.float32)
    is_ = 0
    qoff = 0
    for j in range(0, 256, 64):
        sc1, m1 = sm(is_)
        sc2, m2 = sm(is_ + 1)
        for l in range(32):
            y[j + l] = d * sc1 * (qs[qoff + l] & 0xF) - dmin * m1
            y[j + 32 + l] = d * sc2 * (qs[qoff + l] >> 4) - dmin * m2
        is_ += 2
        qoff += 32
    return y


def test_q4_k_dequant_vs_scalar(rng):
    block = bytes(np.float16(0.33).tobytes()) + bytes(np.float16(0.11).tobytes()) \
        + bytes(rng.integers(0, 256, 12, dtype=np.uint32).astype(np.uint8)) \
        + bytes(rng.integers(0, 256, 128, dtype=np.uint32).astype(np.uint8))
    got = dequant_q4_k(block, 256)
    np.testing.assert_allclose(got, _scalar_q4k(block), atol=1e-4)


def _scalar_q6k(block: bytes) -> np.ndarray:
    ql = block[0:128]
    qh = block[128:192]
    sc = np.frombuffer(block[192:208], np.int8)
    d = np.frombuffer(block[208:210], np.float16)[0].astype(np.float32)
    y = np.zeros(256, np.float32)
    for n in range(2):
        yo, qlo, qho, so = n * 128, n * 64, n * 32, n * 8
        for l in range(32):
            is_ = l // 16
            q1 = ((ql[qlo + l] & 0xF) | (((qh[qho + l] >> 0) & 3) << 4)) - 32
            q2 = ((ql[qlo + l + 32] & 0xF) | (((qh[qho + l] >> 2) & 3) << 4)) - 32
            q3 = ((ql[qlo + l] >> 4) | (((qh[qho + l] >> 4) & 3) << 4)) - 32
            q4 = ((ql[qlo + l + 32] >> 4) | (((qh[qho + l] >> 6) & 3) << 4)) - 32
            y[yo + l] = d * sc[so + is_] * q1
            y[yo + l + 32] = d * sc[so + is_ + 2] * q2
            y[yo + l + 64] = d * sc[so + is_ + 4] * q3
            y[yo + l + 96] = d * sc[so + is_ + 6] * q4
    return y


def test_q6_k_dequant_vs_scalar(rng):
    block = bytes(rng.integers(0, 256, 208, dtype=np.uint32).astype(np.uint8)) \
        + np.float16(0.77).tobytes()
    got = dequant_q6_k(block, 256)
    np.testing.assert_allclose(got, _scalar_q6k(block), atol=1e-4)


# -- arch round-trips: GGUF load must equal safetensors load ----------------

def _hf_to_gguf(hf: str, arch: str) -> str:
    """Invert the name mapping for test emission."""
    import re
    from cake_tpu.utils.gguf import (GGUF_NAME_MAP, GGUF_NAME_OVERRIDES)
    if hf.endswith("embed_tokens.weight"):
        return "token_embd.weight"
    if hf == "model.norm.weight":
        return "output_norm.weight"
    if hf == "lm_head.weight":
        return "output.weight"
    m = re.match(r"model\.layers\.(\d+)\.(.+)\.(weight|bias)$", hf)
    assert m, hf
    inv = {v: k for k, v in GGUF_NAME_MAP.items()}
    inv.update({v: k for k, v in GGUF_NAME_OVERRIDES.get(arch, {}).items()})
    stem = inv[m.group(2)]
    return f"blk.{m.group(1)}.{stem}.{m.group(3)}"


def _roundtrip_arch(tmp_path, fam, gguf_arch, kv_overrides, cfg_overrides,
                    gguf_norm_offset=0.0):
    import jax
    import jax.numpy as jnp
    from cake_tpu.models import init_params, tiny_config
    from cake_tpu.models.common.layers import forward_train
    from cake_tpu.runtime import load_config_and_quant
    from cake_tpu.utils.export import params_to_hf_tensors
    from cake_tpu.utils.gguf import GgufStorage
    from cake_tpu.utils.loaders import ParamLoader
    from cake_tpu.utils.safetensors_io import TensorStorage, save_safetensors

    cfg = tiny_config(fam, **cfg_overrides)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    hf = params_to_hf_tensors(cfg, params)

    # split expert tensors into stacked GGUF banks, map the rest by name
    import re
    banks: dict[str, dict[int, np.ndarray]] = {}
    gguf_tensors: dict[str, np.ndarray] = {}
    for name, arr in hf.items():
        em = re.match(
            r"model\.layers\.(\d+)\.mlp\.experts\.(\d+)\.(\w+)\.weight$",
            name)
        if em:
            stem = {"gate_proj": "ffn_gate_exps", "up_proj": "ffn_up_exps",
                    "down_proj": "ffn_down_exps"}[em.group(3)]
            banks.setdefault(f"blk.{em.group(1)}.{stem}.weight",
                             {})[int(em.group(2))] = arr
        else:
            if gguf_norm_offset and name.endswith("norm.weight"):
                # llama.cpp gemma converters store norms with +1 baked in
                arr = arr + np.float32(gguf_norm_offset)
            gguf_tensors[_hf_to_gguf(name, gguf_arch)] = arr
    for bname, parts in banks.items():
        gguf_tensors[bname] = np.stack([parts[e]
                                        for e in sorted(parts)])

    gdir = tmp_path / "gguf"
    gdir.mkdir()
    write_tiny_gguf(str(gdir / "m.gguf"), gguf_tensors, gguf_arch,
                    kv_overrides)
    sdir = tmp_path / "st"
    sdir.mkdir()
    save_safetensors(str(sdir / "model.safetensors"), hf)

    # config straight from GGUF metadata must select the right family
    gcfg, _, _ = load_config_and_quant(str(gdir))
    assert gcfg.arch == cfg.arch
    assert gcfg.num_hidden_layers == cfg.num_hidden_layers

    p_gguf = ParamLoader(gcfg, GgufStorage(str(gdir / "m.gguf")),
                         jnp.float32).load()
    p_st = ParamLoader(gcfg, TensorStorage.from_model_dir(str(sdir)),
                       jnp.float32).load()
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 255, (1, 7)))
    l_gguf = forward_train(gcfg, p_gguf, toks)
    l_st = forward_train(gcfg, p_st, toks)
    np.testing.assert_allclose(np.asarray(l_gguf), np.asarray(l_st),
                               atol=1e-5, rtol=1e-5)


def test_gguf_gemma3_roundtrip(tmp_path):
    """Sandwich norms: ffn_norm maps to PRE-feedforward for gemma-family."""
    _roundtrip_arch(
        tmp_path, "gemma3", "gemma3",
        kv_overrides={"block_count": 4, "attention.head_count_kv": 2,
                      "attention.sliding_window": 16,
                      "attention.key_length": 16},
        cfg_overrides={}, gguf_norm_offset=1.0)


def test_gguf_olmo2_roundtrip(tmp_path):
    """Post-norm layout via post_attention_norm/post_ffw_norm names."""
    _roundtrip_arch(
        tmp_path, "olmo2", "olmo2",
        kv_overrides={"block_count": 4},
        cfg_overrides={})


def test_gguf_qwen3moe_roundtrip(tmp_path):
    """Stacked expert banks + router through virtual per-expert names."""
    _roundtrip_arch(
        tmp_path, "qwen3_moe", "qwen3moe",
        kv_overrides={"block_count": 4, "expert_count": 8,
                      "expert_used_count": 2,
                      "expert_feed_forward_length": 32,
                      "attention.key_length": 16},
        cfg_overrides={})


def test_gguf_unknown_arch_clear_error(tmp_path):
    write_tiny_gguf(str(tmp_path / "m.gguf"),
                    {"token_embd.weight": np.zeros((8, 64), np.float32)},
                    "qwen3next")
    r = GgufReader(str(tmp_path / "m.gguf"))
    with pytest.raises(NotImplementedError, match="qwen3next"):
        gguf_config_dict(r)
