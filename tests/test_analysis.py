"""Static-analysis framework tests: per-rule firing + clean fixtures,
the suppression roundtrip, the knob registry/docs sync, repo-wide lint
cleanliness, and the two runtime sanitizers (recompile + transfer) over
steady-state batched decode.

Fixture snippets are compiled through SourceFile with VIRTUAL paths so a
snippet can be placed on (or off) the hot-path module set without
touching real files.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu import knobs
from cake_tpu.analysis import RULES, SourceFile, check_file, run_paths
from cake_tpu.analysis.sanitizers import (RecompileError,
                                          assert_no_recompiles,
                                          no_implicit_transfers)

HOT = "cake_tpu/serve/engine.py"        # virtual: on the hot-path set
COLD = "cake_tpu/tui.py"                # virtual: off it


def fire(src: str, rule: str, rel: str = HOT):
    sf = SourceFile(rel, src)
    return [v for v in check_file(sf, [rule]) if v.rule == rule]


# -- host-sync ------------------------------------------------------------

HOST_SYNC_FIRING = """
import numpy as np

def fanout(model, layers, toks):
    packed = model.decode_slots(layers, toks)
    vals = np.asarray(packed)
    return vals

def peek(model, layers, toks):
    packed, layers = model.decode_slots(layers, toks)
    return int(packed)

def item_read(x):
    return x.item()
"""

HOST_SYNC_CLEAN = """
import numpy as np

def host_only(ids):
    arr = np.asarray(list(ids), np.int32)
    return int(arr[0]) + float(arr[1])
"""


def test_host_sync_fires():
    got = fire(HOST_SYNC_FIRING, "host-sync")
    msgs = " | ".join(v.msg for v in got)
    assert len(got) == 3
    assert "np.asarray(packed)" in msgs
    assert "int(packed)" in msgs
    assert ".item()" in msgs


def test_host_sync_clean_on_host_data():
    assert fire(HOST_SYNC_CLEAN, "host-sync") == []


def test_host_sync_scoped_to_hot_paths():
    assert fire(HOST_SYNC_FIRING, "host-sync", rel=COLD) == []


def test_host_sync_tracer_truthiness():
    src = """
import functools, jax

@functools.partial(jax.jit, static_argnames=("n",))
def step(x, flag, n):
    if flag:
        return x + n
    return x
"""
    got = fire(src, "host-sync")
    assert len(got) == 1 and "truthiness" in got[0].msg
    clean = src.replace("if flag:", "if n > 2:")
    assert fire(clean, "host-sync") == []


# -- recompile-hazard -----------------------------------------------------

RECOMPILE_FIRING = """
import functools, jax

@functools.partial(jax.jit, static_argnames=("tag", "scale"))
def step(x, tag, scale):
    return x

def caller(x, i):
    return step(x, f"req-{i}", 0.5)
"""


def test_recompile_unstable_static_args():
    got = fire(RECOMPILE_FIRING, "recompile-hazard")
    assert len(got) == 2
    assert any("f-string" in v.msg for v in got)
    assert any("float literal" in v.msg for v in got)
    clean = RECOMPILE_FIRING.replace('f"req-{i}", 0.5', '"decode", 2')
    assert fire(clean, "recompile-hazard") == []


def test_recompile_shape_branch():
    src = """
import functools, jax

@functools.partial(jax.jit, static_argnames=("nb",))
def step(x, y, nb):
    if nb == x.shape[0]:
        return x
    return y
"""
    got = fire(src, "recompile-hazard")
    assert len(got) == 1 and "x.shape" in got[0].msg
    # branching on the STATIC arg alone is stable
    clean = src.replace("if nb == x.shape[0]:", "if nb == 4:")
    assert fire(clean, "recompile-hazard") == []


# -- use-after-donate -----------------------------------------------------

DONATE_FIRING = """
import functools, jax

@functools.partial(jax.jit, donate_argnums=(1,))
def step(params, cache, tok):
    return tok, cache

def loop(params, cache, tok):
    tok, new_cache = step(params, cache, tok)
    return cache["layers"]
"""

DONATE_CLEAN = DONATE_FIRING.replace(
    "tok, new_cache = step(params, cache, tok)\n    return cache",
    "tok, cache = step(params, cache, tok)\n    return cache")


def test_donation_fires_and_rebind_clears():
    got = fire(DONATE_FIRING, "use-after-donate")
    assert len(got) == 1 and "'cache'" in got[0].msg
    assert fire(DONATE_CLEAN, "use-after-donate") == []


def test_donation_known_method_and_self_attr():
    src = """
def release(self, slot):
    out = self.model.slot_release(self._layers, slot)
    return self._layers
"""
    got = fire(src, "use-after-donate")
    assert len(got) == 1 and "self._layers" in got[0].msg
    clean = src.replace("out =", "self._layers =")
    assert fire(clean, "use-after-donate") == []


# -- knob-registry --------------------------------------------------------

def test_knob_rule_fires_on_raw_reads():
    src = """
import os

def f():
    a = os.environ.get("CAKE_SERVE_SLOTS", "4")
    b = os.getenv("CAKE_MAX_QUEUE")
    c = os.environ["CAKE_SERVE_CTX"]
    return a, b, c
"""
    got = fire(src, "knob-registry")
    assert len(got) == 3


def test_knob_rule_allows_writes_and_non_cake():
    src = """
import os

def f():
    os.environ["CAKE_SERVE_SLOTS"] = "2"
    os.environ.setdefault("CAKE_MAX_QUEUE", "8")
    return os.environ.get("JAX_PLATFORMS")
"""
    assert fire(src, "knob-registry") == []


def test_knob_rule_exempts_registry_module():
    src = 'import os\nX = os.environ.get("CAKE_SERVE_SLOTS")\n'
    assert fire(src, "knob-registry", rel="cake_tpu/knobs.py") == []
    assert len(fire(src, "knob-registry", rel=COLD)) == 1


# -- metric-registry ------------------------------------------------------

def test_metric_rule_fires_on_uncataloged_name():
    src = """
from cake_tpu.obs import REGISTRY

BOGUS = REGISTRY.counter("cake_fixture_bogus_total", "never documented")
"""
    got = fire(src, "metric-registry")
    assert len(got) == 1 and "cake_fixture_bogus_total" in got[0].msg


def test_metric_rule_clean_on_cataloged_and_foreign_names():
    src = """
from cake_tpu.obs import REGISTRY

TTFT = REGISTRY.histogram("cake_ttft_seconds", "documented")
OTHER = REGISTRY.counter("someone_elses_metric_total", "not ours")
H = some.other.histogram([1, 2, 3])
"""
    assert fire(src, "metric-registry") == []


def test_metric_rule_scoped_to_package_and_suppressible():
    src = ('from cake_tpu.obs import REGISTRY\n'
           'X = REGISTRY.gauge("cake_fixture_bogus")\n')
    assert fire(src, "metric-registry", rel="scripts/foo.py") == []
    sup = ('from cake_tpu.obs import REGISTRY\n'
           'X = REGISTRY.gauge("cake_fixture_bogus")'
           '  # lint: disable=metric-registry — fixture\n')
    got = fire(sup, "metric-registry")
    assert len(got) == 1 and got[0].suppressed


def test_metric_rule_slo_bucket_mismatch_fires():
    """SLO-semantic (ttft/itl/e2e *_seconds) histograms must share the
    LATENCY_BUCKETS boundaries — the fleet telemetry plane sums their
    buckets across replicas, and mismatched edges make the merged
    percentiles silently wrong."""
    src = """
from cake_tpu.obs import REGISTRY
from cake_tpu.obs.metrics import LATENCY_BUCKETS

A = REGISTRY.histogram("cake_serve_ttft_seconds", "doc", ("outcome",))
B = REGISTRY.histogram("cake_serve_ttft_seconds", "doc", ("outcome",),
                       buckets=(0.1, 0.5, 1.0))
"""
    got = fire(src, "metric-registry")
    assert len(got) == 2
    assert any("!= the shared LATENCY_BUCKETS" in v.msg for v in got)
    # the same-file same-semantic check names the declaration it differs
    # from
    assert any("line 5" in v.msg for v in got)


def test_metric_rule_slo_buckets_clean_forms():
    """Omitted buckets, the LATENCY_BUCKETS name, and the
    attribute-qualified form all mean 'the canonical boundaries'."""
    src = """
from cake_tpu.obs import REGISTRY, metrics
from cake_tpu.obs.metrics import LATENCY_BUCKETS

A = REGISTRY.histogram("cake_serve_ttft_seconds", "doc", ("outcome",))
B = REGISTRY.histogram("cake_serve_itl_seconds", "doc", ("outcome",),
                       buckets=LATENCY_BUCKETS)
C = REGISTRY.histogram("cake_serve_e2e_seconds", "doc", ("outcome",),
                       buckets=metrics.LATENCY_BUCKETS)
"""
    assert fire(src, "metric-registry") == []


def test_metric_rule_slo_unverifiable_buckets_fire():
    src = """
from cake_tpu.obs import REGISTRY

def mk(edges):
    return REGISTRY.histogram("cake_serve_e2e_seconds", "doc",
                              ("outcome",), buckets=edges)
"""
    got = fire(src, "metric-registry")
    assert len(got) == 1 and "cannot verify statically" in got[0].msg


def test_metric_rule_non_slo_histograms_unconstrained():
    src = """
from cake_tpu.obs import REGISTRY

H = REGISTRY.histogram("cake_api_request_seconds", "doc", ("endpoint",),
                       buckets=(0.1, 0.5, 1.0))
"""
    assert fire(src, "metric-registry") == []


def test_observability_doc_generated_and_in_sync():
    """docs/observability.md is GENERATED (metric table from the obs
    registry, span table from SPAN_CATALOG, event table from
    EVENT_KINDS); regenerate with `make metrics-doc` if this fails —
    the metric-registry lint checks instrument names against it."""
    from cake_tpu.obs.catalog import generate_doc
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "observability.md")
    with open(path, encoding="utf-8") as f:
        assert f.read().rstrip() == generate_doc().rstrip(), \
            "docs/observability.md is stale — run `make metrics-doc`"


def test_catalog_covers_every_registered_instrument():
    """Every instrument in the live registry appears in the catalog the
    lint checks against — the invariant that makes 'lint passes' mean
    'nothing undocumented'."""
    from cake_tpu import obs
    from cake_tpu.analysis.check_metrics import catalog_names
    names = catalog_names()
    missing = [m for m in obs.REGISTRY._metrics if m not in names]
    assert not missing, f"catalog missing {missing} — run `make metrics-doc`"


# -- lock-discipline ------------------------------------------------------

LOCKS_SRC = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cbs = []          # guarded-by: self._lock

    def good(self, cb):
        with self._lock:
            self._cbs.append(cb)

    def bad(self):
        return list(self._cbs)
"""


def test_lock_discipline():
    got = fire(LOCKS_SRC, "lock-discipline")
    assert len(got) == 1 and "self._cbs" in got[0].msg
    clean = LOCKS_SRC.replace(
        "        return list(self._cbs)",
        "        with self._lock:\n            return list(self._cbs)")
    assert fire(clean, "lock-discipline") == []


def test_lock_discipline_wrong_lock_does_not_count():
    src = LOCKS_SRC.replace(
        "        return list(self._cbs)",
        "        with self._other:\n            return list(self._cbs)")
    assert len(fire(src, "lock-discipline")) == 1


# -- hot-timing -----------------------------------------------------------

def test_hot_timing():
    src = "import time\n\ndef f():\n    return time.monotonic()\n"
    got = fire(src, "hot-timing")
    assert len(got) == 1 and "time.monotonic" in got[0].msg
    assert fire(src, "hot-timing", rel=COLD) == []       # not hot
    ok = "import time\n\ndef f():\n    time.sleep(0.1)\n"
    assert fire(ok, "hot-timing") == []                  # sleep is legal


# -- suppressions ---------------------------------------------------------

def test_suppression_roundtrip_inline_and_standalone():
    inline = ("import time\n\ndef f():\n"
              "    return time.monotonic()  "
              "# lint: disable=hot-timing — bench-only helper\n")
    got = fire(inline, "hot-timing")
    assert len(got) == 1 and got[0].suppressed
    assert got[0].reason == "bench-only helper"

    standalone = ("import time\n\ndef f():\n"
                  "    # lint: disable=hot-timing — bench-only helper\n"
                  "    return time.monotonic()\n")
    got = fire(standalone, "hot-timing")
    assert len(got) == 1 and got[0].suppressed

    wrong_rule = inline.replace("hot-timing —", "host-sync —")
    got = fire(wrong_rule, "hot-timing")
    assert len(got) == 1 and not got[0].suppressed


def test_suppression_without_reason_is_a_violation():
    src = ("import time\n\ndef f():\n"
           "    return time.monotonic()  # lint: disable=hot-timing\n")
    sf = SourceFile(HOT, src)
    out = check_file(sf, ["hot-timing"])
    rules = {v.rule for v in out}
    assert "suppression-format" in rules
    # and the underlying violation is NOT suppressed
    assert any(v.rule == "hot-timing" and not v.suppressed for v in out)


# -- registry / repo-wide -------------------------------------------------

def test_all_rules_registered():
    assert set(RULES) == {"host-sync", "recompile-hazard",
                          "use-after-donate", "knob-registry",
                          "lock-discipline", "hot-timing",
                          "metric-registry"}


def test_repo_is_lint_clean():
    """`make lint` in-process: no unsuppressed violations anywhere, and
    every suppression carries a reason (format errors are violations)."""
    bad = [v.render() for v in run_paths() if not v.suppressed]
    assert not bad, "lint violations:\n" + "\n".join(bad)


def test_guarded_by_annotations_present():
    """The lock-discipline rule only has teeth while the annotations
    exist — pin the ones this PR established."""
    from cake_tpu.analysis.check_locks import LockDisciplineChecker
    import ast
    c = LockDisciplineChecker()
    found = {}
    for rel in ("cake_tpu/serve/engine.py", "cake_tpu/cluster/master.py"):
        path = os.path.join(os.path.dirname(__file__), "..", rel)
        sf = SourceFile(rel, open(path).read())
        for cls in ast.walk(sf.tree):
            if isinstance(cls, ast.ClassDef):
                found.update({f"{cls.name}.{k}": v for k, v in
                              c._guarded_fields(sf, cls).items()})
    assert found.get("ServeRequest._token_cb") == "self._sub_lock"
    assert found.get("ServeRequest._done_cbs") == "self._sub_lock"
    assert found.get("DistributedTextModel.degraded") == \
        "self._degraded_lock"


# -- knob registry --------------------------------------------------------

def test_knobs_typed_get_and_empty_fallback(monkeypatch):
    monkeypatch.setenv("CAKE_SERVE_SLOTS", "7")
    assert knobs.get("CAKE_SERVE_SLOTS") == 7
    monkeypatch.setenv("CAKE_SERVE_SLOTS", "")
    assert knobs.get("CAKE_SERVE_SLOTS") == 4       # empty == unset
    monkeypatch.setenv("CAKE_MOE_RAGGED", "0")
    assert knobs.get("CAKE_MOE_RAGGED") is False
    monkeypatch.delenv("CAKE_SPEC", raising=False)
    assert knobs.get("CAKE_SPEC") is None
    assert knobs.get_str("CAKE_SPEC") == ""
    with pytest.raises(KeyError):
        knobs.get("CAKE_NOT_A_KNOB")


def test_knobs_doc_generated_and_in_sync():
    """docs/knobs.md is GENERATED from the registry; regenerate with
    `make knobs-doc` if this fails."""
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "knobs.md")
    want = knobs.generate_doc().rstrip()
    with open(path, encoding="utf-8") as f:
        assert f.read().rstrip() == want, \
            "docs/knobs.md is stale — run `make knobs-doc`"


def test_every_knob_documented_and_typed():
    for kb in knobs.REGISTRY.values():
        assert kb.name.startswith("CAKE_")
        assert kb.cast in (int, float, str, bool)
        assert len(kb.doc) > 10, kb.name
        if kb.default is not None:
            assert isinstance(kb.default, kb.cast), kb.name


# -- runtime sanitizers ---------------------------------------------------

SLOTS = 2


@pytest.fixture(scope="module")
def tiny_model():
    from cake_tpu.models import TextModel, tiny_config
    return TextModel(tiny_config("llama"), dtype=jnp.float32,
                     max_cache_len=64)


def make_state(m):
    """A warmed 2-slot pool mid-decode (the steady state the sanitizers
    must hold over). Fresh per test: the negative tests donate or kill
    buffers, so shared mutable state would leak between tests."""
    layers = m.new_cache(SLOTS, kv_len=64)["layers"]
    for s in range(SLOTS):
        _, layers = m.prefill_chunk(layers, s, [1, 2, 3], 0)
    return {
        "layers": layers,
        "toks": jnp.zeros((SLOTS,), jnp.int32),
        "pos": jnp.full((SLOTS,), 3, jnp.int32),
        "rngs": jnp.stack([jax.random.PRNGKey(i) for i in range(SLOTS)]),
        "recents": jnp.full((SLOTS, 64), -1, jnp.int32),
        "temps": jnp.zeros((SLOTS,), jnp.float32),
        "top_ks": jnp.full((SLOTS,), m.cfg.vocab_size, jnp.int32),
        "top_ps": jnp.ones((SLOTS,), jnp.float32),
        "pens": jnp.ones((SLOTS,), jnp.float32),
        "act": jnp.ones((SLOTS,), jnp.bool_),
    }


def _step(m, st, toks=None, nb=SLOTS):
    (packed, st["layers"], st["toks"], st["pos"], st["rngs"],
     st["recents"]) = m.decode_slots(
        st["layers"], st["toks"] if toks is None else toks, st["pos"],
        st["rngs"], st["recents"], st["temps"], st["top_ks"],
        st["top_ps"], st["pens"], st["act"], nb=nb)
    return packed


def test_steady_state_decode_zero_recompiles_no_transfers(tiny_model):
    """The acceptance bar: >= 8 consecutive steady-state decode_slots
    iterations compile zero new executables, and the step itself performs
    no implicit device<->host transfers (the one planned fetch happens
    outside the guard)."""
    m = tiny_model
    st = make_state(m)
    _step(m, st)                            # warm the nb bucket
    with assert_no_recompiles(m, label="decode_slots steady state"):
        for _ in range(8):
            with no_implicit_transfers():
                packed = _step(m, st)
            ids = np.asarray(packed)        # planned fetch, outside guard
    assert ids.shape == (2, SLOTS)


def test_recompile_sanitizer_catches_new_bucket(tiny_model):
    m = tiny_model
    st = make_state(m)
    _step(m, st)
    with pytest.raises(RecompileError, match="_decode_slots"):
        with assert_no_recompiles(m):
            _step(m, st, nb=1)              # unwarmed bucket: new program


def test_transfer_sanitizer_catches_implicit_host_to_device(tiny_model):
    m = tiny_model
    st = make_state(m)
    _step(m, st)
    with pytest.raises(Exception, match="[Tt]ransfer"):
        with no_implicit_transfers():
            # a host numpy array smuggled into the traced step is exactly
            # the implicit per-iteration upload the guard exists to catch
            _step(m, st, toks=np.zeros((SLOTS,), np.int32))
