"""API integration tests with mock generators — no HTTP server, no TPU
(mirrors ref api/test_helpers.rs MockTextGenerator + integration_tests.rs)."""
import asyncio
import base64
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from cake_tpu.api import ApiState, create_app
from cake_tpu.models.common.text_model import Token


def with_client(state_or_app, fn):
    """Run an async client scenario under asyncio.run (no pytest-asyncio in
    the environment)."""
    async def inner():
        app = state_or_app if not isinstance(state_or_app, ApiState) \
            else create_app(state_or_app)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client)
        finally:
            await client.close()
    asyncio.run(inner())


class MockTokenizer:
    WORDS = {0: "Hello", 1: " world", 2: " !"}

    def encode(self, text):
        return list(range(len(text.split())))

    def decode(self, ids):
        return "".join(self.WORDS.get(i, "") for i in ids)

    def apply_chat(self, messages):
        return " ".join(m["content"] for m in messages)


class MockTextModel:
    """Emits 'Hello world !' one token at a time (ref: MockTextGenerator)."""

    class cfg:
        arch = "mock"
        num_hidden_layers = 4
        hidden_size = 64
        vocab_size = 256

        @staticmethod
        def is_eos(tid):
            return tid == 99

    def __init__(self):
        self.tokenizer = MockTokenizer()
        self.calls = 0

    def chat_generate(self, messages, max_new_tokens=256, sampling=None,
                      on_token=None, **_):
        self.calls += 1
        words = ["Hello", " world", " !"]
        toks = []
        for i, w in enumerate(words[:max_new_tokens]):
            t = Token(id=i, text=w, is_end_of_stream=False)
            toks.append(i)
            if on_token:
                on_token(t)
        if on_token:
            on_token(Token(id=99, text=None, is_end_of_stream=True))
        toks.append(99)
        return toks, {"tok_per_s": 42.0, "ttft_s": 0.01,
                      "decode_tokens": len(toks) - 1, "decode_s": 0.1}

    generate = chat_generate


class MockImageModel:
    def generate_image(self, prompt, width=64, height=64, **kw):
        from PIL import Image
        return Image.new("RGB", (width, height), (128, 0, 255))


class MockAudioModel:
    class _Audio:
        def wav_bytes(self):
            return b"RIFF" + b"\x00" * 44

        def pcm_bytes(self):
            return b"\x00\x01" * 100

    def generate_speech(self, text, **kw):
        return self._Audio()


def make_state():
    return ApiState(model=MockTextModel(), tokenizer=MockTokenizer(),
                    model_id="mock-model", image_model=MockImageModel(),
                    audio_model=MockAudioModel())


def test_models_list():
    async def scenario(client):
        r = await client.get("/v1/models")
        assert r.status == 200
        data = await r.json()
        assert data["object"] == "list"
        assert {m["kind"] for m in data["data"]} == {"text", "image", "audio"}
    with_client(make_state(), scenario)


def test_chat_blocking():
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}]})
        assert r.status == 200
        data = await r.json()
        assert data["choices"][0]["message"]["content"] == "Hello world !"
        assert data["choices"][0]["finish_reason"] == "stop"
        assert data["usage"]["completion_tokens"] == 4
        assert data["object"] == "chat.completion"
    with_client(make_state(), scenario)


def test_chat_stream_sse():
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}], "stream": True})
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        body = (await r.read()).decode()
        chunks = [json.loads(line[6:]) for line in body.split("\n\n")
                  if line.startswith("data: ") and line != "data: [DONE]"]
        text = "".join(c["choices"][0]["delta"].get("content", "")
                       for c in chunks)
        assert text == "Hello world !"
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
        assert body.strip().endswith("data: [DONE]")
    with_client(make_state(), scenario)


def test_chat_stop_string():
    # stop=" world": content trimmed at the match, finish_reason "stop"
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
            "stop": " world"})
        assert r.status == 200
        data = await r.json()
        assert data["choices"][0]["message"]["content"] == "Hello"
        assert data["choices"][0]["finish_reason"] == "stop"
    with_client(make_state(), scenario)


def test_chat_stop_list_earliest_wins():
    # " !" appears later than " world": the EARLIEST match trims
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
            "stop": [" !", " world"]})
        data = await r.json()
        assert data["choices"][0]["message"]["content"] == "Hello"
        assert data["choices"][0]["finish_reason"] == "stop"
    with_client(make_state(), scenario)


def test_chat_stop_stream_sse():
    # stop "o w" spans the token boundary "Hello"|" world": the matcher's
    # holdback must keep every character of the match off the wire
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
            "stream": True, "stop": "o w"})
        body = (await r.read()).decode()
        chunks = [json.loads(line[6:]) for line in body.split("\n\n")
                  if line.startswith("data: ") and line != "data: [DONE]"]
        text = "".join(c["choices"][0]["delta"].get("content", "")
                       for c in chunks)
        assert text == "Hell"
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
        assert body.strip().endswith("data: [DONE]")
    with_client(make_state(), scenario)


def test_chat_stop_stream_no_match_flushes_holdback():
    # a stop that never completes must not eat the held-back tail
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
            "stream": True, "stop": " !ZZZ"})
        body = (await r.read()).decode()
        chunks = [json.loads(line[6:]) for line in body.split("\n\n")
                  if line.startswith("data: ") and line != "data: [DONE]"]
        text = "".join(c["choices"][0]["delta"].get("content", "")
                       for c in chunks)
        assert text == "Hello world !"
    with_client(make_state(), scenario)


def test_chat_stop_validation():
    async def scenario(client):
        for bad in (5, ["a", ""], ["a", 3], ["1", "2", "3", "4", "5"]):
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}],
                "stop": bad})
            assert r.status == 400, bad
    with_client(make_state(), scenario)


def test_stop_matcher_unit():
    from cake_tpu.api.text import StopMatcher
    m = StopMatcher(["ab"])
    # split match: 'a' held back, then 'b' completes it — nothing emitted
    assert m.feed("xa") == "x"
    assert m.feed("by") == ""
    assert m.stopped and m.flush() == ""
    # no match: flush releases the held tail verbatim
    m = StopMatcher(["zz"])
    assert m.feed("abc") == "ab"
    assert m.flush() == "c"


def test_chat_validation():
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={})
        assert r.status == 400
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"bad": 1}]})
        assert r.status == 400
        r = await client.post("/v1/chat/completions", data=b"not json")
        assert r.status == 400
    with_client(make_state(), scenario)


def test_chat_no_model():
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}]})
        assert r.status == 503
    with_client(ApiState(model=None), scenario)


def test_images_b64():
    async def scenario(client):
        r = await client.post("/v1/images/generations", json={
            "prompt": "a cat", "size": "32x32"})
        assert r.status == 200
        data = await r.json()
        png = base64.b64decode(data["data"][0]["b64_json"])
        assert png[:8] == b"\x89PNG\r\n\x1a\n"
    with_client(make_state(), scenario)


def test_images_legacy_png():
    async def scenario(client):
        r = await client.post("/api/v1/image", json={"prompt": "a cat",
                                                     "size": "16x16"})
        assert r.status == 200
        assert r.headers["Content-Type"] == "image/png"
        assert (await r.read())[:8] == b"\x89PNG\r\n\x1a\n"
    with_client(make_state(), scenario)


def test_audio_wav_and_pcm():
    async def scenario(client):
        r = await client.post("/v1/audio/speech", json={"input": "hello"})
        assert r.status == 200
        assert r.headers["Content-Type"] == "audio/wav"
        assert (await r.read())[:4] == b"RIFF"
        r = await client.post("/v1/audio/speech", json={"input": "hello",
                                                        "response_format": "pcm"})
        assert r.headers["Content-Type"] == "application/octet-stream"
        r = await client.post("/v1/audio/speech", json={"input": "x",
                                                        "response_format": "mp3"})
        assert r.status == 400
    with_client(make_state(), scenario)


def test_topology_endpoint():
    async def scenario(client):
        r = await client.get("/api/v1/topology")
        assert r.status == 200
        data = await r.json()
        assert data["master"]["model"] == "mock-model"
        assert data["master"]["num_layers"] == 4
        assert "layers" not in data          # static blob lives elsewhere
        r = await client.get("/api/v1/layers")
        assert r.status == 200
        assert (await r.json())["layers"] == {}
    with_client(make_state(), scenario)


def test_web_ui():
    async def scenario(client):
        r = await client.get("/")
        assert r.status == 200
        html = await r.text()
        assert "cake" in html and "chat/completions" in html
        # the two-view SPA: chat + cluster topology visualization
        for el in ("tabChat", "tabCluster", "layerStrip", "nodeCards",
                   "layerBody", "api/v1/topology", "sendMessage",
                   "refreshTopology"):
            assert el in html, el
    with_client(make_state(), scenario)


def test_basic_auth():
    async def scenario(client):
        r = await client.get("/v1/models")
        assert r.status == 401
        cred = base64.b64encode(b"user:pw").decode()
        r = await client.get("/v1/models",
                             headers={"Authorization": f"Basic {cred}"})
        assert r.status == 200
    app = create_app(ApiState(model=MockTextModel(), model_id="m"),
                     basic_auth="user:pw")
    with_client(app, scenario)


def test_sampling_request_grid():
    """Client sampling params are clamped/quantized to a bounded grid:
    SamplingConfig is a static jit arg, so unbounded distinct values would
    be a compile-cache DoS (round-1 advisor finding)."""
    from cake_tpu.api.text import _sampling_from_request
    a = _sampling_from_request({"temperature": 0.7123, "top_p": 0.912,
                                "top_k": 37, "repetition_penalty": 1.0812})
    assert a.temperature == 0.7 and a.top_p == 0.9
    assert a.top_k == 40 and a.repeat_penalty == 1.1
    # out-of-range values clamp instead of erroring
    b = _sampling_from_request({"temperature": 99.0, "top_p": 1.0})
    assert b.temperature == 2.0 and b.top_p is None
    # nearby floats collapse onto the same grid point (bounded cache)
    c1 = _sampling_from_request({"temperature": 0.701})
    c2 = _sampling_from_request({"temperature": 0.699})
    assert c1 == c2


def test_resolve_voice_sandboxed(tmp_path):
    """Client voice strings resolve only inside the configured voices dir —
    never used as raw server paths (file-probe/arbitrary-read hazard)."""
    from cake_tpu.api.audio import resolve_voice
    from cake_tpu.api.state import ApiState
    (tmp_path / "alloy.safetensors").write_bytes(b"x")
    state = ApiState(model=None, voices_dir=str(tmp_path))
    got = resolve_voice(state, "alloy")
    assert got == str(tmp_path / "alloy.safetensors")
    # path components are stripped; escapes stay inside the dir
    assert resolve_voice(state, "../../etc/passwd") is None
    assert resolve_voice(state, "/etc/passwd") is None
    # without a voices dir every voice is ignored
    assert resolve_voice(ApiState(model=None), "/etc/passwd") is None


def test_top_k_zero_disables():
    from cake_tpu.api.text import _sampling_from_request
    assert _sampling_from_request({"top_k": 0}).top_k is None
    assert _sampling_from_request({"top_k": -1}).top_k is None


def test_bad_sampling_params_400():
    """Malformed numeric params must be a 400 before the SSE response is
    prepared — not a hung stream or a 500."""
    import asyncio
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api.server import create_app
    from cake_tpu.api.state import ApiState

    async def run():
        app = create_app(ApiState(model=object(), model_id="m"))
        async with TestClient(TestServer(app)) as client:
            for payload in (
                    {"messages": [{"role": "user", "content": "x"}],
                     "temperature": "hot", "stream": True},
                    {"messages": [{"role": "user", "content": "x"}],
                     "top_k": "many"},
                    {"messages": [{"role": "user", "content": "x"}],
                     "max_tokens": "all"}):
                r = await client.post("/v1/chat/completions", json=payload)
                assert r.status == 400, payload
    asyncio.new_event_loop().run_until_complete(run())


def test_topology_layer_detail(tmp_path):
    """Per-layer tensor detail (name/shape/dtype/bytes) from the
    safetensors headers feeds the UI's layers view (ref: api/ui.rs
    parallel header scan)."""
    import jax
    import jax.numpy as jnp
    from cake_tpu.api.ui import layer_tensor_details
    from cake_tpu.models import init_params, tiny_config
    from cake_tpu.utils.export import params_to_hf_tensors
    from cake_tpu.utils.safetensors_io import save_safetensors
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    detail = layer_tensor_details(str(tmp_path))
    assert set(detail) == {"0", "1", "2", "3", "other"}
    l0 = {t["name"] for t in detail["0"]}
    assert "model.layers.0.self_attn.q_proj.weight" in l0
    t = detail["0"][0]
    assert t["bytes"] > 0 and t["shape"] and t["dtype"]


def test_stats_endpoint():
    """Empty before any generation; after a chat call it reports the last
    generation's timing snapshot (ttft/tok_s + whatever the model's stats
    carry — on a cluster master that includes the per-hop RTT wire/fwd
    split and prefill pipelining info)."""
    async def scenario(client):
        r = await client.get("/api/v1/stats")
        assert r.status == 200
        data = await r.json()
        assert data == {"model": "mock-model", "stats": {}}

        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}]})
        assert r.status == 200

        r = await client.get("/api/v1/stats")
        data = await r.json()
        assert data["model"] == "mock-model"
        assert "ts" in data["stats"]
        assert data["stats"]["tok_per_s"] > 0

        # the streaming path writes last_stats through a separate branch
        first_ts = data["stats"]["ts"]
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
            "stream": True})
        assert r.status == 200
        await r.read()
        r = await client.get("/api/v1/stats")
        data = await r.json()
        assert "ts" in data["stats"] and data["stats"]["ts"] >= first_ts
        assert data["stats"]["tok_per_s"] > 0
    with_client(make_state(), scenario)


def test_images_img2img_b64():
    """init_image_b64 + strength: image BYTES in the body (never a
    server-side path) route through encode_image when the model has it."""
    import base64 as b64
    import io

    calls = {}

    class I2IModel(MockImageModel):
        def init_latent_from(self, img, w, h):
            img = img.convert("RGB").resize((w, h))
            import numpy as np
            calls["px_shape"] = np.asarray(img).shape
            return "latent"

        def generate_image(self, prompt, **kw):
            calls["kw"] = kw
            return super().generate_image(prompt, **{
                k: v for k, v in kw.items()
                if k not in ("init_image", "strength")})

    from PIL import Image
    buf = io.BytesIO()
    Image.new("RGB", (16, 16), (200, 10, 10)).save(buf, format="PNG")
    png_b64 = b64.b64encode(buf.getvalue()).decode()

    async def scenario(client):
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "size": "32x32", "steps": 2,
            "init_image_b64": png_b64, "strength": 0.5})
        assert r.status == 200, await r.text()
        assert calls["px_shape"] == (32, 32, 3)
        assert calls["kw"]["init_image"] == "latent"
        assert calls["kw"]["strength"] == 0.5
    st = make_state()
    st.image_model = I2IModel()
    with_client(st, scenario)

    async def rejects(client):
        # a model without encode_image rejects img2img with a clear 400
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "init_image_b64": png_b64})
        assert r.status == 400
        assert "SD-only" in (await r.json())["error"]
    with_client(make_state(), rejects)


def test_images_n_samples():
    async def scenario(client):
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "size": "16x16", "n": 3, "seed": 7})
        assert r.status == 200
        data = await r.json()
        assert len(data["data"]) == 3
        for d in data["data"]:
            assert base64.b64decode(d["b64_json"])[:8] == b"\x89PNG\r\n\x1a\n"
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "n": 9})
        assert r.status == 400
    with_client(make_state(), scenario)


def test_images_n_validation():
    async def scenario(client):
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "n": None})      # null -> default 1
        assert r.status == 200
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "n": "abc"})
        assert r.status == 400
        r = await client.post("/v1/images/generations", json={
            "prompt": "x", "n": 2, "response_format": "png"})
        assert r.status == 400
    with_client(make_state(), scenario)


def test_continuation_template_no_duplicate_assistant_header():
    """Continuation-mode templating ends the prompt INSIDE the partial
    assistant turn: exactly one assistant header, the partial content
    appended verbatim, no end-of-turn token after it."""
    from cake_tpu.models.common.text_model import continuation_prompt_ids

    class CapturingTok:
        def encode(self, text):
            self.last = text
            return [1, 2, 3]

    tok = CapturingTok()
    msgs = [{"role": "system", "content": "sys"},
            {"role": "user", "content": "hi"},
            {"role": "assistant", "content": "par tial", "continue": True}]
    continuation_prompt_ids(tok, msgs)
    assert tok.last.endswith("<|im_start|>assistant\npar tial")
    assert tok.last.count("<|im_start|>assistant") == 1
    assert "par tial<|im_end|>" not in tok.last


def test_chat_continuation_mode_and_validation():
    """`"continue": true` on a non-assistant tail is a 400; on an
    assistant tail the request generates normally (the continuation of
    the same message)."""
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi",
                          "continue": True}]})
        assert r.status == 400
        assert "continue" in (await r.json())["error"]
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"},
                         {"role": "assistant", "content": "Hel",
                          "continue": True}]})
        assert r.status == 200
        data = await r.json()
        assert data["choices"][0]["message"]["content"] == "Hello world !"
    with_client(make_state(), scenario)


def test_chat_continuation_stream():
    """Continuation mode streams like any chat (the locked fallback
    path hands token ids to generate())."""
    async def scenario(client):
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"},
                         {"role": "assistant", "content": "Hel",
                          "continue": True}],
            "stream": True})
        assert r.status == 200
        body = (await r.read()).decode()
        text = "".join(
            json.loads(line[6:])["choices"][0]["delta"].get("content", "")
            for line in body.split("\n\n")
            if line.startswith("data: ") and line != "data: [DONE]")
        assert text == "Hello world !"
        assert body.strip().endswith("data: [DONE]")
    with_client(make_state(), scenario)
