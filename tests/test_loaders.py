"""Checkpoint loading tests: safetensors round-trips, layer subsets,
quantization strategies (mirrors ref tests for utils/)."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import init_params, tiny_config
from cake_tpu.utils.export import params_to_hf_tensors
from cake_tpu.utils.loaders import ParamLoader, load_model_params
from cake_tpu.utils.quant import (Fp8Quantization, GptqQuantization,
                                  NoQuantization, detect_quantization,
                                  dequantize_gptq_4bit, unpack_int4)
from cake_tpu.utils.safetensors_io import (TensorStorage, index_file,
                                           layer_of, save_safetensors)


def _write_model(tmp_path, cfg, params, arch, shards=1, fuse_phi=False):
    tensors = params_to_hf_tensors(cfg, params, fuse_phi=fuse_phi)
    names = sorted(tensors)
    per = (len(names) + shards - 1) // shards
    weight_map = {}
    for s in range(shards):
        chunk = {n: tensors[n] for n in names[s * per:(s + 1) * per]}
        fname = f"model-{s:05d}-of-{shards:05d}.safetensors"
        save_safetensors(str(tmp_path / fname), chunk)
        weight_map.update({n: fname for n in chunk})
    if shards > 1:
        with open(tmp_path / "model.safetensors.index.json", "w") as f:
            json.dump({"weight_map": weight_map}, f)
    with open(tmp_path / "config.json", "w") as f:
        json.dump({"architectures": [arch]}, f)
    return tmp_path


def _trees_equal(a, b):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = dict(jax.tree_util.tree_leaves_with_path(b))
    fb = {jax.tree_util.keystr(k): v for k, v in fb.items()}
    for k, v in fa:
        ks = jax.tree_util.keystr(k)
        np.testing.assert_allclose(np.asarray(v), np.asarray(fb[ks]),
                                   atol=1e-6, err_msg=ks)


def test_safetensors_roundtrip(tmp_path, rng):
    tensors = {
        "a.weight": rng.standard_normal((4, 6)).astype(np.float32),
        "b.bias": rng.standard_normal(3).astype(np.float16),
        "c.bf16": jnp.asarray(rng.standard_normal((2, 2)), jnp.bfloat16),
    }
    path = str(tmp_path / "t.safetensors")
    save_safetensors(path, {k: np.asarray(v) for k, v in tensors.items()})
    idx = index_file(path)
    assert idx["a.weight"].shape == (4, 6)
    assert idx["c.bf16"].dtype == "bfloat16"
    st = TensorStorage(idx)
    for name, want in tensors.items():
        np.testing.assert_array_equal(st.read(name), np.asarray(want))
    st.close()


def test_layer_of():
    assert layer_of("model.layers.17.self_attn.q_proj.weight") == 17
    assert layer_of("model.embed_tokens.weight") is None
    assert layer_of("model.language_model.layers.3.mlp.up_proj.weight") == 3


@pytest.mark.parametrize("fam", ["llama", "qwen2", "qwen3", "gemma3",
                                 "olmo2", "qwen3_moe"])
def test_load_roundtrip(tmp_path, fam):
    """init -> export HF names -> save -> load -> identical pytree."""
    cfg = tiny_config(fam)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    _write_model(tmp_path, cfg, params, "X", shards=2)
    loaded = load_model_params(cfg, str(tmp_path), jnp.float32)
    _trees_equal(params, loaded)


def test_load_phi4_fused_split(tmp_path):
    """Phi-4 pre-fused qkv_proj/gate_up_proj split into separate projections."""
    cfg = tiny_config("phi4")
    params = init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    _write_model(tmp_path, cfg, params, "Phi3ForCausalLM", fuse_phi=True)
    loaded = load_model_params(cfg, str(tmp_path), jnp.float32)
    _trees_equal(params, loaded)


def test_load_layer_subset(tmp_path):
    """Worker partial load: only the requested layer range is materialized
    (ref: utils/mod.rs:251-333)."""
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    _write_model(tmp_path, cfg, params, "LlamaForCausalLM")
    sub = load_model_params(cfg, str(tmp_path), jnp.float32, layer_range=(1, 3))
    assert len(sub["layers"]) == 2
    assert "embed_tokens" not in sub and "norm" not in sub
    np.testing.assert_allclose(
        np.asarray(sub["layers"][0]["self_attn"]["q_proj"]["weight"]),
        np.asarray(params["layers"][1]["self_attn"]["q_proj"]["weight"]))


def test_residual_norm_export_import(tmp_path):
    """(1+w) norms: export stores deltas, import re-adds 1."""
    cfg = tiny_config("gemma3")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tensors = params_to_hf_tensors(cfg, params)
    # in-memory weight is ~1.0 -> stored delta ~0.0
    stored = tensors["model.layers.0.input_layernorm.weight"]
    assert np.abs(stored).max() < 1e-6


def test_unpack_int4():
    # value pattern 0..7 packed LSB-first into one uint32
    packed = np.array([[0x76543210]], dtype=np.uint32)
    got = unpack_int4(packed, axis=0)
    assert got[:, 0].tolist() == [0, 1, 2, 3, 4, 5, 6, 7]
    got2 = unpack_int4(packed, axis=1)
    assert got2[0, :].tolist() == [0, 1, 2, 3, 4, 5, 6, 7]


def test_gptq_dequant_known_values():
    """Hand-built 4-bit case with the AutoGPTQ -1 zero convention
    (ref: utils/gptq.rs formula)."""
    in_f, out_f, group = 8, 8, 8
    rng = np.random.default_rng(0)
    q = rng.integers(0, 16, (in_f, out_f)).astype(np.uint32)
    zeros = rng.integers(0, 15, (1, out_f)).astype(np.uint32)
    scales = rng.uniform(0.5, 2.0, (1, out_f)).astype(np.float32)
    # pack
    qweight = np.zeros((1, out_f), np.uint32)
    for i in range(8):
        qweight[0] |= q[i] << (4 * i)
    qzeros = np.zeros((1, 1), np.uint32)
    for j in range(8):
        qzeros[0, 0] |= zeros[0, j] << (4 * j)
    want = ((q.astype(np.int32) - zeros.astype(np.int32) - 1)
            * scales).T.astype(np.float32)
    got = dequantize_gptq_4bit(qweight, scales, qzeros, group)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_gptq_loader_end_to_end(tmp_path):
    """A model dir whose mlp weights are GPTQ-packed loads transparently."""
    cfg = tiny_config("llama", intermediate_size=64, hidden_size=64,
                      num_attention_heads=4, num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tensors = params_to_hf_tensors(cfg, params)
    group = 32
    packed = {}
    for name in list(tensors):
        if ".mlp." in name and name.endswith(".weight"):
            w = tensors.pop(name)                   # [out, in]
            out_f, in_f = w.shape
            # quantize: per-group scale, zero=7
            scales = np.abs(w).reshape(out_f, in_f // group, group).max(-1).T \
                .astype(np.float32) / 7.0           # [groups, out]
            scales = np.maximum(scales, 1e-8)
            g_idx = np.arange(in_f) // group
            q = np.clip(np.round(w.T / scales[g_idx] + 8), 0, 15).astype(np.uint32)
            zeros = np.full((in_f // group, out_f), 7, np.uint32)
            qweight = np.zeros((in_f // 8, out_f), np.uint32)
            for i in range(8):
                qweight |= q[i::8] << np.uint32(4 * i)
            qzeros = np.zeros((in_f // group, out_f // 8), np.uint32)
            for j in range(8):
                qzeros |= zeros[:, j::8] << np.uint32(4 * j)
            packed[name.replace(".weight", ".qweight")] = qweight.view(np.int32)
            packed[name.replace(".weight", ".scales")] = scales.astype(np.float16)
            packed[name.replace(".weight", ".qzeros")] = qzeros.view(np.int32)
    tensors.update(packed)
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    with open(tmp_path / "config.json", "w") as f:
        json.dump({"architectures": ["LlamaForCausalLM"],
                   "quantization_config": {"quant_method": "gptq",
                                           "group_size": group}}, f)
    loaded = load_model_params(cfg, str(tmp_path), jnp.float32)
    w0 = np.asarray(params["layers"][0]["mlp"]["gate_proj"]["weight"])
    g0 = np.asarray(loaded["layers"][0]["mlp"]["gate_proj"]["weight"])
    err = np.abs(w0 - g0).max() / (np.abs(w0).max() + 1e-9)
    assert err < 0.2  # 4-bit quantization error bound
    # non-quantized tensors load exactly
    np.testing.assert_allclose(
        np.asarray(loaded["layers"][0]["self_attn"]["q_proj"]["weight"]),
        np.asarray(params["layers"][0]["self_attn"]["q_proj"]["weight"]))


def test_fp8_loader(tmp_path, rng):
    cfg = tiny_config("llama", hidden_size=64, intermediate_size=128,
                      num_attention_heads=4, num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tensors = params_to_hf_tensors(cfg, params)
    from cake_tpu.ops.fp8 import quant_fp8_blockwise
    name = "model.layers.0.mlp.gate_proj.weight"
    w = tensors.pop(name)
    wq, scale_inv = quant_fp8_blockwise(jnp.asarray(w))
    tensors[name] = np.asarray(wq)
    tensors[name.replace(".weight", ".weight_scale_inv")] = np.asarray(scale_inv)
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    st = TensorStorage.from_model_dir(str(tmp_path))
    loaded = Fp8Quantization().load(st, name)
    err = np.abs(loaded - w).mean()
    assert err < 0.05


def test_detect_quantization():
    assert detect_quantization({}).name == "none"
    assert detect_quantization(
        {"quantization_config": {"quant_method": "gptq", "group_size": 64}}
    ).group_size == 64
    assert detect_quantization(
        {"text_config": {"quantization_config": {"quant_method": "gptq"}}}
    ).name == "gptq"
    assert detect_quantization(
        {"quantization_config": {"quant_method": "fp8"}}).name == "fp8"


def test_fp8_native_dtype_path(tmp_path, rng):
    """keep_native: weights stay f8e4m3 in the params pytree (1 byte/param)
    and the jitted forward dequantizes per layer — logits must match the
    dequant-at-load path (ref: native_dtype_backend.rs)."""
    import json

    from cake_tpu.models import TextModel, tiny_config
    from cake_tpu.ops.fp8 import quant_fp8_blockwise
    from cake_tpu.ops.sampling import SamplingConfig

    cfg = tiny_config("llama", hidden_size=64, intermediate_size=128,
                      num_attention_heads=4, num_key_value_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tensors = params_to_hf_tensors(cfg, params)
    for name in list(tensors):
        if ".mlp." in name and name.endswith(".weight"):
            w = tensors.pop(name)
            wq, si = quant_fp8_blockwise(jnp.asarray(w))
            tensors[name] = np.asarray(wq)
            tensors[name.replace(".weight", ".weight_scale_inv")] = np.asarray(si)
    save_safetensors(str(tmp_path / "model.safetensors"), tensors)
    (tmp_path / "config.json").write_text(json.dumps(
        {"architectures": ["LlamaForCausalLM"],
         "quantization_config": {"quant_method": "fp8"}}))

    dequant = load_model_params(cfg, str(tmp_path), jnp.float32,
                                quant=Fp8Quantization())
    native = load_model_params(cfg, str(tmp_path), jnp.float32,
                               quant=Fp8Quantization(keep_native=True))
    # native pytree holds f8 weights
    wn = native["layers"][0]["mlp"]["gate_proj"]["weight"]
    assert isinstance(wn, dict) and wn["fp8"].dtype == jnp.float8_e4m3fn
    # forwards agree
    m1 = TextModel(cfg, dequant, dtype=jnp.float32, max_cache_len=32)
    m2 = TextModel(cfg, native, dtype=jnp.float32, max_cache_len=32)
    l1, _ = m1.prefill(m1.new_cache(), [1, 2, 3, 4])
    l2, _ = m2.prefill(m2.new_cache(), [1, 2, 3, 4])
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=2e-3,
                               rtol=1e-2)
    # greedy generation runs on the native path
    toks, _ = m2.generate([1, 2, 3], max_new_tokens=4,
                          sampling=SamplingConfig(temperature=0.0), chunk=4)
    assert len(toks) >= 1


def test_gptq_act_order_g_idx():
    """desc_act checkpoints carry a g_idx permutation; dequant must honor
    it, and refuse desc_act without g_idx instead of silently mis-mapping."""
    from cake_tpu.utils.quant import GptqQuantization
    in_f, out_f = 16, 8
    rng = np.random.default_rng(1)
    q = rng.integers(0, 16, (in_f, out_f)).astype(np.uint32)
    zeros = rng.integers(0, 15, (2, out_f)).astype(np.uint32)
    scales = rng.uniform(0.5, 2.0, (2, out_f)).astype(np.float32)
    qweight = np.zeros((2, out_f), np.uint32)
    for blk in range(2):
        for i in range(8):
            qweight[blk] |= q[blk * 8 + i] << (4 * i)
    qzeros = np.zeros((2, 1), np.uint32)
    for g in range(2):
        for j in range(8):
            qzeros[g, 0] |= zeros[g, j] << (4 * j)
    # act-order: interleaved group assignment instead of blocks of 8
    g_idx = (np.arange(in_f) % 2).astype(np.int64)
    want = ((q.astype(np.int32) - zeros[g_idx].astype(np.int32) - 1)
            * scales[g_idx]).T.astype(np.float32)
    got = dequantize_gptq_4bit(qweight, scales, qzeros, 8, g_idx)
    np.testing.assert_allclose(got, want, atol=1e-6)
    # sequential mapping differs -> proves g_idx is honored
    seq = dequantize_gptq_4bit(qweight, scales, qzeros, 8)
    assert not np.allclose(got, seq)

    class FakeStorage(dict):
        def read(self, name):
            return self[name]
    st = FakeStorage({"w.qweight": qweight.view(np.int32),
                      "w.scales": scales, "w.qzeros": qzeros.view(np.int32)})
    import pytest as _pytest
    with _pytest.raises(NotImplementedError, match="desc_act"):
        GptqQuantization(8, desc_act=True).load(st, "w.weight")
    st["w.g_idx"] = g_idx.astype(np.int32)
    np.testing.assert_allclose(
        GptqQuantization(8, desc_act=True).load(st, "w.weight"), want,
        atol=1e-6)


def test_detect_quantization_desc_act():
    from cake_tpu.utils.quant import detect_quantization
    q = detect_quantization({"quantization_config": {
        "quant_method": "gptq", "group_size": 64, "desc_act": True}})
    assert q.desc_act is True
