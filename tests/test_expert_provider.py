"""Expert provider tests: disk-offloaded MoE must match the resident
dense-combine computation exactly (mirrors ref disk_expert_provider tests)."""
import json

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import init_params, tiny_config
from cake_tpu.models.common.expert_provider import (DiskExpertProvider,
                                                    ResidentExpertProvider,
                                                    moe_ffn_offloaded)
from cake_tpu.ops.moe import moe_ffn
from cake_tpu.utils import params_to_hf_tensors, save_safetensors
from cake_tpu.utils.safetensors_io import TensorStorage


def _setup(tmp_path):
    cfg = tiny_config("qwen3_moe")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    mlp = params["layers"][0]["mlp"]
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (5, cfg.hidden_size)), jnp.float32)
    want = moe_ffn(x, mlp["gate"]["weight"], mlp["experts"]["gate_proj"],
                   mlp["experts"]["up_proj"], mlp["experts"]["down_proj"],
                   cfg.num_experts_per_tok, cfg.norm_topk_prob)
    return cfg, params, mlp, x, want


def test_disk_provider_matches_resident(tmp_path):
    cfg, params, mlp, x, want = _setup(tmp_path)
    st = TensorStorage.from_model_dir(str(tmp_path))
    prov = DiskExpertProvider(st, "model.layers.0", cfg.num_experts,
                              dtype=jnp.float32, lru_size=4)
    got = moe_ffn_offloaded(x, mlp["gate"]["weight"], prov,
                            cfg.num_experts_per_tok, cfg.norm_topk_prob)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                               rtol=1e-3)
    # LRU populated and bounded
    assert 0 < len(prov._lru) <= 4


def test_resident_provider_matches(tmp_path):
    cfg, params, mlp, x, want = _setup(tmp_path)
    prov = ResidentExpertProvider(mlp["experts"])
    got = moe_ffn_offloaded(x, mlp["gate"]["weight"], prov,
                            cfg.num_experts_per_tok, cfg.norm_topk_prob)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                               rtol=1e-3)


def test_prefetch_warms_lru(tmp_path):
    cfg, params, mlp, x, want = _setup(tmp_path)
    st = TensorStorage.from_model_dir(str(tmp_path))
    prov = DiskExpertProvider(st, "model.layers.0", cfg.num_experts,
                              dtype=jnp.float32, lru_size=8)
    prov.prefetch([0, 1, 2])
    prov._prefetcher.join(timeout=10)
    assert set(prov._lru) == {0, 1, 2}
