"""Expert provider tests: disk-offloaded MoE must match the resident
dense-combine computation exactly (mirrors ref disk_expert_provider tests)."""
import pytest
import json

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import init_params, tiny_config
from cake_tpu.models.common.expert_provider import (DiskExpertProvider,
                                                    ResidentExpertProvider,
                                                    moe_ffn_offloaded)
from cake_tpu.ops.moe import moe_ffn
from cake_tpu.utils import params_to_hf_tensors, save_safetensors
from cake_tpu.utils.safetensors_io import TensorStorage


def _setup(tmp_path):
    cfg = tiny_config("qwen3_moe")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    mlp = params["layers"][0]["mlp"]
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (5, cfg.hidden_size)), jnp.float32)
    want = moe_ffn(x, mlp["gate"]["weight"], mlp["experts"]["gate_proj"],
                   mlp["experts"]["up_proj"], mlp["experts"]["down_proj"],
                   cfg.num_experts_per_tok, cfg.norm_topk_prob)
    return cfg, params, mlp, x, want


def test_disk_provider_matches_resident(tmp_path):
    cfg, params, mlp, x, want = _setup(tmp_path)
    st = TensorStorage.from_model_dir(str(tmp_path))
    prov = DiskExpertProvider(st, "model.layers.0", cfg.num_experts,
                              dtype=jnp.float32, lru_size=4)
    got = moe_ffn_offloaded(x, mlp["gate"]["weight"], prov,
                            cfg.num_experts_per_tok, cfg.norm_topk_prob)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                               rtol=1e-3)
    # LRU populated and bounded
    assert 0 < len(prov._lru) <= 4


def test_resident_provider_matches(tmp_path):
    cfg, params, mlp, x, want = _setup(tmp_path)
    prov = ResidentExpertProvider(mlp["experts"])
    got = moe_ffn_offloaded(x, mlp["gate"]["weight"], prov,
                            cfg.num_experts_per_tok, cfg.norm_topk_prob)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                               rtol=1e-3)


def test_prefetch_warms_lru(tmp_path):
    cfg, params, mlp, x, want = _setup(tmp_path)
    st = TensorStorage.from_model_dir(str(tmp_path))
    prov = DiskExpertProvider(st, "model.layers.0", cfg.num_experts,
                              dtype=jnp.float32, lru_size=8)
    prov.prefetch([0, 1, 2])
    prov._prefetcher.join(timeout=10)
    assert set(prov._lru) == {0, 1, 2}


def test_disk_offload_full_model_load(tmp_path):
    """Round-1 review gap: the batched-preadv streaming path under a REAL
    MoE forward — every MoE layer of a qwen3_moe model computed via
    DiskExpertProvider (LRU smaller than the expert count, so the run
    evicts and re-streams) must match the resident full-model forward."""
    from cake_tpu.models.common.layers import forward_train
    from cake_tpu.utils import cakekit

    cfg = tiny_config("qwen3_moe")
    params = init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    st = TensorStorage.from_model_dir(str(tmp_path))
    if not cakekit.available():
        import pytest
        pytest.skip("native cakekit core not built (optional)")

    toks = jnp.asarray(np.random.default_rng(2).integers(0, 255, (1, 6)))
    want = forward_train(cfg, params, toks)

    # rebuild the forward with every MoE mlp routed through the provider
    from cake_tpu.models.common.layers import (block_forward, embed_tokens,
                                               lm_head_logits)
    from cake_tpu.ops.norms import rms_norm
    x = embed_tokens(cfg, params, toks)
    rope = params["rope"]
    pos0 = jnp.asarray(0, jnp.int32)
    for i, spec in enumerate(cfg.layer_specs()):
        lp = params["layers"][i]
        if spec.is_moe:
            prov = DiskExpertProvider(st, f"model.layers.{i}",
                                      cfg.num_experts, dtype=jnp.float32,
                                      lru_size=3)   # < num_experts: evicts
            h = rms_norm(x, lp["input_layernorm"]["weight"],
                         cfg.rms_norm_eps)
            from cake_tpu.models.common.layers import attention_forward
            attn_out, _ = attention_forward(cfg, spec, lp["self_attn"], h,
                                            None, pos0, rope)
            x = x + attn_out
            h = rms_norm(x, lp["post_attention_layernorm"]["weight"],
                         cfg.rms_norm_eps)
            flat = h.reshape(-1, cfg.hidden_size)
            y = moe_ffn_offloaded(flat, lp["mlp"]["gate"]["weight"], prov,
                                  cfg.num_experts_per_tok,
                                  cfg.norm_topk_prob)
            x = x + y.reshape(x.shape)
            assert len(prov._lru) <= 3          # LRU actually bounded
        else:
            x, _ = block_forward(cfg, spec, lp, x, None, pos0, rope)
    got = lm_head_logits(cfg, params, x[:, -1:]).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want[:, -1:]),
                               atol=2e-4, rtol=1e-3)


def test_read_many_batched_preadv(tmp_path):
    """TensorStorage.read_many returns the same bytes as per-name reads
    (same-file groups ride one ck_preadv call)."""
    cfg = tiny_config("qwen3_moe")
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    st = TensorStorage.from_model_dir(str(tmp_path))
    names = ["model.layers.0.mlp.experts.1.gate_proj.weight",
             "model.layers.0.mlp.experts.5.down_proj.weight",
             "model.layers.1.input_layernorm.weight"]
    batched = st.read_many(names)
    for n, arr in zip(names, batched):
        np.testing.assert_array_equal(arr, st.read(n))


@pytest.mark.slow      # tier-2 covers it; tier-1 runs under the 870s cap
def test_offloaded_model_end_to_end(tmp_path):
    """The PRODUCT --expert-offload path: load_model_params(expert_offload)
    leaves expert banks on disk (provider leaves, no stacked tensors) and
    OffloadedTextModel's greedy output matches the resident TextModel
    exactly from the same checkpoint."""
    from cake_tpu.models import TextModel
    from cake_tpu.models.common.offload_model import OffloadedTextModel
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.utils.loaders import load_model_params

    cfg = tiny_config("qwen3_moe")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    save_safetensors(str(tmp_path / "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    with open(tmp_path / "config.json", "w") as f:
        json.dump({"architectures": ["Qwen3MoeForCausalLM"]}, f)

    resident = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
    prompt = [5, 9, 2, 7, 1, 4]
    want, _ = resident.generate(prompt, max_new_tokens=8,
                                sampling=SamplingConfig(temperature=0.0))

    off_params = load_model_params(cfg, str(tmp_path), jnp.float32,
                                   expert_offload=True)
    for layer in off_params["layers"]:
        assert "_provider" in layer["mlp"]
        assert "experts" not in layer["mlp"]
    model = OffloadedTextModel(cfg, off_params, dtype=jnp.float32,
                               max_cache_len=64)
    got, stats = model.generate(prompt, max_new_tokens=8,
                                sampling=SamplingConfig(temperature=0.0))
    assert stats["expert_offload"] is True
    assert got == want
