"""External numerics ground truth for the image path: SD UNet and VAE
decoder cross-checked against the installed `diffusers` implementation
(CPU, f32, tiny random configs), mirroring tests/test_hf_parity.py for
text (which found three real semantic bugs the self-consistent goldens
could not).

`diffusers` is NOT installed in the build environment — these tests are
insurance that activates automatically the day the environment gains it
(VERDICT r4 item 7). They exercise the REAL loader path: weights flow a
diffusers `save_pretrained` checkpoint -> sd_loader's mapping ->
our forward, so the name mapping, conv-vs-linear squeeze transforms and
group-norm/timestep conventions are all under test.

FLUX is not covered here: our FLUX.1 loader consumes the BFL/ComfyUI
tensor layout (bare double_blocks.*), not diffusers', so a cross-check
would test a name-translation layer written only for the test.
"""
from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
diffusers = pytest.importorskip("diffusers")

from cake_tpu.models.image.sd import init_unet_params, unet_forward
from cake_tpu.models.image.sd_loader import (sd_configs_from_dir,
                                             sd_unet_mapping,
                                             sd_vae_decoder_mapping)
from cake_tpu.models.image.vae import init_vae_decoder_params, vae_decode
from cake_tpu.utils.mapping import load_mapped_params
from cake_tpu.utils.safetensors_io import TensorStorage

ATOL = 1e-3


def randomize_torch(model, seed: int):
    """Non-trivial random weights everywhere (default inits zero some
    projections, which would hide mapping bugs)."""
    rng = np.random.default_rng(seed)
    with torch.no_grad():
        for p in model.parameters():
            p.copy_(torch.from_numpy(
                rng.normal(0.0, 0.05, tuple(p.shape)).astype(np.float32)))
    model.eval()
    return model


def tiny_unet():
    return diffusers.UNet2DConditionModel(
        sample_size=8, in_channels=4, out_channels=4,
        down_block_types=("CrossAttnDownBlock2D", "DownBlock2D"),
        up_block_types=("UpBlock2D", "CrossAttnUpBlock2D"),
        block_out_channels=(32, 64), layers_per_block=1,
        cross_attention_dim=32, attention_head_dim=4, norm_num_groups=32)


def tiny_vae():
    return diffusers.AutoencoderKL(
        in_channels=3, out_channels=3,
        down_block_types=("DownEncoderBlock2D", "DownEncoderBlock2D"),
        up_block_types=("UpDecoderBlock2D", "UpDecoderBlock2D"),
        block_out_channels=(32, 64), layers_per_block=1,
        latent_channels=4, norm_num_groups=32)


@pytest.fixture(scope="module")
def sd_dir(tmp_path_factory):
    """A tiny diffusers-layout SD checkpoint directory (unet + vae +
    scheduler), randomized, as sd_loader expects it on disk."""
    d = tmp_path_factory.mktemp("sd-diffusers")
    randomize_torch(tiny_unet(), 7).save_pretrained(d / "unet")
    randomize_torch(tiny_vae(), 8).save_pretrained(d / "vae")
    os.makedirs(d / "scheduler", exist_ok=True)
    with open(d / "scheduler" / "scheduler_config.json", "w") as f:
        json.dump({"prediction_type": "epsilon", "beta_start": 0.00085,
                   "beta_end": 0.012, "beta_schedule": "scaled_linear"}, f)
    return str(d)


def test_sd_unet_forward_parity(sd_dir):
    cfg = sd_configs_from_dir(sd_dir)
    st = TensorStorage.from_model_dir(os.path.join(sd_dir, "unet"))
    um, ut = sd_unet_mapping(cfg.unet)
    params = load_mapped_params(
        st, um,
        jax.eval_shape(lambda: init_unet_params(
            cfg.unet, jax.random.PRNGKey(0), jnp.float32)),
        jnp.float32, transforms=ut)

    rng = np.random.default_rng(11)
    x = rng.normal(0.0, 1.0, (1, 4, 8, 8)).astype(np.float32)
    ctx = rng.normal(0.0, 1.0, (1, 7, 32)).astype(np.float32)
    timestep = 450

    hf = diffusers.UNet2DConditionModel.from_pretrained(
        os.path.join(sd_dir, "unet"), torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        want = hf(torch.from_numpy(x),
                  torch.tensor([timestep]),
                  encoder_hidden_states=torch.from_numpy(ctx)).sample.numpy()

    # our t is the timestep fraction in [0,1]; the embedding scales by 1000
    got = np.asarray(unet_forward(
        cfg.unet, params, jnp.asarray(x),
        jnp.asarray([timestep / 1000.0], jnp.float32), jnp.asarray(ctx)))

    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=0)


def test_sd_vae_decode_parity(sd_dir):
    cfg = sd_configs_from_dir(sd_dir)
    st = TensorStorage.from_model_dir(os.path.join(sd_dir, "vae"))
    vm, vt = sd_vae_decoder_mapping(st, cfg.vae)
    shapes = jax.eval_shape(lambda: init_vae_decoder_params(
        cfg.vae, jax.random.PRNGKey(0), jnp.float32))
    lc = cfg.vae.latent_channels
    shapes["post_quant_conv"] = {
        "weight": jax.ShapeDtypeStruct((lc, lc, 1, 1), jnp.float32),
        "bias": jax.ShapeDtypeStruct((lc,), jnp.float32)}
    params = load_mapped_params(st, vm, shapes, jnp.float32, transforms=vt)
    assert "post_quant_conv" in params

    rng = np.random.default_rng(12)
    z = rng.normal(0.0, 1.0, (1, lc, 8, 8)).astype(np.float32)

    hf = diffusers.AutoencoderKL.from_pretrained(
        os.path.join(sd_dir, "vae"), torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        want = hf.decode(torch.from_numpy(z)).sample.numpy()

    # vae_decode applies the pipeline's z/scaling + shift internally;
    # diffusers' decode() takes the already-unscaled latent — feed ours
    # the pre-scaled value so both decoders see the same tensor
    z_ours = (z - cfg.vae.shift_factor) * cfg.vae.scaling_factor
    got = np.asarray(vae_decode(cfg.vae, params, jnp.asarray(z_ours)))

    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=0)


def test_sd_vae_encode_parity(sd_dir):
    from cake_tpu.models.image.sd_loader import sd_vae_encoder_mapping
    from cake_tpu.models.image.vae import (init_vae_encoder_params,
                                           vae_encode)

    cfg = sd_configs_from_dir(sd_dir)
    st = TensorStorage.from_model_dir(os.path.join(sd_dir, "vae"))
    em, et = sd_vae_encoder_mapping(st, cfg.vae)
    params = load_mapped_params(
        st, em,
        jax.eval_shape(lambda: init_vae_encoder_params(
            cfg.vae, jax.random.PRNGKey(0), jnp.float32)),
        jnp.float32, transforms=et)

    rng = np.random.default_rng(13)
    px = rng.uniform(-1.0, 1.0, (1, 3, 16, 16)).astype(np.float32)

    hf = diffusers.AutoencoderKL.from_pretrained(
        os.path.join(sd_dir, "vae"), torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        want = hf.encode(torch.from_numpy(px)).latent_dist.mode().numpy()

    # vae_encode returns the scheduler-space latent (raw - shift) * scale;
    # diffusers' mode() is the raw posterior mean
    got = np.asarray(vae_encode(cfg.vae, params, jnp.asarray(px)))
    got_raw = got / cfg.vae.scaling_factor + cfg.vae.shift_factor

    assert got_raw.shape == want.shape
    np.testing.assert_allclose(got_raw, want, atol=ATOL, rtol=0)
