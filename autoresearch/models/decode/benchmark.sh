#!/bin/sh
python bench.py
