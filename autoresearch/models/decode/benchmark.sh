#!/bin/sh
# TPU: the real flagship decode bench. CAKE_BENCH_CPU=1: the tiny smoke
# model on CPU — validates the gate end-to-end without hardware.
if [ "${CAKE_BENCH_CPU:-}" = "1" ]; then
  python bench.py --smoke --cpu
else
  python bench.py
fi
