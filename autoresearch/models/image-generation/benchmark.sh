#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time, jax, jax.numpy as jnp
from cake_tpu.models.image.flux import tiny_flux_config, FluxImageModel
import cake_tpu.models.image.mmdit as mm
cfg = tiny_flux_config()
m = FluxImageModel(cfg, dtype=jnp.bfloat16)
m.generate_image("warm", width=64, height=64, steps=1, seed=0)
t0 = time.perf_counter()
m.generate_image("bench", width=64, height=64, steps=4, seed=0)
print(json.dumps({"mmdit_step_s": round((time.perf_counter() - t0) / 4, 4)}))
PY
