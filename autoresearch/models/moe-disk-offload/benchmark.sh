#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time, tempfile
import jax, jax.numpy as jnp
from cake_tpu.models import init_params, tiny_config
from cake_tpu.models.common.offload_model import OffloadedTextModel
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.utils import params_to_hf_tensors, save_safetensors
from cake_tpu.utils.loaders import load_model_params

# the REAL --expert-offload path: experts stream from disk per token
cfg = tiny_config("qwen3_moe", num_experts=16, moe_intermediate_size=64)
d = tempfile.mkdtemp()
params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
save_safetensors(f"{d}/model.safetensors", params_to_hf_tensors(cfg, params))
with open(f"{d}/config.json", "w") as f:
    json.dump({"architectures": ["Qwen3MoeForCausalLM"]}, f)
# lru_size=2 << 16 experts: the timed run must hit real disk reads,
# not a warm dequant cache
off = load_model_params(cfg, d, jnp.float32, expert_offload=True,
                        expert_lru_size=2)
m = OffloadedTextModel(cfg, off, dtype=jnp.float32, max_cache_len=128)
m.generate([1, 2, 3], max_new_tokens=8,
           sampling=SamplingConfig(temperature=0.0))      # warm page cache
out, st = m.generate([1, 2, 3], max_new_tokens=48,
                     sampling=SamplingConfig(temperature=0.0))
print(json.dumps({"moe_offload_tok_per_s": round(st["tok_per_s"], 1)}))
PY
