#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time, tempfile, os
import jax, jax.numpy as jnp, numpy as np
from cake_tpu.models import TextModel, tiny_config
from cake_tpu.models.common.layers import init_params
from cake_tpu.ops.sampling import SamplingConfig
cfg = tiny_config("qwen3_moe", num_experts=16, moe_intermediate_size=64)
m = TextModel(cfg, dtype=jnp.float32, max_cache_len=128)
m.generate([1, 2, 3], max_new_tokens=16, chunk=16,
           sampling=SamplingConfig(temperature=0.0))
t0 = time.perf_counter()
out, st = m.generate([1, 2, 3], max_new_tokens=64, chunk=32,
                     sampling=SamplingConfig(temperature=0.0))
print(json.dumps({"moe_offload_tok_per_s": round(st["tok_per_s"], 1)}))
PY
