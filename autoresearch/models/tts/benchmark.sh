#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time, jax.numpy as jnp
from cake_tpu.models.audio import VibeVoiceTTS, tiny_tts_config
tts = VibeVoiceTTS(tiny_tts_config(), dtype=jnp.float32, max_frames=16)
tts.generate_speech("warm up run", max_frames=4, steps=4)
t0 = time.perf_counter()
tts.generate_speech("benchmark sentence for frame timing", max_frames=8,
                    steps=4)
print(json.dumps({"ms_per_frame": round((time.perf_counter() - t0) / 8 * 1e3, 1)}))
PY
