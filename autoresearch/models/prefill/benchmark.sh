#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time
import numpy as np, jax.numpy as jnp
from __graft_entry__ import FLAGSHIP
from cake_tpu.models import TextModel, config_from_hf_dict, tiny_config
import jax
cpu = jax.default_backend() != "tpu"
cfg = tiny_config("qwen3") if cpu else config_from_hf_dict(FLAGSHIP)
m = TextModel(cfg, dtype=jnp.bfloat16, max_cache_len=128 if cpu else 2048)
out = {}
for n in ((32, 64) if cpu else (512, 2048)):
    toks = list(np.random.default_rng(0).integers(0, 1000, n))
    m.prefill(m.new_cache(), toks)                    # compile
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(m.prefill(m.new_cache(), toks)[0])
    out[f"ttft_{n}_ms"] = round((time.perf_counter() - t0) / 3 * 1e3, 1)
print(json.dumps(out))
PY
