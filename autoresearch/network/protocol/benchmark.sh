#!/bin/sh
python benches/bench_micro.py --filter frame
