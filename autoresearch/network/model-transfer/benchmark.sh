#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time, tempfile, os, numpy as np
from cake_tpu.utils.safetensors_io import TensorStorage, save_safetensors
from cake_tpu.cluster import transfer
d = tempfile.mkdtemp()
tensors = {f"model.layers.{i}.w": np.random.default_rng(i).standard_normal(
    (512, 512)).astype(np.float32) for i in range(32)}
save_safetensors(os.path.join(d, "model.safetensors"), tensors)
st = TensorStorage.from_model_dir(d)
names = sorted(st.names())
total, _ = transfer.synthesize_safetensors(st, names)
t0 = time.perf_counter()
n = 0
for chunk in transfer.encode_chunks(
        "model.safetensors", total,
        transfer.synthesize_safetensors(st, names)[1]):
    n += len(chunk.get("d", b""))
dt = time.perf_counter() - t0
print(json.dumps({"transfer_mb_s": round(total / dt / 1e6, 1)}))
PY
