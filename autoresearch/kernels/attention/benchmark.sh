#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time
import numpy as np, jax, jax.numpy as jnp
from cake_tpu.ops.flash import flash_attention
b, s, hq, hkv, d = 1, 4096, 16, 8, 128
if jax.default_backend() != "tpu":
    s = 256                         # interpret mode is slow
k = jax.random.PRNGKey(0)
q = jax.random.normal(k, (b, s, hq, d), jnp.bfloat16)
kv = jax.random.normal(k, (b, s, hkv, d), jnp.bfloat16)
interp = jax.default_backend() != "tpu"   # Pallas needs interpret off-TPU
f = jax.jit(lambda q, k, v: flash_attention(q, k, v, interpret=interp))
np.asarray(f(q, kv, kv))
t0 = time.perf_counter()
for _ in range(10):
    np.asarray(f(q, kv, kv))
dt = (time.perf_counter() - t0) / 10
print(json.dumps({"prefill_tok_per_s": round(s / dt)}))
PY
