#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time
import numpy as np, jax, jax.numpy as jnp
from cake_tpu.models import tiny_config
from cake_tpu.models.common.layers import init_layer_params
from cake_tpu.models.qwen3_5 import gdn_forward
cfg = tiny_config("qwen3_5", hidden_size=1024)
# full-scale GDN dims ride on the tiny config's layer machinery
spec = next(s for s in cfg.layer_specs() if s.kind == "linear")
p = init_layer_params(cfg, spec, jax.random.PRNGKey(0), jnp.bfloat16)
x = jax.random.normal(jax.random.PRNGKey(1), (1, 1024, cfg.hidden_size),
                      jnp.bfloat16)
f = jax.jit(lambda p, x: gdn_forward(cfg, p["linear_attn"], x, None,
                                     jnp.asarray(0, jnp.int32), None)[0])
np.asarray(f(p, x))
t0 = time.perf_counter()
for _ in range(5):
    np.asarray(f(p, x))
dt = (time.perf_counter() - t0) / 5
print(json.dumps({"gdn_prefill_tok_per_s": round(1024 / dt)}))
PY
