#!/bin/sh
# CAKE_BENCH_CPU=1 -> CPU validation mode (TPU busy/absent)
[ "${CAKE_BENCH_CPU:-}" = "1" ] && CPU=--cpu || CPU=
python benches/bench_micro.py --filter sampling $CPU
