#!/bin/sh
python - <<'PY'
import os
if os.environ.get("CAKE_BENCH_CPU") == "1":
    import jax; jax.config.update("jax_platforms", "cpu")
import json, time
import numpy as np, jax, jax.numpy as jnp
from cake_tpu.ops.fp8 import quant_fp8_blockwise
from cake_tpu.ops.linear import linear
k = jax.random.PRNGKey(0)
w = jax.random.normal(k, (4096, 1024), jnp.float32)
wq, si = quant_fp8_blockwise(w)
x = jax.random.normal(k, (1, 16, 1024), jnp.bfloat16)
f8 = jax.jit(lambda x: linear(x, {"fp8": wq, "scale_inv": si}))
fb = jax.jit(lambda x, w: linear(x, w))
wb = w.astype(jnp.bfloat16)
np.asarray(f8(x)); np.asarray(fb(x, wb))
def t(f, *a):
    t0 = time.perf_counter()
    for _ in range(20): np.asarray(f(*a))
    return (time.perf_counter() - t0) / 20 * 1e3
print(json.dumps({"fp8_matmul_ms": round(t(f8, x), 4),
                  "bf16_matmul_ms": round(t(fb, x, wb), 4)}))
PY
