# cake-tpu developer entry points (ref: the reference Makefile's build/test
# targets; mobile app targets have no analog here — see PARITY.md §2f).

.PHONY: install test lint knobs-doc metrics-doc bench bench-micro obs-smoke trace-smoke serve-smoke qos-smoke serve-bench serve-bench-longtail serve-bench-spec serve-bench-fleet serve-bench-qos serve-bench-telemetry serve-bench-kvshare paged-smoke chaos-smoke serve-chaos-smoke fleet-chaos-smoke partition-smoke fleet-soak kvshare-smoke telemetry-smoke spec-smoke spec-serve-smoke spec-bench native clean docker

install:
	pip install -e . --no-build-isolation

# static-analysis gate (docs/static_analysis.md): AST checkers for the
# serving hot path — host-sync, recompile-hazard, use-after-donate,
# knob-registry, lock-discipline, hot-timing. Exits non-zero on any
# violation that lacks an in-line `# lint: disable=<rule> — <reason>`.
lint:
	python -m cake_tpu.analysis

# regenerate docs/knobs.md from the central registry (cake_tpu/knobs.py);
# tests/test_analysis.py pins the file to the registry
knobs-doc:
	python -m cake_tpu.knobs > docs/knobs.md

# regenerate docs/observability.md — the metric/span/timeline catalog —
# from cake_tpu/obs (catalog.py); tests/test_analysis.py pins the file,
# and the metric-registry lint checks instrument names against it
metrics-doc:
	python -m cake_tpu.obs > docs/observability.md

native:
	$(MAKE) -C csrc

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

bench-micro:
	python benches/bench_micro.py

# request-tracing gate: one chat driven through a REAL router + replica
# (tiny CPU model) must yield a stitched timeline with events from BOTH
# tiers retrievable by its trace id from the router, and non-zero
# TTFT/ITL/e2e SLO histograms (with exemplars) in the replica's /metrics
trace-smoke: lint
	JAX_PLATFORMS=cpu python scripts/trace_smoke.py

# observability gate: the static-analysis pass (hot-timing absorbed
# check_hot_timing.py; the other six rules ride along), the cross-tier
# trace-smoke above, and a tiny traced CPU generation asserting /metrics
# histograms and the Chrome-trace export are live
obs-smoke: lint trace-smoke
	JAX_PLATFORMS=cpu python scripts/obs_smoke.py

# continuous-batching gate: concurrent chats 200 through the engine, a 429
# + Retry-After under queue saturation, non-zero serve-queue gauges in
# /metrics while saturated, and non-zero prefix-cache hits on repeated
# prompts (tiny CPU model, in-process aiohttp)
serve-smoke: lint
	JAX_PLATFORMS=cpu python scripts/serve_smoke.py

# QoS admission-plane gate: batch-image saturation (tiny diffusion stub
# through the job executor) with interleaved interactive chat — chat
# TTFT p50 must stay within 2x the idle baseline, every batch job must
# complete, and the class-labeled queue gauges must be live in /metrics
qos-smoke: lint
	JAX_PLATFORMS=cpu python scripts/qos_smoke.py

# mixed-workload QoS bench: idle vs batch-saturated interactive TTFT,
# weighted-fair service shares, job throughput (BENCH_QOS_<tag>.json)
serve-bench-qos:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py --qos --tag qos

# fault-tolerance gate: master + 2 real workers on localhost, one worker
# killed mid-stream by a deterministic fault plan — the generation must
# complete bit-identical to the unfailed run with exactly one replay
# prefill, and the recovery counters must be non-zero in /metrics
chaos-smoke:
	JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

# serve-plane crash-only gate: engine under concurrent API load with one
# injected step crash — every client completes 200 bit-identical to an
# uninjected run, exactly one rebuild (non-zero
# cake_serve_engine_rebuilds_total in /metrics), /health back to 200
serve-chaos-smoke: lint
	JAX_PLATFORMS=cpu python scripts/serve_chaos_smoke.py

# fleet robustness gate: 3 real serve replicas behind the router, one
# killed mid-traffic — zero failed non-streamed requests (transparent
# failover), a visible eject -> readmit cycle in /fleet + /metrics, and
# saturation shed as router-level 429s (shed_by=router), never replica
# errors. Streamed phase (hard gate): the owning replica is killed
# MID-STREAM — the self-healed body must be byte-identical to an
# unbroken run with zero client-visible errors, and resume budget 0
# must preserve the typed error event (now with resume_token).
fleet-chaos-smoke: lint
	JAX_PLATFORMS=cpu python scripts/fleet_chaos_smoke.py

# partition-tolerance gate (tier-2): real serve replicas behind the
# router with a REAL network chaos layer (fleet/netem.ChaosProxy) on
# the victim's wire. Full partition, asymmetric probe-alive/data-dead
# (flipped via the proxy's control socket), and a delay brownout each
# eject within a bounded window with ZERO client-visible errors; the
# asymmetric eject carries evidence=data, probes alone never readmit
# it, the failed trial re-ejects with a doubled hold, and only the
# healed network's data-path trial readmits (docs/fleet.md)
partition-smoke: lint
	JAX_PLATFORMS=cpu python scripts/partition_smoke.py

# closed-loop elastic-fleet gate (tier-2: real multi-process soak, not
# part of the tier-1 pytest run): a real router with the autoscaler on
# bootstraps 0 -> min by spawning real serve child processes, a load
# ramp scales 2 -> 4 on starved headroom, the ramp's end scales 4 -> 2
# through graceful drains (every reap forced=False), a kill -9 victim
# is swept and replaced via below_min — zero client-visible errors and
# zero frozen-gauge contamination across all of it (docs/autoscaling.md)
fleet-soak: lint
	JAX_PLATFORMS=cpu python scripts/fleet_soak.py

# fleet-shared KV gate (tier-2): 3 real replicas behind the router with
# CAKE_KVSHARE=1 — a cordoned warm replica's prefix chain is fetched by
# a cache-cold peer purely off the router-injected X-Cake-KV-Peers
# directory (bit-identical greedy body, kv-fetch hit counter advancing,
# prefix_hit_tokens > 0 on the lander), and a mid-stream drain ships
# the live slot's swap blob to a peer which resumes the stream
# byte-identical with zero client-visible errors (docs/kv_sharing.md)
kvshare-smoke: lint
	JAX_PLATFORMS=cpu python scripts/kvshare_smoke.py

# fleet-shared KV bench: cold-fetch (directory-driven peer fetch) vs
# cold-recompute (kvshare off) vs local-warm TTFT on a shared-prefix
# follow-up. Writes BENCH_KVSHARE_<tag>.json.
serve-bench-kvshare:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py --kvshare --tag r20

# fleet telemetry gate: 2 real engine-backed replicas behind the router,
# a traffic burst -> live rollup (merged fleet TTFT p95 from bucket-wise
# histogram sums, non-zero capacity headroom, burn-rate gauges on
# /metrics), flight ring readable on demand, then one replica killed ->
# stale + outlier(stale) within a probe window with the dead replica's
# mirrored gauges RETRACTED from the router's /metrics (stale-mirror
# rule; docs/telemetry.md)
telemetry-smoke: lint
	JAX_PLATFORMS=cpu python scripts/telemetry_smoke.py

# telemetry rollup overhead bench: synthetic fleet scrapes driven through
# FleetTelemetry.ingest (no sockets) — per-cycle rollup cost gated
# < 5 ms mean. Writes BENCH_TELEM_<tag>.json.
serve-bench-telemetry:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py --telemetry --tag r16

# fleet affinity bench: 2 replicas + router, conversational follow-up
# traffic with prefix-affinity routing vs round-robin — affinity must
# beat round-robin on warm follow-up TTFT (the owning replica holds the
# conversation's prefix KV blocks) — plus the self-healing resume stat
# (splice gap vs cold client retry). Writes BENCH_FLEET_<tag>.json.
serve-bench-fleet:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py --fleet --tag fleet

# serve scheduler bench: TTFT p50/p99 + tok/s for a shared-system-prompt
# workload cold (no prefix cache) vs warm (prefix cached), and the
# decode-interference probe (tokens still flowing while a long prompt is
# admitted chunk-by-chunk). Writes BENCH_SERVE_<tag>.json.
serve-bench:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py

# paged-KV long-tail bench: mixed short/long contexts through the paged
# pool sized to the OLD 4-row pool's bytes — records peak concurrent
# streams (> 4 = the paging win) + preemption/swap counts
serve-bench-longtail:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py --long-tail --tag longtail

# paged-KV gate: paged greedy bit-identical to the sequential path,
# prefix hit = refcount bump (shared-blocks gauge > 0, no KV copy),
# preempt-by-swap under an undersized pool with bit-identical
# continuation, kv-block gauges + preemption counter in /metrics
paged-smoke: lint
	JAX_PLATFORMS=cpu python scripts/paged_smoke.py

# speculative-decoding gate: serve engine + n-gram drafter on the tiny
# CPU model — greedy output bit-identical to a spec-off engine, >= 1
# multi-token accept, non-zero cake_serve_spec_{proposed,accepted}_total
spec-smoke:
	JAX_PLATFORMS=cpu python scripts/spec_smoke.py

# batched-speculation serve gate: concurrent API clients through the
# PAGED speculating engine (no stand-down) — bit-identical greedy
# outputs vs a spec-off engine, non-zero spec counters in /metrics,
# batched spec block in /health
spec-serve-smoke: lint
	JAX_PLATFORMS=cpu python scripts/spec_serve_smoke.py

# batched-speculation bench: acceptance-rate x occupancy x effective
# tok/s, spec on vs off, contiguous + paged engines; fails if greedy
# parity breaks or the best effective speedup on templated traffic
# lands under 1.3x. Writes BENCH_SERVE_<tag>.json.
serve-bench-spec:
	JAX_PLATFORMS=cpu python scripts/serve_bench.py --spec --tag spec

# speculation bench: tokens/s + acceptance (accepted tokens per verify
# step), spec on vs off, repetitive vs non-repetitive prompt. Writes
# BENCH_SPEC_<tag>.json; fails if spec breaks greedy parity or the
# repetitive case does not beat 1.0 accepted/step.
spec-bench:
	JAX_PLATFORMS=cpu python scripts/spec_bench.py

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	python -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

docker:
	docker compose build

clean:
	$(MAKE) -C csrc clean
	find . -name __pycache__ -type d -exec rm -rf {} +
