"""Headline benchmark: Qwen3-0.6B-shaped single-chip decode throughput.

Prints ONE JSON line:
  {"metric": "qwen3_0.6b_decode", "value": <tok/s>, "unit": "tok/s",
   "vs_baseline": <value / 185.7>}

Baseline: the reference's best published small-model decode — Qwen2.5-0.5B
F16 at 185.7 tok/s on an RTX 3080 Laptop (BASELINE.md; the closest published
number to the BASELINE.json north-star config). Random weights: throughput
is weight-value independent, and the environment has no network egress.

Usage: python bench.py [--smoke] [--tokens N] [--runs N]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S = 185.7


def _watchdog(seconds: int):
    """Hard-exit if the TPU grant service wedges mid-compile (observed in
    this environment): better a clean failure JSON than a silent hang."""
    import os
    import threading

    def boom():
        print(json.dumps({"metric": "qwen3_0.6b_decode", "value": 0.0,
                          "unit": "tok/s", "vs_baseline": 0.0,
                          "error": f"watchdog: no result in {seconds}s"}),
              flush=True)
        os._exit(3)

    t = threading.Timer(seconds, boom)
    t.daemon = True
    t.start()
    return t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny model quick check")
    ap.add_argument("--tokens", type=int, default=256)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--watchdog", type=int, default=1200)
    args = ap.parse_args()
    wd = _watchdog(args.watchdog)

    from cake_tpu.models import (SamplingConfig, TextModel, config_from_hf_dict,
                                 tiny_config)
    from __graft_entry__ import FLAGSHIP

    if args.smoke:
        cfg = tiny_config("qwen3")
        cache_len = 128
        args.tokens = min(args.tokens, 64)
    else:
        cfg = config_from_hf_dict(FLAGSHIP)
        cache_len = 2048

    model = TextModel(cfg, dtype=jnp.bfloat16, max_cache_len=cache_len)
    prompt = list(np.random.default_rng(0).integers(
        0, cfg.vocab_size - 1, size=args.prompt_len))
    scfg = SamplingConfig(temperature=0.0)   # greedy, seeded (ref bench: temp=0)

    # warmup / compile
    model.generate(prompt, max_new_tokens=args.chunk, sampling=scfg,
                   chunk=args.chunk)

    rates, ttfts = [], []
    for _ in range(args.runs):
        toks, stats = model.generate(prompt, max_new_tokens=args.tokens,
                                     sampling=scfg, chunk=args.chunk)
        rates.append(stats["tok_per_s"])
        ttfts.append(stats["ttft_s"])

    value = float(np.mean(rates))
    result = {
        "metric": "qwen3_0.6b_decode" if not args.smoke else "smoke_decode",
        "value": round(value, 2),
        "unit": "tok/s",
        "vs_baseline": round(value / BASELINE_TOK_S, 3),
    }
    extra = {
        "p50_ttft_s": round(float(np.median(ttfts)), 4),
        "runs": args.runs, "tokens": args.tokens,
        "device": str(jax.devices()[0]),
        "dtype": "bfloat16",
    }
    wd.cancel()
    print(json.dumps(result))
    print(json.dumps({"detail": extra}), file=sys.stderr)


if __name__ == "__main__":
    main()
