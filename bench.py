"""Headline benchmark: Qwen3-0.6B-shaped single-chip decode throughput.

Prints ONE JSON line:
  {"metric": "qwen3_0.6b_decode", "value": <tok/s>, "unit": "tok/s",
   "vs_baseline": <value / 185.7>, "p50_ttft_ms": <ms>,
   "link_rtt_ms": <ms>, "ttft_net_ms": <ms>}
(failure paths emit the same schema with value 0.0, an "error" field, and
no p50_ttft_ms)

TTFT guard: p50_ttft_ms includes exactly one device->host fetch, and on the
axon tunnel that fetch costs a fixed ~66-90 ms that DRIFTS between runs
(r02 vs r03 "regression" 84->108 ms reproduced at 68 ms with identical
code). link_rtt_ms is that fetch cost measured directly (p50 of fetching a
freshly-computed tiny array), and ttft_net_ms = p50_ttft_ms - link_rtt_ms
is the drift-free number to threshold: it is what the hardware + compiler
actually spend on prefill+sample. Gate on ttft_net_ms.

Baseline: the reference's best published small-model decode — Qwen2.5-0.5B
F16 at 185.7 tok/s on an RTX 3080 Laptop (BASELINE.md; the closest published
number to the BASELINE.json north-star config). Random weights: throughput
is weight-value independent, and the environment has no network egress.

Usage: python bench.py [--smoke] [--tokens N] [--runs N]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S = 185.7


def _deadline(seconds: int, payload: dict, exit_code: int):
    """Daemon timer that prints a failure-JSON line and hard-exits if not
    cancelled within `seconds` — a wedged TPU grant service makes even tiny
    jits hang forever (observed in this environment), and a clean failure
    JSON beats a silent hang."""
    import os
    import threading

    def boom():
        print(json.dumps(payload), flush=True)
        os._exit(exit_code)

    t = threading.Timer(seconds, boom)
    t.daemon = True
    t.start()
    return t


def _fail_payload(metric: str, error: str, **extra) -> dict:
    return {"metric": metric, "value": 0.0, "unit": "tok/s",
            "vs_baseline": 0.0, "error": error, **extra}


_PROBE_SRC = """
import time
t0 = time.perf_counter()
def mark(name):
    # cumulative seconds, one line per completed phase, flushed so the
    # parent sees partial progress even when it kills a wedged attempt
    print(f"probe-phase {name} {time.perf_counter() - t0:.3f}", flush=True)
import jax, jax.numpy as jnp
mark("import")
x = jnp.ones((64, 64), jnp.bfloat16)
mark("device_put")
f = jax.jit(lambda a: (a @ a).sum())
lowered = f.lower(x)
mark("lower")
compiled = lowered.compile()
mark("compile")
y = compiled(x)
mark("dispatch")
y.block_until_ready()
mark("device_wait")
print("probe-ok")
"""

# phase order of _PROBE_SRC: the first missing mark names where a wedged
# attempt is stuck (compile -> XLA/grant service; device_wait -> the TPU
# accepted the program but never finished it)
_PROBE_PHASES = ("import", "device_put", "lower", "compile", "dispatch",
                 "device_wait")


def _parse_probe_phases(stdout: str) -> dict[str, float]:
    """'probe-phase <name> <cumulative_s>' lines -> per-phase seconds."""
    cum: dict[str, float] = {}
    for line in (stdout or "").splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "probe-phase":
            try:
                cum[parts[1]] = float(parts[2])
            except ValueError:
                pass
    out, prev = {}, 0.0
    for name in _PROBE_PHASES:
        if name in cum:
            out[name] = round(cum[name] - prev, 3)
            prev = cum[name]
    return out


def _stuck_phase(phases: dict[str, float]) -> str:
    """First phase that never completed — where the wedge sits."""
    for name in _PROBE_PHASES:
        if name not in phases:
            return name
    return "post-probe"


def _record_probe_spans(phases: dict[str, float], attempt: int):
    """Mirror the probe's phase breakdown into the span recorder;
    _export_probe_trace writes the buffer out before bench exits."""
    from cake_tpu.obs import RECORDER, now
    if not RECORDER.enabled:
        return
    t_us = int(now() * 1e6)
    off = 0
    for name, dur in phases.items():
        RECORDER.add(f"probe.{name}", t_us + off, int(dur * 1e6),
                     cat="bench", attempt=attempt)
        off += int(dur * 1e6)


def _export_probe_trace():
    """Write the recorded probe spans to $CAKE_TRACE_DIR before bench
    exits (success or wedge) — the buffer dies with the process otherwise."""
    from cake_tpu.obs import RECORDER
    if RECORDER.enabled and len(RECORDER):
        try:
            path = RECORDER.export()
            print(f"[bench] probe trace written to {path}", file=sys.stderr)
        except OSError as e:
            print(f"[bench] probe trace export failed: {e}", file=sys.stderr)


def _health_probe(seconds: int, metric: str, budget: int = 1200):
    """Fast-fail TPU health check with bounded retry (round-4 lesson: a
    transient grant-service wedge zeroed an entire round's hardware signal
    because the probe gave up after one attempt). Each attempt runs a 64x64
    jit in a SUBPROCESS — a wedged jit cannot be cancelled in-process, only
    killed — and on timeout we sleep and re-probe until `budget` seconds
    have elapsed, then emit the distinguishable "tpu-wedged" JSON line."""
    import os
    import subprocess

    t0 = time.time()
    attempt = 0
    fast_fails = 0       # consecutive non-timeout failures: deterministic
    env = dict(os.environ)
    phases: dict[str, float] = {}
    while True:
        attempt += 1
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               timeout=seconds, env=env,
                               capture_output=True, text=True)
            phases = _parse_probe_phases(r.stdout)
            _record_probe_spans(phases, attempt)
            if "probe-ok" in r.stdout:
                print(f"[bench] health probe ok after {attempt} attempt(s) "
                      f"({time.time() - t0:.1f}s) phases={phases}",
                      file=sys.stderr)
                _export_probe_trace()
                return
            err = (r.stderr or "").strip().splitlines()
            err = err[-1] if err else f"exit {r.returncode}"
            fast_fails += 1
        except subprocess.TimeoutExpired as te:
            # the probe prints a flushed mark per completed phase, so even
            # a killed attempt yields a breakdown — the first MISSING mark
            # is where the wedge sits (jit-compile vs dispatch vs
            # device-wait), which beats a bare "tpu-wedged"
            so = te.stdout
            if isinstance(so, bytes):
                so = so.decode(errors="replace")
            phases = _parse_probe_phases(so or "")
            _record_probe_spans(phases, attempt)
            err = (f"64x64 jit did not finish in {seconds}s "
                   f"(stuck in {_stuck_phase(phases)}; "
                   f"completed phases: {phases or 'none'})")
            fast_fails = 0
        elapsed = time.time() - t0
        print(f"[bench] probe attempt {attempt} failed ({err}); "
              f"{elapsed:.0f}s/{budget}s of retry budget used",
              file=sys.stderr)
        if fast_fails >= 2:
            # probe exits quickly with the same kind of error twice in a
            # row — that's a deterministic init failure, not a wedge;
            # burning the retry budget would only mislabel it
            _export_probe_trace()
            print(json.dumps(_fail_payload(metric, "probe-failed",
                                           detail=err, phases=phases)),
                  flush=True)
            sys.exit(5)
        if elapsed + 150 + seconds > budget:
            _export_probe_trace()
            print(json.dumps(_fail_payload(
                metric, "tpu-wedged",
                detail=f"{attempt} probe attempts over {elapsed:.0f}s; "
                       f"last: {err}",
                phases=phases, stuck_phase=_stuck_phase(phases))),
                flush=True)
            sys.exit(4)
        time.sleep(150)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny model quick check")
    ap.add_argument("--tokens", type=int, default=256)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--watchdog", type=int, default=1200)
    ap.add_argument("--probe-timeout", type=int, default=60)
    ap.add_argument("--probe-budget", type=int, default=1200,
                    help="total seconds to keep re-probing a wedged TPU "
                         "before emitting the tpu-wedged failure line")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform (JAX_PLATFORMS env is "
                         "ignored when a sitecustomize pre-imports jax)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    metric = "smoke_decode" if args.smoke else "qwen3_0.6b_decode"
    if not args.cpu:    # probe exists to detect a wedged TPU grant service
        _health_probe(args.probe_timeout, metric, budget=args.probe_budget)
    wd = _deadline(args.watchdog, _fail_payload(
        metric, f"watchdog: no result in {args.watchdog}s"), exit_code=3)

    from cake_tpu.models import (SamplingConfig, TextModel, config_from_hf_dict,
                                 tiny_config)
    from __graft_entry__ import FLAGSHIP

    if args.smoke:
        cfg = tiny_config("qwen3")
        cache_len = 128
        args.tokens = min(args.tokens, 64)
    else:
        cfg = config_from_hf_dict(FLAGSHIP)
        cache_len = 2048

    model = TextModel(cfg, dtype=jnp.bfloat16, max_cache_len=cache_len)
    prompt = list(np.random.default_rng(0).integers(
        0, cfg.vocab_size - 1, size=args.prompt_len))
    scfg = SamplingConfig(temperature=0.0)   # greedy, seeded (ref bench: temp=0)

    # warmup / compile — full token count so every cache-length bucket the
    # timed runs will touch is compiled here, not inside the timed loop
    model.generate(prompt, max_new_tokens=args.tokens, sampling=scfg,
                   chunk=args.chunk)

    rates, ttfts = [], []
    for _ in range(args.runs):
        toks, stats = model.generate(prompt, max_new_tokens=args.tokens,
                                     sampling=scfg, chunk=args.chunk)
        rates.append(stats["tok_per_s"])
        ttfts.append(stats["ttft_s"])
    # extra TTFT-only samples: the tunnel-RTT component drifts, so median
    # over more draws than the 3 full runs
    for _ in range(4):
        _, stats = model.generate(prompt, max_new_tokens=1, sampling=scfg,
                                  chunk=args.chunk)
        ttfts.append(stats["ttft_s"])

    # direct link-RTT measurement (shared methodology with bench_full so
    # the two benches' ttft_net numbers stay comparable)
    from bench_full import measure_link_rtt
    link_rtt = measure_link_rtt()

    value = float(np.mean(rates))
    p50_ttft = float(np.median(ttfts))
    result = {
        "metric": metric,
        "value": round(value, 2),
        "unit": "tok/s",
        "vs_baseline": round(value / BASELINE_TOK_S, 3),
        "p50_ttft_ms": round(p50_ttft * 1e3, 1),
        "link_rtt_ms": round(link_rtt * 1e3, 1),
        "ttft_net_ms": round(max(p50_ttft - link_rtt, 0.0) * 1e3, 1),
    }
    extra = {
        "runs": args.runs, "tokens": args.tokens,
        "device": str(jax.devices()[0]),
        "dtype": "bfloat16",
    }
    wd.cancel()
    print(json.dumps(result))
    print(json.dumps({"detail": extra}), file=sys.stderr)


if __name__ == "__main__":
    main()
