// cakekit: native IO/runtime core for cake-tpu.
//
// The reference implements its wire framing and pread tensor storage in Rust
// (ref: cake-core/src/cake/sharding/proto/mod.rs framing;
// utils/tensor_storage.rs pread). This is the C++ equivalent for the hot
// host-side paths, exposed through a C ABI consumed via ctypes
// (cake_tpu/utils/cakekit.py):
//
//   ck_crc32        - CRC-32 (IEEE, zlib-compatible), slice-by-8
//   ck_pread        - positioned read without mmap (page-cache friendly)
//   ck_preadv       - batched positioned reads (expert streaming)
//   ck_frame_parse  - header validation returning payload length
//
// Build: make -C csrc   ->  csrc/libcakekit.so
// ctypes calls release the GIL, so large preads and CRC runs overlap with
// Python-side work (the asyncio loop keeps serving while weights stream).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- crc32

static uint32_t crc_table[8][256];

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int s = 1; s < 8; s++)
            crc_table[s][i] =
                crc_table[0][crc_table[s - 1][i] & 0xFF] ^
                (crc_table[s - 1][i] >> 8);
}

// table built once at library load (thread-safe: dynamic initialization of
// a function-local static is serialized by the C++ runtime)
static const bool crc_ready = [] { crc_init(); return true; }();

uint32_t ck_crc32(const uint8_t* data, uint64_t len, uint32_t seed) {
    (void)crc_ready;
    uint32_t c = ~seed;
    // slice-by-8 over the aligned bulk
    while (len >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, data, 4);
        memcpy(&hi, data + 4, 4);
        lo ^= c;
        c = crc_table[7][lo & 0xFF] ^ crc_table[6][(lo >> 8) & 0xFF] ^
            crc_table[5][(lo >> 16) & 0xFF] ^ crc_table[4][lo >> 24] ^
            crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
            crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
        data += 8;
        len -= 8;
    }
    while (len--) c = crc_table[0][(c ^ *data++) & 0xFF] ^ (c >> 8);
    return ~c;
}

// ---------------------------------------------------------------- pread

// Returns bytes read, or a negative errno.
int64_t ck_pread_fd(int fd, uint64_t offset, uint64_t len, uint8_t* out) {
    uint64_t got = 0;
    while (got < len) {
        ssize_t n = pread(fd, out + got, len - got, (off_t)(offset + got));
        if (n < 0) return -2;
        if (n == 0) break;                 // EOF
        got += (uint64_t)n;
    }
    return (int64_t)got;
}

int64_t ck_pread(const char* path, uint64_t offset, uint64_t len,
                 uint8_t* out) {
    int fd = open(path, O_RDONLY);
    if (fd < 0) return -1;
    int64_t got = ck_pread_fd(fd, offset, len, out);
    close(fd);
    return got;
}

// Batched reads from one file: n ranges, each (offset[i], len[i]) into
// out + out_offsets[i]; actual bytes read per range written to got_lens
// (short at EOF — callers must slice by these, not the request).
// Returns total bytes read or negative errno.
int64_t ck_preadv(const char* path, uint64_t n, const uint64_t* offsets,
                  const uint64_t* lens, uint8_t* out,
                  const uint64_t* out_offsets, uint64_t* got_lens) {
    int fd = open(path, O_RDONLY);
    if (fd < 0) return -1;
    uint64_t total = 0;
    for (uint64_t i = 0; i < n; i++) {
        int64_t got = ck_pread_fd(fd, offsets[i], lens[i],
                                  out + out_offsets[i]);
        if (got < 0) { close(fd); return got; }
        got_lens[i] = (uint64_t)got;
        total += (uint64_t)got;
    }
    close(fd);
    return (int64_t)total;
}

// Batched reads over an already-open fd (callers keep fds cached — no
// per-call open/close). Same contract as ck_preadv.
int64_t ck_preadv_fd(int fd, uint64_t n, const uint64_t* offsets,
                     const uint64_t* lens, uint8_t* out,
                     const uint64_t* out_offsets, uint64_t* got_lens) {
    uint64_t total = 0;
    for (uint64_t i = 0; i < n; i++) {
        int64_t got = ck_pread_fd(fd, offsets[i], lens[i],
                                  out + out_offsets[i]);
        if (got < 0) return got;
        got_lens[i] = (uint64_t)got;
        total += (uint64_t)got;
    }
    return (int64_t)total;
}

// ---------------------------------------------------------------- framing

// Validate a header; returns payload length, or negative on error:
// -1 bad magic, -2 oversized.
int64_t ck_frame_parse(const uint8_t* hdr8, uint32_t expect_magic,
                       uint32_t max_len) {
    uint32_t magic, length;
    memcpy(&magic, hdr8, 4);
    memcpy(&length, hdr8 + 4, 4);
    if (magic != expect_magic) return -1;
    if (length > max_len) return -2;
    return (int64_t)length;
}

}  // extern "C"
