"""Distributed-plane throughput: localhost 2-worker decode tok/s with a
per-hop RTT breakdown (VERDICT r3 item 6 — the reference's analog is the
`--ignored` protocol throughput benches in cake-core/tests/protocol.rs).

The number tracks PROTOCOL + scheduling overhead, not model compute: the
tiny model makes per-stage forward time negligible, so tok/s here is
dominated by the per-token master->worker->master round trips the
architecture pays (one per contiguous remote range, ref:
text_model.rs:298-331). Run on CPU; commit the JSON (BENCH_CLUSTER_r*.json)
so regressions in framing/serialization show up between rounds.

Workers run as separate PROCESSES (like real deployments): VERDICT r4
found mean RTT 7x above p95 when workers were threads in the master's
process — GIL contention between the master's jit dispatch and the worker
event loops produced hundreds-of-ms stalls that are scheduling artifacts,
not protocol behavior.

Per-token budget breakdown (VERDICT r4 item 8): each decode token costs
  sum(hop RTTs) + master_ms
where each hop RTT = worker fwd (device compute, worker-reported) + wire
(serialization + TCP + event-loop scheduling), and master_ms = embed +
local stages + head + sample + the device->host sync. The sequential
chain is irreducible for a single sequence — token t+1's input IS token
t's sampled output — so the ceiling is (hops * wire_floor + compute);
the breakdown in the committed JSON states where the budget goes.

Usage: python benches/bench_cluster.py [--tokens N]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, ".")

_WORKER_SRC = """
import asyncio, sys
import jax
jax.config.update("jax_platforms", "cpu")
from cake_tpu.cluster.worker import WorkerServer

async def main():
    s = WorkerServer(sys.argv[1], sys.argv[2], port=0, advertise=False,
                     cache_root=sys.argv[3])
    await s.start()
    print(f"PORT {s.port}", flush=True)
    await s.serve_forever()

asyncio.run(main())
"""


def start_worker(name, key, cache_root):
    p = subprocess.Popen(
        [sys.executable, "-c", _WORKER_SRC, name, key, cache_root],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import select

    deadline = time.monotonic() + 60
    port = None
    buf = b""
    fd = p.stdout.fileno()
    try:
        # raw fd reads: select and the reader see the same bytes (a
        # buffered readline would strand data in Python's buffer and then
        # block past the deadline on a silent hang)
        while time.monotonic() < deadline and port is None:
            ready, _, _ = select.select(
                [fd], [], [], max(deadline - time.monotonic(), 0.0))
            if not ready:
                break
            chunk = os.read(fd, 4096)
            if not chunk:
                raise RuntimeError(f"worker {name} died (exit {p.poll()})")
            buf += chunk
            for line in buf.decode(errors="replace").splitlines():
                if line.startswith("PORT "):
                    port = int(line.split()[1])
                    break
        if port is None:
            raise RuntimeError(f"worker {name} did not report a port in 60s")
    except BaseException:
        p.kill()
        raise
    return p, port


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=128)
    args = ap.parse_args()

    import tempfile

    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel, tiny_config
    from cake_tpu.models.common.layers import init_params
    from cake_tpu.utils.export import params_to_hf_tensors
    from cake_tpu.utils.safetensors_io import save_safetensors

    # 512 positions (tiny_config default is 128): the 256-token decode and
    # the 384-token TTFT prompt must stay inside the rope tables
    cfg = tiny_config("qwen3", max_position_embeddings=512)
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    mdir = tempfile.mkdtemp(prefix="bench-cluster-")
    save_safetensors(f"{mdir}/model.safetensors",
                     params_to_hf_tensors(cfg, params))
    with open(f"{mdir}/config.json", "w") as f:
        json.dump({"architectures": ["Qwen3ForCausalLM"], "vocab_size": 256,
                   "hidden_size": 64, "intermediate_size": 128,
                   "num_hidden_layers": 4, "num_attention_heads": 4,
                   "num_key_value_heads": 2, "rms_norm_eps": 1e-5,
                   "rope_theta": 10000.0, "max_position_embeddings": 512,
                   "eos_token_id": 255}, f)

    # per-worker cache root: two workers on ONE host would race on the
    # shared content-keyed cache (different layer subsets, same key) —
    # real deployments have one worker per host
    procs: list = []
    try:
        p0, port0 = start_worker("w0", "bench", f"{mdir}/wc0")
        procs.append(p0)
        p1, port1 = start_worker("w1", "bench", f"{mdir}/wc1")
        procs.append(p1)
        workers = [
            {"name": "w0", "host": "127.0.0.1", "port": port0,
             "caps": {"backend": "cpu", "device": "cpu",
                      "memory_bytes": 8 << 30, "tflops": 100.0}},
            {"name": "w1", "host": "127.0.0.1", "port": port1,
             "caps": {"backend": "cpu", "device": "cpu",
                      "memory_bytes": 8 << 30, "tflops": 100.0}},
        ]
        setup = master_setup(mdir, "bench", cfg, workers,
                             assignments={"w0": (0, 2), "w1": (2, 4)},
                             dtype_str="f32", max_cache_len=512)
        dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                    dtype=jnp.float32, max_cache_len=512)
        prompt = [11, 23, 5, 190, 77, 3]
        scfg = SamplingConfig(temperature=0.0)
        # warm run compiles the MASTER's local/embed/head/sample shapes
        # (workers pre-warmed every bucket at assignment via warm="full")
        dist.generate(prompt, max_new_tokens=args.tokens, sampling=scfg)
        for c in setup.clients:
            c.rtts.clear()          # stats cover the timed run only

        t_start = time.monotonic()
        toks, stats = dist.generate(prompt, max_new_tokens=args.tokens,
                                    sampling=scfg)
        wall = time.monotonic() - t_start
        # the budget breakdown below is decode-only (per_token_ms excludes
        # prefill), so drop each stage's first RTT sample — the prefill
        # round trip, which is wider and would skew the hop means
        remote = [s for s in dist.stages if s.kind == "remote"]
        for s in remote:
            s.runner.rtts.popleft()
        stats["stage_rtts"] = {
            f"{s.runner.name}[{s.start}:{s.end}]": s.runner.rtt_stats()
            for s in remote}

        # pipelined-prefill TTFT: a 384-token prompt as 3x128-token chunks
        # overlapping across the 2 remote hops, vs the same prompt single-
        # shot. Same chain, interleaved min-of-3 (1-core box is noisy).
        long_prompt = [(i * 11 + 7) % 250 for i in range(384)]
        scfg1 = SamplingConfig(temperature=0.0)
        dist.prefill_chunk = 128
        pp_ms, ss_ms = [], []
        for _ in range(4):
            _, st_p = dist.generate(long_prompt, max_new_tokens=1,
                                    sampling=scfg1)
            assert st_p["prefill"]["pipelined"] is True
            pp_ms.append(st_p["ttft_s"] * 1e3)
            dist.prefill_chunk = 1 << 20          # force single-shot
            _, st_s = dist.generate(long_prompt, max_new_tokens=1,
                                    sampling=scfg1)
            assert st_s["prefill"]["pipelined"] is False
            ss_ms.append(st_s["ttft_s"] * 1e3)
            dist.prefill_chunk = 128
        pp, ss = min(pp_ms[1:]), min(ss_ms[1:])   # drop compile-warm pair

        # all-local reference on the same host: isolates protocol overhead
        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=512)
        local.generate(prompt, max_new_tokens=8, sampling=scfg)
        _, lstats = local.generate(prompt, max_new_tokens=args.tokens,
                                   sampling=scfg)

        n = stats["decode_tokens"]
        per_token_ms = stats["decode_s"] / max(n, 1) * 1e3
        hop_means = [s.get("mean_ms", 0.0)
                     for s in stats["stage_rtts"].values()]
        result = {
            "metric": "cluster_2worker_decode",
            "value": round(stats["tok_per_s"], 1), "unit": "tok/s",
            "vs_baseline": None,      # reference publishes no protocol numbers
            "decode_tokens": n,
            "wall_s": round(wall, 2),
            "per_token_ms": round(per_token_ms, 2),
            # per-token budget: remote hops (split wire vs worker-fwd in
            # stage_rtts) + everything the master does between hops
            "hops_ms": round(sum(hop_means), 2),
            "master_ms": round(max(per_token_ms - sum(hop_means), 0.0), 2),
            "stage_rtts": stats["stage_rtts"],
            "ttft_384tok_pipelined_ms": round(pp, 1),
            "ttft_384tok_singleshot_ms": round(ss, 1),
            "ttft_pipeline_speedup": round(ss / max(pp, 1e-9), 2),
            "local_same_model_tok_s": round(lstats["tok_per_s"], 1),
            "note": "tiny model, localhost, workers as separate processes: "
                    "the number is protocol + per-hop scheduling overhead "
                    "(2 TCP round trips per token), tracked round-over-round",
        }
        print(json.dumps(result))
        for c in setup.clients:
            c.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    main()
