"""Distributed-plane throughput: localhost 2-worker decode tok/s with a
per-hop RTT breakdown (VERDICT r3 item 6 — the reference's analog is the
`--ignored` protocol throughput benches in cake-core/tests/protocol.rs).

The number tracks PROTOCOL + scheduling overhead, not model compute: the
tiny model makes per-stage forward time negligible, so tok/s here is
dominated by the per-token master->worker->master round trips the
architecture pays (one per contiguous remote range, ref:
text_model.rs:298-331). Run on CPU; commit the JSON (BENCH_CLUSTER_r*.json)
so regressions in framing/serialization show up between rounds.

Usage: python benches/bench_cluster.py [--tokens N]
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, ".")


def start_worker(name, key, ready, cache_root):
    from cake_tpu.cluster.worker import WorkerServer
    holder = {}

    def run():
        async def main():
            # per-worker cache root: two workers on ONE host would race on
            # the shared content-keyed cache (different layer subsets,
            # same key) — real deployments have one worker per host
            server = WorkerServer(name, key, port=0, advertise=False,
                                  cache_root=cache_root)
            await server.start()
            holder["port"] = server.port
            holder["loop"] = asyncio.get_running_loop()
            holder["server"] = server
            ready.set()
            await server.serve_forever()
        asyncio.run(main())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return holder, t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=128)
    args = ap.parse_args()

    import tempfile

    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel, tiny_config
    from cake_tpu.models.common.layers import init_params
    from cake_tpu.utils.export import params_to_hf_tensors
    from cake_tpu.utils.safetensors_io import save_safetensors

    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    mdir = tempfile.mkdtemp(prefix="bench-cluster-")
    save_safetensors(f"{mdir}/model.safetensors",
                     params_to_hf_tensors(cfg, params))
    with open(f"{mdir}/config.json", "w") as f:
        json.dump({"architectures": ["Qwen3ForCausalLM"], "vocab_size": 256,
                   "hidden_size": 64, "intermediate_size": 128,
                   "num_hidden_layers": 4, "num_attention_heads": 4,
                   "num_key_value_heads": 2, "rms_norm_eps": 1e-5,
                   "rope_theta": 10000.0, "max_position_embeddings": 512,
                   "eos_token_id": 255}, f)

    r0, r1 = threading.Event(), threading.Event()
    h0, t0 = start_worker("w0", "bench", r0, f"{mdir}/wc0")
    h1, t1 = start_worker("w1", "bench", r1, f"{mdir}/wc1")
    assert r0.wait(30) and r1.wait(30)
    workers = [
        {"name": "w0", "host": "127.0.0.1", "port": h0["port"],
         "caps": {"backend": "cpu", "device": "cpu",
                  "memory_bytes": 8 << 30, "tflops": 100.0}},
        {"name": "w1", "host": "127.0.0.1", "port": h1["port"],
         "caps": {"backend": "cpu", "device": "cpu",
                  "memory_bytes": 8 << 30, "tflops": 100.0}},
    ]
    setup = master_setup(mdir, "bench", cfg, workers,
                         assignments={"w0": (0, 2), "w1": (2, 4)},
                         dtype_str="f32", max_cache_len=512)
    dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                dtype=jnp.float32, max_cache_len=512)
    prompt = [11, 23, 5, 190, 77, 3]
    scfg = SamplingConfig(temperature=0.0)
    # warm at FULL length: every growth bucket the timed run will touch
    # compiles here (master + both workers), not inside the timing
    dist.generate(prompt, max_new_tokens=args.tokens, sampling=scfg)
    for c in setup.clients:
        c.rtts.clear()          # stats cover the timed run only

    t_start = time.monotonic()
    toks, stats = dist.generate(prompt, max_new_tokens=args.tokens,
                                sampling=scfg)
    wall = time.monotonic() - t_start

    # all-local reference on the same host: isolates protocol overhead
    local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=512)
    local.generate(prompt, max_new_tokens=8, sampling=scfg)
    _, lstats = local.generate(prompt, max_new_tokens=args.tokens,
                               sampling=scfg)

    n = stats["decode_tokens"]
    result = {
        "metric": "cluster_2worker_decode",
        "value": round(stats["tok_per_s"], 1), "unit": "tok/s",
        "vs_baseline": None,      # reference publishes no protocol numbers
        "decode_tokens": n,
        "wall_s": round(wall, 2),
        "per_token_ms": round(stats["decode_s"] / max(n, 1) * 1e3, 2),
        "stage_rtts": stats["stage_rtts"],
        "local_same_model_tok_s": round(lstats["tok_per_s"], 1),
        "note": "tiny model on localhost CPU: the number is protocol + "
                "per-hop scheduling overhead (2 TCP round trips per "
                "token), tracked round-over-round",
    }
    print(json.dumps(result))
    for c in setup.clients:
        c.close()
    for holder, t in ((h0, t0), (h1, t1)):
        loop, srv = holder.get("loop"), holder.get("server")
        if loop and srv:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop)
        t.join(timeout=5)


if __name__ == "__main__":
    main()
