"""Micro-benchmark suite (ref: cake-core/benches/ — 23 divan modules).

Times the hot host-side and device-side primitives; prints one JSON object
per benchmark. Run: python benches/bench_micro.py [--filter NAME] [--cpu]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, warmup=3, iters=20) -> float:
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def bench_crc32():
    from cake_tpu.cluster.proto import crc32
    data = np.random.default_rng(0).integers(0, 256, 8 << 20,
                                             dtype=np.uint32).astype(np.uint8).tobytes()
    dt = timeit(lambda: crc32(data))
    return {"gb_per_s": round(len(data) / dt / 1e9, 2)}


def bench_frame_roundtrip():
    from cake_tpu.cluster import proto
    x = np.random.default_rng(0).standard_normal((1, 64, 2048)).astype(np.float32)
    msg = proto.forward(x, 0, None)

    def run():
        frame = proto.encode_frame(msg)
        proto.decode_payload(frame[8:])
    dt = timeit(run)
    return {"ms": round(dt * 1000, 3), "mb": round(x.nbytes / 1e6, 1)}


def bench_auth():
    import asyncio

    from cake_tpu.cluster.auth import (authenticate_as_master,
                                       authenticate_as_worker)

    async def once():
        done = asyncio.Event()

        async def on_conn(r, w):
            await authenticate_as_worker(r, w, "k")
            w.close()
            done.set()
        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        r, w = await asyncio.open_connection(
            "127.0.0.1", server.sockets[0].getsockname()[1])
        await authenticate_as_master(r, w, "k")
        await done.wait()
        w.close()
        server.close()
    dt = timeit(lambda: asyncio.run(once()), warmup=2, iters=10)
    return {"ms": round(dt * 1000, 2)}


def bench_pread():
    import os
    import tempfile

    from cake_tpu.utils import cakekit
    with tempfile.NamedTemporaryFile(delete=False) as f:
        f.write(os.urandom(32 << 20))
        path = f.name
    try:
        dt = timeit(lambda: cakekit.pread(path, 0, 32 << 20))
        return {"gb_per_s": round((32 << 20) / dt / 1e9, 2),
                "native": cakekit.available()}
    finally:
        os.unlink(path)


def bench_decode_step():
    import jax
    import jax.numpy as jnp

    from cake_tpu.models import TextModel, tiny_config
    from cake_tpu.ops.sampling import SamplingConfig
    m = TextModel(tiny_config("qwen3"), dtype=jnp.float32, max_cache_len=128)
    m.generate([1, 2, 3], max_new_tokens=8, chunk=8,
               sampling=SamplingConfig())          # compile
    dt = timeit(lambda: m.generate([1, 2, 3], max_new_tokens=32, chunk=32,
                                   sampling=SamplingConfig()),
                warmup=1, iters=5)
    return {"tiny_tok_per_s": round(32 / dt, 1)}


def bench_flash_attention():
    """Real-TPU flash smoke + timing: the compiled Pallas kernel vs the XLA
    einsum path on a prefill-sized problem (round-1 gap: the kernel had
    only interpret-mode coverage). On CPU the kernel runs in interpret
    mode as a correctness smoke."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cake_tpu.ops.attention import causal_sdpa
    from cake_tpu.ops.flash import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    b, s, hq, hkv, d = 1, 1024, 16, 8, 128
    if not on_tpu:
        b, s, hq, hkv, d = 1, 256, 4, 2, 32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), jnp.bfloat16)

    flash = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, interpret=not on_tpu))
    ref = jax.jit(causal_sdpa)
    got = np.asarray(flash(q, k, v), np.float32)
    want = np.asarray(ref(q, k, v), np.float32)
    np.testing.assert_allclose(got, want, atol=3e-2, rtol=3e-2)
    out = {"backend": jax.default_backend(), "seq": s,
           "parity_max_err": round(float(np.max(np.abs(got - want))), 5)}
    if on_tpu:
        out["flash_ms"] = round(timeit(
            lambda: flash(q, k, v).block_until_ready()) * 1e3, 3)
        out["xla_ms"] = round(timeit(
            lambda: ref(q, k, v).block_until_ready()) * 1e3, 3)
    return out


def bench_moe_dispatch():
    """Ragged segment-GEMM dispatch vs the dense all-experts combine on a
    prefill-sized 128-expert problem (the k/E FLOP claim measured on
    hardware — ref: qwen3_moe/moe.rs top-8 over 128 experts; on CPU the
    ragged op densifies in lowering, so only parity is reported there).
    Timed with a host fetch: block_until_ready does not sync through the
    axon tunnel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cake_tpu.ops.moe import combine_weights, moe_ffn, router_topk

    on_tpu = jax.default_backend() == "tpu"
    e, k = (128, 8)
    t, i, h = (1024, 768, 2048) if on_tpu else (64, 16, 32)
    rng = np.random.default_rng(0)
    router = jnp.asarray(rng.normal(0, .3, (e, h)), jnp.bfloat16)
    gp = jnp.asarray(rng.normal(0, .02, (e, i, h)), jnp.bfloat16)
    up = jnp.asarray(rng.normal(0, .02, (e, i, h)), jnp.bfloat16)
    dp = jnp.asarray(rng.normal(0, .02, (e, h, i)), jnp.bfloat16)
    x = jnp.asarray(rng.normal(0, 1, (t, h)), jnp.bfloat16)

    def dense(x):
        logits = jnp.einsum("th,eh->te", x, router,
                            preferred_element_type=jnp.float32)
        w, idx = router_topk(logits, k, True, "softmax")
        w_te = combine_weights(w, idx, e).astype(x.dtype)
        a = jax.nn.silu(jnp.einsum("th,eih->tei", x, gp)) \
            * jnp.einsum("th,eih->tei", x, up)
        return jnp.einsum("te,teh->th", w_te,
                          jnp.einsum("tei,ehi->teh", a, dp))

    ragged = jax.jit(lambda x: moe_ffn(x, router, gp, up, dp, k, True))
    jdense = jax.jit(dense)
    got = np.asarray(ragged(x), np.float32)
    want = np.asarray(jdense(x), np.float32)
    err = float(np.max(np.abs(got - want)))
    out = {"backend": jax.default_backend(), "tokens": t, "experts": e,
           "topk": k, "parity_max_err": round(err, 4)}
    if on_tpu:
        out["ragged_ms"] = round(timeit(
            lambda: np.asarray(ragged(x)), warmup=2, iters=5) * 1e3, 2)
        out["dense_ms"] = round(timeit(
            lambda: np.asarray(jdense(x)), warmup=2, iters=5) * 1e3, 2)
        out["speedup"] = round(out["dense_ms"] / max(out["ragged_ms"], 1e-9),
                               2)
    return out


def bench_moe_crossover():
    """Ragged-vs-dense crossover sweep: the token count where the sorted
    segment-GEMM dispatch starts beating the dense all-experts combine is
    what ops/moe.RAGGED_MIN_TOKENS should be set to (VERDICT r4 item 4:
    32 was a guess, measure it). TPU-only (the ragged op densifies in CPU
    lowering, so a CPU sweep measures nothing)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cake_tpu.ops import moe as moe_mod
    from cake_tpu.ops.moe import combine_weights, moe_ffn, router_topk

    if jax.default_backend() != "tpu":
        return {"skipped": "crossover is only meaningful on TPU"}

    e, k, i, h = 128, 8, 768, 2048
    rng = np.random.default_rng(0)
    router = jnp.asarray(rng.normal(0, .3, (e, h)), jnp.bfloat16)
    gp = jnp.asarray(rng.normal(0, .02, (e, i, h)), jnp.bfloat16)
    up = jnp.asarray(rng.normal(0, .02, (e, i, h)), jnp.bfloat16)
    dp = jnp.asarray(rng.normal(0, .02, (e, h, i)), jnp.bfloat16)

    def dense(x):
        logits = jnp.einsum("th,eh->te", x, router,
                            preferred_element_type=jnp.float32)
        w, idx = router_topk(logits, k, True, "softmax")
        w_te = combine_weights(w, idx, e).astype(x.dtype)
        a = jax.nn.silu(jnp.einsum("th,eih->tei", x, gp)) \
            * jnp.einsum("th,eih->tei", x, up)
        return jnp.einsum("te,teh->th", w_te,
                          jnp.einsum("tei,ehi->teh", a, dp))

    # force both paths regardless of the RAGGED_MIN_TOKENS gate
    def ragged_full(x):
        logits = jnp.einsum("th,eh->te", x, router,
                            preferred_element_type=jnp.float32)
        w, idx = router_topk(logits, k, True, "softmax")
        return moe_mod._moe_ragged(x, w, idx, gp, up, dp, "silu")

    ragged = jax.jit(ragged_full)
    jdense = jax.jit(dense)
    rows = []
    crossover = None
    for t in (8, 16, 32, 64, 128, 256, 512):
        x = jnp.asarray(rng.normal(0, 1, (t, h)), jnp.bfloat16)
        r_ms = timeit(lambda: np.asarray(ragged(x)), warmup=2, iters=5) * 1e3
        d_ms = timeit(lambda: np.asarray(jdense(x)), warmup=2, iters=5) * 1e3
        rows.append({"tokens": t, "ragged_ms": round(r_ms, 3),
                     "dense_ms": round(d_ms, 3)})
        if crossover is None and r_ms < d_ms:
            crossover = t
    return {"experts": e, "topk": k, "sweep": rows,
            "crossover_tokens": crossover,
            "current_gate": moe_mod.RAGGED_MIN_TOKENS}


def bench_sampling():
    import jax
    import jax.numpy as jnp

    from cake_tpu.ops.sampling import SamplingConfig, sample
    logits = jax.random.normal(jax.random.PRNGKey(0), (151936,))
    cfg = SamplingConfig(temperature=0.8, top_k=40, top_p=0.9,
                         repeat_penalty=1.1)
    recent = jnp.full((64,), -1, jnp.int32)
    fn = jax.jit(lambda l, k: sample(l, k, cfg, recent))
    k = jax.random.PRNGKey(1)
    fn(logits, k).block_until_ready()
    dt = timeit(lambda: fn(logits, k).block_until_ready())
    return {"us": round(dt * 1e6, 1)}


def bench_gguf_dequant():
    from cake_tpu.utils.gguf import dequant_q4_k
    raw = np.random.default_rng(0).integers(
        0, 256, 144 * 4096, dtype=np.uint32).astype(np.uint8).tobytes()
    n = 256 * 4096
    dt = timeit(lambda: dequant_q4_k(raw, n))
    return {"m_weights_per_s": round(n / dt / 1e6, 1)}


BENCHES = {
    "crc32": bench_crc32,
    "frame_roundtrip": bench_frame_roundtrip,
    "auth_handshake": bench_auth,
    "pread_32mb": bench_pread,
    "decode_tiny": bench_decode_step,
    "flash_attention": bench_flash_attention,
    "moe_dispatch": bench_moe_dispatch,
    "moe_crossover": bench_moe_crossover,
    "sampling_151k_vocab": bench_sampling,
    "gguf_q4k_dequant": bench_gguf_dequant,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--filter", default="")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (e.g. TPU busy)")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    for name, fn in BENCHES.items():
        if args.filter and args.filter not in name:
            continue
        try:
            out = fn()
        except Exception as e:  # keep the suite running
            out = {"error": str(e)[:120]}
        print(json.dumps({"bench": name, **out}))


if __name__ == "__main__":
    main()
