"""Decode-step component profile on the real chip.

Times the pieces of one decode step (embed+layers, lm_head, sampling,
while_loop packaging) separately to locate the gap between measured decode
throughput and the HBM roofline (params_bytes / HBM_BW).

Usage: python benches/profile_decode.py [--steps 64]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import TextModel, config_from_hf_dict
from cake_tpu.models.common.layers import (embed_tokens, forward_layers,
                                           lm_head_logits)
from __graft_entry__ import FLAGSHIP


def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--kv", type=int, default=512)
    args = ap.parse_args()

    cfg = config_from_hf_dict(FLAGSHIP)
    model = TextModel(cfg, dtype=jnp.bfloat16, max_cache_len=2048)
    params = model.params

    n_param = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    print(f"params: {n_param/1e6:.1f}M -> {n_param*2/1e9:.3f} GB read/step (bf16)")

    tok = jnp.asarray([5], jnp.int32)

    def chain(step_fn, n=64, warmup=8):
        """Chained decode steps (output cache feeds the next call) — honest
        per-step latency including dispatch, matching real decode."""
        cache = model.new_cache(1, kv_len=args.kv)
        _, cache = model.prefill(cache, list(range(100)))
        out = None
        for _ in range(warmup):
            out, cache = step_fn(cache)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out, cache = step_fn(cache)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n

    # full step (logits, no sampling)
    t_step = chain(lambda c: model._decode_step(params, tok, c))
    print(f"decode_step (layers+head):  {t_step*1e3:8.3f} ms  -> {1/t_step:7.1f} tok/s")

    # layers only
    @jax.jit
    def _layers(params, tok, cache):
        x = embed_tokens(cfg, params, tok[:, None])
        x, cache = forward_layers(cfg, params, x, cache, cache["pos"])
        return x, cache

    t_layers = chain(lambda c: _layers(params, tok, c))
    print(f"embed+layers only:          {t_layers*1e3:8.3f} ms")

    # head only
    x = jnp.zeros((1, 1, cfg.hidden_size), jnp.bfloat16)

    @jax.jit
    def _head(params, x):
        return lm_head_logits(cfg, params, x)

    t_head = timeit(lambda: _head(params, x))
    print(f"lm_head only:               {t_head*1e3:8.3f} ms")

    # decode_until (while_loop) — time two budgets and diff so prefill /
    # fetch fixed costs cancel: per_tok = (T(n2) - T(n1)) / (n2 - n1)
    from cake_tpu.ops.sampling import SamplingConfig
    scfg = SamplingConfig(temperature=0.0)
    rng = jax.random.PRNGKey(0)
    recent = jnp.full((64,), -1, jnp.int32)

    def until(n_limit, nbuf, reps=5):
        def run():
            c = model.new_cache(1, kv_len=args.kv)
            _, c = model.prefill(c, list(range(100)))
            packed, c, r, rec = model._decode_until(
                params, tok, c, rng, recent,
                jnp.asarray(n_limit, jnp.int32), scfg, nbuf)
            return np.asarray(packed)   # includes the real host fetch
        run(); run()
        t0 = time.perf_counter()
        for _ in range(reps):
            run()
        return (time.perf_counter() - t0) / reps

    n1, n2 = min(8, max(args.steps // 2, 1)), args.steps
    if n2 <= n1:
        n2 = n1 + 8
    t1, t2 = until(n1, args.steps), until(n2, args.steps)
    per_tok = (t2 - t1) / (n2 - n1)
    print(f"decode_until diff({n1}->{n2}): {per_tok*1e3:8.3f} ms/tok"
          f"  -> {1/per_tok:7.1f} tok/s")
    print(f"  (vs bare chained step: {(per_tok-t_step)*1e3:+.3f} ms/tok)")

    # generate() end to end, as the headline bench measures it
    out, stats = model.generate(list(range(32)), max_new_tokens=args.steps,
                                sampling=scfg, chunk=64)
    out, stats = model.generate(list(range(32)), max_new_tokens=args.steps,
                                sampling=scfg, chunk=64)
    print(f"generate(): {stats['tok_per_s']:.1f} tok/s, "
          f"ttft {stats['ttft_s']*1e3:.1f} ms")

    dev = jax.devices()[0]
    print(f"device: {dev}")


if __name__ == "__main__":
    main()
