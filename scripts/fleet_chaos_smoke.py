#!/usr/bin/env python
"""Fleet chaos smoke: 3 real serve replicas behind the fleet router, one
killed mid-traffic — the fleet must absorb it invisibly.

Asserts, in order:
  1. transparent failover: concurrent NON-STREAMED chat traffic across
     the kill completes with ZERO failed requests (the router retries
     dropped attempts on the next-best replica);
  2. the kill is visible as an eject -> (restart) -> readmit cycle in
     the router's /fleet view AND /metrics (cake_fleet_ejects_total,
     cake_fleet_readmits_total);
  3. saturation sheds at the ROUTER: with a small global admission bound
     and slowed decode, overflow answers 429 with shed_by=router (and
     zero replica-originated 5xx/429s leak through).

Every phase polls WITH A DEADLINE (the serve-chaos lesson: fixed sleeps
flake on this container's slow CPU). Exits non-zero on any missing
signal. Run via `make fleet-chaos-smoke`.
"""
from __future__ import annotations

import asyncio
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402
from aiohttp import web                                    # noqa: E402
from aiohttp.test_utils import TestClient, TestServer      # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.fleet import (FleetRouter, MembershipPolicy,  # noqa: E402
                            ReplicaRegistry, create_router_app)
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402
from cake_tpu.serve import faults as serve_faults          # noqa: E402

CTX = 128
N_REPLICAS = 3
MAX_NEW = 8


class SmokeTok:
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:48] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


class ReplicaProc:
    """One in-process serve replica: real engine, real HTTP socket on a
    stable port so a restart is indistinguishable from a process coming
    back."""

    def __init__(self, name: str, model):
        self.name = name
        self.engine = ServeEngine(model, slots=2, max_queue=16, ctx_len=CTX)
        self.state = ApiState(model=model, tokenizer=SmokeTok(),
                              model_id=f"tiny-{name}")
        self.state.engine = self.engine
        self.runner = None
        self.port = None

    async def start(self) -> str:
        self.runner = web.AppRunner(create_app(self.state))
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", self.port or 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def kill(self):
        """Sever the HTTP surface (the engine thread stays, like a
        network partition / crashed frontend)."""
        await self.runner.cleanup()
        self.runner = None

    def close(self):
        self.engine.close()


async def _chat(client, convo: int, turn: int):
    return await client.post("/v1/chat/completions", json={
        "messages": [
            {"role": "system", "content": "fleet smoke system prompt "
                                          "shared by every conversation"},
            {"role": "user", "content": f"conversation {convo} says "
                                        f"hello at turn {turn}"}],
        "max_tokens": MAX_NEW, "temperature": 0.0})


async def _poll_fleet(client, pred, deadline_s: float, what: str):
    deadline = time.monotonic() + deadline_s
    snap = None
    while time.monotonic() < deadline:
        snap = await (await client.get("/fleet")).json()
        if pred(snap):
            return snap
        await asyncio.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}: {snap}")


async def main_async() -> dict:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    out: dict = {}
    replicas = [ReplicaProc(f"r{i}", model) for i in range(N_REPLICAS)]
    registry = ReplicaRegistry(MembershipPolicy(
        eject_fails=2, err_window=16, err_rate=0.5,
        degraded_ttft_ms=0.0, eject_s=0.3))
    router = FleetRouter(registry, retries=2, backoff_s=0.01,
                         probe_s=0.15, hedge_ms=0.0, max_inflight=0)
    client = None
    try:
        for rep in replicas:
            registry.add(rep.name, await rep.start())
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        # -- phase 1: concurrent traffic across a replica kill ------------
        statuses: list[int] = []
        victim = replicas[1]

        async def worker(convo: int):
            for turn in range(6):
                r = await _chat(client, convo, turn)
                statuses.append(r.status)
                await r.read()

        tasks = [asyncio.create_task(worker(c)) for c in range(6)]
        await asyncio.sleep(0.25)       # let traffic + compiles start
        await victim.kill()
        out["killed"] = victim.name
        await asyncio.gather(*tasks)
        failed = [s for s in statuses if s != 200]
        assert not failed, f"non-streamed requests failed across the " \
                           f"kill: {failed} of {len(statuses)}"
        out["requests_across_kill"] = len(statuses)
        out["failed_across_kill"] = 0

        # the kill shows up as an ejection in the membership view
        snap = await _poll_fleet(
            client, lambda s: any(r["name"] == victim.name
                                  and r["state"] == "ejected"
                                  for r in s["replicas"]),
            10.0, f"{victim.name} ejected")
        out["ejected_visible"] = True

        # -- phase 2: restart the replica -> readmission ------------------
        await victim.start()            # same port, same name
        snap = await _poll_fleet(
            client, lambda s: any(r["name"] == victim.name
                                  and r["state"] == "healthy"
                                  for r in s["replicas"]),
            15.0, f"{victim.name} readmitted")
        out["readmitted_visible"] = True
        assert snap["routable"] == N_REPLICAS

        # eject + readmit cycle is in /metrics
        mtext = await (await client.get("/metrics")).text()
        for metric in ("cake_fleet_ejects_total", "cake_fleet_readmits_total"):
            m = re.search(rf'^{metric}{{[^}}]*replica="{victim.name}"'
                          rf'[^}}]*}}\s+(\d+)', mtext, re.M)
            assert m and int(m.group(1)) >= 1, f"{metric} missing: " \
                f"{[l for l in mtext.splitlines() if metric in l]}"
        out["metrics_cycle"] = True

        # -- phase 3: saturation sheds 429 AT THE ROUTER ------------------
        router.max_inflight = 3
        serve_faults.install("delay_ms=40")     # slow every decode step
        try:
            results = await asyncio.gather(
                *[_chat(client, 100 + i, 0) for i in range(16)])
            sat = [(r.status, await r.json()) for r in results]
        finally:
            serve_faults.clear()
            router.max_inflight = 0
        shed = [b for s, b in sat if s == 429]
        ok = [b for s, b in sat if s == 200]
        bad = [(s, b) for s, b in sat if s not in (200, 429)]
        assert not bad, f"unexpected statuses under saturation: {bad}"
        assert shed, "saturation produced no 429s"
        assert all(b.get("shed_by") == "router" for b in shed), \
            f"429s not shed by the router: {shed[:2]}"
        out["saturation"] = {"ok": len(ok), "shed_by_router": len(shed)}
        mtext = await (await client.get("/metrics")).text()
        m = re.search(r"^cake_fleet_sheds_total{[^}]*}\s+(\d+)", mtext,
                      re.M)
        assert m and int(m.group(1)) >= 1, "cake_fleet_sheds_total missing"

        # fleet health is clean again
        h = await client.get("/health")
        assert h.status == 200, await h.text()
        out["health"] = 200
        return out
    finally:
        if client is not None:
            await client.close()
        for rep in replicas:
            if rep.runner is not None:
                await rep.kill()
            rep.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print("fleet-chaos-smoke OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
