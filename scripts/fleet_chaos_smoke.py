#!/usr/bin/env python
"""Fleet chaos smoke: 3 real serve replicas behind the fleet router, one
killed mid-traffic — the fleet must absorb it invisibly.

Asserts, in order:
  1. transparent failover: concurrent NON-STREAMED chat traffic across
     the kill completes with ZERO failed requests (the router retries
     dropped attempts on the next-best replica);
  2. the kill is visible as an eject -> (restart) -> readmit cycle in
     the router's /fleet view AND /metrics (cake_fleet_ejects_total,
     cake_fleet_readmits_total);
  3. saturation sheds at the ROUTER: with a small global admission bound
     and slowed decode, overflow answers 429 with shed_by=router (and
     zero replica-originated 5xx/429s leak through);
  4. SELF-HEALING STREAMS (ISSUE 15 hard gate): the owning replica is
     killed MID-STREAM with one resume in the budget — the client
     receives the complete greedy body BYTE-IDENTICAL to an unbroken
     run with zero client-visible errors,
     cake_fleet_stream_resumes_total{outcome="ok"} > 0, and the
     router timeline for that request id chains
     stream_broken -> stream_resume -> resume_spliced -> done;
  5. with the resume budget at 0 the legacy typed error event is
     preserved — now carrying a resume_token + honest content
     accounting so a client can finish via continuation mode;
  6. REAL network partition (fleet/netem.ChaosProxy on the wire): a
     replica's traffic is rerouted through a chaos proxy and hard
     partitioned — ZERO client-visible errors across the episode,
     exactly ONE eject, and after heal the replica readmits through a
     data-path trial (the deeper drills live in partition_smoke.py).

Every phase polls WITH A DEADLINE (the serve-chaos lesson: fixed sleeps
flake on this container's slow CPU). Exits non-zero on any missing
signal. Run via `make fleet-chaos-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402
from aiohttp import web                                    # noqa: E402
from aiohttp.test_utils import TestClient, TestServer      # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.fleet import (ChaosProxy, FleetRouter,       # noqa: E402
                            MembershipPolicy, ReplicaRegistry,
                            create_router_app)
from cake_tpu.fleet import faults as fleet_faults          # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402
from cake_tpu.serve import faults as serve_faults          # noqa: E402

CTX = 128
N_REPLICAS = 3
MAX_NEW = 8


class SmokeTok:
    """Word-hash for prose, ROUND-TRIP for generated ids: decode emits
    " t<id>" words and encode parses them back verbatim, so a
    continuation splice (chat template + partial content) re-encodes to
    exactly `prompt ids + generated ids` — the property the streamed
    byte-parity drill rests on (real tokenizers round-trip their own
    decodes the same way)."""

    def encode(self, text):
        out = []
        for w in text.split():
            if w[:1] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(3 + (sum(w.encode()) % 200))
        return out[:64] or [3]

    def decode(self, ids):
        return "".join(f" t{i}" for i in ids)


class ReplicaProc:
    """One in-process serve replica: real engine, real HTTP socket on a
    stable port so a restart is indistinguishable from a process coming
    back."""

    def __init__(self, name: str, model):
        self.name = name
        self.engine = ServeEngine(model, slots=2, max_queue=16, ctx_len=CTX)
        self.state = ApiState(model=model, tokenizer=SmokeTok(),
                              model_id=f"tiny-{name}")
        self.state.engine = self.engine
        self.runner = None
        self.port = None

    async def start(self) -> str:
        self.runner = web.AppRunner(create_app(self.state))
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", self.port or 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def kill(self):
        """Sever the HTTP surface ABRUPTLY (the engine thread stays,
        like a network partition / crashed frontend): in-flight
        responses — including a mid-relay SSE stream — die with a
        reset instead of being drained gracefully, which is what the
        self-healing drill needs a kill to look like."""
        server = self.runner.server
        for proto in list(getattr(server, "connections", []) or []):
            tr = getattr(proto, "transport", None)
            if tr is not None:
                tr.abort()
        await self.runner.cleanup()
        self.runner = None

    def close(self):
        self.engine.close()


async def _chat(client, convo: int, turn: int):
    return await client.post("/v1/chat/completions", json={
        "messages": [
            {"role": "system", "content": "fleet smoke system prompt "
                                          "shared by every conversation"},
            {"role": "user", "content": f"conversation {convo} says "
                                        f"hello at turn {turn}"}],
        "max_tokens": MAX_NEW, "temperature": 0.0})


async def _poll_fleet(client, pred, deadline_s: float, what: str):
    deadline = time.monotonic() + deadline_s
    snap = None
    while time.monotonic() < deadline:
        snap = await (await client.get("/fleet")).json()
        if pred(snap):
            return snap
        await asyncio.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}: {snap}")


async def _pump_fleet(client, pred, deadline_s: float, what: str,
                      statuses: list | None = None):
    """_poll_fleet with chat traffic flowing: a replica ejected on DATA
    evidence readmits only through a successful data-path trial request
    — probes alone can never clear it, so an idle poll would wait
    forever."""
    deadline = time.monotonic() + deadline_s
    snap, convo = None, 9000
    while time.monotonic() < deadline:
        convo += 1
        r = await _chat(client, convo, 0)
        await r.read()
        if statuses is not None:
            statuses.append(r.status)
        snap = await (await client.get("/fleet")).json()
        if pred(snap):
            return snap
        await asyncio.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}: {snap}")


async def main_async() -> dict:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    # streamed chunks decode per-token through the MODEL's tokenizer
    # (the API-layer tokenizer only renders prompts/blocking bodies)
    model.tokenizer = SmokeTok()
    out: dict = {}
    replicas = [ReplicaProc(f"r{i}", model) for i in range(N_REPLICAS)]
    registry = ReplicaRegistry(MembershipPolicy(
        eject_fails=2, err_window=16, err_rate=0.5,
        degraded_ttft_ms=0.0, eject_s=0.3))
    router = FleetRouter(registry, retries=2, backoff_s=0.01,
                         probe_s=0.15, hedge_ms=0.0, max_inflight=0,
                         stream_resumes=1)
    client = None
    try:
        for rep in replicas:
            registry.add(rep.name, await rep.start())
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        # -- phase 1: concurrent traffic across a replica kill ------------
        statuses: list[int] = []
        victim = replicas[1]

        async def worker(convo: int):
            for turn in range(6):
                r = await _chat(client, convo, turn)
                statuses.append(r.status)
                await r.read()

        tasks = [asyncio.create_task(worker(c)) for c in range(6)]
        await asyncio.sleep(0.25)       # let traffic + compiles start
        await victim.kill()
        out["killed"] = victim.name
        await asyncio.gather(*tasks)
        failed = [s for s in statuses if s != 200]
        assert not failed, f"non-streamed requests failed across the " \
                           f"kill: {failed} of {len(statuses)}"
        out["requests_across_kill"] = len(statuses)
        out["failed_across_kill"] = 0

        # the kill shows up as an ejection in the membership view
        snap = await _poll_fleet(
            client, lambda s: any(r["name"] == victim.name
                                  and r["state"] == "ejected"
                                  for r in s["replicas"]),
            10.0, f"{victim.name} ejected")
        out["ejected_visible"] = True

        # -- phase 2: restart the replica -> readmission ------------------
        await victim.start()            # same port, same name
        # the kill produced DATA evidence, so readmission needs a real
        # data-path trial — pump traffic while polling
        snap = await _pump_fleet(
            client, lambda s: any(r["name"] == victim.name
                                  and r["state"] == "healthy"
                                  for r in s["replicas"]),
            20.0, f"{victim.name} readmitted", statuses)
        failed = [s for s in statuses if s != 200]
        assert not failed, f"readmit pump saw client errors: {failed}"
        out["readmitted_visible"] = True
        assert snap["routable"] == N_REPLICAS

        # eject + readmit cycle is in /metrics
        mtext = await (await client.get("/metrics")).text()
        for metric in ("cake_fleet_ejects_total", "cake_fleet_readmits_total"):
            m = re.search(rf'^{metric}{{[^}}]*replica="{victim.name}"'
                          rf'[^}}]*}}\s+(\d+)', mtext, re.M)
            assert m and int(m.group(1)) >= 1, f"{metric} missing: " \
                f"{[l for l in mtext.splitlines() if metric in l]}"
        out["metrics_cycle"] = True

        # -- phase 3: saturation sheds 429 AT THE ROUTER ------------------
        router.max_inflight = 3
        serve_faults.install("delay_ms=40")     # slow every decode step
        try:
            results = await asyncio.gather(
                *[_chat(client, 100 + i, 0) for i in range(16)])
            sat = [(r.status, await r.json()) for r in results]
        finally:
            serve_faults.clear()
            router.max_inflight = 0
        shed = [b for s, b in sat if s == 429]
        ok = [b for s, b in sat if s == 200]
        bad = [(s, b) for s, b in sat if s not in (200, 429)]
        assert not bad, f"unexpected statuses under saturation: {bad}"
        assert shed, "saturation produced no 429s"
        assert all(b.get("shed_by") == "router" for b in shed), \
            f"429s not shed by the router: {shed[:2]}"
        out["saturation"] = {"ok": len(ok), "shed_by_router": len(shed)}
        mtext = await (await client.get("/metrics")).text()
        m = re.search(r"^cake_fleet_sheds_total{[^}]*}\s+(\d+)", mtext,
                      re.M)
        assert m and int(m.group(1)) >= 1, "cake_fleet_sheds_total missing"

        # -- phase 4: self-healing streams across a mid-stream kill -------
        STREAM_MAX_NEW = 24

        def smsg(convo: int) -> list:
            return [
                {"role": "system", "content": "fleet smoke system prompt "
                                              "shared by every conversation"},
                {"role": "user", "content": f"stream conversation {convo} "
                                            "tell me a long story"}]

        async def stream_once(convo: int, kill_after: int | None = None,
                              victim: ReplicaProc | None = None):
            """One streamed request through the router; optionally kill
            `victim` once `kill_after` content chunks have arrived.
            Returns (content, error_events, request_id)."""
            content, errors = "", []
            killed = False
            buf = b""
            async with client.post("/v1/chat/completions", json={
                    "messages": smsg(convo), "max_tokens": STREAM_MAX_NEW,
                    "temperature": 0.0, "stream": True}) as r:
                assert r.status == 200, await r.text()
                rid = r.headers.get("X-Cake-Request-Id")
                ntoks = 0
                async for piece in r.content.iter_any():
                    buf += piece
                    while b"\n\n" in buf:
                        ev, buf = buf.split(b"\n\n", 1)
                        if not ev.startswith(b"data: "):
                            continue
                        pl = ev[6:].strip()
                        if pl == b"[DONE]":
                            continue
                        obj = json.loads(pl)
                        if "error" in obj:
                            errors.append(obj["error"])
                            continue
                        delta = obj["choices"][0]["delta"]
                        if delta.get("content"):
                            content += delta["content"]
                            ntoks += 1
                            if (kill_after is not None and not killed
                                    and ntoks >= kill_after):
                                killed = True
                                await victim.kill()
            return content, errors, rid

        def commit_replica(rid: str) -> str:
            tl = router.timelines.get(rid)
            return next(e["replica"] for e in tl["events"]
                        if e["kind"] == "commit")

        serve_faults.install("delay_ms=40")     # stretch decode so the
        try:                                    # kill lands mid-stream
            convo = base = rid0 = None
            for c in range(40, 48):     # find a convo that decodes long
                base, errs, rid0 = await stream_once(c)
                assert not errs, errs
                if base.count(" t") >= 10:
                    convo = c
                    break
            assert convo is not None, "no convo produced >= 10 tokens"
            owner = next(rp for rp in replicas
                         if rp.name == commit_replica(rid0))
            healed, errs, rid = await stream_once(convo, kill_after=5,
                                                  victim=owner)
            assert not errs, f"client saw error events: {errs}"
            assert healed == base, \
                f"healed stream diverged:\n  base:   {base!r}\n" \
                f"  healed: {healed!r}"
            out["stream_killed"] = owner.name
            out["stream_body_identical"] = True
            kinds = [e["kind"] for e in router.timelines.get(rid)["events"]]
            for k in ("stream_broken", "stream_resume", "resume_spliced",
                      "done"):
                assert k in kinds, (k, kinds)
            assert kinds.index("stream_broken") \
                < kinds.index("stream_resume") \
                < kinds.index("resume_spliced") < kinds.index("done")
            out["stream_timeline_chain"] = True
            mtext = await (await client.get("/metrics")).text()
            m = re.search(r'^cake_fleet_stream_resumes_total'
                          r'{outcome="ok"}\s+(\d+)', mtext, re.M)
            assert m and int(m.group(1)) >= 1, \
                'cake_fleet_stream_resumes_total{outcome="ok"} missing'
            out["stream_resumes_ok"] = int(m.group(1))
        finally:
            serve_faults.clear()
        await owner.start()                 # same port, same name
        await _poll_fleet(
            client, lambda s: s["routable"] == N_REPLICAS,
            15.0, "stream victim readmitted")

        # -- phase 5: resume budget 0 preserves the legacy typed error ----
        base2, errs2, rid2 = await stream_once(60)
        assert not errs2, errs2
        owner2 = commit_replica(rid2)
        router.stream_resumes = 0
        fleet_faults.install(f"replica={owner2};break_stream_after=3")
        try:
            part, errs2, _ = await stream_once(60)
            assert errs2 and errs2[0]["type"] == "replica_stream_broken", \
                errs2
            resume = errs2[0]["resume"]
            assert resume.get("resume_token"), resume
            assert resume["tokens_generated"] >= 1
            assert resume["content_chars"] == len(part)
            assert part and base2.startswith(part)
            out["legacy_typed_error_with_token"] = True
        finally:
            fleet_faults.clear()
            router.stream_resumes = 1

        # -- phase 6: REAL network partition via the chaos proxy ----------
        pvict = replicas[0]
        proxy = ChaosProxy("127.0.0.1", pvict.port)
        await proxy.start()
        registry.add(pvict.name, proxy.base_url)   # reroute over the wire
        part_statuses: list = []

        def prow(s):
            return next(x for x in s["replicas"]
                        if x["name"] == pvict.name)

        try:
            r = await _chat(client, 700, 0)        # crosses the proxy
            await r.read()
            assert r.status == 200
            ej_before = prow(await (await client.get("/fleet")).json()
                             )["ejects"]
            proxy.apply("partition")
            for i in range(8):                     # absorbed by failover
                r = await _chat(client, 710 + i, 0)
                await r.read()
                part_statuses.append(r.status)
            snap = await _poll_fleet(
                client, lambda s: prow(s)["state"] == "ejected",
                10.0, f"{pvict.name} partition-ejected")
            assert prow(snap)["ejects"] == ej_before + 1, \
                "a partition episode must cost exactly one eject"
            proxy.heal()
            snap = await _pump_fleet(
                client, lambda s: prow(s)["state"] == "healthy",
                30.0, f"{pvict.name} readmitted after heal",
                part_statuses)
            failed = [s for s in part_statuses if s != 200]
            assert not failed, f"partition leg saw client errors: {failed}"
            out["partition_leg"] = {"requests": len(part_statuses),
                                    "errors": 0, "readmitted": True}
        finally:
            registry.add(pvict.name, f"http://127.0.0.1:{pvict.port}")
            await proxy.close()

        # fleet health is clean again
        h = await client.get("/health")
        assert h.status == 200, await h.text()
        out["health"] = 200
        return out
    finally:
        if client is not None:
            await client.close()
        for rep in replicas:
            if rep.runner is not None:
                await rep.kill()
            rep.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print("fleet-chaos-smoke OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
