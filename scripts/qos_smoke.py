#!/usr/bin/env python
"""QoS admission-plane smoke: interactive chat latency must survive
batch-image saturation.

Drives the REAL API app (aiohttp TestServer) over a tiny CPU TextModel
engine plus a tiny diffusion STUB image model (N steps of real jnp
dispatches with on_step checkpoints — the shape of a FLUX loop without
its weights). Phases:

  1. idle baseline — stream=True chats, client-observed TTFT p50;
  2. saturation — a backlog of batch-class image jobs (default class
     for /v1/images/generations) kept deep for the whole phase, with
     interactive chats interleaved;
  3. gates — chat TTFT p50 under saturation within 2x the idle
     baseline (floored at 50 ms to absorb scheduler noise on this
     shared CPU box), ZERO batch failures (every image job 200s), and
     a non-zero class-labeled queue gauge scraped from /metrics while
     saturated.

Exits non-zero on any missed gate. Run via `make qos-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.obs import SERVE_QOS_QUEUE_DEPTH             # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402
from cake_tpu.serve.admission import get_plane             # noqa: E402

BASELINE_FLOOR_S = 0.05
N_IDLE = 6
N_SAT = 8
N_JOBS = 6
JOB_STEPS = 24


class SmokeTok:
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:48] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


class StubDiffusion:
    """The SHAPE of a FLUX generation without its weights: JOB_STEPS
    real device dispatches with an on_step callback after each — which
    is where the admission plane's job.checkpoint() yield runs."""

    def __init__(self):
        self._w = jnp.ones((64, 64), jnp.float32)

    def generate_image(self, prompt, width=64, height=64, steps=JOB_STEPS,
                       on_step=None, **kw):
        x = jnp.ones((64, 64), jnp.float32)
        for i in range(steps):
            x = jnp.tanh(x @ self._w * 1e-3)
            x.block_until_ready()
            time.sleep(0.003)           # a real step is not free
            if on_step:
                on_step(i + 1, steps)
        from PIL import Image
        return Image.new("RGB", (width, height), (int(abs(float(x[0, 0])))
                                                  % 255, 64, 128))


def _pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


async def _ttft_stream(client, content: str) -> float:
    """Client-observed TTFT: POST a streamed chat, stamp the first
    content-bearing SSE chunk."""
    t0 = time.monotonic()
    async with client.post("/v1/chat/completions", json={
            "messages": [{"role": "user", "content": content}],
            "max_tokens": 4, "temperature": 0.0, "stream": True}) as r:
        assert r.status == 200, await r.text()
        async for piece in r.content.iter_any():
            for line in piece.split(b"\n"):
                if not line.startswith(b"data: ") or b"[DONE]" in line:
                    continue
                chunk = json.loads(line[6:])
                if chunk["choices"][0]["delta"].get("content"):
                    ttft = time.monotonic() - t0
                    await r.release()
                    return ttft
    raise AssertionError("stream produced no content chunk")


async def main_async() -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=128)
    model.tokenizer = SmokeTok()
    engine = ServeEngine(model, slots=2, max_queue=16, ctx_len=128,
                         prefill_chunk=16, prefix_cache_mb=0)
    state = ApiState(model=model, tokenizer=model.tokenizer,
                     model_id="qos-smoke", image_model=StubDiffusion())
    state.engine = engine
    get_plane(state)                    # job executor (1 worker)
    app = create_app(state)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        # warm the decode path (first chat compiles the slot programs)
        await _ttft_stream(client, "warmup request one")
        await _ttft_stream(client, "warmup request two")

        # -- phase 1: idle TTFT baseline
        idle = [await _ttft_stream(client, f"idle probe {i}")
                for i in range(N_IDLE)]
        idle_p50 = _pctl(idle, 0.5)

        # -- phase 2: batch-image saturation + interleaved chat
        jobs = [asyncio.ensure_future(client.post(
            "/v1/images/generations",
            json={"prompt": f"cake {i}", "size": "64x64",
                  "steps": JOB_STEPS}))
            for i in range(N_JOBS)]
        # the stub job runs ~steps x (dispatch+3ms) on 1 worker: the
        # backlog stays deep for the whole chat phase
        max_batch_depth = 0.0
        sat = []
        for i in range(N_SAT):
            sat.append(await _ttft_stream(client, f"interactive {i}"))
            max_batch_depth = max(max_batch_depth,
                                  SERVE_QOS_QUEUE_DEPTH.value(qos="batch"))
        # class-labeled queue gauge visible in a real scrape
        metrics = await (await client.get("/metrics")).text()
        mm = re.search(
            r'^cake_serve_qos_queue_depth\{qos="batch"\} (\S+)$',
            metrics, re.M)
        assert mm is not None, "no class-labeled queue gauge in /metrics"
        assert max_batch_depth > 0, \
            "batch queue depth never rose — saturation phase is broken"

        # -- phase 3: every batch job completes 200 (zero failures)
        statuses = [(await t).status for t in jobs]
        assert statuses == [200] * N_JOBS, f"batch failures: {statuses}"

        sat_p50 = _pctl(sat, 0.5)
        baseline = max(idle_p50, BASELINE_FLOOR_S)
        assert sat_p50 <= 2.0 * baseline, (
            f"interactive TTFT p50 {sat_p50 * 1e3:.1f}ms exceeds 2x the "
            f"idle baseline {baseline * 1e3:.1f}ms under batch "
            "saturation")
        return {
            "qos_smoke": "ok",
            "idle_ttft_p50_ms": round(idle_p50 * 1e3, 2),
            "saturated_ttft_p50_ms": round(sat_p50 * 1e3, 2),
            "gate_ratio": round(sat_p50 / baseline, 3),
            "batch_jobs": statuses.count(200),
            "max_batch_queue_depth": max_batch_depth,
            "idle_ms": [round(x * 1e3, 1) for x in idle],
            "saturated_ms": [round(x * 1e3, 1) for x in sat],
        }
    finally:
        await client.close()
        engine.close()


def main() -> int:
    out = asyncio.run(main_async())
    print(json.dumps(out, indent=2))
    mean_idle = statistics.fmean(out["idle_ms"])
    print(f"\nqos-smoke OK: idle p50 {out['idle_ttft_p50_ms']}ms "
          f"(mean {mean_idle:.1f}ms), saturated p50 "
          f"{out['saturated_ttft_p50_ms']}ms, ratio {out['gate_ratio']}x, "
          f"{out['batch_jobs']} batch jobs clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
