#!/usr/bin/env python
"""Partition smoke: real serve replicas behind the fleet router with a
REAL network chaos layer (fleet/netem.ChaosProxy — actual TCP relay,
actual severs/black-holes/delays) in front of the victim. The partition
shapes a health-checker is most often fooled by are drilled end to end:

  1. FULL partition: the victim's port refuses + live connections are
     severed — traffic stays clean via failover, the victim is ejected
     within a bounded window, EXACTLY ONE eject for the whole episode,
     and capacity drops out of total_capacity while it is gone;
  2. ASYMMETRIC probe-alive/data-dead (flipped at runtime through the
     proxy's CONTROL SOCKET): /health flows, /v1/chat dies — the eject
     carries evidence="data", healthy probes park the replica in
     half_open but may NEVER readmit it, the data-path trial fails and
     re-ejects with a DOUBLED hold (damped flap), and only after the
     network heals does a successful trial readmit it;
  3. DELAY brownout: every byte is delayed past the router's
     first-byte deadline — requests fail over in bounded time instead
     of wedging, and the victim cycles eject -> heal -> readmit;
  4. ledger: ZERO client-visible errors across every leg, the evidence
     dimension is in /fleet and cake_fleet_ejects_total, the episode
     accrued cake_fleet_partition_seconds_total, and the
     replica_partition_suspected -> partition_healed event pair is in
     the victim's replica:<name> pseudo-timeline.

Every phase polls WITH A DEADLINE (fixed sleeps flake on this
container's slow CPU). Exits non-zero on any missing signal. Run via
`make partition-smoke` (tier-2; not part of the tier-1 pytest run).
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402
from aiohttp import web                                    # noqa: E402
from aiohttp.test_utils import TestClient, TestServer      # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.fleet import (ChaosProxy, FleetRouter,       # noqa: E402
                            MembershipPolicy, ReplicaRegistry,
                            create_router_app)
from cake_tpu.fleet.netem import control_send              # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402

CTX = 128
N_REPLICAS = 3
MAX_NEW = 8


class SmokeTok:
    """Word-hash prose, round-trip for generated ids (decode emits
    " t<id>", encode parses them back) — the fleet smokes' tokenizer."""

    def encode(self, text):
        out = []
        for w in text.split():
            if w[:1] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(3 + (sum(w.encode()) % 200))
        return out[:64] or [3]

    def decode(self, ids):
        return "".join(f" t{i}" for i in ids)


class ReplicaProc:
    """One in-process serve replica: real engine, real HTTP socket."""

    def __init__(self, name: str, model):
        self.name = name
        self.engine = ServeEngine(model, slots=2, max_queue=16, ctx_len=CTX)
        self.state = ApiState(model=model, tokenizer=SmokeTok(),
                              model_id=f"tiny-{name}")
        self.state.engine = self.engine
        self.runner = None
        self.port = None

    async def start(self) -> str:
        self.runner = web.AppRunner(create_app(self.state))
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", self.port or 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def stop(self):
        if self.runner is not None:
            await self.runner.cleanup()
            self.runner = None

    def close(self):
        self.engine.close()


async def _poll(fn, pred, deadline_s: float, what: str, interval=0.05):
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        last = await fn()
        if pred(last):
            return last
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out after {deadline_s:.0f}s waiting for "
                         f"{what}; last: {json.dumps(last)[:600]}")


async def main_async() -> dict:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    model.tokenizer = SmokeTok()
    out: dict = {}
    statuses: list = []                     # the zero-client-errors ledger
    replicas = [ReplicaProc(f"r{i}", model) for i in range(N_REPLICAS)]
    victim = replicas[1]
    registry = ReplicaRegistry(MembershipPolicy(
        eject_fails=2, err_window=16, err_rate=0.5,
        degraded_ttft_ms=0.0, eject_s=0.3))
    # split data-path deadlines do the partition detection: connect
    # bounded at 1s, first byte at 0.6s — a black-holed or browned-out
    # attempt turns into a retryable transport failure, never a wedge
    router = FleetRouter(registry, retries=2, backoff_s=0.01,
                         probe_s=0.15, hedge_ms=0.0, max_inflight=0,
                         connect_timeout_s=1.0, first_byte_timeout_s=0.6)
    client = None
    proxy = None
    try:
        import aiohttp
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=300)) as warm:
            for rep in replicas:
                url = await rep.start()
                # warm the engine DIRECTLY (JAX compiles on the first
                # request — minutes on this CPU, which would read as a
                # first-byte timeout and eject a healthy replica)
                async with warm.post(
                        url + "/v1/chat/completions",
                        json={"messages": [{"role": "user",
                                            "content": "warm t7"}],
                              "max_tokens": MAX_NEW,
                              "temperature": 0.0}) as r:
                    assert r.status == 200, await r.text()
                if rep is victim:
                    continue                # joins through the proxy
                registry.add(rep.name, url)
        proxy = ChaosProxy("127.0.0.1", victim.port)
        await proxy.start()
        registry.add(victim.name, proxy.base_url)
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        convo = [0]

        async def chat(stream=False) -> float:
            """One chat request (fresh conversation id, so the fleet's
            rendezvous placement keeps exercising every replica);
            returns its wall time. Statuses land in the ledger."""
            convo[0] += 1
            t0 = time.monotonic()
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user",
                              "content": f"partition convo {convo[0]} "
                                         f"says t{3 + convo[0] % 200}"}],
                "max_tokens": MAX_NEW, "temperature": 0.0,
                "stream": stream})
            body = await r.read()
            if stream and r.status == 200:
                assert b"[DONE]" in body, body[-200:]
            statuses.append(r.status)
            return time.monotonic() - t0

        async def fleet():
            return await (await client.get("/fleet")).json()

        def row(snap, name=None):
            name = name or victim.name
            return next(r for r in snap["replicas"] if r["name"] == name)

        async def pump_until(pred, deadline_s, what):
            """Poll /fleet while keeping chat traffic flowing — readmit
            needs a real data-path trial request, not just probes."""
            async def step():
                await chat()
                return await fleet()
            return await _poll(step, pred, deadline_s, what)

        # -- phase 0: baseline — traffic flows through the proxy ----------
        for _ in range(4):
            await chat()
        await chat(stream=True)
        snap = await fleet()
        assert row(snap)["state"] == "healthy"
        capacity_full = registry.total_capacity()
        assert capacity_full > 0
        out["baseline"] = {"capacity": capacity_full}

        # -- phase 1: FULL partition --------------------------------------
        proxy.apply("partition")
        ejects_before = row(await fleet())["ejects"]
        for _ in range(8):                  # all absorbed by failover
            await chat()
        snap = await _poll(
            fleet, lambda s: row(s)["state"] == "ejected",
            10.0, "full partition ejected the victim")
        assert row(snap)["ejects"] == ejects_before + 1
        # a partitioned replica contributes NOTHING to capacity
        assert registry.total_capacity() < capacity_full
        # the episode never re-ejects while the fault persists: probes
        # keep failing against an already-EJECTED replica
        await asyncio.sleep(0.6)            # > eject hold, fault still on
        snap = await fleet()
        assert row(snap)["state"] == "ejected"
        assert row(snap)["ejects"] == ejects_before + 1, \
            "full partition must cost exactly one eject per episode"
        proxy.heal()
        snap = await pump_until(
            lambda s: row(s)["state"] == "healthy",
            20.0, "heal readmitted the victim")
        # heal restores the capacity exactly once (no double-count)
        assert registry.total_capacity() == capacity_full
        out["full_partition"] = {
            "ejects": row(snap)["ejects"] - ejects_before,
            "readmitted": True}

        # -- phase 2: ASYMMETRIC probe-alive/data-dead (control socket) ---
        st = await control_send("127.0.0.1", proxy.control_port,
                                "SET partition_out;match=/v1/chat")
        assert st["ok"] and st["plan"]["partition_out"], st
        streak0 = row(await fleet())["eject_streak"]
        # detection NEEDS data traffic: the probe path is deliberately
        # alive, so only the router's own failing requests can eject
        snap = await pump_until(
            lambda s: row(s)["state"] == "ejected",
            20.0, "asymmetric partition ejected the victim")
        assert row(snap)["eject_evidence"] == "data", row(snap)
        assert row(snap)["partition_s"] is not None
        out["asymmetric_evidence"] = "data"
        # probes are ALIVE: the victim advances to half_open after the
        # hold, but probes alone never readmit a data-evidence eject —
        # the data-path trial fails against the live fault and re-ejects
        # with the next hold on the backoff ladder (damped flap)
        snap = await pump_until(
            lambda s: (row(s)["state"] == "ejected"
                       and row(s)["eject_streak"] >= streak0 + 2),
            20.0, "failed trial re-ejected with a doubled hold")
        assert row(snap)["eject_evidence"] == "data"
        out["flap_damped_streak"] = row(snap)["eject_streak"]
        # heal the network; only now may a trial readmit it
        st = await control_send("127.0.0.1", proxy.control_port, "HEAL")
        assert st["ok"] and st["plan"] == {}, st
        snap = await pump_until(
            lambda s: row(s)["state"] == "healthy",
            30.0, "post-heal trial readmitted the victim")
        assert row(snap)["eject_evidence"] is None
        assert row(snap)["partition_s"] is None
        out["asymmetric_readmit_after_heal"] = True

        # -- phase 3: DELAY brownout vs the first-byte deadline -----------
        proxy.apply("delay_ms=1200")        # >> first_byte_timeout_s
        durs = [await chat() for _ in range(6)]
        assert max(durs) < 8.0, f"brownout wedged a request: {durs}"
        snap = await _poll(
            fleet, lambda s: row(s)["state"] == "ejected",
            15.0, "brownout ejected the victim")
        proxy.heal()
        snap = await pump_until(
            lambda s: row(s)["state"] == "healthy",
            30.0, "brownout heal readmitted the victim")
        out["brownout"] = {"max_request_s": round(max(durs), 2),
                           "readmitted": True}

        # -- phase 4: ledgers ---------------------------------------------
        failed = [s for s in statuses if s != 200]
        assert not failed, f"client-visible errors: {failed} " \
                           f"of {len(statuses)}"
        out["requests"] = len(statuses)
        out["client_errors"] = 0

        mtext = await (await client.get("/metrics")).text()
        m = re.search(rf'^cake_fleet_ejects_total{{replica="{victim.name}"'
                      rf',reason="[a-z_]+",evidence="data"}}\s+(\d+)',
                      mtext, re.M)
        assert m and int(m.group(1)) >= 1, \
            [ln for ln in mtext.splitlines() if "ejects_total" in ln]
        m = re.search(rf'^cake_fleet_partition_seconds_total'
                      rf'{{replica="{victim.name}"}}\s+([0-9.]+)',
                      mtext, re.M)
        assert m and float(m.group(1)) > 0, \
            "cake_fleet_partition_seconds_total missing"
        out["partition_seconds"] = float(m.group(1))

        tl = router.timelines.get(f"replica:{victim.name}")
        kinds = [e["kind"] for e in tl["events"]]
        assert "replica_partition_suspected" in kinds, kinds
        assert "partition_healed" in kinds, kinds
        assert kinds.index("replica_partition_suspected") \
            < kinds.index("partition_healed")
        out["episode_timeline"] = True

        h = await client.get("/health")
        assert h.status == 200, await h.text()
        out["health"] = 200
        out["proxy"] = proxy.status()["plan"] == {} and "healed"
        return out
    finally:
        if client is not None:
            await client.close()
        if proxy is not None:
            await proxy.close()
        for rep in replicas:
            await rep.stop()
            rep.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print("partition-smoke OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
