#!/usr/bin/env python
"""Paged-KV gate (tiny CPU model, in-process):

  1. greedy parity — paged engine output bit-identical to the sequential
     (contiguous) path;
  2. refcount prefix sharing — a second request with the same system
     prefix reports skipped tokens, and the shared-blocks gauge goes
     positive while it decodes (no KV copy, by construction);
  3. preemption — a pool sized below the working set preempts a victim
     and BOTH streams still finish bit-identical;
  4. observability — cake_serve_kv_blocks_{free,used,shared} and
     cake_serve_preemptions_total are present and non-zero in the
     Prometheus exposition.

Run via `make paged-smoke`.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.obs import REGISTRY                          # noqa: E402
from cake_tpu.ops.sampling import SamplingConfig           # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16
SYS = [3 + (i * 7) % 200 for i in range(40)]
P_A = [3, 17, 42, 99, 7]
P_B = [100, 2, 5, 9, 11, 40]


def check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}", file=sys.stderr)
        sys.exit(1)
    print(f"ok: {msg}")


def main() -> int:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)

    # 1+2: parity and refcount sharing on a roomy pool
    eng = ServeEngine(model, slots=2, max_queue=8, ctx_len=CTX,
                      prefill_chunk=CHUNK, kv_blocks=24, kv_block_tokens=8,
                      prefix_cache_mb=8)
    try:
        ref, _ = model.generate(SYS + [9, 11], max_new_tokens=8,
                                sampling=GREEDY)
        r = eng.submit(SYS + [9, 11], max_new_tokens=8, sampling=GREEDY)
        check(r.wait(300) and r.result["tokens"] == ref,
              "paged greedy bit-identical to sequential path")
        rb = eng.submit(SYS + [77, 31], max_new_tokens=40, sampling=GREEDY)
        deadline = time.monotonic() + 60
        shared = 0
        while time.monotonic() < deadline and not rb.done.is_set():
            shared = max(shared, eng.paged.alloc.shared_count)
            if shared and rb.tokens:
                break
            time.sleep(0.002)
        rb.cancel()
        rb.wait(60)
        check(rb.stats.get("prefix_hit_tokens", 0) > 0,
              f"prefix hit skipped {rb.stats.get('prefix_hit_tokens')} "
              "tokens")
        check(shared >= 2, f"blocks shared by refcount (peak {shared})")
    finally:
        eng.close()

    # 3: preemption under a pool below the working set
    eng = ServeEngine(model, slots=2, max_queue=8, ctx_len=CTX,
                      prefill_chunk=CHUNK, kv_blocks=12, kv_block_tokens=8,
                      prefix_cache_mb=0, preempt_mode="swap")
    try:
        ref_a, _ = model.generate(P_A, max_new_tokens=60, sampling=GREEDY)
        ref_b, _ = model.generate(P_B, max_new_tokens=60, sampling=GREEDY)
        ra = eng.submit(P_A, max_new_tokens=60, sampling=GREEDY)
        rb = eng.submit(P_B, max_new_tokens=60, sampling=GREEDY)
        check(ra.wait(600) and rb.wait(600), "both streams finished")
        check(ra.result["tokens"] == ref_a
              and rb.result["tokens"] == ref_b,
              "bit-identical continuation across preempt-by-swap")
        check(eng.paged.swaps >= 1, f"swap preemptions: {eng.paged.swaps}")
    finally:
        eng.close()

    # 4: exposition carries the new instruments
    text = REGISTRY.render()
    for name in ("cake_serve_kv_blocks_free", "cake_serve_kv_blocks_used",
                 "cake_serve_kv_blocks_shared",
                 "cake_serve_preemptions_total"):
        check(name in text, f"{name} exported")
    check('cake_serve_preemptions_total{mode="swap"}' in text,
          "preemption counter labeled by mode")
    print("PAGED SMOKE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
