#!/usr/bin/env python
"""Speculative-decoding smoke: the serve engine with the n-gram drafter
on a tiny CPU model must (a) produce greedy output bit-identical to a
spec-off engine for the same repetitive prompt, (b) land at least one
MULTI-token accept (a verify step that accepted >= 2 drafts — the whole
point of speculation), and (c) leave non-zero
cake_serve_spec_{proposed,accepted}_total counters plus the /health
engine spec block behind. Exits non-zero on any missing signal. Run via
`make spec-smoke`.
"""
from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.obs import REGISTRY                          # noqa: E402
from cake_tpu.ops.sampling import SamplingConfig           # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402

GREEDY = SamplingConfig(temperature=0.0)
PROMPT = [5, 17, 42, 9, 88, 23] * 8      # n-gram-drafter-friendly
MAX_NEW = 32


def _run(engine):
    r = engine.submit(PROMPT, max_new_tokens=MAX_NEW, sampling=GREEDY)
    assert r.wait(300), "request timed out"
    assert "error" not in r.result, r.result.get("error")
    return list(r.tokens)


def _metric(text, name):
    m = re.search(rf"^{name}(?:{{[^}}]*}})? ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


def main() -> int:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=128)

    eng = ServeEngine(model, slots=2, ctx_len=128, spec=False)
    try:
        plain = _run(eng)
    finally:
        eng.close()

    eng = ServeEngine(model, slots=2, ctx_len=128, spec="ngram", spec_k=8)
    try:
        spec = _run(eng)
        health = eng.health()["spec"]
    finally:
        eng.close()

    checks = {
        "bit_identical": spec == plain,
        "accepted_nonzero": health["accepted"] > 0,
        "steps_nonzero": health["steps"] > 0,
        # each verify step emits accepted+1 tokens, so fewer steps than
        # decode tokens <=> at least one step emitted >= 2 (a multi-token
        # accept; the first of len(spec) tokens comes from the prefill)
        "multi_token_accept": 0 < health["steps"] < len(spec) - 1,
    }
    text = REGISTRY.render()
    checks["metrics_proposed"] = \
        _metric(text, "cake_serve_spec_proposed_total") > 0
    checks["metrics_accepted"] = \
        _metric(text, "cake_serve_spec_accepted_total") > 0

    print(f"tokens={len(spec)} health.spec={health}")
    for k, ok in checks.items():
        print(f"  {'ok' if ok else 'FAIL'}: {k}")
    if not all(checks.values()):
        return 1
    print("spec smoke passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
