#!/usr/bin/env python
"""Multi-process fleet soak: the closed loop against REAL processes.

The driver runs a real fleet router (TCP socket, autoscaler on) with
ZERO replicas and lets the control loop do everything else: the
below-min rule bootstraps the fleet by spawning real `--serve` child
processes (this same file; tiny CPU model, real ServeEngine, real HTTP),
a load ramp starves headroom until the loop scales 2 -> 4, the ramp
ends and the clean-window dwell scales 4 -> 2 through graceful drains,
and a kill -9 of a managed replica is swept and replaced through the
same below-min rule that bootstrapped the fleet.

Asserts, in order:
  1. bootstrap: 0 -> CAKE_SCALE_MIN via below_min decisions, replicas
     admitted only after their /health answers;
  2. scale-OUT under ramp: saturated slots drive fleet headroom under
     CAKE_SCALE_HEADROOM_MIN and the fleet reaches CAKE_SCALE_MAX, one
     spawn per decision (pending spawns hold further triggers);
  3. scale-IN after the ramp: burn clean + headroom above the
     high-water for a full cooldown retires replicas back to min —
     every reap is graceful (forced=False: drained, never SIGKILLed);
  4. kill -9: a managed replica killed outright is reaped by the sweep
     (`died` on the decisions ring) and replaced via below_min;
  5. NETWORK PARTITION (fleet/netem.ChaosProxy on the wire): a managed
     replica whose PROCESS STAYS ALIVE is partitioned — it is ejected,
     its headroom leaves the capacity rollup, and the same below_min
     rule spawns a replacement; on heal the victim readmits through a
     data-path trial exactly once (no capacity double-count);
  6. ZERO client-visible errors across every phase (transparent
     failover absorbs the kill and the partition; cordons absorb the
     drains);
  7. zero frozen-gauge contamination: every retired/died replica's
     per-replica labelsets are retracted from router /metrics and gone
     from the telemetry rollup.

Every phase polls WITH A DEADLINE (fixed sleeps flake on this
container's slow CPU — spawns here are real JAX-importing processes).
Run via `make fleet-soak` (tier-2; not part of the tier-1 pytest run).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

CTX = 96
MAX_NEW = 12

# the whole policy the soak runs under, applied BEFORE the router (and
# its telemetry plane) is constructed. Windows are short so the loop
# reacts in seconds; the TTFT SLO is parked out of reach so burn stays
# clean and HEADROOM is the scaling driver (robust on a slow CPU where
# queue-wait TTFT is noise, saturation is not).
SOAK_KNOBS = {
    "CAKE_SCALE": "1",
    "CAKE_SCALE_MIN": "2",
    "CAKE_SCALE_MAX": "4",
    "CAKE_SCALE_COOLDOWN_S": "12",
    "CAKE_SCALE_WARMUP_S": "8",
    "CAKE_SCALE_HEADROOM_MIN": "2",
    "CAKE_SCALE_HEADROOM_HIGH": "10",
    "CAKE_SCALE_SPAWN_TIMEOUT_S": "300",
    "CAKE_SLO_TTFT_MS": "600000",
    "CAKE_TELEM_FAST_WINDOW_S": "8",
    "CAKE_TELEM_SLOW_WINDOW_S": "24",
    "CAKE_DRAIN_TIMEOUT_S": "15",
}


# ---------------------------------------------------------------------------
# --serve: one replica child process (real engine, real socket)
# ---------------------------------------------------------------------------


class SmokeTok:
    """Word-hash prose, round-trip for generated ids (decode emits
    " t<id>", encode parses them back) — the fleet smokes' tokenizer."""

    def encode(self, text):
        out = []
        for w in text.split():
            if w[:1] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(3 + (sum(w.encode()) % 200))
        return out[:64] or [3]

    def decode(self, ids):
        return "".join(f" t{i}" for i in ids)


def serve_child(name: str, port: int, step_delay_ms: int) -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from cake_tpu.api import ApiState
    from cake_tpu.api.server import serve
    from cake_tpu.models import TextModel, tiny_config
    from cake_tpu.serve import ServeEngine
    from cake_tpu.serve import faults as serve_faults

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    model.tokenizer = SmokeTok()
    if step_delay_ms > 0:
        # stretch decode so a handful of concurrent clients genuinely
        # saturates the slots (the scale-out pressure the soak ramps)
        serve_faults.install(f"delay_ms={step_delay_ms}")
    state = ApiState(model=model, tokenizer=SmokeTok(),
                     model_id=f"soak-{name}")
    state.engine = ServeEngine(model, slots=2, max_queue=32, ctx_len=CTX)
    # blocking; SIGTERM -> aiohttp on_shutdown -> graceful_drain (the
    # lifecycle manager's scale-in counts on exactly this path)
    serve(state, host="127.0.0.1", port=port)
    return 0


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


async def _poll(fn, pred, deadline_s: float, what: str, interval=0.25):
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        last = await fn()
        if pred(last):
            return last
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out after {deadline_s:.0f}s waiting for "
                         f"{what}; last: {json.dumps(last)[:600]}")


class LoadGroup:
    """N looping chat workers sharing one stop event."""

    def __init__(self, load: "Load", n: int, pause_s: float):
        self._stop = asyncio.Event()
        self._tasks = [asyncio.create_task(load._worker(self._stop,
                                                        pause_s))
                       for _ in range(n)]

    async def stop(self):
        self._stop.set()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []


class Load:
    """Chat workers against the router; every status (or transport
    failure) is recorded — the zero-client-errors ledger. Groups start
    and stop independently (a trickle can outlive the heavy ramp)."""

    def __init__(self, session, base):
        self.session = session
        self.base = base
        self.statuses: list = []
        self._convo = 0
        self._groups: list = []

    def group(self, n: int, pause_s: float = 0.0) -> LoadGroup:
        g = LoadGroup(self, n, pause_s)
        self._groups.append(g)
        return g

    async def stop_all(self):
        for g in self._groups:
            await g.stop()
        self._groups = []

    async def _one(self, convo: int):
        try:
            async with self.session.post(
                    self.base + "/v1/chat/completions",
                    json={"messages": [
                        {"role": "user",
                         "content": f"soak conversation {convo} says "
                                    f"hello t{3 + convo % 200}"}],
                        "max_tokens": MAX_NEW, "temperature": 0.0}) as r:
                await r.read()
                self.statuses.append(r.status)
        except Exception as e:
            self.statuses.append(f"{type(e).__name__}: {e}")

    async def _worker(self, stop, pause_s: float):
        while not stop.is_set():
            self._convo += 1
            await self._one(self._convo)
            if pause_s:
                await asyncio.sleep(pause_s)

    def errors(self) -> list:
        return [s for s in self.statuses if s != 200]


async def main_async(args) -> dict:
    os.environ.update(SOAK_KNOBS)
    os.environ["CAKE_SCALE_SPAWN_CMD"] = (
        f"{sys.executable} {os.path.abspath(__file__)} --serve "
        f"--name {{name}} --port {{port}} "
        f"--step-delay-ms {args.step_delay_ms}")

    import aiohttp
    from aiohttp import web

    from cake_tpu.fleet import (FleetRouter, MembershipPolicy,
                                ReplicaRegistry, create_router_app)

    registry = ReplicaRegistry(MembershipPolicy(
        eject_fails=3, err_window=16, err_rate=0.9,
        degraded_ttft_ms=0.0, eject_s=0.5))
    router = FleetRouter(registry, retries=2, backoff_s=0.05,
                         probe_s=0.5, hedge_ms=0.0, max_inflight=0,
                         autoscale=True)
    out: dict = {}
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    print(f"fleet-soak: router on {base}, scale "
          f"[{SOAK_KNOBS['CAKE_SCALE_MIN']}..{SOAK_KNOBS['CAKE_SCALE_MAX']}]")

    session = aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=120))
    load = Load(session, base)

    async def fleet():
        async with session.get(base + "/fleet") as r:
            return await r.json()

    async def autoscale():
        async with session.get(base + "/api/v1/fleet/autoscale") as r:
            return await r.json()

    async def metrics_text():
        async with session.get(base + "/metrics") as r:
            return await r.text()

    def ring_kinds(snap) -> list:
        return [(e["kind"], e.get("reason")) for e in snap["decisions"]]

    try:
        # -- phase 1: bootstrap 0 -> min via below_min --------------------
        t0 = time.monotonic()
        snap = await _poll(
            autoscale, lambda s: len(s["lifecycle"]["managed"]) >= 2,
            60.0, "below_min spawned 2 replicas")
        assert ("scale_out", "below_min") in ring_kinds(snap), \
            ring_kinds(snap)
        snap = await _poll(fleet, lambda s: s["routable"] >= 2, 300.0,
                           "bootstrap replicas admitted")
        out["bootstrap_s"] = round(time.monotonic() - t0, 1)
        out["bootstrap"] = sorted(r["name"] for r in snap["replicas"])
        # light trickle teaches the telemetry plane per-slot throughput
        # (idle headroom would otherwise read 0 and mimic saturation);
        # runs for the whole soak so signals never go dark
        load.group(1, pause_s=0.5)
        await _poll(autoscale,
                    lambda s: not s["lifecycle"]["pending_spawns"]
                    and len(s["lifecycle"]["managed"]) == 2,
                    120.0, "fleet settled at min")

        # -- phase 2: ramp -> scale out to max ----------------------------
        t0 = time.monotonic()
        heavy = load.group(6)           # saturate 2 replicas x 2 slots
        snap = await _poll(
            fleet, lambda s: s["routable"] >= 4, 600.0,
            "scale-out to max under ramp")
        out["scale_out_s"] = round(time.monotonic() - t0, 1)
        snap = await autoscale()
        reasons = [r for k, r in ring_kinds(snap) if k == "scale_out"]
        assert "headroom_low" in reasons, reasons
        out["scale_out_reasons"] = reasons
        # one spawn per decision: never more pending than one at a time
        # once past bootstrap (pending spawns hold further triggers)
        assert snap["lifecycle"]["pending_spawns"] == 0

        # -- phase 3: ramp down -> scale in to min ------------------------
        t0 = time.monotonic()
        await heavy.stop()              # the trickle keeps signals live
        snap = await _poll(
            autoscale,
            lambda s: len(s["lifecycle"]["managed"]) == 2
            and not any(m["retiring"] for m in s["lifecycle"]["managed"]),
            600.0, "scale-in back to min")
        out["scale_in_s"] = round(time.monotonic() - t0, 1)
        kinds = ring_kinds(snap)
        assert ("scale_in", "headroom_high") in kinds, kinds
        # drained replicas finish in flight: every reap was graceful
        reaps = [e for e in snap["decisions"] if e["kind"] == "reaped"]
        assert reaps and all(e.get("forced") is False for e in reaps), \
            reaps
        out["graceful_reaps"] = len(reaps)
        retired = {e["replica"] for e in snap["decisions"]
                   if e["kind"] in ("retire", "reaped")}

        # -- phase 4: kill -9 -> sweep + below_min replacement ------------
        snap = await autoscale()
        victim = snap["lifecycle"]["managed"][0]
        os.kill(victim["pid"], signal.SIGKILL)
        out["killed"] = {"name": victim["name"], "pid": victim["pid"]}
        t0 = time.monotonic()
        snap = await _poll(
            autoscale,
            lambda s: any(e["kind"] == "died"
                          and e.get("replica") == victim["name"]
                          for e in s["decisions"]),
            60.0, "sweep reaped the kill -9")
        snap = await _poll(
            fleet,
            lambda s: s["routable"] >= 2
            and victim["name"] not in [r["name"] for r in s["replicas"]],
            300.0, "below_min replacement admitted")
        out["replace_s"] = round(time.monotonic() - t0, 1)
        snap = await autoscale()
        after_died = False
        for kind, reason in ring_kinds(snap):
            if kind == "died":
                after_died = True
            if after_died and (kind, reason) == ("scale_out", "below_min"):
                break
        else:
            raise AssertionError(f"no below_min replacement after died: "
                                 f"{ring_kinds(snap)}")
        retired.add(victim["name"])

        # -- phase 5: network partition -> below_min replacement + heal ---
        from cake_tpu.fleet import ChaosProxy
        snap = await fleet()
        vrow = next(r for r in snap["replicas"] if r["state"] == "healthy")
        vname, vurl = vrow["name"], vrow["base_url"]
        proxy = ChaosProxy("127.0.0.1", int(vurl.rsplit(":", 1)[1]))
        await proxy.start()
        registry.add(vname, proxy.base_url)     # reroute over the wire
        try:
            t0 = time.monotonic()
            proxy.apply("partition")
            out["partitioned"] = vname
            # the process is ALIVE but the network is gone: ejected
            snap = await _poll(fleet, lambda s: any(
                r["name"] == vname and r["state"] == "ejected"
                for r in s["replicas"]), 60.0, "partitioned replica ejected")
            # capacity honesty: the partitioned replica's headroom is out
            # of the rollup the autoscaler reads
            async with session.get(base + "/api/v1/fleet/telemetry") as r:
                roll = await r.json()
            vtel = (roll.get("replicas") or {}).get(vname) or {}
            assert not vtel.get("headroom_tokens_per_s"), vtel
            # the SAME below_min rule that replaces a dead process
            # replaces a partitioned one — routable capacity is what
            # counts, not process liveness
            await _poll(autoscale,
                        lambda s: len(s["lifecycle"]["managed"]) >= 3,
                        90.0, "below_min spawned a partition replacement")
            await _poll(fleet, lambda s: s["routable"] >= 2, 300.0,
                        "partition replacement admitted")
            out["partition_replace_s"] = round(time.monotonic() - t0, 1)
            # heal: the victim readmits through a data-path trial (the
            # trickle supplies it) and is counted exactly once
            proxy.heal()
            snap = await _poll(fleet, lambda s: any(
                r["name"] == vname and r["state"] == "healthy"
                for r in s["replicas"]), 180.0, "healed replica readmitted")
            names = [r["name"] for r in snap["replicas"]]
            assert names.count(vname) == 1, names
            out["partition_heal_readmit"] = True
        finally:
            registry.add(vname, vurl)           # direct again
            await proxy.close()

        # -- phase 6: ledgers --------------------------------------------
        await load.stop_all()
        errors = load.errors()
        assert not errors, f"client-visible errors: {errors[:10]} " \
                           f"({len(errors)} of {len(load.statuses)})"
        out["requests"] = len(load.statuses)
        out["client_errors"] = 0

        mtext = await metrics_text()
        for direction, floor in (("out", 3), ("in", 2)):
            total = sum(int(m) for m in re.findall(
                rf'^cake_fleet_scale_actions_total{{[^}}]*'
                rf'direction="{direction}"[^}}]*}}\s+(\d+)', mtext, re.M))
            assert total >= floor, (direction, total, floor)
            out[f"scale_actions_{direction}"] = total
        # frozen-gauge contamination: every retired/died replica's
        # labelsets are retracted, and the rollup no longer knows them
        for name in retired:
            stale = [ln for ln in mtext.splitlines()
                     if f'replica="{name}"' in ln
                     and ("queue_depth" in ln or "occupancy" in ln)]
            assert not stale, stale
        async with session.get(base + "/api/v1/fleet/telemetry") as r:
            roll = await r.json()
        assert not retired & set(roll.get("replicas") or {}), \
            (retired, list(roll["replicas"]))
        out["retired_names_retracted"] = sorted(retired)
        return out
    finally:
        try:
            await load.stop_all()
        except Exception:
            pass
        await session.close()
        await runner.cleanup()          # drains router, closes lifecycle


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true",
                    help="internal: run as one replica child process")
    ap.add_argument("--name", default="soak")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--step-delay-ms", type=int, default=25)
    args = ap.parse_args()
    if args.serve:
        return serve_child(args.name, args.port, args.step_delay_ms)
    out = asyncio.new_event_loop().run_until_complete(main_async(args))
    print("fleet-soak OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
