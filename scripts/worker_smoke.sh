#!/usr/bin/env bash
# Worker portability smoke — see scripts/worker_smoke.py for details.
# Usage: scripts/worker_smoke.sh
# Prints one JSON line {"worker_smoke": "ok", ...} and exits 0 on success.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
exec python scripts/worker_smoke.py
