#!/usr/bin/env python
"""Request-tracing smoke: one chat driven through a REAL fleet router
fronting a REAL engine-backed replica (tiny CPU model) must yield

  (a) a trace id minted by the router, injected into the replica via the
      X-Cake-Request-Id header, and echoed on the response;
  (b) a STITCHED timeline retrievable by that id from the router's
      /api/v1/requests/<id> — router-tier events (route/attempt/done)
      AND replica-tier engine events (enqueue/admit/prefill/first_token/
      finish) on the same id;
  (c) non-zero TTFT / inter-token / e2e SLO histograms in the replica's
      /metrics, and /api/v1/slo exemplars linking back to the traced id.

Exits non-zero on any missing signal. Run via `make trace-smoke` (also a
prerequisite of obs-smoke).
"""
from __future__ import annotations

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.fleet.registry import (MembershipPolicy,     # noqa: E402
                                     ReplicaRegistry)
from cake_tpu.fleet.router import (FleetRouter,            # noqa: E402
                                   create_router_app)
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.obs import TRACE_HEADER                      # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402


class SmokeTok:
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:48] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


async def main_async() -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=128)
    model.tokenizer = SmokeTok()
    engine = ServeEngine(model, slots=2, max_queue=8, ctx_len=128,
                         prefill_chunk=16)
    state = ApiState(model=model, tokenizer=model.tokenizer,
                     model_id="trace-smoke")
    state.engine = engine
    replica_server = TestServer(create_app(state))
    await replica_server.start_server()
    replica_url = str(replica_server.make_url("")).rstrip("/")

    registry = ReplicaRegistry(MembershipPolicy())
    registry.add("r0", replica_url)
    router = FleetRouter(registry, retries=1, backoff_s=0.01,
                         probe_s=30.0, hedge_ms=0.0)
    client = TestClient(TestServer(create_router_app(router)))
    await client.start_server()
    try:
        # -- (a) one request through router -> replica -> engine --------
        r = await client.post("/v1/chat/completions", json={
            "messages": [{"role": "user",
                          "content": "alpha bravo charlie delta"}],
            "max_tokens": 6, "temperature": 0.0})
        assert r.status == 200, await r.text()
        rid = r.headers.get(TRACE_HEADER)
        assert rid and rid.startswith("trace-"), \
            f"router did not echo a trace id (got {rid!r})"
        body = await r.json()
        cid = body["id"]

        # -- (b) stitched timeline from the router ----------------------
        tr = await client.get(f"/api/v1/requests/{rid}")
        assert tr.status == 200, await tr.text()
        stitched = await tr.json()
        tiers = {t["tier"]: t for t in stitched["tiers"]}
        assert set(tiers) >= {"router", "replica"}, sorted(tiers)
        router_kinds = [e["kind"] for e in tiers["router"]["events"]]
        replica_kinds = [e["kind"] for e in tiers["replica"]["events"]]
        for k in ("route", "attempt", "done"):
            assert k in router_kinds, (k, router_kinds)
        for k in ("received", "enqueue", "admit", "prefill_chunk",
                  "prefill_done", "first_token", "decode", "finish"):
            assert k in replica_kinds, (k, replica_kinds)
        # the replica timeline resolves by the completion id alias too
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.get(replica_url + f"/api/v1/requests/{cid}") as ar:
                assert ar.status == 200, "completion-id alias missing"

            # -- (c) SLO histograms + exemplars -------------------------
            async with s.get(replica_url + "/metrics") as mr:
                text = await mr.text()
            for needle in ("cake_serve_ttft_seconds_count",
                           "cake_serve_itl_seconds_count",
                           "cake_serve_e2e_seconds_count"):
                line = [ln for ln in text.splitlines()
                        if ln.startswith(needle)
                        and 'outcome="ok"' in ln]
                assert line and not line[0].endswith(" 0"), \
                    f"/metrics missing non-zero {needle}: {line}"
            async with s.get(replica_url + "/api/v1/slo") as sr:
                slo = await sr.json()
            exemplars = [
                ex["exemplar"]
                for hist in slo.values() for series in hist["series"]
                for ex in series["exemplars"].values()]
            assert rid in exemplars, \
                f"SLO exemplars do not link to the traced id: {exemplars}"
        return {"trace_smoke": "ok", "request_id": rid,
                "router_events": len(router_kinds),
                "replica_events": len(replica_kinds)}
    finally:
        await client.close()
        await replica_server.close()
        engine.close()


def main() -> int:
    out = asyncio.run(main_async())
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
