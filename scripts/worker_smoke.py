"""Worker portability smoke: brings up a real two-process localhost
cluster — one `WorkerServer` child process + one master — on the JAX
**CPU** backend and checks greedy parity against a local single-process
run. This is the runnable form of the PARITY.md mobile-scope claim ("any
aarch64 JAX-CPU box joins via `cake-tpu worker`"); the CI workflow runs
it on an ARM runner (ref: the reference's Android aarch64 CI job,
/root/reference/.github/workflows/ci.yml).

Must live in a real file (not a heredoc): the worker child is spawned via
multiprocessing, which re-imports __main__ and cannot do so from stdin.

Usage: python scripts/worker_smoke.py  (or scripts/worker_smoke.sh)
Prints one JSON line {"worker_smoke": "ok", ...} and exits 0 on success.
"""
import json
import multiprocessing as mp
import os
import platform
import socket
import sys
import tempfile
import time

# repo root on sys.path: script lives in scripts/, package at the root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force CPU before first device use: on hosts where a sitecustomize
# pre-imports jax (e.g. the TPU tunnel image), JAX_PLATFORMS is ignored
import jax

jax.config.update("jax_platforms", "cpu")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def make_checkpoint(tmp):
    """Tiny qwen3-shaped synthetic checkpoint on disk (no egress here);
    mirrors tests/test_cluster.py cluster_model_dir."""
    import jax.numpy as jnp

    from cake_tpu.models import tiny_config
    from cake_tpu.models.common.layers import init_params
    from cake_tpu.utils.export import params_to_hf_tensors
    from cake_tpu.utils.safetensors_io import save_safetensors

    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    save_safetensors(os.path.join(tmp, "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    with open(os.path.join(tmp, "config.json"), "w") as f:
        json.dump({"architectures": ["Qwen3ForCausalLM"], "vocab_size": 256,
                   "hidden_size": 64, "intermediate_size": 128,
                   "num_hidden_layers": 4, "num_attention_heads": 4,
                   "num_key_value_heads": 2, "rms_norm_eps": 1e-5,
                   "rope_theta": 10000.0, "max_position_embeddings": 128,
                   "eos_token_id": 2}, f)
    return cfg, params


def worker_main(port, cache_root):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")
    from cake_tpu.cluster.worker import run_worker
    run_worker("smoke-w0", "smoke-key", port=port, cache_root=cache_root,
               advertise=False)


def main():
    tmp = tempfile.mkdtemp(prefix="cake-smoke-")
    cfg, params = make_checkpoint(tmp)
    port = free_port()
    proc = mp.get_context("spawn").Process(
        target=worker_main, args=(port, os.path.join(tmp, "wcache")),
        daemon=True)
    proc.start()
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.2).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        print(json.dumps({"worker_smoke": "fail",
                          "error": "worker never listened"}))
        sys.exit(1)

    import jax.numpy as jnp

    from cake_tpu.cluster.master import DistributedTextModel, master_setup
    from cake_tpu.models import SamplingConfig, TextModel

    prompt = [11, 23, 5, 190, 77, 3]
    scfg = SamplingConfig(temperature=0.0)

    local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
    want, _ = local.generate(prompt, max_new_tokens=12, sampling=scfg)

    workers = [{"name": "smoke-w0", "host": "127.0.0.1", "port": port,
                "caps": {"backend": "cpu", "device": "cpu",
                         "memory_bytes": 4 << 30, "tflops": 50.0}}]
    setup = master_setup(tmp, "smoke-key", cfg, workers,
                         assignments={"smoke-w0": (2, 4)},
                         dtype_str="f32", max_cache_len=64)
    dist = DistributedTextModel(cfg, setup.master_params, setup.stages,
                                dtype=jnp.float32, max_cache_len=64)
    got, _ = dist.generate(prompt, max_new_tokens=12, sampling=scfg)
    for c in setup.clients:
        c.close()
    proc.terminate()

    ok = list(got) == list(want)
    print(json.dumps({"worker_smoke": "ok" if ok else "fail",
                      "machine": platform.machine(),
                      "python": platform.python_version(),
                      "tokens": [int(t) for t in got]}))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
