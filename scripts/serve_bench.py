#!/usr/bin/env python
"""Serve-engine scheduling bench: TTFT p50/p99 and tokens/s for a
shared-system-prompt chat workload, COLD (empty/disabled prefix cache)
vs WARM (system prefix already cached), plus the decode-interference
probe — max inter-token gap of an active stream while a long prompt is
admitted chunk-by-chunk. Tiny CPU model; numbers are for the SCHEDULER,
not the hardware.

Writes BENCH_SERVE_<tag>.json (default tag from --tag, else "local") and
prints it. Run via `make serve-bench`.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.ops.sampling import SamplingConfig           # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402

GREEDY = SamplingConfig(temperature=0.0)
CTX = 128
CHUNK = 16
SYSTEM = [3 + (i * 7) % 200 for i in range(64)]     # shared system prompt
N_REQ = 12
MAX_NEW = 8


def _prompts():
    """N_REQ chats sharing the 64-token system prefix, distinct 8-token
    user suffixes (the workload prefix caching exists for)."""
    return [SYSTEM + [(11 * j + i * 3) % 200 + 3 for i in range(8)]
            for j in range(N_REQ)]


def _pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def _run_workload(eng, prompts):
    ttfts, tps = [], []
    for p in prompts:
        r = eng.submit(p, max_new_tokens=MAX_NEW, sampling=GREEDY)
        assert r.wait(300), "request timed out"
        assert "error" not in r.result, r.result.get("error")
        ttfts.append(r.stats["ttft_s"])
        if r.stats.get("tok_per_s"):
            tps.append(r.stats["tok_per_s"])
    return {
        "requests": len(prompts),
        "ttft_p50_s": round(_pctl(ttfts, 0.50), 5),
        "ttft_p99_s": round(_pctl(ttfts, 0.99), 5),
        "ttft_mean_s": round(statistics.mean(ttfts), 5),
        "decode_tok_per_s_mean": round(statistics.mean(tps), 2) if tps else 0,
    }


def bench_cold_vs_warm(model):
    prompts = _prompts()
    # cold: prefix reuse off — every admission prefills the full prompt
    eng = ServeEngine(model, slots=2, max_queue=32, ctx_len=CTX,
                      prefill_chunk=CHUNK, prefix_cache_mb=0)
    try:
        _run_workload(eng, prompts[:2])          # compile warmup, untimed
        cold = _run_workload(eng, prompts)
    finally:
        eng.close()
    # warm: prefix cache on, primed by one request carrying the system
    # prompt — the steady state of a chat server under real traffic
    eng = ServeEngine(model, slots=2, max_queue=32, ctx_len=CTX,
                      prefill_chunk=CHUNK, prefix_cache_mb=64)
    try:
        _run_workload(eng, prompts[:2])          # warmup + primes the cache
        warm = _run_workload(eng, prompts)
        occ = eng.health()["prefix_cache"]
        warm["prefix_cache"] = {k: occ[k] for k in
                                ("blocks", "bytes", "hits", "misses")}
    finally:
        eng.close()
    return {"cold": cold, "warm": warm,
            "warm_faster_p50": warm["ttft_p50_s"] < cold["ttft_p50_s"]}


def bench_admission_interference(model):
    """Max inter-token gap of an active stream while a long prompt is
    admitted: with chunked prefill this is bounded by ~one chunk of
    compute, not the whole prompt. Reported for chunked admission AND for
    a whole-prompt-sized chunk (the monolithic-equivalent baseline)."""
    long_prompt = [3 + (i * 13) % 200 for i in range(120)]

    def probe(chunk):
        eng = ServeEngine(model, slots=2, max_queue=4, ctx_len=CTX,
                          prefill_chunk=chunk, prefix_cache_mb=0)
        try:
            # warm every executable: chunk buckets AND the nb=2 decode
            # slot bucket (two requests in flight at once), so the timed
            # region measures scheduling, not one-time XLA compiles
            w = eng.submit(long_prompt, max_new_tokens=4, sampling=GREEDY)
            w2 = eng.submit([8, 8, 1, 30], max_new_tokens=8, sampling=GREEDY)
            assert w.wait(300) and w2.wait(300)
            stamps = []
            r = eng.submit([8, 8, 1, 30], max_new_tokens=200,
                           sampling=GREEDY)
            while len(r.tokens) < 3:
                time.sleep(0.001)
            t0 = time.monotonic()
            seen = len(r.tokens)
            rl = eng.submit(long_prompt, max_new_tokens=4, sampling=GREEDY)
            # coarse poll: on the 1-core CI box a tight loop would starve
            # the scheduler thread of the GIL and inflate every number
            while not rl.tokens and time.monotonic() - t0 < 300:
                n = len(r.tokens)
                if n > seen:
                    stamps.append(time.monotonic())
                    seen = n
                time.sleep(0.004)
            r.cancel()
            assert rl.wait(300)
            gaps = [b - a for a, b in zip(stamps, stamps[1:])]
            return {
                "prefill_chunk": eng.chunk,
                "tokens_during_admission": len(stamps),
                "max_token_gap_s": round(max(gaps), 5) if gaps else None,
                "long_ttft_s": round(rl.stats["ttft_s"], 5),
            }
        finally:
            eng.close()

    chunked = probe(CHUNK)
    monolithic = probe(CTX)      # one chunk swallows the whole prompt
    return {"chunked": chunked, "monolithic_equivalent": monolithic,
            "decode_stall_removed":
                (chunked["tokens_during_admission"] or 0)
                > (monolithic["tokens_during_admission"] or 0)}


def bench_long_tail(model):
    """Paged-pool long-tail mode: MORE CONCURRENT STREAMS than the old
    contiguous pool could hold, in the SAME HBM budget. The contiguous
    baseline provisions slots x ctx tokens of KV (4 x 128 = 512 here);
    the paged engine gets exactly those bytes as 64 x 8-token blocks but
    8 slots, and a mixed short/long workload (the long-tail shape: many
    small chats, a few near-ctx contexts). Records peak concurrent
    occupancy and preemption counts — the acceptance is occupancy >
    CAKE_SERVE_SLOTS-equivalent (4) within the old pool's bytes."""
    from cake_tpu.obs import SERVE_PREEMPTIONS

    base_slots = 4                       # the old fixed pool's row count
    blocks = base_slots * CTX // 8       # same KV bytes, 8-token blocks
    shorts = [[3 + (11 * j + i * 3) % 200 for i in range(8)]
              for j in range(8)]
    longs = [[3 + (13 * j + i * 7) % 200 for i in range(96)]
             for j in range(4)]
    pre_swap = SERVE_PREEMPTIONS.value(mode="swap")
    pre_rec = SERVE_PREEMPTIONS.value(mode="recompute")
    eng = ServeEngine(model, slots=8, max_queue=32, ctx_len=CTX,
                      prefill_chunk=CHUNK, prefix_cache_mb=0,
                      kv_blocks=blocks, kv_block_tokens=8,
                      preempt_mode="swap")
    try:
        # warmup: compile the wide-occupancy buckets outside the record
        w = [eng.submit(p, max_new_tokens=4, sampling=GREEDY)
             for p in shorts[:8]]
        assert all(r.wait(600) for r in w)
        # longs first so they are resident when the short burst lands —
        # the working set (3 x ~14 + 8 x ~4 blocks) overcommits the
        # 64-block pool and preemption has to arbitrate
        reqs = [eng.submit(p, max_new_tokens=24, sampling=GREEDY)
                for p in longs]
        reqs += [eng.submit(p, max_new_tokens=24, sampling=GREEDY)
                 for p in shorts]
        peak_busy = peak_used = 0
        while not all(r.done.is_set() for r in reqs):
            h = eng.health()
            peak_busy = max(peak_busy, h["slots_busy"])
            peak_used = max(peak_used, h["kv_pool"]["used"])
            time.sleep(0.002)
        assert all(r.wait(600) for r in reqs)
        errors = sum(1 for r in reqs if "error" in r.result)
        h = eng.health()["kv_pool"]
        return {
            "pool_blocks": blocks,
            "pool_tokens": blocks * 8,
            "contiguous_equivalent_slots": base_slots,
            "slots": 8,
            "requests": len(reqs),
            "short_ctx": len(shorts[0]),
            "long_ctx": len(longs[0]),
            "errors": errors,
            "peak_concurrent_streams": peak_busy,
            "peak_blocks_used": peak_used,
            "preemptions_swap": SERVE_PREEMPTIONS.value(mode="swap")
            - pre_swap,
            "preemptions_recompute":
                SERVE_PREEMPTIONS.value(mode="recompute") - pre_rec,
            "swaps": h["swaps"],
            "beats_contiguous_pool": peak_busy > base_slots,
        }
    finally:
        eng.close()


# -- batched speculation: acceptance x occupancy x effective tok/s ----------
# Templated traffic for the speculative bench: motif prompts whose greedy
# continuation re-quotes context the n-gram drafter can look up (the
# summarize/code-edit/RAG shape). The tiny model has random weights, so
# the motifs are pre-screened for continuations the drafter predicts over
# long runs — acceptance (tokens/step) is the hardware-independent
# signal; the CPU wall numbers measure how much of it the SCHEDULER
# converts into effective tok/s.
SPEC_MOTIFS = (3, 23, 16, 4)
SPEC_CTX = 256
SPEC_MAX_NEW = 96
SPEC_K = 8


def _spec_prompts(model, n):
    out = []
    for j in SPEC_MOTIFS[:n]:
        motif = [(5 + j * 7) % 200 + 3, (9 + j * 7) % 200 + 3,
                 (17 + j * 7) % 200 + 3, (23 + j * 7) % 200 + 3]
        pre = motif * 6 + motif[:2]
        cont, _ = model.generate(pre, max_new_tokens=24, sampling=GREEDY,
                                 spec=False)
        out.append(pre + cont)      # templated: output re-quotes context
    return out


def bench_spec(model):
    """Speculation on vs off through the BATCHED engine at occupancy
    1 / 2 / 4: effective tok/s (all requests' tokens over the
    concurrent-workload wall), acceptance rate, accepted tokens per
    verify step, per-slot-bucket acceptance (the
    cake_serve_spec_bucket_accepted_length histogram), and greedy
    bit-parity spec-on vs spec-off. The paged variant runs the same
    sweep at occupancy 4 to show speculation no longer stands down."""
    from cake_tpu.obs import SPEC_BUCKET_ACCEPTED

    def run(spec, occ, **ekw):
        eng = ServeEngine(model, slots=occ, max_queue=32, ctx_len=SPEC_CTX,
                          prefill_chunk=32, prefix_cache_mb=0,
                          spec=spec, spec_k=SPEC_K, **ekw)
        try:
            ps = _spec_prompts(model, occ)
            warm = [eng.submit(p, max_new_tokens=SPEC_MAX_NEW,
                               sampling=GREEDY) for p in ps]
            assert all(r.wait(600) for r in warm), "warmup timed out"
            t0 = time.monotonic()
            rs = [eng.submit(p, max_new_tokens=SPEC_MAX_NEW,
                             sampling=GREEDY) for p in ps]
            assert all(r.wait(600) for r in rs), "bench run timed out"
            for r in rs:
                assert "error" not in r.result, r.result.get("error")
            wall = time.monotonic() - t0
            toks = sum(len(r.tokens) for r in rs)
            return (toks / wall, [list(r.tokens) for r in rs],
                    eng.health().get("spec"))
        finally:
            eng.close()

    cases = []
    for occ in (1, 2, 4):
        off_tps, off_out, _ = run(False, occ)
        pre = {b: (SPEC_BUCKET_ACCEPTED.sum(bucket=str(b)),
                   SPEC_BUCKET_ACCEPTED.count(bucket=str(b)))
               for b in (1, 2, 4)}
        on_tps, on_out, h = run("ngram", occ)
        per_bucket = {}
        for b in (1, 2, 4):
            ds = SPEC_BUCKET_ACCEPTED.sum(bucket=str(b)) - pre[b][0]
            dn = SPEC_BUCKET_ACCEPTED.count(bucket=str(b)) - pre[b][1]
            if dn:
                per_bucket[str(b)] = round(ds / dn, 3)
        cases.append({
            "occupancy": occ,
            "bit_identical": on_out == off_out,
            "off_tok_per_s": round(off_tps, 1),
            "on_tok_per_s": round(on_tps, 1),
            "effective_speedup": round(on_tps / off_tps, 3),
            "verify_steps": h["steps"],
            "proposed": h["proposed"],
            "accepted": h["accepted"],
            "accept_rate": round(h["accepted"] / h["proposed"], 4)
            if h["proposed"] else 0.0,
            "tokens_per_step": round(
                (h["accepted"] + h["steps"]) / h["steps"], 3)
            if h["steps"] else 0.0,
            "accepted_per_step_by_bucket": per_bucket,
        })
    # paged mode at the deepest occupancy: speculation active, no
    # stand-down (blocks sized so the workload fits without preemption)
    blocks = 4 * SPEC_CTX // 16
    pg_off, pg_off_out, _ = run(False, 4, kv_blocks=blocks,
                                kv_block_tokens=16)
    pg_on, pg_on_out, ph = run("ngram", 4, kv_blocks=blocks,
                               kv_block_tokens=16)
    paged = {
        "occupancy": 4,
        "bit_identical": pg_on_out == pg_off_out,
        "off_tok_per_s": round(pg_off, 1),
        "on_tok_per_s": round(pg_on, 1),
        "effective_speedup": round(pg_on / pg_off, 3),
        "verify_steps": ph["steps"],
        "accepted": ph["accepted"],
    }
    best = max(c["effective_speedup"] for c in cases)
    return {"contiguous": cases, "paged": paged,
            "spec_k": SPEC_K, "max_new_tokens": SPEC_MAX_NEW,
            "best_effective_speedup": best,
            "speculation_pays": best >= 1.3}


# -- fleet affinity bench ---------------------------------------------------

FLEET_CONVOS = 8
FLEET_TURNS = 3
FLEET_MAX_NEW = 6
FLEET_CTX = 256     # conversations grow ~2 blocks per turn; the affinity
                    # win is the convo-SPECIFIC prefix, so prompts must
                    # outgrow the small shared system block


class FleetTok:
    """Word-hash tokenizer (no length cap): conversation prompts grow a
    shared token prefix turn over turn, which is what the replica prefix
    caches (and therefore affinity routing) exist for. decode/encode
    round-trip generated ids (" t<id>" words) so a resume splice
    re-encodes the relayed partial to the exact generated tokens —
    the resume bench rides the same contract the chaos drill pins."""

    def encode(self, text):
        out = []
        for w in text.split():
            if w[:1] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(3 + (sum(w.encode()) % 200))
        return out or [3]

    def decode(self, ids):
        return "".join(f" t{i}" for i in ids)


def _fleet_messages(convo: int, turn: int) -> list:
    """Realistic multi-turn shape: a SMALL shared system prompt (one
    block — both replicas cache it immediately, it is not what affinity
    is for) and a LARGE conversation-specific history (~3 blocks of
    opening + ~2 blocks per turn) that only the owning replica holds."""
    msgs = [{"role": "system",
             "content": "fleet bench shared system prompt please answer "
                        "helpfully and briefly at all times ok"}]
    msgs.append({"role": "user",
                 "content": f"conversation {convo} opening question: "
                 + " ".join(f"ctx{convo}word{i}" for i in range(44))})
    for t in range(turn):
        msgs.append({"role": "assistant", "content": " ".join(
            f"answer{convo}t{t}w{i}" for i in range(14))})
        msgs.append({"role": "user", "content": " ".join(
            f"follow{convo}t{t}w{i}" for i in range(14))})
    return msgs


def bench_fleet(model):
    """Prefix-affinity routing vs round-robin through the REAL router
    over 2 real replicas: conversational follow-up traffic, per-turn
    time-to-first-content-token. Affinity keeps every turn of a
    conversation on its owning replica, whose prefix cache then serves
    the shared head warm; round-robin alternates replicas per request,
    so roughly half the follow-ups prefill cold. Fresh replicas per
    mode (no cache pollution across modes); untimed warmup pass
    compiles every chunk/slot bucket first."""
    import asyncio

    from aiohttp import web as aioweb
    from aiohttp.test_utils import TestClient, TestServer

    from cake_tpu.api import ApiState, create_app
    from cake_tpu.fleet import (FleetRouter, MembershipPolicy,
                                ReplicaRegistry, create_router_app)

    async def run_mode(affinity: bool) -> dict:
        engines, runners = [], []
        registry = ReplicaRegistry(MembershipPolicy())
        for i in range(2):
            eng = ServeEngine(model, slots=2, max_queue=32,
                              ctx_len=FLEET_CTX,
                              prefill_chunk=CHUNK, prefix_cache_mb=64)
            engines.append(eng)
            state = ApiState(model=model, tokenizer=FleetTok(),
                             model_id=f"bench-r{i}")
            state.engine = eng
            runner = aioweb.AppRunner(create_app(state))
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            port = site._server.sockets[0].getsockname()[1]
            registry.add(f"r{i}", f"http://127.0.0.1:{port}")
        router = FleetRouter(registry, retries=1, backoff_s=0.01,
                             probe_s=5.0, hedge_ms=0.0,
                             affinity=affinity)
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        import aiohttp
        stats_session = aiohttp.ClientSession()

        async def first_token_s(messages) -> dict:
            """Route one streamed turn through the ROUTER, then read the
            serving replica's engine-reported stats (/api/v1/stats,
            matched by completion id): engine ttft_s covers queue +
            prefill + first decode without the HTTP/poll quantization
            (~20ms on this box) that would otherwise drown the
            chunk-level prefill cost the bench compares, and
            prefix_hit_tokens shows the warm-admission mechanism
            directly."""
            cid = None
            buf = b""
            async with client.post("/v1/chat/completions", json={
                    "messages": messages, "stream": True,
                    "max_tokens": FLEET_MAX_NEW,
                    "temperature": 0.0}) as r:
                assert r.status == 200, await r.text()
                async for piece in r.content.iter_any():
                    buf += piece
                    # parse only once a COMPLETE event arrived — a TCP
                    # piece can end mid-JSON
                    if cid is None and b"\n\n" in buf:
                        first = buf.split(b"\n\n", 1)[0]
                        cid = json.loads(
                            first.split(b"data: ", 1)[1])["id"]
            assert cid is not None, "stream carried no completion id"
            for rep in registry.replicas():
                async with stats_session.get(
                        rep.base_url + "/api/v1/stats") as sr:
                    stats = (await sr.json()).get("stats") or {}
                # the router injects a trace id that becomes the
                # replica's request_id; the OpenAI completion id rides
                # along as completion_id — match on either so the bench
                # works with and without a fronting router
                if cid in (stats.get("request_id"),
                           stats.get("completion_id")):
                    return {"ttft_s": stats["ttft_s"],
                            "prefix_hit_tokens":
                                stats.get("prefix_hit_tokens", 0)}
            raise AssertionError(f"no replica reported stats for {cid}")

        try:
            for c in range(3):                  # untimed compile warmup
                for t in range(3):
                    await first_token_s(_fleet_messages(90 + c, t))
            # fixed-seed shuffled arrival order: real users interleave
            # arbitrarily. (Turn-major order would stride requests by
            # convo count — an even stride over 2 replicas makes plain
            # round-robin accidentally convo-sticky, hiding exactly the
            # effect this bench measures. Out-of-order turns still warm
            # correctly: a later turn's prompt CONTAINS every earlier
            # turn's prompt as a prefix, so whichever lands first
            # inserts the blocks the other hits.)
            import random as _random
            order = [(c, t) for t in range(FLEET_TURNS)
                     for c in range(FLEET_CONVOS)]
            _random.Random(7).shuffle(order)
            opening, followup = [], []
            for c, t in order:
                s = await first_token_s(_fleet_messages(c, t))
                (opening if t == 0 else followup).append(s)
            hits = sum((e.health().get("prefix_cache") or {})
                       .get("hits", 0) for e in engines)
            fu = [s["ttft_s"] for s in followup]
            return {
                "mode": "affinity" if affinity else "round_robin",
                "opening_ttft_p50_s": round(
                    _pctl([s["ttft_s"] for s in opening], 0.5), 5),
                "followup_ttft_p50_s": round(_pctl(fu, 0.5), 5),
                "followup_ttft_p99_s": round(_pctl(fu, 0.99), 5),
                "followup_ttft_mean_s": round(statistics.mean(fu), 5),
                "followup_prefix_hit_tokens": sum(
                    s["prefix_hit_tokens"] for s in followup),
                "prefix_cache_hits": hits,
            }
        finally:
            await stats_session.close()
            await client.close()
            for runner in runners:
                await runner.cleanup()
            for eng in engines:
                eng.close()

    aff = asyncio.new_event_loop().run_until_complete(run_mode(True))
    rr = asyncio.new_event_loop().run_until_complete(run_mode(False))
    return {
        "affinity": aff,
        "round_robin": rr,
        "followup_speedup_p50": round(
            rr["followup_ttft_p50_s"] / aff["followup_ttft_p50_s"], 3),
        "affinity_wins": aff["followup_ttft_p50_s"]
        < rr["followup_ttft_p50_s"],
    }


RESUME_ITERS = 4
RESUME_MAX_NEW = 24


def bench_fleet_resume(model):
    """Self-healing stream cost (ISSUE 15): for a mid-stream break that
    the router heals transparently, measure the client-visible SPLICE
    GAP — the largest inter-chunk arrival gap of the healed stream,
    which is where break detection + the continuation splice + the
    survivor's prefill all hide — against the COLD alternative a manual
    client retry pays. The honest retry baseline is CATCH-UP time: a
    naive re-issue prefills from scratch AND regenerates every token
    the client already had before it produces the first NEW one; the
    splice skips the regeneration entirely (the partial is prefilled,
    not decoded). Cold TTFR alone is also recorded for scale."""
    import asyncio

    from aiohttp import web as aioweb
    from aiohttp.test_utils import TestClient, TestServer

    from cake_tpu.api import ApiState, create_app
    from cake_tpu.fleet import (FleetRouter, MembershipPolicy,
                                ReplicaRegistry, create_router_app)
    from cake_tpu.fleet import faults as fleet_faults
    from cake_tpu.serve import faults as serve_faults

    # streamed chunks decode per-token through the MODEL's tokenizer
    model.tokenizer = FleetTok()

    async def run() -> dict:
        engines, runners = [], []
        # breaks are injected on purpose: keep the detector from
        # ejecting the target replica mid-bench
        registry = ReplicaRegistry(MembershipPolicy(eject_fails=100,
                                                    err_rate=1.1))
        for i in range(2):
            eng = ServeEngine(model, slots=2, max_queue=32,
                              ctx_len=FLEET_CTX,
                              prefill_chunk=CHUNK, prefix_cache_mb=64)
            engines.append(eng)
            state = ApiState(model=model, tokenizer=FleetTok(),
                             model_id=f"bench-rs{i}")
            state.engine = eng
            runner = aioweb.AppRunner(create_app(state))
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            port = site._server.sockets[0].getsockname()[1]
            registry.add(f"r{i}", f"http://127.0.0.1:{port}")
        router = FleetRouter(registry, retries=1, backoff_s=0.01,
                             probe_s=5.0, hedge_ms=0.0, affinity=True,
                             stream_resumes=1)
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        async def stream(convo: int, max_new: int):
            """(t_post, content_arrival_times, rid, error_seen)."""
            times = []
            buf = b""
            t_post = time.perf_counter()
            async with client.post("/v1/chat/completions", json={
                    "messages": _fleet_messages(convo, 0),
                    "stream": True, "max_tokens": max_new,
                    "temperature": 0.0}) as r:
                assert r.status == 200, await r.text()
                rid = r.headers.get("X-Cake-Request-Id")
                async for piece in r.content.iter_any():
                    buf += piece
                    while b"\n\n" in buf:
                        ev, buf = buf.split(b"\n\n", 1)
                        if not ev.startswith(b"data: "):
                            continue
                        pl = ev[6:].strip()
                        if pl == b"[DONE]":
                            continue
                        obj = json.loads(pl)
                        if "error" in obj:
                            return t_post, times, rid, True
                        d = obj["choices"][0]["delta"]
                        if d.get("content"):
                            times.append(time.perf_counter())
            return t_post, times, rid, False

        def owner_of(rid: str) -> str:
            tl = router.timelines.get(rid)
            return next(e["replica"] for e in tl["events"]
                        if e["kind"] == "commit")

        gaps, colds, catchups = [], [], []
        healed = 0
        serve_faults.install("delay_ms=15")     # keep breaks mid-stream
        try:
            await stream(900, 6)                # compile warmup
            for i in range(RESUME_ITERS):
                convo = 910 + i
                _, _, rid, _ = await stream(convo, 4)   # probe the owner
                fleet_faults.install(
                    f"replica={owner_of(rid)};break_stream_after=6;"
                    "break_times=1")
                try:
                    _, times, rid, err = await stream(convo,
                                                      RESUME_MAX_NEW)
                finally:
                    fleet_faults.clear()
                if err or len(times) < 3:
                    continue
                healed += 1
                deltas = [b - a for a, b in zip(times, times[1:])]
                gap_at = max(range(len(deltas)), key=deltas.__getitem__)
                gaps.append(deltas[gap_at])
                # cold retry baseline: fresh conversation, full prefill,
                # and it must REGENERATE the gap_at+1 tokens the broken
                # stream had already delivered before the first new one
                t0, ctimes, _, _ = await stream(950 + i, RESUME_MAX_NEW)
                if len(ctimes) > gap_at + 1:
                    colds.append(ctimes[0] - t0)
                    catchups.append(ctimes[gap_at + 1] - t0)
            return {
                "iters": RESUME_ITERS,
                "healed": healed,
                "splice_gap_p50_s": round(_pctl(gaps, 0.5), 5),
                "splice_gap_max_s": round(max(gaps), 5),
                "cold_ttfr_p50_s": round(_pctl(colds, 0.5), 5),
                "cold_catchup_p50_s": round(_pctl(catchups, 0.5), 5),
                "resume_beats_cold_retry":
                    _pctl(gaps, 0.5) < _pctl(catchups, 0.5),
            }
        finally:
            serve_faults.clear()
            await client.close()
            for runner in runners:
                await runner.cleanup()
            for eng in engines:
                eng.close()

    return asyncio.new_event_loop().run_until_complete(run())


KVSHARE_ITERS = 5
KVSHARE_MAX_NEW = 6
KVSHARE_PREFIX_WORDS = 96   # ~6 share units of prefill to fetch vs redo


def _kvshare_messages(tag: str, i: int, user: str) -> list:
    """A LONG per-iteration system prompt (the shared prefix under
    test — fresh words each iteration so the cold replica is genuinely
    cold for it) plus a short user turn."""
    return [{"role": "system", "content": " ".join(
        f"{tag}{i}word{w}" for w in range(KVSHARE_PREFIX_WORDS))},
        {"role": "user", "content": user}]


def bench_kvshare(model):
    """Fleet-shared KV fetch economics (ISSUE 20): the same
    long-shared-prefix follow-up answered three ways by the same cold
    replica — COLD-FETCH (an X-Cake-KV-Peers directory names a warm
    peer; the replica pulls the prefix blob and splices), COLD-RECOMPUTE
    (no directory: the honest full prefill the fetch replaces), and
    LOCAL-WARM (the fetch-installed chain hit again locally — the floor
    a fetch converges to). Two timings per request: client wall time
    (includes the fetch wire cost — the engine can't see it) and the
    engine's own ttft_s. The directory header is hand-built here to
    isolate replica-side fetch cost from router scheduling; the
    router-injected path is gated end-to-end by `make kvshare-smoke`.
    Deterministic gate: every fetch splices prefix tokens
    (prefix_hit_tokens > 0) and every recompute splices none."""
    import asyncio

    import aiohttp
    from aiohttp import web as aioweb

    from cake_tpu.api import ApiState, create_app
    from cake_tpu.fleet.kvshare import KV_DIR_HEADER, encode_directory

    model.tokenizer = FleetTok()
    # create_app wires KVShareReplica only under the knob (env is read
    # live, nothing is snapshotted at import) — flip it for the bench
    # and restore after, so the default benches keep measuring stock
    # replicas
    # lint: disable=knob-registry — saving/restoring the raw env SLOT
    # (set vs unset), not reading the knob's value; knobs.get would
    # parse away the distinction the restore needs
    prev = os.environ.get("CAKE_KVSHARE")
    os.environ["CAKE_KVSHARE"] = "1"

    async def run() -> dict:
        states, runners, urls = [], [], []
        for name in ("warm", "cold"):
            eng = ServeEngine(model, slots=2, max_queue=32,
                              ctx_len=FLEET_CTX, prefill_chunk=CHUNK,
                              kv_blocks=96, kv_block_tokens=8,
                              prefix_cache_mb=64)
            state = ApiState(model=model, tokenizer=FleetTok(),
                             model_id=f"bench-kv-{name}")
            state.engine = eng
            states.append(state)
            runner = aioweb.AppRunner(create_app(state))
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            port = site._server.sockets[0].getsockname()[1]
            urls.append(f"http://127.0.0.1:{port}")
        warm_state, cold_state = states
        warm_url, cold_url = urls
        assert warm_state.kvshare is not None, \
            "CAKE_KVSHARE did not wire the replicas"
        session = aiohttp.ClientSession()

        async def chat(url: str, messages: list,
                       directory: str | None = None) -> dict:
            """One blocking chat; returns client wall seconds + the
            serving engine's own stats snapshot for the request."""
            hdrs = {KV_DIR_HEADER: directory} if directory else {}
            t0 = time.perf_counter()
            async with session.post(
                    url + "/v1/chat/completions",
                    json={"messages": messages,
                          "max_tokens": KVSHARE_MAX_NEW,
                          "temperature": 0.0},
                    headers=hdrs) as r:
                body = await r.json()
                assert r.status == 200, body
                wall = time.perf_counter() - t0
            async with session.get(url + "/api/v1/stats") as sr:
                stats = (await sr.json()).get("stats") or {}
            assert stats.get("completion_id") == body["id"], \
                (stats, body["id"])
            return {"wall_s": wall, "ttft_s": stats["ttft_s"],
                    "prefix_hit_tokens":
                        stats.get("prefix_hit_tokens", 0)}

        async def warm_chains(n_before: int) -> list:
            """Wait for the warm replica's inventory to grow past
            `n_before` entries (the insert runs inside the scheduler
            step — nudge it awake while polling)."""
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                chains = warm_state.kvshare.health_view()["chains"]
                if len(chains) > n_before or len(chains) >= 32:
                    return list(chains)
                warm_state.engine._wake.set()
                await asyncio.sleep(0.02)
            raise AssertionError("warm replica advertised no new chains")

        fetch, recompute, local = [], [], []
        try:
            # untimed warmup: compile every chunk/slot bucket both sides
            await chat(warm_url, _kvshare_messages("wa", 99, "warmup"))
            await chat(cold_url, _kvshare_messages("wb", 99, "warmup"))
            for i in range(KVSHARE_ITERS):
                n0 = len(warm_state.kvshare.health_view()["chains"])
                await chat(warm_url,
                           _kvshare_messages("p", i, "opening turn"))
                chains = await warm_chains(n0)
                directory = encode_directory([(warm_url, chains)])
                s = await chat(cold_url,
                               _kvshare_messages("p", i, "follow up one"),
                               directory=directory)
                assert s["prefix_hit_tokens"] > 0, \
                    f"iter {i}: fetch spliced no prefix tokens: {s}"
                fetch.append(s)
                s = await chat(cold_url,
                               _kvshare_messages("q", i, "follow up one"))
                assert s["prefix_hit_tokens"] == 0, \
                    f"iter {i}: recompute baseline was not cold: {s}"
                recompute.append(s)
                s = await chat(cold_url,
                               _kvshare_messages("p", i, "follow up two"))
                assert s["prefix_hit_tokens"] > 0, \
                    f"iter {i}: fetched chain missed locally: {s}"
                local.append(s)

            def mode(rows: list) -> dict:
                return {
                    "wall_p50_s": round(
                        _pctl([r["wall_s"] for r in rows], 0.5), 5),
                    "ttft_p50_s": round(
                        _pctl([r["ttft_s"] for r in rows], 0.5), 5),
                    "prefix_hit_tokens": sum(
                        r["prefix_hit_tokens"] for r in rows),
                }
            out = {
                "iters": KVSHARE_ITERS,
                "prefix_words": KVSHARE_PREFIX_WORDS,
                "cold_fetch": mode(fetch),
                "cold_recompute": mode(recompute),
                "local_warm": mode(local),
            }
            out["fetch_beats_recompute"] = (
                out["cold_fetch"]["wall_p50_s"]
                < out["cold_recompute"]["wall_p50_s"])
            out["every_fetch_spliced"] = True
            return out
        finally:
            await session.close()
            for runner in runners:
                await runner.cleanup()
            for state in states:
                state.engine.close()

    try:
        return asyncio.new_event_loop().run_until_complete(run())
    finally:
        if prev is None:
            os.environ.pop("CAKE_KVSHARE", None)
        else:
            os.environ["CAKE_KVSHARE"] = prev


def bench_qos(model):
    """Mixed-workload QoS section: (1) weighted-fair service shares out
    of a saturated class-aware queue (pure scheduler — deterministic),
    (2) interactive chat TTFT through the engine, idle vs saturated by
    a flood of batch diffusion-stub jobs on the plane's executor, (3)
    batch job throughput under that interleaving. The TTFT gate mirrors
    qos-smoke: saturated p50 within 2x the idle baseline (50 ms floor
    on this shared CPU box)."""
    import jax.numpy as jnp2
    from cake_tpu.serve.admission import (AdmissionQueue, GenerationJob,
                                          JobExecutor)
    from types import SimpleNamespace

    # -- (1) service shares: 3 saturated lanes, 2 full DRR rounds
    q = AdmissionQueue(64, weights={"interactive": 8.0, "standard": 4.0,
                                    "batch": 1.0})
    for _ in range(30):
        for cls in ("interactive", "standard", "batch"):
            q.put(SimpleNamespace(qos=cls))
    served = [q.pop().qos for _ in range(26)]
    shares = {c: served.count(c) for c in
              ("interactive", "standard", "batch")}
    q.drain()

    # -- (2) idle interactive TTFT
    eng = ServeEngine(model, slots=2, max_queue=16, ctx_len=CTX,
                      prefill_chunk=CHUNK, prefix_cache_mb=0)
    prompts = _prompts()

    def chat_ttfts(n, phase):
        ttfts = []
        for i in range(n):
            r = eng.submit(prompts[i % len(prompts)], max_new_tokens=4,
                           sampling=GREEDY, qos="interactive")
            assert r.wait(300), f"{phase} chat timed out"
            assert "error" not in r.result, r.result.get("error")
            ttfts.append(r.stats["ttft_s"])
        return ttfts

    w = jnp2.ones((64, 64), jnp2.float32)

    def stub_job(job):
        x = jnp2.ones((64, 64), jnp2.float32)
        for _ in range(24):
            x = jnp2.tanh(x @ w * 1e-3)
            x.block_until_ready()
            time.sleep(0.002)
            job.checkpoint()
        return True

    try:
        chat_ttfts(2, "warmup")
        idle = chat_ttfts(8, "idle")
        # -- (3) saturate with batch jobs, interleave interactive chat
        ex = JobExecutor(workers=1, max_queue=32)
        t0 = time.monotonic()
        jobs = [ex.submit(GenerationJob("image", stub_job, qos="batch"))
                for _ in range(10)]
        try:
            sat = chat_ttfts(8, "saturated")
            for j in jobs:
                assert j.wait(300), "batch job timed out"
                assert "error" not in j.result, j.result.get("error")
            jobs_wall = time.monotonic() - t0
        finally:
            ex.close()
    finally:
        eng.close()
    idle_p50, sat_p50 = _pctl(idle, 0.5), _pctl(sat, 0.5)
    baseline = max(idle_p50, 0.05)
    return {
        "service_shares_2_rounds": shares,
        "idle_ttft_p50_s": round(idle_p50, 5),
        "idle_ttft_p95_s": round(_pctl(idle, 0.95), 5),
        "saturated_ttft_p50_s": round(sat_p50, 5),
        "saturated_ttft_p95_s": round(_pctl(sat, 0.95), 5),
        "gate_ratio": round(sat_p50 / baseline, 3),
        "batch_jobs": len(jobs),
        "batch_jobs_per_s": round(len(jobs) / jobs_wall, 3),
        "qos_protected": sat_p50 <= 2.0 * baseline,
    }


TELEM_GATE_MS = 5.0


def bench_telemetry():
    """Telemetry rollup overhead: synthetic fleet scrapes driven through
    FleetTelemetry.ingest — the exact per-probe-cycle work the router
    does (parse N expositions, fold histogram rings, recompute burn /
    headroom / percentiles / outliers + export gauges) with the network
    taken out. A fake clock steps one probe interval per cycle so the
    windows behave like an hour of real probing; rollup_ms is measured
    on the real clock inside ingest. Gate: mean < TELEM_GATE_MS."""
    from cake_tpu.fleet import MembershipPolicy, ReplicaRegistry
    from cake_tpu.fleet.telemetry import FleetTelemetry
    from cake_tpu.obs.metrics import LATENCY_BUCKETS

    n_rep, cycles, probe_s = 8, 120, 1.0
    edges = [float(e) for e in LATENCY_BUCKETS]

    def scrape_text(rep: int, cycle: int) -> str:
        """One replica's /metrics as the rollup sees it: the three SLO
        histograms on the shared bucket grid, every gauge/counter family
        replica_signals() reduces, and padding families the parser must
        walk past — sized like a real exposition (~200 sample lines)."""
        c = cycle + 1
        lines = []
        for sem in ("ttft", "itl", "e2e"):
            cum = 0
            for j, e in enumerate(edges):
                cum += (j % 5) + 1 + rep
                lines.append(f'cake_serve_{sem}_seconds_bucket'
                             f'{{le="{e}",outcome="ok"}} {cum * c}')
            lines.append(f'cake_serve_{sem}_seconds_bucket'
                         f'{{le="+Inf",outcome="ok"}} {(cum + 2) * c}')
            lines.append(f'cake_serve_{sem}_seconds_count'
                         f'{{outcome="ok"}} {(cum + 2) * c}')
        lines.append(f'cake_serve_e2e_seconds_count{{outcome="error"}} {c}')
        lines.append(f'cake_generated_tokens_total{{path="serve"}} '
                     f'{40 * c * (rep + 1)}')
        lines.append(f'cake_serve_queue_depth {rep % 3}')
        lines.append(f'cake_serve_slots_busy {1 + rep % 3}')
        lines.append('cake_serve_kv_blocks_free 48')
        lines.append('cake_serve_kv_blocks_used 16')
        lines.append(f'cake_serve_spec_proposed_total {30 * c}')
        lines.append(f'cake_serve_spec_accepted_total {24 * c}')
        for i in range(140):            # realistic non-signal bulk
            lines.append(f'cake_api_requests_total{{endpoint="/e{i}",'
                         f'status="200"}} {c * (i + 1)}')
        return "\n".join(lines) + "\n"

    reg = ReplicaRegistry(MembershipPolicy(
        eject_fails=2, err_window=16, err_rate=0.5,
        degraded_ttft_ms=0.0, eject_s=0.3))
    for i in range(n_rep):
        rep = reg.add(f"bench{i}", f"http://bench:{i + 1}")
        rep.observe_health(200, {"engine": {"alive": True, "slots": 4,
                                            "queue_depth": 1}})
    fake_t = [1000.0]
    tel = FleetTelemetry(reg, clock=lambda: fake_t[0],
                         fast_window_s=300.0, slow_window_s=3600.0,
                         outlier_min_n=3)
    per_cycle_ms = []
    for c in range(cycles):
        fake_t[0] += probe_s
        body = tel.ingest({f"bench{i}": scrape_text(i, c)
                           for i in range(n_rep)})
        per_cycle_ms.append(body["rollup_ms"]["last"])
    # sanity: the synthetic fleet actually exercised the full rollup
    assert body["percentiles"]["ttft"]["count"] > 0, body["percentiles"]
    assert body["headroom_tokens_per_s"] is not None
    assert body["burn_rate"]["fast"] is not None
    warm = per_cycle_ms[2:]             # first cycles pay ring setup
    mean_ms = statistics.mean(warm)
    return {
        "replicas": n_rep,
        "cycles": cycles,
        "exposition_lines": len(scrape_text(0, 0).splitlines()),
        "rollup_ms_mean": round(mean_ms, 3),
        "rollup_ms_p50": round(_pctl(warm, 0.50), 3),
        "rollup_ms_p99": round(_pctl(warm, 0.99), 3),
        "rollup_ms_max": round(max(per_cycle_ms), 3),
        "gate_ms": TELEM_GATE_MS,
        "gate_ok": mean_ms < TELEM_GATE_MS,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tag", default="local")
    ap.add_argument("--out", default=None)
    ap.add_argument("--long-tail", action="store_true",
                    help="paged-pool mode: mixed short/long contexts, "
                    "records occupancy + preemptions instead of the "
                    "TTFT/interference benches")
    ap.add_argument("--spec", action="store_true",
                    help="batched-speculation mode: acceptance x "
                    "occupancy x effective tok/s, spec on vs off, "
                    "contiguous + paged engines")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: 2 replicas + router, follow-up "
                    "TTFT under prefix-affinity routing vs round-robin, "
                    "plus the self-healing stream splice gap vs a cold "
                    "restart")
    ap.add_argument("--qos", action="store_true",
                    help="QoS mode: weighted-fair service shares + "
                    "interactive TTFT idle vs batch-job saturation")
    ap.add_argument("--kvshare", action="store_true",
                    help="fleet-shared KV mode: cold-fetch (peer prefix "
                    "blob) vs cold-recompute vs local-warm TTFT on a "
                    "long shared-prefix follow-up")
    ap.add_argument("--telemetry", action="store_true",
                    help="telemetry mode: per-probe-cycle rollup "
                    "overhead through FleetTelemetry.ingest on "
                    "synthetic fleet scrapes, gated < 5 ms mean")
    args = ap.parse_args()

    if args.telemetry:
        out = {
            "bench": "fleet-telemetry",
            "ts": int(time.time()),
            "config": {"replicas": 8, "cycles": 120,
                       "fast_window_s": 300.0, "slow_window_s": 3600.0,
                       "platform": "cpu"},
            "telemetry": bench_telemetry(),
        }
        path = args.out or f"BENCH_TELEM_{args.tag}.json"
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps(out, indent=2))
        print(f"\nwrote {path}", file=sys.stderr)
        if not out["telemetry"]["gate_ok"]:
            print(f"FAIL: telemetry rollup mean "
                  f"{out['telemetry']['rollup_ms_mean']}ms >= "
                  f"{TELEM_GATE_MS}ms per probe cycle", file=sys.stderr)
            return 1
        return 0

    if args.qos:
        model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                          max_cache_len=CTX)
        out = {
            "bench": "serve-qos",
            "ts": int(time.time()),
            "config": {"ctx": CTX, "prefill_chunk": CHUNK,
                       "weights": {"interactive": 8, "standard": 4,
                                   "batch": 1},
                       "job_workers": 1, "platform": "cpu-tiny"},
            "qos": bench_qos(model),
        }
        path = args.out or f"BENCH_QOS_{args.tag}.json"
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps(out, indent=2))
        print(f"\nwrote {path}", file=sys.stderr)
        if not out["qos"]["qos_protected"]:
            print(f"FAIL: saturated interactive TTFT p50 ratio "
                  f"{out['qos']['gate_ratio']} > 2x idle baseline",
                  file=sys.stderr)
            return 1
        return 0

    if args.kvshare:
        model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                          max_cache_len=FLEET_CTX)
        out = {
            "bench": "serve-kvshare",
            "ts": int(time.time()),
            "config": {"ctx": FLEET_CTX, "prefill_chunk": CHUNK,
                       "kv_blocks": 96, "kv_block_tokens": 8,
                       "iters": KVSHARE_ITERS,
                       "prefix_words": KVSHARE_PREFIX_WORDS,
                       "platform": "cpu-tiny"},
            "kvshare": bench_kvshare(model),
        }
        path = args.out or f"BENCH_KVSHARE_{args.tag}.json"
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps(out, indent=2))
        print(f"\nwrote {path}", file=sys.stderr)
        kv = out["kvshare"]
        # deterministic gate: splice accounting (hit tokens) cannot
        # flake; wall-clock comparisons are advisory on a noisy CPU box
        if not kv["every_fetch_spliced"]:
            print("FAIL: a directory-driven fetch spliced no prefix "
                  "tokens", file=sys.stderr)
            return 1
        if not kv["fetch_beats_recompute"]:
            print("warning: cold-fetch wall p50 did not beat "
                  "cold-recompute this run (wall-clock noise)",
                  file=sys.stderr)
        return 0

    if args.fleet:
        model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                          max_cache_len=FLEET_CTX)
        out = {
            "bench": "serve-fleet",
            "ts": int(time.time()),
            "config": {"ctx": FLEET_CTX, "prefill_chunk": CHUNK,
                       "replicas": 2, "convos": FLEET_CONVOS,
                       "turns": FLEET_TURNS, "platform": "cpu-tiny"},
            "fleet": bench_fleet(model),
        }
        out["fleet"]["resume"] = bench_fleet_resume(model)
        path = args.out or f"BENCH_FLEET_{args.tag}.json"
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps(out, indent=2))
        print(f"\nwrote {path}", file=sys.stderr)
        fl = out["fleet"]
        # hard gate on the DETERMINISTIC signal (routing is a pure
        # function of the shuffled workload, so hit tokens cannot
        # flake); wall-clock TTFT is advisory on a noisy CPU box
        if not (fl["affinity"]["followup_prefix_hit_tokens"]
                > fl["round_robin"]["followup_prefix_hit_tokens"]):
            print("FAIL: affinity routing reused no more prefix tokens "
                  "than round-robin", file=sys.stderr)
            return 1
        if not fl["affinity_wins"]:
            print("warning: affinity follow-up TTFT p50 did not beat "
                  "round-robin this run (wall-clock noise)",
                  file=sys.stderr)
        rs = fl["resume"]
        if rs["healed"] == 0:
            print("FAIL: no mid-stream break was healed in the resume "
                  "bench", file=sys.stderr)
            return 1
        if not rs["resume_beats_cold_retry"]:
            print("warning: splice gap did not beat the cold catch-up "
                  "baseline this run (wall-clock noise)",
                  file=sys.stderr)
        return 0

    if args.spec:
        model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                          max_cache_len=SPEC_CTX)
        out = {
            "bench": "serve-spec",
            "ts": int(time.time()),
            "config": {"ctx": SPEC_CTX, "spec_k": SPEC_K,
                       "drafter": "ngram", "platform": "cpu-tiny"},
            "spec": bench_spec(model),
        }
        path = args.out or f"BENCH_SERVE_{args.tag}.json"
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps(out, indent=2))
        print(f"\nwrote {path}", file=sys.stderr)
        sp = out["spec"]
        if not all(c["bit_identical"] for c in sp["contiguous"]) \
                or not sp["paged"]["bit_identical"]:
            print("FAIL: spec-on output differs from spec-off",
                  file=sys.stderr)
            return 1
        if not sp["speculation_pays"]:
            print(f"FAIL: best effective speedup "
                  f"{sp['best_effective_speedup']} < 1.3x", file=sys.stderr)
            return 1
        return 0

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    if args.long_tail:
        out = {
            "bench": "serve-long-tail",
            "ts": int(time.time()),
            "config": {"ctx": CTX, "prefill_chunk": CHUNK,
                       "platform": "cpu-tiny"},
            "long_tail": bench_long_tail(model),
        }
        path = args.out or f"BENCH_SERVE_{args.tag}.json"
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps(out, indent=2))
        print(f"\nwrote {path}", file=sys.stderr)
        return 0
    out = {
        "bench": "serve",
        "ts": int(time.time()),
        "config": {"ctx": CTX, "prefill_chunk": CHUNK,
                   "system_tokens": len(SYSTEM), "requests": N_REQ,
                   "max_new_tokens": MAX_NEW, "platform": "cpu-tiny"},
        "prefix_reuse": bench_cold_vs_warm(model),
        "admission_interference": bench_admission_interference(model),
    }
    path = args.out or f"BENCH_SERVE_{args.tag}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    print(f"\nwrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
