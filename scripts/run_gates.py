"""Run every autoresearch brief's benchmark.sh gate and collect the
one-line JSON results into a single artifact (GATES_r{N}.json).

The reference treats its bench harness as a regression contract
(/root/reference/cake-core/benches/, 23 divan modules + autoresearch/
briefs); this is the equivalent sweep. Failures are recorded honestly —
a brief whose gate errors or times out appears with "error" set.

Usage:
  python scripts/run_gates.py --mode cpu --out GATES_r05.json
  python scripts/run_gates.py --mode tpu --out GATES_r05_tpu.json

cpu mode sets CAKE_BENCH_CPU=1 (every gate honors it) — validates the
gate logic without hardware; tpu mode runs on the default backend and is
the number that counts.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time


def run_gate(path: str, mode: str, timeout: int) -> dict:
    env = dict(os.environ)
    if mode == "cpu":
        env["CAKE_BENCH_CPU"] = "1"
    else:
        # an inherited CAKE_BENCH_CPU=1 would silently turn the TPU
        # artifact ("the number that counts") into CPU smoke numbers
        env.pop("CAKE_BENCH_CPU", None)
    t0 = time.monotonic()
    # own process group: on timeout we must kill the python grandchild
    # too, or it keeps the captured pipes open (communicate() then blocks
    # forever) and keeps the TPU busy for every later gate
    proc = subprocess.Popen(["sh", path], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, start_new_session=True,
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
    try:
        out, err = proc.communicate(timeout=timeout)
        r = subprocess.CompletedProcess(proc.args, proc.returncode, out, err)
    except subprocess.TimeoutExpired:
        import signal
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.communicate()
        return {"error": f"timeout after {timeout}s",
                "wall_s": round(time.monotonic() - t0, 1)}
    wall = round(time.monotonic() - t0, 1)
    # gates print one JSON object per line; keep every parseable line
    # (bench_micro sweeps print several)
    rows = []
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    if not rows:
        tail = (r.stderr or r.stdout).strip().splitlines()
        return {"error": tail[-1][:200] if tail else f"exit {r.returncode}",
                "exit": r.returncode, "wall_s": wall}
    out = {"wall_s": wall}
    if r.returncode != 0:
        out["exit"] = r.returncode
    out["result"] = rows[0] if len(rows) == 1 else rows
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["cpu", "tpu"], default="cpu")
    ap.add_argument("--out", default="")
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--filter", default="")
    args = ap.parse_args()

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gates = sorted(glob.glob(os.path.join(root, "autoresearch", "*", "*",
                                          "benchmark.sh")))
    results = {}
    for g in gates:
        brief = "/".join(g.split(os.sep)[-3:-1])
        if args.filter and args.filter not in brief:
            continue
        print(f"[gates] {brief} ...", file=sys.stderr, flush=True)
        results[brief] = run_gate(g, args.mode, args.timeout)
        print(f"[gates] {brief}: "
              f"{json.dumps(results[brief])[:160]}", file=sys.stderr,
              flush=True)
    def gate_ok(r: dict) -> bool:
        if "error" in r or r.get("exit"):
            return False
        rows = r.get("result", {})
        rows = rows if isinstance(rows, list) else [rows]
        # bench_micro-style sweeps exit 0 but report per-bench errors
        return not any("error" in row for row in rows)

    payload = {"mode": args.mode, "gates": results,
               "n_ok": sum(1 for r in results.values() if gate_ok(r)),
               "n_total": len(results)}
    line = json.dumps(payload)
    if args.out:
        with open(os.path.join(root, args.out), "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
