#!/usr/bin/env python
"""Hot-path timing lint: fail if ad-hoc wall-clock calls reappear in
serving hot paths outside cake_tpu/obs/.

The observability subsystem (cake_tpu/obs) is the single owner of
wall-clock deltas on hot paths: stats code uses obs.now(), phase
accounting uses obs.PhaseTimer / RECORDER.span. Before it existed, three
ad-hoc idioms (time.monotonic deltas in master/worker, PhaseTimer in
utils.tracing, fwd_ms plumbing) drifted apart; this check keeps new ones
from creeping back in. Run via `make obs-smoke` or directly.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# serving hot paths: every per-token / per-message code path. cli/tui/
# image pipelines and discovery keep plain time.* — they are not hot.
HOT_PATHS = [
    "cake_tpu/models/common/text_model.py",
    "cake_tpu/models/common/offload_model.py",
    "cake_tpu/cluster/master.py",
    "cake_tpu/cluster/worker.py",
    "cake_tpu/cluster/client.py",
    "cake_tpu/cluster/proto.py",
    "cake_tpu/api/state.py",
]

BANNED = ("time.monotonic(", "time.time(", "time.perf_counter(")


def main() -> int:
    bad = []
    for rel in HOT_PATHS:
        path = os.path.join(REPO, rel)
        if not os.path.exists(path):
            print(f"[check_hot_timing] warning: {rel} missing", file=sys.stderr)
            continue
        with open(path) as f:
            for i, line in enumerate(f, 1):
                if any(tok in line for tok in BANNED):
                    bad.append(f"{rel}:{i}: {line.strip()}")
    if bad:
        print("ad-hoc wall-clock calls on hot paths — route them through "
              "cake_tpu.obs (now() / PhaseTimer / RECORDER.span):",
              file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    print(f"[check_hot_timing] ok: {len(HOT_PATHS)} hot-path files clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
