#!/usr/bin/env python
"""Observability smoke: a tiny CPU generation with tracing enabled must
yield (a) Prometheus text with non-zero TTFT/decode histograms, (b) a
Chrome-trace JSON that round-trips through json.loads with generation
spans. Exits non-zero on any missing signal. Run via `make obs-smoke`.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from cake_tpu import obs                                   # noqa: E402
from cake_tpu.models import (SamplingConfig, TextModel,    # noqa: E402
                             tiny_config)


def main() -> int:
    obs.RECORDER.enable()
    obs.RECORDER.clear()
    model = TextModel(tiny_config("qwen3"), max_cache_len=128)
    with obs.request_scope() as rid:
        toks, stats = model.generate([1, 2, 3, 4], max_new_tokens=8,
                                     sampling=SamplingConfig(temperature=0.0))
    assert toks, "generation produced no tokens"

    text = obs.REGISTRY.render()
    for needle in ("cake_ttft_seconds_count", "cake_decode_token_seconds_sum",
                   "cake_generated_tokens_total"):
        assert needle in text, f"/metrics missing {needle}"
    assert obs.TTFT_SECONDS.count() >= 1, "TTFT histogram empty"
    assert obs.DECODE_TOKEN_SECONDS.count() >= 1, "decode histogram empty"

    path = obs.RECORDER.export(
        os.path.join(tempfile.mkdtemp(prefix="cake-obs-"), "trace.json"))
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events, "trace export is empty"
    names = {e["name"] for e in events}
    assert "prefill" in names and "sample" in names, names
    assert all(e["args"]["request_id"] == rid
               for e in events if "args" in e
               and "request_id" in e["args"]), "request id not propagated"

    print(json.dumps({"obs_smoke": "ok", "tokens": len(toks),
                      "ttft_s": round(stats["ttft_s"], 4),
                      "trace_events": len(events), "trace_path": path}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
