#!/usr/bin/env python
"""Batched-speculation serve smoke: the API on a tiny CPU model with the
drafter-free n-gram mode must (a) answer CONCURRENT chats 200 through
the speculating engine — in PAGED KV mode, where speculation used to
stand down entirely, (b) produce greedy outputs bit-identical to a
spec-off engine for every client, (c) leave non-zero
cake_serve_spec_{proposed,accepted}_total counters in /metrics, and
(d) expose the spec block in the /health engine section. Exits non-zero
on any missing signal. Run via `make spec-serve-smoke`.
"""
from __future__ import annotations

import asyncio
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402


class NumTok:
    """Chat content is a space-separated token-id list — the smoke
    controls the exact prompt ids (repetitive, n-gram-friendly)."""

    def encode(self, text):
        return [int(w) for w in text.split() if w.isdigit()] or [3]

    def decode(self, ids):
        return " ".join(str(i) for i in ids)


# period-4 repetition: the n-gram drafter finds continuations and the
# batched verify gets real multi-token accepts (same prompt family the
# spec tests pin bit-parity on)
PROMPTS = [" ".join(str(t) for t in [a, b, 17, 23] * 4 + [a, b])
           for a, b in ((5, 9), (7, 11), (6, 13))]
MAX_NEW = 24


async def run_engine(model, **ekw) -> tuple[list[str], str, dict]:
    from aiohttp.test_utils import TestClient, TestServer
    engine = ServeEngine(model, slots=2, max_queue=8, ctx_len=128,
                         prefill_chunk=16, prefix_cache_mb=0, **ekw)
    state = ApiState(model=model, tokenizer=model.tokenizer,
                     model_id="spec-serve-smoke")
    state.engine = engine
    app = create_app(state)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        async def chat(content):
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": content}],
                "max_tokens": MAX_NEW, "temperature": 0.0})
            assert r.status == 200, await r.text()
            return (await r.json())["choices"][0]["message"]["content"]

        # concurrent clients through the speculating engine
        outs = list(await asyncio.gather(*[chat(p) for p in PROMPTS]))
        metrics = await (await client.get("/metrics")).text()
        health = engine.health()
        return outs, metrics, health
    finally:
        await client.close()
        engine.close()


def _metric(text, name):
    m = re.search(rf"^{name}(?:{{[^}}]*}})? ([0-9.e+-]+)$", text, re.M)
    return float(m.group(1)) if m else 0.0


async def main_async() -> int:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=128)
    model.tokenizer = NumTok()

    plain, _, _ = await run_engine(model, spec=False)
    # paged KV + n-gram speculation: the combination that used to stand
    # down (12 x 8-token blocks comfortably hold 2 x ~42-token streams)
    spec, metrics, health = await run_engine(
        model, spec="ngram", spec_k=6, kv_blocks=24, kv_block_tokens=8)

    proposed = _metric(metrics, "cake_serve_spec_proposed_total")
    accepted = _metric(metrics, "cake_serve_spec_accepted_total")
    checks = {
        "bit_identical": spec == plain,
        "spec_block_in_health": "spec" in health
        and health["spec"]["mode"] == "batched",
        "paged_pool_active": "kv_pool" in health,
        "metrics_proposed": proposed > 0,
        "metrics_accepted": accepted > 0,
    }
    print(f"clients={len(PROMPTS)} proposed={proposed} accepted={accepted} "
          f"spec={health.get('spec')}")
    for k, ok in checks.items():
        print(f"  {'ok' if ok else 'FAIL'}: {k}")
    if not all(checks.values()):
        return 1
    print("spec-serve smoke passed")
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main_async()))
