#!/usr/bin/env python
"""Chaos smoke: a master + 2 real workers on localhost (tiny CPU model,
weights streamed over TCP) must survive one worker being killed
mid-stream. The fault plan severs the master->w0 connection after 5
forward ops; the generation must still complete with greedy tokens
bit-identical to a fully-local run, with exactly one replay prefill, and
the recovery counters (cake_cluster_reconnects_total,
cake_cluster_replays_total) must be non-zero in /metrics. /health must be
back to 200 afterwards. Exits non-zero on any missing signal. Run via
`make chaos-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                     # noqa: E402

from cake_tpu.cluster import faults                         # noqa: E402
from cake_tpu.cluster.master import (DistributedTextModel,  # noqa: E402
                                     master_setup)
from cake_tpu.cluster.worker import WorkerServer            # noqa: E402
from cake_tpu.models import (SamplingConfig, TextModel,     # noqa: E402
                             init_params, tiny_config)
from cake_tpu.utils.export import params_to_hf_tensors      # noqa: E402
from cake_tpu.utils.safetensors_io import save_safetensors  # noqa: E402

GREEDY = SamplingConfig(temperature=0.0)
PROMPT = [1, 2, 3, 4, 5, 6, 7]
MAX_NEW = 10


def _write_model(tmp: str):
    cfg = tiny_config("qwen3")
    params = init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    mdir = os.path.join(tmp, "model")
    os.makedirs(mdir)
    save_safetensors(os.path.join(mdir, "model.safetensors"),
                     params_to_hf_tensors(cfg, params))
    with open(os.path.join(mdir, "config.json"), "w") as f:
        json.dump(dict(architectures=["Qwen3ForCausalLM"], vocab_size=256,
                       hidden_size=64, intermediate_size=128,
                       num_hidden_layers=4, num_attention_heads=4,
                       num_key_value_heads=2, rms_norm_eps=1e-5,
                       rope_theta=10000.0, max_position_embeddings=128,
                       eos_token_id=2), f)
    return cfg, params, mdir


def _start_worker(name: str, cache_root: str):
    ready = threading.Event()
    holder = {}

    def run():
        async def main():
            server = WorkerServer(name, "chaos", port=0,
                                  cache_root=cache_root, advertise=False)
            await server.start()
            holder["port"] = server.port
            holder["server"] = server
            ready.set()
            try:
                await server.serve_forever()
            except asyncio.CancelledError:
                pass

        loop = asyncio.new_event_loop()
        holder["loop"] = loop
        try:
            loop.run_until_complete(main())
        finally:
            loop.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert ready.wait(30), f"worker {name} never came up"
    holder["thread"] = t
    return holder


def _stop_worker(holder):
    loop, srv = holder.get("loop"), holder.get("server")
    if loop and srv and loop.is_running():
        try:
            asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(
                timeout=5)
        except Exception:
            pass
    holder["thread"].join(timeout=10)


async def _scrape(dist) -> dict:
    from aiohttp.test_utils import TestClient, TestServer
    from cake_tpu.api import ApiState, create_app

    client = TestClient(TestServer(create_app(
        ApiState(model=dist, model_id="chaos-smoke"))))
    await client.start_server()
    try:
        r = await client.get("/metrics")
        metrics = await r.text()
        h = await client.get("/health")
        return {"metrics": metrics, "health_status": h.status,
                "health": await h.json()}
    finally:
        await client.close()


def _metric_total(text: str, name: str) -> float:
    # sum across label sets: `name{...} v` and bare `name v`
    vals = re.findall(rf"^{name}(?:\{{[^}}]*\}})? (\S+)$", text, re.M)
    return sum(float(v) for v in vals)


def main() -> int:
    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        cfg, params, mdir = _write_model(tmp)
        local = TextModel(cfg, params, dtype=jnp.float32, max_cache_len=64)
        want, _ = local.generate(PROMPT, max_new_tokens=MAX_NEW,
                                 sampling=GREEDY)

        w0 = _start_worker("w0", os.path.join(tmp, "wc0"))
        w1 = _start_worker("w1", os.path.join(tmp, "wc1"))
        try:
            setup = master_setup(
                mdir, "chaos", cfg,
                workers=[
                    {"name": "w0", "host": "127.0.0.1", "port": w0["port"],
                     "caps": {"backend": "cpu", "device": "cpu",
                              "memory_bytes": 8 << 30, "tflops": 1.0}},
                    {"name": "w1", "host": "127.0.0.1", "port": w1["port"],
                     "caps": {"backend": "cpu", "device": "cpu",
                              "memory_bytes": 8 << 30, "tflops": 1.0}},
                ],
                assignments={"w0": (1, 2), "w1": (2, 4)},
                dtype_str="f32", max_cache_len=64)
            dist = DistributedTextModel(
                cfg, setup.master_params, setup.stages, dtype=jnp.float32,
                max_cache_len=64, recovery_retries=4,
                recovery_backoff_s=0.1, restore_interval_s=0.5)

            # kill w0's connection after 5 forward ops — mid-decode
            faults.install("w0:drop_after_ops=5")
            got, stats = dist.generate(PROMPT, max_new_tokens=MAX_NEW,
                                       sampling=GREEDY)
            assert got == want, (
                f"recovered generation diverged: {got} != {want}")
            assert stats["replays"] == 1, stats
            assert stats["recoveries"] == 1, stats
            faults.clear()

            scraped = asyncio.new_event_loop().run_until_complete(
                _scrape(dist))
            reconnects = _metric_total(scraped["metrics"],
                                       "cake_cluster_reconnects_total")
            replays = _metric_total(scraped["metrics"],
                                    "cake_cluster_replays_total")
            assert reconnects > 0, "no reconnects recorded in /metrics"
            assert replays > 0, "no replays recorded in /metrics"
            assert scraped["health_status"] == 200, scraped["health"]
            assert scraped["health"]["status"] == "ok"

            # and the recovered cluster serves the next request cleanly
            got2, stats2 = dist.generate(PROMPT, max_new_tokens=MAX_NEW,
                                         sampling=GREEDY)
            assert got2 == want and stats2["recoveries"] == 0

            out = {"chaos_smoke": "ok", "tokens": got,
                   "replays": stats["replays"],
                   "recoveries": stats["recoveries"],
                   "reconnects_total": reconnects,
                   "replays_total": replays,
                   "health": scraped["health"]["status"]}
            for c in setup.clients:
                c.close()
        finally:
            faults.clear()
            _stop_worker(w0)
            _stop_worker(w1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
