#!/usr/bin/env bash
# One-shot TPU evidence harvest (round 5): run the full bench matrix, the
# TPU-mode autoresearch gates, and the micro sweeps the moment the chip is
# healthy; each stage tees to its artifact so partial progress survives.
# Usage: scripts/tpu_harvest.sh [round-suffix, default r05]
#        CPU=1 scripts/tpu_harvest.sh rehearsal   # CPU dress rehearsal
set -uo pipefail
cd "$(dirname "$0")/.."
R="${1:-r05}"
if [ "${CPU:-}" = "1" ]; then
  BCPU="--cpu --smoke"; FCPU="--cpu --smoke"; GMODE=cpu; MCPU="--cpu"
else
  BCPU=""; FCPU=""; GMODE=tpu; MCPU=""
fi

echo "[harvest] headline bench.py" >&2
python bench.py --probe-budget 600 $BCPU | tail -1 | tee "BENCH_headline_${R}.json"

echo "[harvest] bench_full matrix" >&2
python bench_full.py --probe-budget 300 $FCPU | tee "BENCH_FULL_${R}.json"

echo "[harvest] micro: moe crossover + flash + decode" >&2
python benches/bench_micro.py --filter moe $MCPU > "MOE_MICRO_${R}.json" 2>/dev/null
python benches/bench_micro.py --filter flash $MCPU >> "MOE_MICRO_${R}.json" 2>/dev/null
cat "MOE_MICRO_${R}.json"

echo "[harvest] gates (${GMODE} mode)" >&2
python scripts/run_gates.py --mode "$GMODE" --out "GATES_${R}_${GMODE}.json" --timeout 1500

echo "[harvest] done" >&2
