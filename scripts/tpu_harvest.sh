#!/usr/bin/env bash
# One-shot TPU evidence harvest (round 5): run the full bench matrix, the
# TPU-mode autoresearch gates, and the micro sweeps the moment the chip is
# healthy; each stage tees to its artifact so partial progress survives.
# Usage: scripts/tpu_harvest.sh [round-suffix, default r05]
set -uo pipefail
cd "$(dirname "$0")/.."
R="${1:-r05}"

echo "[harvest] headline bench.py" >&2
python bench.py --probe-budget 600 | tail -1 | tee "BENCH_headline_${R}.json"

echo "[harvest] bench_full matrix" >&2
python bench_full.py --probe-budget 300 | tee "BENCH_FULL_${R}.json"

echo "[harvest] micro: moe crossover + flash + decode" >&2
python benches/bench_micro.py --filter moe > "MOE_MICRO_${R}.json" 2>/dev/null
python benches/bench_micro.py --filter flash >> "MOE_MICRO_${R}.json" 2>/dev/null
cat "MOE_MICRO_${R}.json"

echo "[harvest] gates (tpu mode)" >&2
python scripts/run_gates.py --mode tpu --out "GATES_${R}_tpu.json" --timeout 1500

echo "[harvest] done" >&2
