#!/usr/bin/env python
"""Telemetry-plane smoke: 2 real engine-backed serve replicas behind the
fleet router, a traffic burst, then one replica killed — the telemetry
plane (docs/telemetry.md) must turn the probe stream into decision-grade
signals.

Asserts, in order:
  1. after a concurrent chat burst the rollup is LIVE: telemetry cycles
     advance, the merged fleet TTFT percentiles carry the burst's
     samples (count > 0, p95 > 0), and capacity headroom is non-zero
     (per-slot token rate was learned from real traffic);
  2. the same signals are on the router's /metrics as autoscaler food:
     cake_fleet_slo_burn_rate{window="fast"|"slow"} present,
     cake_fleet_headroom_tokens_per_s > 0;
  3. the on-demand flight recorder is readable on a live replica
     (GET /api/v1/flight: scheduler iterations from the burst);
  4. killing one replica flags it `stale` + outlier reason "stale" in
     the telemetry body within a probe window or two, and the
     STALE-MIRROR rule holds on the router's /metrics: the dead
     replica's queue-depth/occupancy gauges are RETRACTED (no frozen
     labelsets averaging into fleet signals), with
     cake_fleet_replica_stale{...} 1 + cake_fleet_replica_outlier 1
     raised in their place while the survivor's mirrors stay live.

Every phase polls WITH A DEADLINE (fixed sleeps flake on this
container's slow CPU). Exits non-zero on any missing signal. Run via
`make telemetry-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import aiohttp                                             # noqa: E402
import jax.numpy as jnp                                    # noqa: E402
from aiohttp import web                                    # noqa: E402
from aiohttp.test_utils import TestClient, TestServer      # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.fleet import (FleetRouter, MembershipPolicy,  # noqa: E402
                            ReplicaRegistry, create_router_app)
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402

CTX = 128
N_REPLICAS = 2
MAX_NEW = 8


class SmokeTok:
    """Word-hash for prose, round-trip for generated ids (same contract
    as the fleet-chaos smoke's tokenizer)."""

    def encode(self, text):
        out = []
        for w in text.split():
            if w[:1] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(3 + (sum(w.encode()) % 200))
        return out[:64] or [3]

    def decode(self, ids):
        return "".join(f" t{i}" for i in ids)


class ReplicaProc:
    """One in-process serve replica: real engine, real HTTP socket."""

    def __init__(self, name: str, model):
        self.name = name
        self.engine = ServeEngine(model, slots=2, max_queue=16, ctx_len=CTX)
        self.state = ApiState(model=model, tokenizer=SmokeTok(),
                              model_id=f"tiny-{name}")
        self.state.engine = self.engine
        self.runner = None
        self.port = None

    async def start(self) -> str:
        self.runner = web.AppRunner(create_app(self.state))
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", self.port or 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def kill(self):
        """Sever the HTTP surface abruptly — scrapes and probes must see
        connection resets, not graceful drains."""
        server = self.runner.server
        for proto in list(getattr(server, "connections", []) or []):
            tr = getattr(proto, "transport", None)
            if tr is not None:
                tr.abort()
        await self.runner.cleanup()
        self.runner = None

    def close(self):
        self.engine.close()


async def _chat(client, convo: int, turn: int):
    return await client.post("/v1/chat/completions", json={
        "messages": [
            {"role": "system", "content": "telemetry smoke system prompt "
                                          "shared by every conversation"},
            {"role": "user", "content": f"conversation {convo} says "
                                        f"hello at turn {turn}"}],
        "max_tokens": MAX_NEW, "temperature": 0.0})


async def _poll_telemetry(client, pred, deadline_s: float, what: str):
    deadline = time.monotonic() + deadline_s
    body = None
    while time.monotonic() < deadline:
        body = await (await client.get("/api/v1/fleet/telemetry")).json()
        if pred(body):
            return body
        await asyncio.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}: "
                         f"{json.dumps(body, default=str)[:2000]}")


def _gauge(text: str, pattern: str) -> float | None:
    m = re.search(pattern, text, re.M)
    return float(m.group(1)) if m else None


async def main_async() -> dict:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    model.tokenizer = SmokeTok()
    out: dict = {}
    replicas = [ReplicaProc(f"r{i}", model) for i in range(N_REPLICAS)]
    registry = ReplicaRegistry(MembershipPolicy(
        eject_fails=2, err_window=16, err_rate=0.5,
        degraded_ttft_ms=0.0, eject_s=0.3))
    router = FleetRouter(registry, retries=2, backoff_s=0.01,
                         probe_s=0.15, hedge_ms=0.0, max_inflight=0)
    urls: dict[str, str] = {}
    client = None
    try:
        for rep in replicas:
            urls[rep.name] = await rep.start()
            registry.add(rep.name, urls[rep.name])
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        # -- phase 1: traffic burst -> live rollup ------------------------
        statuses: list[int] = []

        async def worker(convo: int):
            for turn in range(4):
                r = await _chat(client, convo, turn)
                statuses.append(r.status)
                await r.read()

        await asyncio.gather(*[worker(c) for c in range(6)])
        failed = [s for s in statuses if s != 200]
        assert not failed, f"burst requests failed: {failed}"
        out["burst_requests"] = len(statuses)

        body = await _poll_telemetry(
            client,
            lambda b: (b.get("cycles", 0) >= 2
                       and b.get("percentiles", {}).get("ttft", {})
                            .get("count", 0) > 0
                       and (b["percentiles"]["ttft"].get("p95") or 0) > 0
                       and (b.get("headroom_tokens_per_s") or 0) > 0),
            20.0, "live rollup (cycles, merged ttft p95, headroom)")
        pct = body["percentiles"]["ttft"]
        out["cycles"] = body["cycles"]
        out["merged_ttft_p95_ms"] = round(pct["p95"] * 1000, 2)
        out["merged_ttft_count"] = pct["count"]
        out["headroom_tokens_per_s"] = round(body["headroom_tokens_per_s"], 2)
        assert body["mismatched_histograms_skipped"] == 0, body
        assert not body["stale"], body["stale"]
        assert set(body["replicas"]) == {r.name for r in replicas}, body
        assert body["burn_rate"]["fast"] is not None
        assert body["series"], "fleet series rings empty"
        assert body["rollup_ms"]["mean"] is not None
        out["rollup_ms_mean"] = round(body["rollup_ms"]["mean"], 3)

        # -- phase 2: autoscaler signals on the router's /metrics ---------
        mtext = await (await client.get("/metrics")).text()
        for window in ("fast", "slow"):
            assert _gauge(
                mtext, rf'^cake_fleet_slo_burn_rate{{window="{window}"}}'
                       rf'\s+([0-9.e+-]+)') is not None, \
                f"burn-rate gauge missing for window={window}"
        headroom = _gauge(mtext, r"^cake_fleet_headroom_tokens_per_s"
                                 r"\s+([0-9.e+-]+)")
        assert headroom is not None and headroom > 0, \
            f"cake_fleet_headroom_tokens_per_s not live: {headroom}"
        out["metrics_headroom"] = round(headroom, 2)

        # -- phase 3: flight recorder readable on a live replica ----------
        async with aiohttp.ClientSession() as s:
            async with s.get(urls[replicas[0].name]
                             + "/api/v1/flight?n=16") as r:
                assert r.status == 200, await r.text()
                flight = await r.json()
        assert flight["count"] >= 1, flight
        assert all("seq" in it and "t" in it
                   for it in flight["iterations"]), flight
        out["flight_iterations"] = flight["count"]

        # -- phase 4: kill one replica -> stale + outlier + retraction ----
        victim, survivor = replicas[1], replicas[0]
        # both mirrors live before the kill
        for rep in replicas:
            assert _gauge(
                mtext, rf'^cake_fleet_replica_queue_depth{{replica='
                       rf'"{rep.name}"}}\s+([0-9.e+-]+)') is not None, \
                f"queue-depth mirror missing for {rep.name} pre-kill"
        await victim.kill()
        out["killed"] = victim.name

        t_kill = time.monotonic()
        body = await _poll_telemetry(
            client,
            lambda b: (victim.name in b.get("stale", [])
                       and b.get("outliers", {}).get(victim.name) == "stale"),
            10.0, f"{victim.name} stale + outlier(stale)")
        out["stale_detected_s"] = round(time.monotonic() - t_kill, 2)
        row = body["replicas"][victim.name]
        assert row["stale"] and row["outlier"], row
        assert not body["replicas"][survivor.name]["stale"], body

        # stale-mirror rule: frozen gauges RETRACTED, stale+outlier raised
        mtext = await (await client.get("/metrics")).text()
        for metric in ("cake_fleet_replica_queue_depth",
                       "cake_fleet_replica_occupancy"):
            assert not re.search(
                rf'^{metric}{{replica="{victim.name}"}}', mtext, re.M), \
                f"frozen gauge contamination: {metric} still exported " \
                f"for dead {victim.name}"
            assert re.search(
                rf'^{metric}{{replica="{survivor.name}"}}', mtext, re.M), \
                f"{metric} lost for live {survivor.name}"
        assert _gauge(mtext, rf'^cake_fleet_replica_stale{{replica='
                             rf'"{victim.name}"}}\s+([0-9.e+-]+)') == 1.0
        assert _gauge(mtext, rf'^cake_fleet_replica_outlier{{replica='
                             rf'"{victim.name}"}}\s+([0-9.e+-]+)') == 1.0
        assert _gauge(mtext, rf'^cake_fleet_replica_stale{{replica='
                             rf'"{survivor.name}"}}\s+([0-9.e+-]+)') == 0.0
        out["stale_mirror_retracted"] = True

        # the telemetry endpoint itself stays healthy on a 1-replica fleet
        body = await (await client.get("/api/v1/fleet/telemetry")).json()
        assert body["headroom_tokens_per_s"] is not None
        out["post_kill_cycles"] = body["cycles"]
        return out
    finally:
        if client is not None:
            await client.close()
        for rep in replicas:
            if rep.runner is not None:
                await rep.kill()
            rep.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print("telemetry-smoke OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
