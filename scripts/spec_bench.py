#!/usr/bin/env python
"""Speculative-decoding bench: tokens/s and acceptance for the n-gram
(prompt-lookup) drafter, SPEC ON vs OFF, on a REPETITIVE prompt (the
workload speculation exists for — quote the context, fix this code,
summarize) vs a NON-REPETITIVE one (worst case: the drafter mostly
abstains and every verify degenerates to ~plain decode). Tiny CPU model;
wall-clock numbers measure the SCHEDULING of the loop, not TPU speedup —
the acceptance columns (accepted tokens per verify step) are the
hardware-independent signal, and greedy spec output is asserted
bit-identical to plain decode on every case.

Writes BENCH_SPEC_<tag>.json (default tag from --tag, else "local") and
prints it. Run via `make spec-bench`.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.ops.sampling import SamplingConfig           # noqa: E402

GREEDY = SamplingConfig(temperature=0.0)
CTX = 256
MAX_NEW = 64
SPEC_K = 8

# a 6-token motif repeated 10x: the prompt-lookup drafter always finds
# the recent context earlier in the sequence
REPETITIVE = [5, 17, 42, 9, 88, 23] * 10
# multiplicative-congruential walk over the vocab: no n-gram repeats
RANDOM = [(i * 2654435761) % 199 + 3 for i in range(60)]


def _gen(model, prompt, spec, rng):
    t0 = time.monotonic()
    out, stats = model.generate(prompt, max_new_tokens=MAX_NEW,
                                sampling=GREEDY, spec=spec,
                                spec_k=SPEC_K, rng=rng)
    wall = time.monotonic() - t0
    return out, stats, wall


def bench_case(model, name, prompt):
    rng = jax.random.PRNGKey(7)
    _gen(model, prompt, False, rng)           # warmup plain executables
    _gen(model, prompt, "ngram", rng)         # warmup verify buckets
    base_out, base_stats, base_wall = _gen(model, prompt, False, rng)
    spec_out, spec_stats, spec_wall = _gen(model, prompt, "ngram", rng)
    steps = spec_stats["spec_steps"]
    return {
        "prompt": name,
        "prompt_tokens": len(prompt),
        "new_tokens": len(base_out),
        "bit_identical": spec_out == base_out,
        "off": {"wall_s": round(base_wall, 4),
                "tok_per_s": round(base_stats["tok_per_s"], 2)},
        "on": {
            "wall_s": round(spec_wall, 4),
            "tok_per_s": round(spec_stats["tok_per_s"], 2),
            "verify_steps": steps,
            "proposed": spec_stats["spec_proposed"],
            "accepted": spec_stats["spec_accepted"],
            "accept_rate": spec_stats["spec_accept_rate"],
            # the speedup proxies: device steps saved is what the TPU sees
            "accepted_per_step": round(spec_stats["spec_accepted"] / steps, 4)
            if steps else 0.0,
            "tokens_per_step": spec_stats["spec_tokens_per_step"],
        },
    }


def bench_engine(model):
    """Batched-engine speculation at occupancy 1 and 2: acceptance x
    occupancy x effective tok/s through the serve scheduler (the full
    sweep, paged mode included, lives in `serve_bench.py --spec`)."""
    from cake_tpu.serve import ServeEngine

    def run(spec, occ):
        eng = ServeEngine(model, slots=occ, max_queue=16, ctx_len=CTX,
                          prefill_chunk=32, prefix_cache_mb=0,
                          spec=spec, spec_k=SPEC_K)
        try:
            ps = [REPETITIVE[occ - 1:] + REPETITIVE[:occ - 1]
                  for _ in range(occ)]
            warm = [eng.submit(p, max_new_tokens=MAX_NEW, sampling=GREEDY)
                    for p in ps]
            assert all(r.wait(600) for r in warm)
            t0 = time.monotonic()
            rs = [eng.submit(p, max_new_tokens=MAX_NEW, sampling=GREEDY)
                  for p in ps]
            assert all(r.wait(600) for r in rs)
            wall = time.monotonic() - t0
            toks = sum(len(r.tokens) for r in rs)
            return toks / wall, [list(r.tokens) for r in rs], \
                eng.health().get("spec")
        finally:
            eng.close()

    out = []
    for occ in (1, 2):
        off, off_out, _ = run(False, occ)
        on, on_out, h = run("ngram", occ)
        out.append({
            "occupancy": occ,
            "bit_identical": on_out == off_out,
            "off_tok_per_s": round(off, 1),
            "on_tok_per_s": round(on, 1),
            "effective_speedup": round(on / off, 3),
            "accept_rate": round(h["accepted"] / h["proposed"], 4)
            if h["proposed"] else 0.0,
            "tokens_per_step": round(
                (h["accepted"] + h["steps"]) / h["steps"], 3)
            if h["steps"] else 0.0,
        })
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tag", default="local")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    cases = [bench_case(model, "repetitive", REPETITIVE),
             bench_case(model, "random", RANDOM)]
    out = {
        "bench": "spec",
        "ts": int(time.time()),
        "config": {"ctx": CTX, "max_new_tokens": MAX_NEW, "spec_k": SPEC_K,
                   "drafter": "ngram", "platform": "cpu-tiny"},
        "cases": cases,
        "engine": bench_engine(model),
    }
    path = args.out or f"BENCH_SPEC_{args.tag}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    print(f"\nwrote {path}", file=sys.stderr)
    if not all(c["bit_identical"] for c in cases):
        print("FAIL: greedy spec output differs from plain decode",
              file=sys.stderr)
        return 1
    rep = cases[0]["on"]
    if rep["accepted_per_step"] <= 1.0:
        print(f"FAIL: repetitive-prompt accepted_per_step "
              f"{rep['accepted_per_step']} <= 1.0", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
