#!/usr/bin/env python
"""Continuous-batching serve smoke: the API on a tiny CPU model must (a)
answer concurrent chats 200 through the engine, (b) shed load with a 429 +
Retry-After once the admission queue saturates, and (c) expose non-zero
cake_serve_queue_depth samples in /metrics while saturated. Exits non-zero
on any missing signal. Run via `make serve-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.obs import (SERVE_QUEUE_DEPTH,               # noqa: E402
                          SERVE_SLOTS_BUSY)
from cake_tpu.serve import ServeEngine                     # noqa: E402


class SmokeTok:
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:16] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


async def _poll(fn, timeout=20.0, every=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        await asyncio.sleep(every)
    return False


async def main_async() -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=256)
    model.tokenizer = SmokeTok()
    engine = ServeEngine(model, slots=1, max_queue=2, ctx_len=256)
    state = ApiState(model=model, tokenizer=model.tokenizer,
                     model_id="serve-smoke")
    state.engine = engine
    app = create_app(state)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        def chat(content, max_tokens):
            return client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": content}],
                "max_tokens": max_tokens, "temperature": 0.0})

        # occupy the single slot with a long decode...
        t_long = asyncio.ensure_future(chat("long request", 200))
        assert await _poll(lambda: SERVE_SLOTS_BUSY.value() >= 1), \
            "slot never went busy"
        # ...then fill the admission queue behind it
        t_q = [asyncio.ensure_future(chat(f"queued {i}", 4))
               for i in range(2)]
        assert await _poll(lambda: SERVE_QUEUE_DEPTH.value() >= 1), \
            "queue depth never rose"

        # saturated scrape: /metrics must carry a non-zero depth sample
        r = await client.get("/metrics")
        metrics = await r.text()
        m = re.search(r"^cake_serve_queue_depth (\S+)$", metrics, re.M)
        assert m and float(m.group(1)) > 0, \
            f"no non-zero cake_serve_queue_depth sample: {m}"

        # overflow sheds load instead of queueing unboundedly
        r429 = await chat("one too many", 4)
        assert r429.status == 429, r429.status
        assert int(r429.headers.get("Retry-After", "0")) >= 1

        # everyone admitted still completes 200
        statuses = [(await t).status for t in [t_long, *t_q]]
        assert statuses == [200, 200, 200], statuses

        r = await client.get("/health")
        health = await r.json()
        assert health["engine"]["alive"] is True

        return {"serve_smoke": "ok", "statuses": statuses,
                "rejected": r429.status,
                "retry_after_s": int(r429.headers["Retry-After"]),
                "queue_depth_sample": float(m.group(1)),
                "engine": health["engine"]}
    finally:
        await client.close()
        engine.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
