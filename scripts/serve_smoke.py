#!/usr/bin/env python
"""Continuous-batching serve smoke: the API on a tiny CPU model must (a)
answer concurrent chats 200 through the engine, (b) shed load with a 429 +
Retry-After once the admission queue saturates, (c) expose non-zero
cake_serve_queue_depth samples in /metrics while saturated, and (d) reuse
shared-prefix KV across chats (non-zero prefix-cache hits in /metrics and
the /health engine block). Every phase polls WITH A DEADLINE — on this
container's slow single-core CPU decode, fixed-sleep assumptions about
when the queue drains or the slot frees are exactly what made the old
smoke flaky under load. Exits non-zero on any missing signal. Run via
`make serve-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.obs import (SERVE_PREFIX_HITS,               # noqa: E402
                          SERVE_QUEUE_DEPTH, SERVE_SLOTS_BUSY)
from cake_tpu.serve import ServeEngine                     # noqa: E402


class SmokeTok:
    # cap must exceed the 16-token prefix block + 1 (reuse keeps one live
    # suffix token), or the shared-prefix phase could never hit
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:48] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


async def _poll(fn, timeout=60.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        await asyncio.sleep(every)
    return False


async def main_async() -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=128)
    model.tokenizer = SmokeTok()
    engine = ServeEngine(model, slots=1, max_queue=2, ctx_len=128,
                         prefill_chunk=16, prefix_cache_mb=16)
    state = ApiState(model=model, tokenizer=model.tokenizer,
                     model_id="serve-smoke")
    state.engine = engine
    app = create_app(state)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        def chat(content, max_tokens):
            return client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": content}],
                "max_tokens": max_tokens, "temperature": 0.0})

        # -- shared-prefix phase: two chats with an identical long message
        # (>= one 16-token block in common) must produce a prefix-cache hit
        shared = "alpha bravo charlie delta echo foxtrot golf hotel " \
                 "india juliet kilo lima mike november oscar papa"
        r1 = await chat(shared, 4)
        assert r1.status == 200, await r1.text()
        r2 = await chat(shared, 4)
        assert r2.status == 200, await r2.text()
        assert (await r1.json())["choices"][0]["message"]["content"] == \
            (await r2.json())["choices"][0]["message"]["content"], \
            "prefix-cache hit changed the greedy output"
        prefix_hits = SERVE_PREFIX_HITS.value()
        assert prefix_hits > 0, "no prefix-cache hit on identical prompts"

        # -- saturation phase: occupy the single slot with a long decode...
        t_long = asyncio.ensure_future(chat("long request", 200))
        assert await _poll(
            lambda: SERVE_SLOTS_BUSY.value() >= 1
            or engine.health()["prefilling"] >= 1), "slot never went busy"
        # ...then fill the admission queue behind it
        t_q = [asyncio.ensure_future(chat(f"queued {i}", 4))
               for i in range(2)]
        assert await _poll(lambda: SERVE_QUEUE_DEPTH.value() >= 1), \
            "queue depth never rose"

        # saturated scrape: /metrics must carry a non-zero depth sample
        r = await client.get("/metrics")
        metrics = await r.text()
        m = re.search(r"^cake_serve_queue_depth (\S+)$", metrics, re.M)
        assert m and float(m.group(1)) > 0, \
            f"no non-zero cake_serve_queue_depth sample: {m}"
        mh = re.search(r"^cake_serve_prefix_cache_hits_total (\S+)$",
                       metrics, re.M)
        assert mh and float(mh.group(1)) > 0, \
            "no non-zero cake_serve_prefix_cache_hits_total sample"

        # overflow sheds load instead of queueing unboundedly. The slow
        # CPU decode means the queue drains at its own pace: keep probing
        # against a DEADLINE until a 429 lands (each probe that sneaks in
        # as a 200 just refills the queue and keeps the engine saturated)
        deadline = time.monotonic() + 120
        r429 = None
        probes = []
        while time.monotonic() < deadline:
            resp = await chat("one too many", 4)
            if resp.status == 429:
                r429 = resp
                break
            probes.append(resp.status)
        assert r429 is not None, \
            f"queue never answered 429 (probe statuses: {probes[:10]}...)"
        assert int(r429.headers.get("Retry-After", "0")) >= 1

        # everyone admitted still completes 200 (deadline-bounded by the
        # client's own timeout; 200-token decode on a slow CPU can take a
        # while — that is the point of polling, not sleeping)
        statuses = [(await t).status for t in [t_long, *t_q]]
        assert statuses == [200, 200, 200], statuses

        r = await client.get("/health")
        health = await r.json()
        assert health["engine"]["alive"] is True
        assert health["engine"]["prefix_cache"]["hits"] > 0

        return {"serve_smoke": "ok", "statuses": statuses,
                "rejected": r429.status, "probes_before_429": len(probes),
                "retry_after_s": int(r429.headers["Retry-After"]),
                "queue_depth_sample": float(m.group(1)),
                "prefix_cache_hits": float(mh.group(1)),
                "engine": health["engine"]}
    finally:
        await client.close()
        engine.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
