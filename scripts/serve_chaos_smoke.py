#!/usr/bin/env python
"""Serve-plane chaos smoke: the crash-only engine under concurrent load
must survive one injected step crash. A deterministic fault plan kills
decode dispatch 6 while 3 clients stream through the aiohttp API; every
client must still complete 200 with greedy text bit-identical to an
uninjected engine, exactly one rebuild must be recorded (non-zero
cake_serve_engine_rebuilds_total in /metrics), and /health must be back
to 200 with the engine block clean afterwards. Every phase polls WITH A
DEADLINE (fixed-sleep assumptions are what made earlier smokes flaky on
this container's slow single-core CPU). Exits non-zero on any missing
signal. Run via `make serve-chaos-smoke`.
"""
from __future__ import annotations

import asyncio
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                    # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402
from cake_tpu.serve import faults                          # noqa: E402

CTX = 128
CRASH_STEP = 6
PROMPTS = [f"hello chaos client {i}" for i in range(3)]
MAX_NEW = 12


class SmokeTok:
    def encode(self, text):
        return [3 + (sum(w.encode()) % 200) for w in text.split()][:48] or [3]

    def decode(self, ids):
        return "".join(f"<{i}>" for i in ids)


async def _chat(client, content: str):
    resp = await client.post("/v1/chat/completions", json={
        "messages": [{"role": "user", "content": content}],
        "max_tokens": MAX_NEW, "temperature": 0.0})
    body = await resp.json()
    return resp.status, body


async def main_async() -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    tok = SmokeTok()
    out: dict = {}

    # -- reference pass: same engine config, no faults ----------------------
    engine = ServeEngine(model, slots=4, max_queue=8, ctx_len=CTX)
    state = ApiState(model=model, tokenizer=tok, model_id="tiny-chaos")
    state.engine = engine
    client = TestClient(TestServer(create_app(state)))
    await client.start_server()
    try:
        ref = await asyncio.gather(*[_chat(client, p) for p in PROMPTS])
        assert all(s == 200 for s, _ in ref), f"reference pass failed: {ref}"
        out["reference_texts"] = [
            b["choices"][0]["message"]["content"] for _, b in ref]
    finally:
        await client.close()
        engine.close()

    # -- chaos pass: kill decode dispatch CRASH_STEP mid-generation ---------
    faults.install(f"raise_on_step={CRASH_STEP};kind=device")
    try:
        engine = ServeEngine(model, slots=4, max_queue=8, ctx_len=CTX)
        state = ApiState(model=model, tokenizer=tok, model_id="tiny-chaos")
        state.engine = engine
        client = TestClient(TestServer(create_app(state)))
        await client.start_server()
        try:
            t0 = time.monotonic()
            res = await asyncio.gather(*[_chat(client, p) for p in PROMPTS])
            out["chaos_wall_s"] = round(time.monotonic() - t0, 2)
            assert all(s == 200 for s, _ in res), \
                f"client failed across the crash: {res}"
            texts = [b["choices"][0]["message"]["content"] for _, b in res]
            assert texts == out["reference_texts"], \
                f"continuation diverged: {texts} vs {out['reference_texts']}"
            out["bit_identical"] = True
            assert engine.supervisor.rebuild_count == 1, \
                f"expected exactly 1 rebuild, saw " \
                f"{engine.supervisor.rebuild_count}"
            out["rebuilds"] = engine.supervisor.rebuild_count

            # /metrics carries the recovery counter
            mresp = await client.get("/metrics")
            mtext = await mresp.text()
            m = re.search(
                r"^cake_serve_engine_rebuilds_total\s+(\d+)", mtext, re.M)
            assert m and int(m.group(1)) >= 1, \
                "cake_serve_engine_rebuilds_total missing/zero in /metrics"
            out["metric_rebuilds"] = int(m.group(1))

            # /health is back to 200 with a clean engine block
            deadline = time.monotonic() + 30
            hstatus, hbody = 0, {}
            while time.monotonic() < deadline:
                hresp = await client.get("/health")
                hstatus, hbody = hresp.status, await hresp.json()
                if hstatus == 200:
                    break
                await asyncio.sleep(0.05)
            assert hstatus == 200, f"/health stuck degraded: {hbody}"
            eng_block = hbody.get("engine", {})
            assert eng_block.get("alive") and not eng_block.get("wedged") \
                and not eng_block.get("down"), eng_block
            assert eng_block.get("rebuilds") == 1, eng_block
            out["health"] = 200
        finally:
            await client.close()
            engine.close()
    finally:
        faults.clear()
    return out


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print("serve-chaos-smoke OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
