#!/usr/bin/env python
"""Fleet-shared KV smoke: 3 real serve replicas behind the fleet router
with the kvshare tier enabled end-to-end (CAKE_KVSHARE=1).

Asserts, in order:
  1. CROSS-REPLICA PREFIX FETCH (ISSUE 20 hard gate): replica r0 is
     warmed directly, then cordoned; the SAME follow-up routed through
     the router lands on a cache-cold peer which — driven purely by the
     router-injected X-Cake-KV-Peers directory — fetches r0's prefix
     blob and splices instead of re-prefilling. Gated on ALL of:
     bit-identical greedy body vs the honest direct-to-r0 reference,
     cake_fleet_kv_fetches_total{outcome="hit"} advancing, AND the
     landing replica's /api/v1/stats reporting prefix_hit_tokens > 0
     for that exact request id (a fetch that produced no spliced tokens
     is a miss wearing a hit's label);
  2. the directory is registry-mirrored, not config: r0's inventory
     appears in the router's registry only after a probe scrape of the
     warmed replica's /health kvshare block;
  3. LIVE STREAM BLOB MIGRATION: the stream's owner begins draining
     MID-STREAM — the drain sweep parks the slot as a swap blob, the
     router ships it to a peer, and the client receives the complete
     greedy body BYTE-IDENTICAL to an unbroken run with ZERO
     client-visible error events,
     cake_fleet_kv_migrations_total{outcome="shipped"} > 0, and the
     router timeline chaining stream_broken -> kv_migrate(shipped) ->
     stream_resume -> done.

Every phase polls WITH A DEADLINE (fixed sleeps flake on this
container's slow CPU). Exits non-zero on any missing signal. Run via
`make kvshare-smoke`.
"""
from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the kvshare tier is knob-gated at BOTH ends — create_app only wires
# KVShareReplica and FleetRouter only injects directories when the knob
# is on — so flip it before any cake_tpu import
os.environ["CAKE_KVSHARE"] = "1"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import aiohttp                                             # noqa: E402
import jax.numpy as jnp                                    # noqa: E402
from aiohttp import web                                    # noqa: E402
from aiohttp.test_utils import TestClient, TestServer      # noqa: E402

from cake_tpu.api import ApiState, create_app              # noqa: E402
from cake_tpu.fleet import (FleetRouter, MembershipPolicy,  # noqa: E402
                            ReplicaRegistry, create_router_app)
from cake_tpu.models import TextModel, tiny_config         # noqa: E402
from cake_tpu.serve import ServeEngine                     # noqa: E402
from cake_tpu.serve import faults as serve_faults          # noqa: E402

CTX = 128
N_REPLICAS = 3
MAX_NEW = 8
STREAM_MAX_NEW = 24
SYSTEM = ("fleet kv smoke shared system prompt with enough words to "
          "span several sixteen token share units so a cold peer has "
          "a real prefix chain to fetch from the warm one instead of "
          "prefilling it all over again from scratch")


class SmokeTok:
    """Word-hash for prose, ROUND-TRIP for generated ids (decode emits
    " t<id>" words, encode parses them back) — same property the fleet
    chaos smoke rests on, here so a migrated stream's continuation
    splice re-encodes to exactly `prompt ids + generated ids`."""

    def encode(self, text):
        out = []
        for w in text.split():
            if w[:1] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(3 + (sum(w.encode()) % 200))
        return out[:64] or [3]

    def decode(self, ids):
        return "".join(f" t{i}" for i in ids)


class ReplicaProc:
    """One in-process serve replica with a PAGED pool + prefix cache —
    the substrate the kvshare tier exports from and imports into."""

    def __init__(self, name: str, model):
        self.name = name
        self.engine = ServeEngine(model, slots=2, max_queue=16,
                                  ctx_len=CTX, prefill_chunk=16,
                                  kv_blocks=32, kv_block_tokens=8,
                                  prefix_cache_mb=8)
        self.state = ApiState(model=model, tokenizer=SmokeTok(),
                              model_id=f"tiny-{name}")
        self.state.engine = self.engine
        self.runner = None
        self.port = None

    async def start(self) -> str:
        self.runner = web.AppRunner(create_app(self.state))
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", self.port or 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self):
        self.engine.close()


def _msgs(user: str) -> list:
    return [{"role": "system", "content": SYSTEM},
            {"role": "user", "content": user}]


async def main_async() -> dict:
    model = TextModel(tiny_config("llama"), dtype=jnp.float32,
                      max_cache_len=CTX)
    model.tokenizer = SmokeTok()    # streamed chunks decode per-token
    out: dict = {}
    replicas = [ReplicaProc(f"r{i}", model) for i in range(N_REPLICAS)]
    registry = ReplicaRegistry(MembershipPolicy(
        eject_fails=2, err_window=16, err_rate=0.5,
        degraded_ttft_ms=0.0, eject_s=0.3))
    router = FleetRouter(registry, retries=2, backoff_s=0.01,
                         probe_s=0.15, hedge_ms=0.0, stream_resumes=1)
    assert router.kvshare, "CAKE_KVSHARE knob did not reach the router"
    client = None
    session = aiohttp.ClientSession()   # direct-to-replica control path
    try:
        for rep in replicas:
            registry.add(rep.name, await rep.start())
        for rep in replicas:
            assert rep.state.kvshare is not None, \
                f"{rep.name}: create_app did not wire KVShareReplica"
        client = TestClient(TestServer(create_router_app(router)))
        await client.start_server()

        def reg(name: str):
            return next(r for r in registry.replicas() if r.name == name)

        async def direct_chat(rep: ReplicaProc, user: str):
            async with session.post(
                    rep.base_url + "/v1/chat/completions",
                    json={"messages": _msgs(user), "max_tokens": MAX_NEW,
                          "temperature": 0.0}) as r:
                body = await r.json()
                assert r.status == 200, body
                return body["choices"][0]["message"]["content"]

        async def metric(pattern: str) -> int:
            mtext = await (await client.get("/metrics")).text()
            m = re.search(pattern, mtext, re.M)
            return int(m.group(1)) if m else 0

        async def poll(pred, deadline_s: float, what: str):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if pred():
                    return
                # nudge idle engines: the inventory rebuild runs inside
                # the scheduler step, which only spins when woken
                for rp in replicas:
                    rp.engine._wake.set()
                await asyncio.sleep(0.05)
            raise AssertionError(f"timed out waiting for {what}")

        # -- phase 1: warm r0 directly, mirror its inventory --------------
        warm_src = replicas[0]
        await direct_chat(warm_src, "warmup turn for the shared prefix")
        ref = await direct_chat(warm_src, "now the real follow up question")
        # the directory is fed by the router's probe scrape of /health,
        # not by any side channel — wait for the mirror to fill
        await poll(lambda: len(reg(warm_src.name).kv_inventory()) >= 1,
                   10.0, "registry-mirrored kv inventory for r0")
        out["inventory_mirrored"] = len(reg(warm_src.name).kv_inventory())

        # -- phase 2: cordoned warm source, cold peer fetches --------------
        # cordon r0 so the routed follow-up MUST land on a cache-cold
        # peer; a cordoned replica keeps advertising its inventory (it
        # is exactly the cache peers should siphon before it goes)
        reg(warm_src.name).cordon()
        hits0 = await metric(
            r'^cake_fleet_kv_fetches_total{outcome="hit"}\s+(\d+)')
        r = await client.post("/v1/chat/completions", json={
            "messages": _msgs("now the real follow up question"),
            "max_tokens": MAX_NEW, "temperature": 0.0})
        body = await r.json()
        assert r.status == 200, body
        rid = r.headers.get("X-Cake-Request-Id")
        got = body["choices"][0]["message"]["content"]
        assert got == ref, \
            f"cross-replica fetched body diverged:\n  ref: {ref!r}\n" \
            f"  got: {got!r}"
        out["fetched_body_identical"] = True
        tl = router.timelines.get(rid)
        lander = next(e["replica"] for e in tl["events"]
                      if e["kind"] == "attempt"
                      and e.get("outcome") == "final")
        assert lander != warm_src.name, \
            f"follow-up landed on the cordoned warm source {lander}"
        out["cold_lander"] = lander
        hits1 = await metric(
            r'^cake_fleet_kv_fetches_total{outcome="hit"}\s+(\d+)')
        assert hits1 > hits0, \
            f"kv fetch hit counter did not advance ({hits0} -> {hits1})"
        out["kv_fetch_hits"] = hits1
        # the hit must be LOAD-BEARING: the landing replica's own stats
        # for this request id report spliced prefix tokens
        lander_proc = next(rp for rp in replicas if rp.name == lander)
        async with session.get(lander_proc.base_url + "/api/v1/stats") as r:
            st = (await r.json())["stats"]
        assert st.get("request_id") == rid, st
        assert st.get("prefix_hit_tokens", 0) > 0, \
            f"fetch hit produced no spliced prefix tokens: {st}"
        out["prefix_hit_tokens"] = st["prefix_hit_tokens"]
        # and it is visible in the peer's /health kv_pool block
        async with session.get(lander_proc.base_url + "/health") as r:
            h = await r.json()
        kv_pool = h["engine"]["kv_pool"]
        assert kv_pool["prefix_entries"] >= 1, kv_pool
        assert kv_pool["prefix_pinned_blocks"] >= 1, kv_pool
        out["lander_prefix_entries"] = kv_pool["prefix_entries"]

        # -- phase 2b: failed fetch degrades to honest recompute -----------
        # a directory naming a dead peer (advertising the RIGHT chains,
        # so the fetch is genuinely attempted) must cost nothing the
        # client can see: 200, bit-identical body, zero spliced tokens
        from cake_tpu.fleet.kvshare import KV_DIR_HEADER, encode_directory
        chains = list(reg(warm_src.name).kv_inventory())
        bogus = encode_directory([("http://127.0.0.1:9", chains)])
        ferr0 = await metric(
            r'^cake_fleet_kv_fetches_total{outcome="error"}\s+(\d+)')
        other = next(rp for rp in replicas
                     if rp.name not in (warm_src.name, lander))
        async with session.post(
                other.base_url + "/v1/chat/completions",
                json={"messages": _msgs("now the real follow up question"),
                      "max_tokens": MAX_NEW, "temperature": 0.0},
                headers={KV_DIR_HEADER: bogus}) as r:
            body = await r.json()
            assert r.status == 200, body
        assert body["choices"][0]["message"]["content"] == ref, \
            "recompute after failed fetch diverged"
        async with session.get(other.base_url + "/api/v1/stats") as r:
            st = (await r.json())["stats"]
        assert st.get("prefix_hit_tokens", 0) == 0, \
            f"failed fetch claimed spliced tokens: {st}"
        ferr1 = await metric(
            r'^cake_fleet_kv_fetches_total{outcome="error"}\s+(\d+)')
        assert ferr1 > ferr0, \
            f"dead-peer fetch not accounted ({ferr0} -> {ferr1})"
        out["failed_fetch_degrades"] = True

        # -- phase 3: live stream blob migration on drain ------------------
        def smsg(convo: int) -> list:
            return _msgs(f"stream conversation {convo} tell me a long story")

        async def stream_once(convo: int, drain_after: int | None = None,
                              victim: ReplicaProc | None = None):
            """One streamed request through the router; optionally begin
            draining `victim` once `drain_after` content chunks have
            arrived. Returns (content, error_events, request_id)."""
            content, errors = "", []
            drained = False
            buf = b""
            async with client.post("/v1/chat/completions", json={
                    "messages": smsg(convo), "max_tokens": STREAM_MAX_NEW,
                    "temperature": 0.0, "stream": True}) as r:
                assert r.status == 200, await r.text()
                rid = r.headers.get("X-Cake-Request-Id")
                ntoks = 0
                async for piece in r.content.iter_any():
                    buf += piece
                    while b"\n\n" in buf:
                        ev, buf = buf.split(b"\n\n", 1)
                        if not ev.startswith(b"data: "):
                            continue
                        pl = ev[6:].strip()
                        if pl == b"[DONE]":
                            continue
                        obj = json.loads(pl)
                        if "error" in obj:
                            errors.append(obj["error"])
                            continue
                        delta = obj["choices"][0]["delta"]
                        if delta.get("content"):
                            content += delta["content"]
                            ntoks += 1
                            if (drain_after is not None and not drained
                                    and ntoks >= drain_after):
                                drained = True
                                victim.engine.begin_drain()
            return content, errors, rid

        def commit_replica(rid: str) -> str:
            tl = router.timelines.get(rid)
            return next(e["replica"] for e in tl["events"]
                        if e["kind"] == "commit")

        serve_faults.install("delay_ms=40")     # stretch decode so the
        try:                                    # drain lands mid-stream
            convo = base = rid0 = None
            for c in range(40, 48):     # find a convo that decodes long
                base, errs, rid0 = await stream_once(c)
                assert not errs, errs
                if base.count(" t") >= 10:
                    convo = c
                    break
            assert convo is not None, "no convo produced >= 10 tokens"
            owner = next(rp for rp in replicas
                         if rp.name == commit_replica(rid0))
            healed, errs, rid = await stream_once(convo, drain_after=5,
                                                  victim=owner)
            assert not errs, f"client saw error events: {errs}"
            assert healed == base, \
                f"migrated stream diverged:\n  base:   {base!r}\n" \
                f"  healed: {healed!r}"
            out["stream_drained"] = owner.name
            out["stream_body_identical"] = True
            events = router.timelines.get(rid)["events"]
            kinds = [e["kind"] for e in events]
            for k in ("stream_broken", "stream_resume", "kv_migrate",
                      "resume_spliced", "done"):
                assert k in kinds, (k, kinds)
            # the resume decision is logged first, THEN the blob ships,
            # THEN the resumed leg splices on the new owner
            assert kinds.index("stream_broken") \
                < kinds.index("stream_resume") < kinds.index("kv_migrate") \
                < kinds.index("resume_spliced") < kinds.index("done"), kinds
            mig = next(e for e in events if e["kind"] == "kv_migrate")
            assert mig["outcome"] == "shipped", mig
            assert mig["from"] == owner.name, mig
            out["migration_timeline_chain"] = True
            shipped = await metric(
                r'^cake_fleet_kv_migrations_total{outcome="shipped"}'
                r'\s+(\d+)')
            assert shipped >= 1, \
                'cake_fleet_kv_migrations_total{outcome="shipped"} missing'
            out["kv_migrations_shipped"] = shipped
        finally:
            serve_faults.clear()
        return out
    finally:
        await session.close()
        if client is not None:
            await client.close()
        for rep in replicas:
            if rep.runner is not None:
                await rep.runner.cleanup()
            rep.close()


def main() -> int:
    out = asyncio.new_event_loop().run_until_complete(main_async())
    print("kvshare-smoke OK:")
    for k, v in out.items():
        print(f"  {k}: {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
