"""Full benchmark matrix: the committed TPU numbers behind BASELINE.md's
non-decode rows (VERDICT r3 item 2 — "perf evidence is a single number").

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N|null, ...}

Baselines (BASELINE.md, RTX 3080 Laptop 16 GB):
  * FLUX.1-dev FP8 768x1024: 3.5 s/step        -> flux2_klein_step_s
    (klein-4B is the FLUX.2 family member that fits 16 GB HBM in bf16;
    FLUX.1-dev needs the fp8-native path and is benched separately)
  * VibeVoice TTS: 20 ms/frame                  -> vibevoice_ms_frame
  * prefill TTFT: no published reference number -> vs_baseline null
  * MoE decode: no published reference number   -> vs_baseline null

Timing discipline (memory: axon tunnel): block_until_ready does not wait
through the tunnel — every timed region ends in a real host fetch, and
TTFT-style numbers also report the measured link RTT so the fixed ~66-90 ms
fetch cost (which drifts run-to-run) is separable from device time.

Usage: python bench_full.py [--only m1,m2] [--cpu] [--smoke]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import traceback

import numpy as np

import jax
import jax.numpy as jnp


def _fetch(x):
    return np.asarray(x)


def measure_link_rtt(n: int = 5) -> float:
    f = jax.jit(lambda a, b: (a * b).sum())
    x = jnp.ones((8, 8), jnp.bfloat16)
    ts = []
    for i in range(n):
        t0 = time.monotonic()
        _fetch(f(x, jnp.asarray(float(i + 1), jnp.bfloat16)))
        ts.append(time.monotonic() - t0)
    return float(np.median(ts))


# ---------------------------------------------------------------------------
# prefill TTFT at 512 / 2048-token prompts (flagship Qwen3-0.6B shape)
# ---------------------------------------------------------------------------


def bench_prefill(smoke: bool):
    from __graft_entry__ import FLAGSHIP

    from cake_tpu.models import SamplingConfig, TextModel, config_from_hf_dict
    from cake_tpu.models import tiny_config

    cfg = tiny_config("qwen3") if smoke else config_from_hf_dict(FLAGSHIP)
    model = TextModel(cfg, dtype=jnp.bfloat16,
                      max_cache_len=128 if smoke else 4096)
    scfg = SamplingConfig(temperature=0.0)
    rtt = measure_link_rtt()
    out = []
    for n in ((16, 32) if smoke else (512, 2048)):
        prompt = list(np.random.default_rng(0).integers(
            0, cfg.vocab_size - 1, size=n))
        model.generate(prompt, max_new_tokens=1, sampling=scfg)   # compile
        ttfts = []
        for _ in range(5):
            _, stats = model.generate(prompt, max_new_tokens=1, sampling=scfg)
            ttfts.append(stats["ttft_s"])
        p50 = float(np.median(ttfts))
        out.append({
            "metric": f"prefill_ttft_{n}",
            "value": round(p50 * 1e3, 1), "unit": "ms",
            "vs_baseline": None,
            "link_rtt_ms": round(rtt * 1e3, 1),
            "ttft_net_ms": round(max(p50 - rtt, 0.0) * 1e3, 1),
        })
    return out


# ---------------------------------------------------------------------------
# FLUX.2-klein denoise step (768x1024, the reference's FLUX.1 geometry)
# ---------------------------------------------------------------------------


def bench_flux2(smoke: bool):
    from cake_tpu.models.image.flux2 import (Flux2ImageModel,
                                             Flux2PipelineConfig,
                                             tiny_flux2_config)
    cfg = tiny_flux2_config() if smoke else Flux2PipelineConfig()
    m = Flux2ImageModel(cfg, dtype=jnp.bfloat16)
    w, h = (64, 64) if smoke else (768, 1024)
    steps = 2 if smoke else 4
    m.generate_image("warmup", width=w, height=h, steps=1, seed=0)  # compile
    t0 = time.monotonic()
    img = m.generate_image("bench", width=w, height=h, steps=steps, seed=0)
    _fetch(img)        # generate already decodes+fetches; keep it explicit
    per_step = (time.monotonic() - t0) / steps
    return [{
        "metric": "flux2_klein_step_s",
        "value": round(per_step, 3), "unit": "s/step",
        # reference headline: FLUX.1-dev FP8 3.5 s/step at this geometry
        "vs_baseline": round(3.5 / per_step, 2),
        "note": "includes VAE decode amortized over steps; klein-4B bf16 "
                "vs reference flux1-dev-12B fp8 (the 16 GB-fitting member "
                "of each family)",
    }]


# ---------------------------------------------------------------------------
# VibeVoice-Realtime-0.5B speech frame rate
# ---------------------------------------------------------------------------


def bench_tts(smoke: bool):
    from cake_tpu.models.audio.vibevoice import (VibeVoiceConfig, VibeVoiceTTS,
                                                 tiny_tts_config)
    from cake_tpu.models.common.config import tiny_config

    if smoke:
        cfg = tiny_tts_config()
    else:
        # VibeVoice-Realtime-0.5B: Qwen2.5-0.5B backbone split 4 base +
        # 20 TTS layers (ref: vibevoice.rs model shape / BASELINE.md row)
        qwen05 = dict(vocab_size=151936, hidden_size=896,
                      intermediate_size=4864, num_attention_heads=14,
                      num_key_value_heads=2, rms_norm_eps=1e-6,
                      rope_theta=1e6, max_position_embeddings=4096,
                      eos_token_id=151645, tie_word_embeddings=True)
        base = tiny_config("qwen2", **{**qwen05, "num_hidden_layers": 4})
        tts = tiny_config("qwen2", **{**qwen05, "num_hidden_layers": 20})
        cfg = VibeVoiceConfig(lm_base=base, lm_tts=tts)
    m = VibeVoiceTTS(cfg, dtype=jnp.bfloat16, max_frames=16)
    text = "The quick brown fox jumps over the lazy dog."
    m.generate_speech(text, max_frames=2, seed=0)    # compile
    n_frames = 4 if smoke else 12
    t0 = time.monotonic()
    audio = m.generate_speech(text, max_frames=n_frames, seed=0)
    _fetch(audio.samples)
    frames = max(1, round(len(audio.samples) / (cfg.hop)))
    ms = (time.monotonic() - t0) / frames * 1e3
    return [{
        "metric": "vibevoice_ms_frame",
        "value": round(ms, 1), "unit": "ms/frame",
        "vs_baseline": round(20.0 / ms, 2),    # reference: 20 ms/frame
        "frames": frames,
    }]


# ---------------------------------------------------------------------------
# MoE decode (largest qwen3-moe-shaped config fitting 16 GB HBM)
# ---------------------------------------------------------------------------


def bench_moe(smoke: bool):
    from cake_tpu.models import SamplingConfig, TextModel, tiny_config
    if smoke:
        cfg = tiny_config("qwen3_moe")
    else:
        # ~11.5 GB bf16: 48 experts x (3 * 768 * 2048) x 24 layers
        cfg = tiny_config(
            "qwen3_moe", vocab_size=151936, hidden_size=2048,
            intermediate_size=6144, num_hidden_layers=24,
            num_attention_heads=16, num_key_value_heads=4, head_dim=128,
            num_experts=48, num_experts_per_tok=8, moe_intermediate_size=768,
            max_position_embeddings=4096)
    model = TextModel(cfg, dtype=jnp.bfloat16,
                      max_cache_len=128 if smoke else 1024)
    scfg = SamplingConfig(temperature=0.0)
    prompt = list(np.random.default_rng(0).integers(
        0, cfg.vocab_size - 1, size=32))
    tokens = 32 if smoke else 256
    model.generate(prompt, max_new_tokens=tokens, sampling=scfg)   # compile
    rates = []
    for _ in range(3):
        _, stats = model.generate(prompt, max_new_tokens=tokens, sampling=scfg)
        rates.append(stats["tok_per_s"])
    active = cfg.num_experts_per_tok / cfg.num_experts
    return [{
        "metric": "qwen3_moe_decode",
        "value": round(float(np.mean(rates)), 1), "unit": "tok/s",
        "vs_baseline": None,     # reference publishes no MoE numbers
        "config": f"{cfg.num_experts}e-top{cfg.num_experts_per_tok}"
                  f"-h{cfg.hidden_size}-L{cfg.num_hidden_layers}",
        "active_fraction": round(active, 3),
    }]


# ---------------------------------------------------------------------------
# Llama-3-8B fp8-native decode (the 16 GB "largest dense" config)
# ---------------------------------------------------------------------------


def bench_llama8b_fp8(smoke: bool):
    from cake_tpu.models import SamplingConfig, TextModel, tiny_config
    from cake_tpu.models.common.layers import init_params

    if smoke:
        cfg = tiny_config("llama")
    else:
        # Llama-3-8B geometry (ref BASELINE.json north star); bf16 needs
        # ~16 GB for weights alone, fp8-native halves it to ~8 GB resident
        cfg = tiny_config(
            "llama", vocab_size=128256, hidden_size=4096,
            intermediate_size=14336, num_hidden_layers=32,
            num_attention_heads=32, num_key_value_heads=8, head_dim=128,
            rope_theta=500000.0, max_position_embeddings=4096)

    # build the fp8-native pytree directly: every matmul weight becomes a
    # {"fp8", "scale_inv"} marker dict resolved inside the jitted forward
    # (same in-HBM layout the --fp8-native loader produces; values are
    # irrelevant to throughput)
    def to_fp8(path_key, w):
        if w.ndim == 2 and w.shape[0] % 128 == 0 and w.shape[1] % 128 == 0 \
                and path_key not in ("embed_tokens", "lm_head"):
            f8 = w.astype(jnp.float8_e4m3fn)
            si = jnp.ones((w.shape[0] // 128, w.shape[1] // 128), jnp.float32)
            return {"fp8": f8, "scale_inv": si}
        return w

    params = init_params(cfg, jax.random.PRNGKey(0), jnp.bfloat16)
    for layer in params["layers"]:
        for grp in ("self_attn", "mlp"):
            for name, p in layer.get(grp, {}).items():
                if isinstance(p, dict) and "weight" in p \
                        and getattr(p["weight"], "ndim", 0) == 2:
                    w = p["weight"]
                    if w.shape[0] % 128 == 0 and w.shape[1] % 128 == 0:
                        p["weight"] = to_fp8(name, w)

    model = TextModel(cfg, params=params, dtype=jnp.bfloat16,
                      max_cache_len=128 if smoke else 1024)
    scfg = SamplingConfig(temperature=0.0)
    prompt = list(np.random.default_rng(0).integers(
        0, cfg.vocab_size - 1, size=32))
    tokens = 32 if smoke else 128
    model.generate(prompt, max_new_tokens=tokens, sampling=scfg)   # compile
    rates = []
    for _ in range(3):
        _, stats = model.generate(prompt, max_new_tokens=tokens, sampling=scfg)
        rates.append(stats["tok_per_s"])
    return [{
        "metric": "llama3_8b_fp8_decode",
        "value": round(float(np.mean(rates)), 1), "unit": "tok/s",
        "vs_baseline": None,    # reference cannot fit 8B on its 16 GB GPU
        "note": "fp8-native resident weights (~8 GB HBM), bf16 compute",
    }]


BENCHES = {
    "prefill": bench_prefill,
    "flux2": bench_flux2,
    "tts": bench_tts,
    "moe": bench_moe,
    "llama8b_fp8": bench_llama8b_fp8,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", help="comma-separated subset of "
                                   f"{sorted(BENCHES)}")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    names = args.only.split(",") if args.only else list(BENCHES)
    for name in names:
        try:
            for row in BENCHES[name](args.smoke):
                print(json.dumps(row), flush=True)
        except Exception as e:       # noqa: BLE001 — emit per-metric failure
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({"metric": name, "value": 0.0, "unit": "",
                              "vs_baseline": None, "error": str(e)[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
