"""Full benchmark matrix: the committed TPU numbers behind BASELINE.md's
non-decode rows (VERDICT r3 item 2 — "perf evidence is a single number").

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N|null, ...}

Baselines (BASELINE.md, RTX 3080 Laptop 16 GB):
  * FLUX.1-dev FP8 768x1024: 3.5 s/step        -> flux2_klein_step_s
    (klein-4B is the FLUX.2 family member that fits 16 GB HBM in bf16;
    FLUX.1-dev needs the fp8-native path and is benched separately)
  * VibeVoice TTS: 20 ms/frame                  -> vibevoice_ms_frame
  * prefill TTFT: no published reference number -> vs_baseline null
  * MoE decode: no published reference number   -> vs_baseline null

Timing discipline (memory: axon tunnel): block_until_ready does not wait
through the tunnel — every timed region ends in a real host fetch, and
TTFT-style numbers also report the measured link RTT so the fixed ~66-90 ms
fetch cost (which drifts run-to-run) is separable from device time.

Usage: python bench_full.py [--only m1,m2] [--cpu] [--smoke]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import traceback

import numpy as np

import jax
import jax.numpy as jnp


def _fetch(x):
    return np.asarray(x)


def device_mem_mb() -> dict:
    """HBM residency snapshot (verdict r4 item 3: the fp8-native story needs
    a device measurement, not host-side byte arithmetic)."""
    try:
        ms = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return {}
    out = {}
    if "bytes_in_use" in ms:
        out["hbm_in_use_mb"] = round(ms["bytes_in_use"] / 2**20)
    if "peak_bytes_in_use" in ms:
        out["hbm_peak_mb"] = round(ms["peak_bytes_in_use"] / 2**20)
    return out


def _build_fp8_tree(shape_tree, skip_substrings=("embed_tokens", "lm_head")):
    """Materialize a param tree directly from ShapeDtypeStructs, placing every
    128x128-divisible 2D matmul weight on device as an fp8-native marker dict
    ({"fp8", "scale_inv"}) and everything else in its declared dtype — the
    same in-HBM layout `load_mapped_params(fp8_native=True)` produces, but
    without ever materializing the bf16 model first (an 8B bf16 init would
    blow 16 GB-class HBM before the fp8 conversion could start)."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    leaves, treedef = tree_flatten_with_path(shape_tree)
    rng = np.random.default_rng(0)
    # weight VALUES are throughput-irrelevant (TPU matmul speed is
    # data-independent) — tile one modest random block instead of drawing
    # ~8e9 host-side gaussians for an 8B model
    block = rng.standard_normal(1 << 20, dtype=np.float32) * 0.02

    def _rand(shape, np_dtype):
        n = int(np.prod(shape)) if shape else 1
        reps = -(-n // block.size)
        flat = np.tile(block, reps)[:n] if reps > 1 else block[:n]
        return jnp.asarray(flat.reshape(shape), np_dtype)

    out = []
    for path, leaf in leaves:
        pstr = jax.tree_util.keystr(path)
        shape, dtype = leaf.shape, leaf.dtype
        if (len(shape) == 2 and shape[0] % 128 == 0 and shape[1] % 128 == 0
                and dtype == jnp.bfloat16
                and not any(s in pstr for s in skip_substrings)):
            f8 = _rand(shape, jnp.float8_e4m3fn)
            si = jnp.ones((shape[0] // 128, shape[1] // 128), jnp.float32)
            out.append({"fp8": f8, "scale_inv": si})
        else:
            out.append(_rand(shape, dtype))
    return tree_unflatten(treedef, out)


def measure_link_rtt(n: int = 5) -> float:
    f = jax.jit(lambda a, b: (a * b).sum())
    x = jnp.ones((8, 8), jnp.bfloat16)
    ts = []
    for i in range(n):
        t0 = time.monotonic()
        _fetch(f(x, jnp.asarray(float(i + 1), jnp.bfloat16)))
        ts.append(time.monotonic() - t0)
    return float(np.median(ts))


# ---------------------------------------------------------------------------
# prefill TTFT at 512 / 2048-token prompts (flagship Qwen3-0.6B shape)
# ---------------------------------------------------------------------------


def bench_prefill(smoke: bool):
    from __graft_entry__ import FLAGSHIP

    from cake_tpu.models import SamplingConfig, TextModel, config_from_hf_dict
    from cake_tpu.models import tiny_config

    cfg = tiny_config("qwen3") if smoke else config_from_hf_dict(FLAGSHIP)
    model = TextModel(cfg, dtype=jnp.bfloat16,
                      max_cache_len=128 if smoke else 4096)
    scfg = SamplingConfig(temperature=0.0)
    rtt = measure_link_rtt()
    out = []
    for n in ((16, 32) if smoke else (512, 2048)):
        prompt = list(np.random.default_rng(0).integers(
            0, cfg.vocab_size - 1, size=n))
        model.generate(prompt, max_new_tokens=1, sampling=scfg)   # compile
        ttfts = []
        for _ in range(5):
            _, stats = model.generate(prompt, max_new_tokens=1, sampling=scfg)
            ttfts.append(stats["ttft_s"])
        p50 = float(np.median(ttfts))
        out.append({
            "metric": f"prefill_ttft_{n}",
            "value": round(p50 * 1e3, 1), "unit": "ms",
            "vs_baseline": None,
            "link_rtt_ms": round(rtt * 1e3, 1),
            "ttft_net_ms": round(max(p50 - rtt, 0.0) * 1e3, 1),
        })
    return out


# ---------------------------------------------------------------------------
# FLUX.2-klein denoise step (768x1024, the reference's FLUX.1 geometry)
# ---------------------------------------------------------------------------


def bench_flux2(smoke: bool):
    from cake_tpu.models.image.flux2 import (Flux2ImageModel,
                                             Flux2PipelineConfig,
                                             tiny_flux2_config)
    cfg = tiny_flux2_config() if smoke else Flux2PipelineConfig()
    m = Flux2ImageModel(cfg, dtype=jnp.bfloat16)
    w, h = (64, 64) if smoke else (768, 1024)
    steps = 2 if smoke else 4
    m.generate_image("warmup", width=w, height=h, steps=1, seed=0)  # compile
    t0 = time.monotonic()
    img = m.generate_image("bench", width=w, height=h, steps=steps, seed=0)
    _fetch(img)        # generate already decodes+fetches; keep it explicit
    per_step = (time.monotonic() - t0) / steps
    return [{
        "metric": "flux2_klein_step_s",
        "value": round(per_step, 3), "unit": "s/step",
        # reference headline: FLUX.1-dev FP8 3.5 s/step at this geometry
        "vs_baseline": round(3.5 / per_step, 2),
        "note": "includes VAE decode amortized over steps; klein-4B bf16 "
                "vs reference flux1-dev-12B fp8 (the 16 GB-fitting member "
                "of each family)",
    }]


# ---------------------------------------------------------------------------
# VibeVoice-Realtime-0.5B speech frame rate
# ---------------------------------------------------------------------------


def bench_tts(smoke: bool):
    from cake_tpu.models.audio.vibevoice import (VibeVoiceConfig, VibeVoiceTTS,
                                                 tiny_tts_config)
    from cake_tpu.models.common.config import tiny_config

    if smoke:
        cfg = tiny_tts_config()
    else:
        # VibeVoice-Realtime-0.5B: Qwen2.5-0.5B backbone split 4 base +
        # 20 TTS layers (ref: vibevoice.rs model shape / BASELINE.md row)
        qwen05 = dict(vocab_size=151936, hidden_size=896,
                      intermediate_size=4864, num_attention_heads=14,
                      num_key_value_heads=2, rms_norm_eps=1e-6,
                      rope_theta=1e6, max_position_embeddings=4096,
                      eos_token_id=151645, tie_word_embeddings=True)
        base = tiny_config("qwen2", **{**qwen05, "num_hidden_layers": 4})
        tts = tiny_config("qwen2", **{**qwen05, "num_hidden_layers": 20})
        cfg = VibeVoiceConfig(lm_base=base, lm_tts=tts)
    m = VibeVoiceTTS(cfg, dtype=jnp.bfloat16, max_frames=16)
    text = "The quick brown fox jumps over the lazy dog."
    m.generate_speech(text, max_frames=2, seed=0)    # compile
    n_frames = 4 if smoke else 12
    t0 = time.monotonic()
    audio = m.generate_speech(text, max_frames=n_frames, seed=0)
    _fetch(audio.samples)
    frames = max(1, round(len(audio.samples) / (cfg.hop)))
    ms = (time.monotonic() - t0) / frames * 1e3
    return [{
        "metric": "vibevoice_ms_frame",
        "value": round(ms, 1), "unit": "ms/frame",
        "vs_baseline": round(20.0 / ms, 2),    # reference: 20 ms/frame
        "frames": frames,
    }]


# ---------------------------------------------------------------------------
# MoE decode (largest qwen3-moe-shaped config fitting 16 GB HBM)
# ---------------------------------------------------------------------------


def bench_moe(smoke: bool):
    from cake_tpu.models import SamplingConfig, TextModel, tiny_config
    if smoke:
        cfg = tiny_config("qwen3_moe")
    else:
        # ~11.5 GB bf16: 48 experts x (3 * 768 * 2048) x 24 layers
        cfg = tiny_config(
            "qwen3_moe", vocab_size=151936, hidden_size=2048,
            intermediate_size=6144, num_hidden_layers=24,
            num_attention_heads=16, num_key_value_heads=4, head_dim=128,
            num_experts=48, num_experts_per_tok=8, moe_intermediate_size=768,
            max_position_embeddings=4096)
    model = TextModel(cfg, dtype=jnp.bfloat16,
                      max_cache_len=128 if smoke else 1024)
    scfg = SamplingConfig(temperature=0.0)
    prompt = list(np.random.default_rng(0).integers(
        0, cfg.vocab_size - 1, size=32))
    tokens = 32 if smoke else 256
    model.generate(prompt, max_new_tokens=tokens, sampling=scfg)   # compile
    rates = []
    for _ in range(3):
        _, stats = model.generate(prompt, max_new_tokens=tokens, sampling=scfg)
        rates.append(stats["tok_per_s"])
    active = cfg.num_experts_per_tok / cfg.num_experts
    return [{
        "metric": "qwen3_moe_decode",
        "value": round(float(np.mean(rates)), 1), "unit": "tok/s",
        "vs_baseline": None,     # reference publishes no MoE numbers
        "config": f"{cfg.num_experts}e-top{cfg.num_experts_per_tok}"
                  f"-h{cfg.hidden_size}-L{cfg.num_hidden_layers}",
        "active_fraction": round(active, 3),
    }]


# ---------------------------------------------------------------------------
# Llama-3-8B fp8-native decode (the 16 GB "largest dense" config)
# ---------------------------------------------------------------------------


def bench_llama8b_fp8(smoke: bool):
    from cake_tpu.models import SamplingConfig, TextModel, tiny_config
    from cake_tpu.models.common.layers import init_params

    if smoke:
        cfg = tiny_config("llama")
    else:
        # Llama-3-8B geometry (ref BASELINE.json north star); bf16 needs
        # ~16 GB for weights alone, fp8-native halves it to ~8 GB resident
        cfg = tiny_config(
            "llama", vocab_size=128256, hidden_size=4096,
            intermediate_size=14336, num_hidden_layers=32,
            num_attention_heads=32, num_key_value_heads=8, head_dim=128,
            rope_theta=500000.0, max_position_embeddings=4096)

    # build the fp8-native pytree directly from shapes: every matmul weight
    # becomes a {"fp8", "scale_inv"} marker dict resolved inside the jitted
    # forward — never materializing the ~16 GB bf16 model first
    shapes = jax.eval_shape(
        lambda k: init_params(cfg, k, jnp.bfloat16), jax.random.PRNGKey(0))
    params = _build_fp8_tree(shapes)
    mem_resident = device_mem_mb()

    model = TextModel(cfg, params=params, dtype=jnp.bfloat16,
                      max_cache_len=128 if smoke else 1024)
    scfg = SamplingConfig(temperature=0.0)
    prompt = list(np.random.default_rng(0).integers(
        0, cfg.vocab_size - 1, size=32))
    tokens = 32 if smoke else 128
    model.generate(prompt, max_new_tokens=tokens, sampling=scfg)   # compile
    rates = []
    for _ in range(3):
        _, stats = model.generate(prompt, max_new_tokens=tokens, sampling=scfg)
        rates.append(stats["tok_per_s"])
    return [{
        "metric": "llama3_8b_fp8_decode",
        "value": round(float(np.mean(rates)), 1), "unit": "tok/s",
        "vs_baseline": None,    # reference cannot fit 8B on its 16 GB GPU
        "note": "fp8-native resident weights (~8 GB HBM), bf16 compute",
        "hbm_weights_mb": mem_resident.get("hbm_in_use_mb"),
        **device_mem_mb(),
    }]


# ---------------------------------------------------------------------------
# FLUX.1-dev fp8-native denoise step (the reference's actual headline row:
# 3.5 s/step at 768x1024, 13,317 MB resident — docs/benchmarks/README.md)
# ---------------------------------------------------------------------------


def bench_flux1_fp8(smoke: bool):
    from cake_tpu.models.image.flux import (FluxImageModel, FluxPipelineConfig,
                                            tiny_flux_config)
    from cake_tpu.models.image.mmdit import init_mmdit_params
    from cake_tpu.models.image.vae import init_vae_decoder_params

    cfg = tiny_flux_config() if smoke else FluxPipelineConfig()
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    shapes = jax.eval_shape(
        lambda a, b: {
            "transformer": init_mmdit_params(cfg.mmdit, a, jnp.bfloat16),
            "vae": init_vae_decoder_params(cfg.vae, b, jnp.bfloat16),
        }, k1, k2)
    # fp8 the transformer matmuls only; VAE convs + norms stay bf16
    params = _build_fp8_tree(shapes, skip_substrings=("vae",))
    mem_resident = device_mem_mb()
    m = FluxImageModel(cfg, params=params, dtype=jnp.bfloat16)
    w, h = (64, 64) if smoke else (768, 1024)
    steps = 2 if smoke else 4
    m.generate_image("warmup", width=w, height=h, steps=1, seed=0)   # compile
    t0 = time.monotonic()
    img = m.generate_image("bench", width=w, height=h, steps=steps, seed=0)
    _fetch(img)
    per_step = (time.monotonic() - t0) / steps
    return [{
        "metric": "flux1_fp8_step_s",
        "value": round(per_step, 3), "unit": "s/step",
        "vs_baseline": round(3.5 / per_step, 2),   # ref: 3.5 s/step fp8
        "note": "FLUX.1-dev geometry (19+38 blocks, h3072), fp8-native "
                "resident transformer weights, bf16 compute; includes VAE "
                "decode amortized over steps",
        "hbm_weights_mb": mem_resident.get("hbm_in_use_mb"),
        **device_mem_mb(),
    }]


BENCHES = {
    "prefill": bench_prefill,
    "flux2": bench_flux2,
    "flux1_fp8": bench_flux1_fp8,
    "tts": bench_tts,
    "moe": bench_moe,
    "llama8b_fp8": bench_llama8b_fp8,
}

# generous per-bench wall budgets (first compile of a 57-block MMDiT or a
# 32-layer 8B model is minutes on its own)
BENCH_TIMEOUT_S = {"flux2": 2400, "flux1_fp8": 2400, "llama8b_fp8": 1800}
DEFAULT_TIMEOUT_S = 1200


def _fail_row(metric: str, error: str) -> str:
    return json.dumps({"metric": metric, "value": 0.0, "unit": "",
                       "vs_baseline": None, "error": error[:200]})


def _run_inproc(names, smoke):
    for name in names:
        try:
            for row in BENCHES[name](smoke):
                print(json.dumps(row), flush=True)
        except Exception as e:       # noqa: BLE001 — emit per-metric failure
            traceback.print_exc(file=sys.stderr)
            print(_fail_row(name, str(e)), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", help="comma-separated subset of "
                                   f"{sorted(BENCHES)}")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--inproc", action="store_true",
                    help="run benches in this process (child mode; the "
                         "default parent spawns one subprocess per bench so "
                         "memory_stats peaks are per-metric and a single "
                         "OOM/wedge can't zero the rest of the matrix)")
    ap.add_argument("--probe-budget", type=int, default=1200)
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    names = args.only.split(",") if args.only else list(BENCHES)
    if args.inproc:
        _run_inproc(names, args.smoke)
        return

    # parent mode: never touches the TPU itself (one process at a time owns
    # the chip); probe with retry, then one subprocess per bench
    import subprocess
    if not args.cpu:
        from bench import _health_probe
        _health_probe(60, "bench_full", budget=args.probe_budget)
    for name in names:
        cmd = [sys.executable, __file__, "--only", name, "--inproc"]
        if args.smoke:
            cmd.append("--smoke")
        if args.cpu:
            cmd.append("--cpu")
        def _emit_rows(stdout) -> bool:
            emitted = False
            for line in (stdout or "").splitlines():
                line = line.strip()
                if line.startswith("{"):
                    print(line, flush=True)
                    emitted = True
            return emitted

        try:
            r = subprocess.run(
                cmd, timeout=BENCH_TIMEOUT_S.get(name, DEFAULT_TIMEOUT_S),
                capture_output=True, text=True)
            sys.stderr.write(r.stderr[-4000:] if r.stderr else "")
            emitted = _emit_rows(r.stdout)
            if not emitted:
                print(_fail_row(name, f"no output (exit {r.returncode})"),
                      flush=True)
            elif r.returncode != 0:
                # partial output then a hard crash (XLA abort / OOM kills
                # the interpreter past _run_inproc's except) — the missing
                # metrics must not vanish silently
                print(_fail_row(name, f"child exit {r.returncode} after "
                                      f"partial output"), flush=True)
        except subprocess.TimeoutExpired as e:
            # salvage rows the child completed before hanging + the stderr
            # tail that says where it hung
            out = e.stdout.decode() if isinstance(e.stdout, bytes) else e.stdout
            errtxt = e.stderr.decode() if isinstance(e.stderr, bytes) else e.stderr
            sys.stderr.write(errtxt[-4000:] if errtxt else "")
            _emit_rows(out)
            print(_fail_row(name, f"timeout after "
                                  f"{BENCH_TIMEOUT_S.get(name, DEFAULT_TIMEOUT_S)}s"),
                  flush=True)


if __name__ == "__main__":
    main()
