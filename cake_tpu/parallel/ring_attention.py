"""Ring attention: causal attention with the sequence sharded over a mesh
axis ('sp'), K/V blocks rotating around the ring via collective permute.

This is long-context capability the reference does NOT have (SURVEY §5:
"no ring attention, no context parallelism") — on TPU it is the idiomatic
way to scale sequence length across ICI: each device holds S/N queries and
streams all N K/V blocks through, merging partial results with the online
(flash-style) log-sum-exp accumulation so the full [S, S] score matrix is
never materialized.

Written with shard_map + jax.lax.ppermute (XLA overlaps the permute with
the block computation); runs identically on the CPU test mesh and on ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale):
    """Partial attention of a Q block against one K/V block.

    q: [B, Sq, H, D]; k/v: [B, Skv, Hkv, D].
    Returns (acc [B, Sq, H, D] f32 — unnormalized, m [B, Sq, H] rowmax,
    l [B, Sq, H] rowsum) for online-softmax merging.
    """
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qf = q.reshape(b, sq, hkv, g, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", qf, k,
                        preferred_element_type=jnp.float32) * scale
    mask = (k_pos[:, None, :] <= q_pos[:, :, None])          # causal
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                             # [B,Hkv,G,Sq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bkgst,btkd->bskgd", p.astype(v.dtype), v)
    acc = acc.reshape(b, sq, hq, d).astype(jnp.float32)
    m = m.transpose(0, 3, 1, 2).reshape(b, sq, hq)
    l = l.transpose(0, 3, 1, 2).reshape(b, sq, hq)
    return acc, m, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two partial softmax accumulations (flash-attention algebra)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    acc = acc1 * a1[..., None] + acc2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return acc, m, l


def ring_attention_sharded(q, k, v, axis_name: str, scale: float | None = None,
                           vary_axes: tuple[str, ...] = (),
                           axis_size: int | None = None):
    """Body run per-device under shard_map: q/k/v are the local sequence
    shards [B, S_local, H(.kv), D]; global sequence = concat over the axis.
    vary_axes: additional manual mesh axes the inputs vary over (e.g. the
    tp head axis) — the accumulators must be cast varying over them too or
    the fori_loop carry type mismatches. axis_size: static ring size from
    the mesh — older jax has no jax.lax.axis_size accessor, and the
    ppermute schedule below needs the concrete value either way."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n = axis_size if axis_size is not None else jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, s_local = q.shape[0], q.shape[1]

    q_pos = (me * s_local + jnp.arange(s_local, dtype=jnp.int32))[None, :]
    q_pos = jnp.broadcast_to(q_pos, (b, s_local))

    # pvary: accumulators start device-varying over the ring axis (and any
    # extra manual axes) so the fori_loop carry type matches (shard_map
    # manual-axes typing rule). Older jax has no varying-type system (and
    # no pcast) — there the shard_map is built with check_rep=False and
    # the plain accumulators are already well-typed.
    pcast = getattr(jax.lax, "pcast", None)
    vary = (axis_name, *vary_axes)
    cast = ((lambda a: pcast(a, vary, to='varying')) if pcast is not None
            else (lambda a: a))
    acc = cast(jnp.zeros(q.shape, jnp.float32))
    m = cast(jnp.full(q.shape[:3], -jnp.inf, jnp.float32))
    l = cast(jnp.zeros(q.shape[:3], jnp.float32))

    def step(i, carry):
        acc, m, l, k_blk, v_blk = carry
        src = (me - i) % n                    # whose K/V block we hold now
        k_pos = (src * s_local + jnp.arange(s_local, dtype=jnp.int32))[None, :]
        k_pos = jnp.broadcast_to(k_pos, (b, s_local))
        a2, m2, l2 = _block_attend(q, k_blk, v_blk, q_pos, k_pos, scale)
        acc, m, l = _merge(acc, m, l, a2, m2, l2)
        # rotate K/V to the right neighbor (overlaps with next compute)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return acc, m, l, k_blk, v_blk

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, step, (acc, m, l, k, v))
    # fully-masked rows (never for causal q_pos>=0) guarded by l=0 -> 0
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   scale: float | None = None, head_axis: str = "tp"):
    """q/k/v: [B, S, H(.kv), D] global tensors; S must divide by mesh[axis].

    Composes with tensor parallelism: when the mesh also has a >1
    `head_axis`, heads stay sharded over it inside the ring (head blocks
    are aligned between q and kv, so local GQA grouping is preserved) —
    otherwise the shard_map region would silently all-gather the heads
    and compute the full attention redundantly on every tp member."""
    h = (head_axis if head_axis in mesh.axis_names
         and mesh.shape[head_axis] > 1 else None)
    fn = functools.partial(ring_attention_sharded, axis_name=axis, scale=scale,
                           vary_axes=(h,) if h else (),
                           axis_size=mesh.shape[axis])
    spec = P(None, axis, h, None)
    # jax.shard_map is the promoted name (jax >= 0.5); older releases only
    # ship jax.experimental.shard_map.shard_map, whose replication checker
    # predates the varying-type annotations the body would need — disable
    # it there (the out_specs still pin the result layout)
    smap = getattr(jax, "shard_map", None)
    if smap is not None:
        mapped = smap(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    else:
        from jax.experimental.shard_map import shard_map as _shard_map
        mapped = _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_rep=False)
    return mapped(q, k, v)
