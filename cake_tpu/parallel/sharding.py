"""Parameter / cache sharding rules.

GSPMD style: annotate the pytrees with NamedSharding and let XLA insert the
collectives (psum after the row×col sharded matmul pair) — the TPU-idiomatic
replacement for hand-written NCCL calls the reference never had (SURVEY §2g:
TP is a "natural TPU win the reference cannot do").

Megatron-style layout per decoder layer (projections kept separate so row
chunks stay head-aligned — see layers.py init_attention_params):
  q/k/v_proj [out, H] : rows over tp (head-parallel)
  o_proj   [H, q]     : cols over tp -> XLA inserts the psum
  gate/up_proj [I, H] : rows over tp
  down_proj [H, I]    : cols over tp
  MoE expert banks    : leading E axis over ep (+ inner tp)
  KV cache            : heads over tp, batch over dp, LENGTH over sp
                        (context memory scales across the sp devices;
                        ring prefill writes each sequence shard locally,
                        decode attends over the sharded length with GSPMD
                        inserting the softmax-reduction collectives)
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.common.config import ModelConfig


def _ax(mesh: Mesh, name: str):
    return name if name in mesh.axis_names and mesh.shape[name] > 1 else None


def param_pspec(path: tuple[str, ...], mesh: Mesh) -> P:
    """PartitionSpec for a parameter identified by its pytree path."""
    tp, ep = _ax(mesh, "tp"), _ax(mesh, "ep")
    name = path[-1]
    parent = path[-2] if len(path) >= 2 else ""
    if parent == "experts":
        # stacked expert banks [E, I, H] / [E, H, I]: experts over ep,
        # FFN channels over tp
        if name in ("gate_proj", "up_proj"):
            return P(ep, tp, None)
        if name == "down_proj":
            return P(ep, None, tp)
    if name == "weight" or name == "bias":
        if parent in ("q_proj", "k_proj", "v_proj"):
            return P(tp, None) if name == "weight" else P(tp)
        if parent == "o_proj":
            return P(None, tp)
        if parent in ("gate_proj", "up_proj"):
            return P(tp, None)
        if parent == "down_proj":
            return P(None, tp)
        if parent in ("embed_tokens", "lm_head", "gate",
                      "shared_expert_gate"):
            return P(None, None)
    if parent == "rope":
        return P(None, None)
    return P(None)      # norms and other vectors


def _dense_pspec_for(leaf, spec: P) -> P:
    """Trim a spec to the leaf's rank (MoE dense tensors are 3D, rest 2D)."""
    ndim = getattr(leaf, "ndim", 0)
    parts = list(spec)
    if len(parts) > ndim:
        parts = parts[-ndim:] if ndim else []
    while len(parts) < ndim:
        parts.append(None)
    return P(*parts)


def params_shardings(params, mesh: Mesh):
    """Pytree of NamedSharding matching `params`."""
    def f(path, leaf):
        keys = tuple(getattr(p, "key", getattr(p, "idx", None)) for p in path)
        keys = tuple(str(k) for k in keys if k is not None)
        spec = _dense_pspec_for(leaf, param_pspec(keys, mesh))
        # fail with the tensor name, not a deep GSPMD error, on indivisibility
        for dim, ax in enumerate(spec):
            if ax is not None and leaf.shape[dim] % mesh.shape[ax]:
                raise ValueError(
                    f"{'.'.join(keys)}: dim {dim} of shape {leaf.shape} not "
                    f"divisible by mesh axis {ax}={mesh.shape[ax]}")
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map_with_path(f, params)


def cache_shardings(cache, mesh: Mesh):
    dp, tp = _ax(mesh, "dp"), _ax(mesh, "tp")
    sp = _ax(mesh, "sp")

    def _fit(leaf, spec: P) -> P:
        """Drop axes the leaf's dims can't be divided by (batch=1 under dp,
        GDN conv channels not a tp multiple): replicate rather than fail —
        these states are small relative to the weights."""
        parts = []
        for dim, ax in enumerate(spec):
            if ax is not None and leaf.shape[dim] % mesh.shape[ax]:
                ax = None
            parts.append(ax)
        return P(*parts)

    def f(path, leaf):
        name = str(getattr(path[-1], "key", getattr(path[-1], "idx", "")))
        ndim = getattr(leaf, "ndim", 0)
        spec = P()
        # sp: KV buffers shard over the LENGTH axis, so context memory
        # scales across the sp devices (ring prefill writes each shard
        # locally; decode attention over the sharded length is partial
        # per device with GSPMD inserting the softmax-reduction
        # collectives). _fit drops sp when the capacity (e.g. an SWA
        # window) is not divisible.
        if ndim == 4 and name in ("k", "v"):
            spec = P(dp, sp, tp, None)
        elif ndim == 4 and name == "state":     # GDN [B, Hv, Dk, Dv]
            spec = P(dp, tp, None, None)
        elif ndim == 3 and name == "conv":      # GDN conv state [B, C, K-1]
            spec = P(dp, tp, None)
        elif ndim == 2 and name == "pos":
            spec = P(dp, sp)
        return NamedSharding(mesh, _fit(leaf, spec))
    return jax.tree_util.tree_map_with_path(f, cache)


def shard_params(params, mesh: Mesh | None):
    """No-op without a mesh so product call sites need no guard."""
    if mesh is None:
        return params
    return jax.device_put(params, params_shardings(params, mesh))


def shard_cache(cache, mesh: Mesh | None):
    if mesh is None:
        return cache
    return jax.device_put(cache, cache_shardings(cache, mesh))


def check_tp_divisibility(cfg: ModelConfig, mesh: Mesh):
    tp = mesh.shape.get("tp", 1)
    if cfg.num_key_value_heads % tp or cfg.num_attention_heads % tp:
        raise ValueError(
            f"tp={tp} must divide heads {cfg.num_attention_heads}/"
            f"{cfg.num_key_value_heads}")
    if cfg.intermediate_size % tp:
        raise ValueError(f"tp={tp} must divide intermediate {cfg.intermediate_size}")
