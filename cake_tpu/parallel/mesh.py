"""Device mesh helpers.

Axis convention (the TPU-native replacement for the reference's intra-worker
multi-GPU layer split, ref: worker.rs:126-229; see SURVEY §2g):

  dp - data / batch replicas
  tp - tensor parallel (attention heads / FFN channels)
  sp - sequence / context parallel (ring attention)
  ep - expert parallel (MoE expert banks)

Pipeline parallelism is host-level by design (cluster/ layer ranges over the
wire, like the reference); within a host a contiguous layer range is one jit
over this mesh.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """axes e.g. {"dp": 2, "tp": 4}; product must equal device count."""
    devices = devices if devices is not None else jax.devices()
    if not axes:
        axes = {"tp": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {axes} does not match {len(devices)} devices")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("tp",))


def serving_mesh(tp: int | str | None) -> Mesh | None:
    """Mesh for the serve/run/worker product path (the in-host tensor
    parallelism the reference approximates with its multi-GPU layer split,
    ref: worker.rs:126-229).

    tp: None/0/1 -> None (single device, no mesh);
        "auto"   -> all local devices;
        int N    -> first N local devices (error if fewer exist).
    """
    devices = jax.devices()
    if tp in (None, 0, 1, "1"):
        return None
    if tp == "auto":
        n = len(devices)
        if n == 1:
            return None
    else:
        n = int(tp)
        if n > len(devices):
            raise ValueError(
                f"--tp {n}: only {len(devices)} local device(s) available")
        if n <= 1:
            return None
    return make_mesh({"tp": n}, devices=devices[:n])


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def named(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding with axis names absent from the mesh dropped to None."""
    clean = tuple(s if (s is None or s in mesh.axis_names) else None
                  for s in spec)
    return NamedSharding(mesh, P(*clean))
