"""Device mesh helpers.

Axis convention (the TPU-native replacement for the reference's intra-worker
multi-GPU layer split, ref: worker.rs:126-229; see SURVEY §2g):

  dp - data / batch replicas
  tp - tensor parallel (attention heads / FFN channels)
  sp - sequence / context parallel (ring attention)
  ep - expert parallel (MoE expert banks)

Pipeline parallelism is host-level by design (cluster/ layer ranges over the
wire, like the reference); within a host a contiguous layer range is one jit
over this mesh.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """axes e.g. {"dp": 2, "tp": 4}; product must equal device count."""
    devices = devices if devices is not None else jax.devices()
    if not axes:
        axes = {"tp": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {axes} does not match {len(devices)} devices")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("tp",))


def serving_mesh(tp: int | str | None,
                 sp: int | None = None) -> Mesh | None:
    """Mesh for the serve/run/worker product path (the in-host tensor
    parallelism the reference approximates with its multi-GPU layer split,
    ref: worker.rs:126-229).

    tp: None/0/1 -> None (single device, no mesh);
        "auto"   -> all local devices;
        int N    -> first N local devices (error if fewer exist).
    sp: sequence-parallel axis size (ring-attention prefill); composes
        with tp — tp*sp devices are used.
    """
    devices = jax.devices()
    sp = int(sp or 1)
    if tp in (None, 0, 1, "1") and sp <= 1:
        return None
    if tp == "auto":
        if sp > len(devices):
            raise ValueError(
                f"--sp {sp}: only {len(devices)} local device(s) available")
        n = max(len(devices) // sp, 1)
        if n * sp == 1:
            return None
    else:
        n = int(tp) if tp not in (None, 0) else 1
        if n * sp > len(devices):
            raise ValueError(
                f"--tp {n} --sp {sp}: only {len(devices)} local device(s) "
                "available")
        if n * sp <= 1:
            return None
    axes = {}
    if sp > 1:
        axes["sp"] = sp
    if n > 1 or not axes:
        axes["tp"] = n
    return make_mesh(axes, devices=devices[:n * sp])


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def named(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding with axis names absent from the mesh dropped to None."""
    clean = tuple(s if (s is None or s in mesh.axis_names) else None
                  for s in spec)
    return NamedSharding(mesh, P(*clean))
