"""TPU-native parallelism: device meshes, GSPMD shardings (tp/dp/ep),
ring-attention sequence parallelism (sp), and a sharded train step.

Host-level pipeline parallelism (layer-range sharding over the LAN) lives
in cluster/ — the same split the reference makes (SURVEY §2g)."""
from .mesh import (axis_size, make_mesh, named, serving_mesh,
                   single_device_mesh)
from .ring_attention import ring_attention, ring_attention_sharded
from .sharding import (cache_shardings, check_tp_divisibility,
                       params_shardings, shard_cache, shard_params)
from .train import loss_fn, make_train_step
