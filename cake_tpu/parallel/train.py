"""Sharded training step (beyond-parity: the reference is inference-only).

A full next-token-prediction step — forward, cross-entropy, grads, AdamW —
jitted over the mesh with the same GSPMD param shardings the inference path
uses (tp for matmuls, dp for the batch). Exists so the framework's sharding
layout is exercised under both dispatch directions (forward + backward
collectives) and validated by dryrun_multichip on a virtual mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.common.config import ModelConfig
from ..models.common.layers import forward_train
from .sharding import params_shardings


def loss_fn(cfg: ModelConfig, params, tokens):
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1]."""
    logits = forward_train(cfg, params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_train_step(cfg: ModelConfig, mesh: Mesh, params,
                    learning_rate: float = 1e-4):
    """Returns (train_step, opt_state). train_step(params, opt_state, tokens)
    -> (params, opt_state, loss), jitted with sharded in/out."""
    tx = optax.adamw(learning_rate)

    # params arrive already committed to params_shardings layouts
    # (shard_params) — leave their in_shardings UNSPECIFIED so the step
    # follows the committed layout instead of re-declaring it: with an
    # explicit respec, GSPMD may hand back a propagated layout for a
    # donated buffer (e.g. a tied embed row-sharded by the lm_head
    # matmul) and the second step either raises an in_shardings/arg
    # mismatch or breaks donation aliasing on older jax. Committing the
    # params here keeps the first/steady-state layouts identical.
    params = jax.device_put(params, params_shardings(params, mesh))
    opt_state = tx.init(params)
    tok_shard = NamedSharding(mesh, P("dp" if "dp" in mesh.axis_names else None,
                                      None))

    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       in_shardings=(None, None, tok_shard))
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step, opt_state
